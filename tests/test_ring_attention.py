"""Sequence-parallelism numerics: ring + Ulysses attention vs the oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.models import burnin
from k8s_dra_driver_tpu.ops.ring_attention import (
    reference_attention,
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)
from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
from tests.conftest import cpu_devices

B, S, H, D = 2, 32, 4, 8


@pytest.fixture(scope="module")
def qkv():
    # Pin to CPU: the default backend may be a TPU whose default matmul
    # precision (bf16) would skew the f32 oracle vs the CPU-mesh kernels.
    cpu = cpu_devices(1)[0]
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    return tuple(
        jax.device_put(jax.random.normal(k, (B, S, H, D), jnp.float32), cpu)
        for k in keys
    )


@pytest.fixture(scope="module")
def seq_mesh():
    # data=2, seq=4, model=1: pure sequence parallelism over 4 shards
    return build_mesh(cpu_devices(8), MeshShape(data=2, seq=4, model=1))


def shard(x, mesh, spec):
    return jax.device_put(x, NamedSharding(mesh, spec))


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, qkv, seq_mesh, causal):
        q, k, v = qkv
        want = reference_attention(q, k, v, causal=causal)
        spec = P("data", "seq", None, None)
        got = jax.jit(
            lambda a, b, c: ring_attention(
                a, b, c, mesh=seq_mesh, causal=causal, head_axis=None
            )
        )(*(shard(x, seq_mesh, spec) for x in (q, k, v)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_with_model_sharded_heads(self, qkv):
        # seq=2 x model=2: heads sharded too (the burnin TP+SP layout)
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        q, k, v = qkv
        want = reference_attention(q, k, v)
        spec = P("data", "seq", "model", None)
        got = jax.jit(
            lambda a, b, c: ring_attention(a, b, c, mesh=mesh)
        )(*(shard(x, mesh, spec) for x in (q, k, v)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_gradients_flow(self, qkv, seq_mesh):
        q, k, v = qkv
        spec = P("data", "seq", None, None)
        qs, ks, vs = (shard(x, seq_mesh, spec) for x in (q, k, v))

        def loss(a, b, c):
            return jnp.sum(
                ring_attention(a, b, c, mesh=seq_mesh, head_axis=None) ** 2
            )

        def ref_loss(a, b, c):
            return jnp.sum(reference_attention(a, b, c) ** 2)

        got = jax.jit(jax.grad(loss))(qs, ks, vs)
        want = jax.grad(ref_loss)(q, k, v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=5e-4)


class TestRingFlashAttention:
    """The pallas flash kernel per k/v block + lse merge across the ring —
    the long-context flagship path (flash intra-block, ring inter-block)."""

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, qkv, seq_mesh, causal):
        q, k, v = qkv
        want = reference_attention(q, k, v, causal=causal)
        spec = P("data", "seq", None, None)
        got = jax.jit(
            lambda a, b, c: ring_flash_attention(
                a, b, c, mesh=seq_mesh, causal=causal, head_axis=None,
                block_q=8, block_k=8, interpret=True,
            )
        )(*(shard(x, seq_mesh, spec) for x in (q, k, v)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_with_model_sharded_heads(self, qkv):
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        q, k, v = qkv
        want = reference_attention(q, k, v)
        spec = P("data", "seq", "model", None)
        got = jax.jit(
            lambda a, b, c: ring_flash_attention(
                a, b, c, mesh=mesh, block_q=16, block_k=16, interpret=True
            )
        )(*(shard(x, mesh, spec) for x in (q, k, v)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, qkv, seq_mesh, causal):
        q, k, v = qkv
        spec = P("data", "seq", None, None)
        qs, ks, vs = (shard(x, seq_mesh, spec) for x in (q, k, v))

        def loss(a, b, c):
            return jnp.sum(
                ring_flash_attention(
                    a, b, c, mesh=seq_mesh, causal=causal, head_axis=None,
                    block_q=8, block_k=8, interpret=True,
                ) ** 2
            )

        def ref_loss(a, b, c):
            return jnp.sum(reference_attention(a, b, c, causal=causal) ** 2)

        got = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(qs, ks, vs)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4)


    def test_bf16_inputs_merge_in_f32(self, seq_mesh):
        """Per-block partials stay f32 through the ring merge: bf16 inputs
        see ONE final rounding, not O(n_ring) accumulated roundings."""
        cpu = cpu_devices(1)[0]
        keys = jax.random.split(jax.random.PRNGKey(3), 3)
        q, k, v = (
            jax.device_put(
                jax.random.normal(kk, (B, S, H, D), jnp.float32), cpu
            ).astype(jnp.bfloat16)
            for kk in keys
        )
        want = reference_attention(
            *(x.astype(jnp.float32) for x in (q, k, v))
        )
        spec = P("data", "seq", None, None)
        got = jax.jit(
            lambda a, b, c: ring_flash_attention(
                a, b, c, mesh=seq_mesh, head_axis=None,
                block_q=8, block_k=8, interpret=True,
            )
        )(*(shard(x, seq_mesh, spec) for x in (q, k, v)))
        # single-rounding scale (~bf16 eps), not n-times that
        np.testing.assert_allclose(
            np.asarray(got, dtype=np.float32), np.asarray(want), atol=2.5e-2
        )

    def test_awkward_shard_length_degrades_block_size(self, seq_mesh):
        """s_loc=24 with default 128 blocks: the ring path falls back to the
        largest divisor (gcd) instead of raising like plain flash."""
        cpu = cpu_devices(1)[0]
        keys = jax.random.split(jax.random.PRNGKey(5), 3)
        q, k, v = (
            jax.device_put(jax.random.normal(kk, (2, 96, 2, 8), jnp.float32), cpu)
            for kk in keys
        )
        want = reference_attention(q, k, v)
        spec = P("data", "seq", None, None)
        got = jax.jit(
            lambda a, b, c: ring_flash_attention(
                a, b, c, mesh=seq_mesh, head_axis=None, interpret=True
            )
        )(*(shard(x, seq_mesh, spec) for x in (q, k, v)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


class TestUlyssesFlashAttention:
    def test_flash_inner_matches_reference(self, qkv, seq_mesh):
        q, k, v = qkv
        want = reference_attention(q, k, v)
        spec = P("data", "seq", None, None)
        got = jax.jit(
            lambda a, b, c: ulysses_attention(
                a, b, c, mesh=seq_mesh, use_flash=True,
                block_q=16, block_k=16, interpret=True,
            )
        )(*(shard(x, seq_mesh, spec) for x in (q, k, v)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, qkv, seq_mesh, causal):
        q, k, v = qkv
        want = reference_attention(q, k, v, causal=causal)
        spec = P("data", "seq", None, None)
        got = jax.jit(
            lambda a, b, c: ulysses_attention(a, b, c, mesh=seq_mesh, causal=causal)
        )(*(shard(x, seq_mesh, spec) for x in (q, k, v)))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_rejects_indivisible_heads(self):
        mesh = build_mesh(cpu_devices(8), MeshShape(data=1, seq=8, model=1))
        q = jnp.ones((1, 16, 4, 8))  # 4 heads, 8-way seq axis
        with pytest.raises(ValueError, match="divisible"):
            jax.jit(lambda a: ulysses_attention(a, a, a, mesh=mesh))(
                shard(q, mesh, P("data", "seq", None, None))
            )


class TestBurninRingIntegration:
    def test_invalid_scheme_rejected(self):
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        with pytest.raises(ValueError, match="sequence_parallel must be one of"):
            burnin.build_train_step(burnin.TINY, mesh=mesh, sequence_parallel="rings")

    def test_ulysses_requires_unsharded_heads(self):
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        with pytest.raises(ValueError, match="full head dim"):
            burnin.build_train_step(burnin.TINY, mesh=mesh, sequence_parallel="ulysses")

    def test_ulysses_train_step(self):
        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=4, model=1))
        fns = burnin.build_train_step(cfg, mesh=mesh, sequence_parallel="ulysses")
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32),
                NamedSharding(mesh, P("data", None)),
            )
            _, _, loss = fns.step(params, opt_state, tokens)
        assert jnp.isfinite(loss)

    def test_ring_train_step_matches_dense(self):
        cfg = burnin.TINY
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32)
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        ref = float(jax.jit(lambda p, t: burnin.loss_fn(p, t, cfg))(params, tokens))

        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        fns = burnin.build_train_step(cfg, mesh=mesh, sequence_parallel="ring")
        with mesh:
            sharded_params = jax.device_put(
                params,
                jax.tree.map(
                    lambda spec: NamedSharding(mesh, spec),
                    burnin.param_pspecs(cfg),
                    is_leaf=lambda x: isinstance(x, P),
                ),
            )
            opt_state = burnin.make_optimizer().init(sharded_params)
            sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
            _, _, loss = fns.step(sharded_params, opt_state, sharded_tokens)
        assert abs(float(loss) - ref) < 0.05

    def test_ring_flash_train_step_matches_dense(self):
        """Full train-step integration of flash ring attention: same loss as
        the single-device dense oracle."""
        cfg = burnin.TINY
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32)
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        ref = float(jax.jit(lambda p, t: burnin.loss_fn(p, t, cfg))(params, tokens))

        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        fns = burnin.build_train_step(
            cfg, mesh=mesh, sequence_parallel="ring", attention="flash"
        )
        with mesh:
            sharded_params = jax.device_put(
                params,
                jax.tree.map(
                    lambda spec: NamedSharding(mesh, spec),
                    burnin.param_pspecs(cfg),
                    is_leaf=lambda x: isinstance(x, P),
                ),
            )
            opt_state = burnin.make_optimizer().init(sharded_params)
            sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
            _, _, loss = fns.step(sharded_params, opt_state, sharded_tokens)
        assert abs(float(loss) - ref) < 0.05

    def test_explicit_none_sp_with_flash_on_seq_mesh_rejected(self):
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        with pytest.raises(ValueError, match="unsharded sequence"):
            burnin.build_train_step(
                burnin.TINY, mesh=mesh, sequence_parallel="none", attention="flash"
            )


class TestRingBlocks:
    def test_block_selection(self):
        from k8s_dra_driver_tpu.ops.ring_attention import _ring_blocks

        # short shard: one full-width block, not a gcd sliver
        assert _ring_blocks(24, 128, 128) == (24, 24)
        assert _ring_blocks(96, 128, 128) == (96, 96)
        # longer-than-block shard that 128 doesn't divide: gcd fallback
        assert _ring_blocks(192, 128, 128) == (64, 64)
        # exact multiples keep the requested block
        assert _ring_blocks(256, 128, 128) == (128, 128)
        assert _ring_blocks(256, 128, 64) == (128, 64)
