"""Workload generator + simulated-engine unit suite (PR 12).

* Seeded determinism: the same WorkloadSpec seed replays an IDENTICAL
  trace — times, lengths, SLO tiers — and different seeds diverge.
* Distribution moments: the lognormal prompt-length and Pareto
  stream-length samplers hit their documented means (exp(mu + sigma^2/2)
  and xm*alpha/(alpha-1)) within sampling tolerance.
* Rate curve: diurnal modulation, flash-crowd multipliers, and the
  piecewise majorant all bound rate_at correctly.
* SimEngine: satisfies the fleet Engine protocol, generates tokens as a
  pure function of the prompt (bit-equal across snapshot/restore), and
  keeps restore atomic (a refused restore mutates NOTHING — the fleet
  re-parks the whole batch on raise).
* replay(): drives a FleetRouter in simulated time and accounts every
  offered request exactly once (completed + shed + lost == offered).

The closed-loop autoscaler suite lives in tests/test_autoscaler.py; the
fault-injected end-to-end suite is tests/test_autoscale_chaos.py
(`make chaos-autoscale`).
"""

import math

import pytest

from k8s_dra_driver_tpu.models import fleet
from k8s_dra_driver_tpu.models import workload as W
from k8s_dra_driver_tpu.models.telemetry import EngineStats


def _spec(**kw):
    kw.setdefault("seed", 7)
    kw.setdefault("duration_s", 120.0)
    kw.setdefault("base_rate_rps", 20.0)
    return W.WorkloadSpec(**kw)


class TestTraceDeterminism:
    def test_same_seed_identical_trace(self):
        a = list(W.generate(_spec()))
        b = list(W.generate(_spec()))
        assert a == b
        assert len(a) > 100

    def test_different_seed_diverges(self):
        a = list(W.generate(_spec(seed=1)))
        b = list(W.generate(_spec(seed=2)))
        assert a != b

    def test_arrivals_ordered_and_bounded(self):
        trace = list(W.generate(_spec()))
        times = [a.t for a in trace]
        assert times == sorted(times)
        assert all(0.0 <= t < 120.0 for t in times)
        assert [a.rid for a in trace] == list(range(len(trace)))

    def test_arrival_count_tracks_offered_integral(self):
        # Over a full diurnal period the sine integrates to zero, so the
        # expected count is base * duration; allow 5 sigma of Poisson
        # noise.
        spec = _spec(duration_s=600.0, base_rate_rps=30.0,
                     diurnal_period_s=600.0)
        n = sum(1 for _ in W.generate(spec))
        expect = 30.0 * 600.0
        assert abs(n - expect) < 5.0 * math.sqrt(expect)


class TestDistributions:
    def test_prompt_lengths_hit_lognormal_mean(self):
        spec = _spec(duration_s=2000.0, prompt_len_max=100_000)
        lens = [a.prompt_len for a in W.generate(spec)]
        want = math.exp(spec.prompt_len_mu + spec.prompt_len_sigma ** 2 / 2)
        got = sum(lens) / len(lens)
        assert len(lens) > 10_000
        assert got == pytest.approx(want, rel=0.10)

    def test_stream_lengths_hit_pareto_mean(self):
        spec = _spec(duration_s=2000.0, stream_len_max=100_000)
        lens = [a.max_tokens for a in W.generate(spec)]
        a, xm = spec.stream_len_alpha, spec.stream_len_min
        want = xm * a / (a - 1.0)
        got = sum(lens) / len(lens)
        assert got == pytest.approx(want, rel=0.10)
        assert min(lens) >= 1

    def test_slo_tier_mix_matches_weights(self):
        spec = _spec(duration_s=2000.0)
        trace = list(W.generate(spec))
        interactive = sum(1 for a in trace if a.ttft_slo_s == 1.0)
        assert interactive / len(trace) == pytest.approx(0.5, abs=0.03)


class TestRateCurve:
    def test_flash_crowd_multiplies_rate(self):
        spec = _spec(flash_crowds=(W.FlashCrowd(50.0, 10.0, 4.0),),
                     diurnal_amplitude=0.0)
        assert W.rate_at(spec, 55.0) == pytest.approx(4.0 * 20.0)
        assert W.rate_at(spec, 49.0) == pytest.approx(20.0)
        assert W.rate_at(spec, 60.0) == pytest.approx(20.0)

    def test_majorant_bounds_rate_everywhere(self):
        spec = _spec(flash_crowds=(W.FlashCrowd(30.0, 20.0, 3.0),))
        segs = W._majorant_segments(spec)
        assert segs[0][0] == 0.0 and segs[-1][1] == spec.duration_s
        for a, b, m in segs:
            for frac in (0.0, 0.25, 0.5, 0.75, 0.999):
                t = a + (b - a) * frac
                assert W.rate_at(spec, t) <= m + 1e-9
        assert max(m for _, _, m in segs) == pytest.approx(W.peak_rate(spec))

    def test_clock_advances_monotonically(self):
        clock = W.SimClock()
        clock.advance(1.5)
        assert clock() == pytest.approx(1.5)
        with pytest.raises(ValueError):
            clock.advance(-0.1)


class TestSimEngine:
    def _engine(self, clock, **kw):
        kw.setdefault("n_slots", 4)
        kw.setdefault("n_blocks", 256)
        return W.SimEngine(clock=clock, **kw)

    def test_satisfies_fleet_engine_protocol(self):
        assert isinstance(self._engine(W.SimClock()), fleet.Engine)

    def test_stats_contract_and_strict_uptime_advance(self):
        eng = self._engine(W.SimClock())
        s1, s2 = eng.stats(), eng.stats()
        assert isinstance(s1, EngineStats)
        # The router's stale-feed detector needs uptime to STRICTLY
        # advance between consecutive reads even at frozen sim time.
        assert s2.uptime_s > s1.uptime_s

    def test_tokens_are_pure_function_of_prompt(self):
        clock = W.SimClock()
        e1, e2 = self._engine(clock), self._engine(clock)
        r1 = e1.submit([3, 1, 4, 1, 5], max_tokens=12)
        r2 = e2.submit([3, 1, 4, 1, 5], max_tokens=12)
        for _ in range(40):
            clock.advance(0.1)
            e1.step_burst()
            e2.step_burst()
        c1 = {c.request_id: c for c in e1.completions()}[r1]
        c2 = {c.request_id: c for c in e2.completions()}[r2]
        assert c1.generated == c2.generated
        assert len(c1.generated) == 12

    def test_snapshot_restore_continues_bit_equal(self):
        clock = W.SimClock()
        # decode_tps=10 so five 0.1s bursts leave the stream mid-flight.
        ref = self._engine(clock, decode_tps=10.0)
        rid_ref = ref.submit([9, 8, 7], max_tokens=16)

        src = self._engine(clock, decode_tps=10.0)
        dst = self._engine(clock, decode_tps=10.0)
        rid_src = src.submit([9, 8, 7], max_tokens=16)
        for _ in range(5):
            clock.advance(0.1)
            ref.step_burst()
            src.step_burst()
        snap = src.snapshot_active()
        src.release_active()
        restored = dst.restore(snap, merge=True)
        assert restored == [rid_src]  # rids survive the migration
        for _ in range(60):
            clock.advance(0.1)
            ref.step_burst()
            dst.step_burst()
        ref_out = {c.request_id: c for c in ref.completions()}[rid_ref]
        dst_out = {c.request_id: c for c in dst.completions()}[rid_src]
        assert dst_out.generated == ref_out.generated

    def test_restore_is_atomic_on_refusal(self):
        clock = W.SimClock()
        src = self._engine(clock, n_slots=3)
        for p in ([1, 2], [3, 4], [5, 6]):
            src.submit(p, max_tokens=8)
        snap = src.snapshot_active()
        dst = self._engine(clock, n_slots=2)  # one slot short
        before = (dst.free_slots(), dst._free_blocks)
        with pytest.raises(RuntimeError):
            dst.restore(snap, merge=True)
        # The fleet re-parks the WHOLE batch on raise, so a partial
        # restore would duplicate streams: nothing may have landed.
        assert (dst.free_slots(), dst._free_blocks) == before
        assert not dst._active

    def test_submit_raises_when_full(self):
        clock = W.SimClock()
        eng = self._engine(clock, n_slots=1)
        eng.submit([1], max_tokens=4)
        with pytest.raises(RuntimeError):
            eng.submit([2], max_tokens=4)


class TestReplay:
    def _run(self, seed=11, **kw):
        spec = _spec(seed=seed, duration_s=60.0, base_rate_rps=10.0)
        clock = W.SimClock()
        sink = W.SimSink()
        engines = [
            W.SimEngine(clock=clock, n_slots=8, n_blocks=1024, sink=sink)
            for _ in range(2)
        ]
        router = fleet.FleetRouter(engines, clock=clock)
        return W.replay(W.generate(spec), router, clock=clock, sink=sink,
                        dt=0.25, **kw)

    def test_accounts_every_offered_request(self):
        rep = self._run()
        assert rep.offered > 100
        assert rep.lost == 0
        assert rep.completed + rep.shed == rep.offered
        assert 0 <= rep.attained <= rep.offered
        assert rep.slo_attainment == pytest.approx(rep.attained / rep.offered)

    def test_replay_is_deterministic(self):
        a, b = self._run().to_json(), self._run().to_json()
        a.pop("wall_s"), b.pop("wall_s")  # the one wall-clock field
        assert a == b

    def test_bounded_backlog_sheds_overflow(self):
        rep = self._run(seed=12, queue_limit=4)
        assert rep.offered == rep.completed + rep.shed
        assert rep.lost == 0


class TestSharedPrefixTrace:
    def _spec(self, **kw):
        kw.setdefault("base", _spec(seed=7, duration_s=90.0, base_rate_rps=10.0))
        kw.setdefault("n_system_prompts", 4)
        kw.setdefault("system_len_tokens", 48)
        kw.setdefault("n_users", 16)
        kw.setdefault("turn_tokens", 16)
        return W.SharedPrefixSpec(**kw)

    def test_deterministic_and_rides_the_base_trace(self):
        spec = self._spec()
        a = list(W.generate_shared_prefix(spec))
        b = list(W.generate_shared_prefix(spec))
        assert a == b
        base = list(W.generate(spec.base))
        assert [(x.t, x.rid, x.max_tokens) for x in a] == [
            (x.t, x.rid, x.max_tokens) for x in base
        ]

    def test_zipf_skews_toward_head_system_prompt(self):
        trace = list(W.generate_shared_prefix(self._spec()))
        counts = [0] * 4
        for a in trace:
            counts[a.system_id] += 1
        # rank-0 weight is 1/sum(1/(i+1)^1.2) ~ 0.39 of traffic; the head
        # must strictly dominate the tail.
        assert counts[0] > counts[1] > counts[3]
        assert counts[0] / len(trace) > 0.3

    def test_turn_prompts_are_prefix_extensions(self):
        trace = list(W.generate_shared_prefix(self._spec()))
        by_conv: dict = {}
        for a in trace:
            by_conv.setdefault((a.system_id, a.user_id), []).append(a)
        checked = 0
        for conv in by_conv.values():
            for prev, cur in zip(conv, conv[1:]):
                if cur.turn != prev.turn + 1:
                    continue  # turn counter capped at max_turns
                tp = W.shared_prefix_tokens(prev, 64, None)
                tc = W.shared_prefix_tokens(cur, 64, None)
                assert tc[: len(tp)] == tp
                assert cur.shared_len == prev.prompt_len
                checked += 1
        assert checked > 10

    def test_cross_user_shares_system_prompt_only(self):
        trace = list(W.generate_shared_prefix(self._spec()))
        picks: dict = {}
        for a in trace:
            if a.system_id == 0 and a.user_id not in picks:
                picks[a.user_id] = a
            if len(picks) >= 2:
                break
        a, b = list(picks.values())[:2]
        ta, tb = (W.shared_prefix_tokens(x, 64, None) for x in (a, b))
        assert ta[:48] == tb[:48]          # the system prompt is shared...
        assert ta[48:64] != tb[48:64]      # ...the conversation body is not

    def test_sim_chain_block_identities(self):
        trace = W.generate_shared_prefix(self._spec())
        a = next(x for x in trace if x.turn == 1)
        chain = W.sim_prefix_chain(a, 16)
        # 48 sys + 16 tail = 64 tokens -> rungs at 16/32/48 (>=1 left)
        assert [d for d, _ in chain] == [16, 32, 48]
        assert chain[-1][1] == (
            ("sys", a.system_id, 0),
            ("sys", a.system_id, 1),
            ("sys", a.system_id, 2),
        )
        assert W.sim_prefix_chain(a, 0) == []


class TestSimEnginePrefixModel:
    def _engine(self, clock, index=None, name="sim", **kw):
        kw.setdefault("n_slots", 4)
        kw.setdefault("n_blocks", 512)
        kw.setdefault("prefill_tps", 100.0)
        kw.setdefault("prefix_block_tokens", 16)
        kw.setdefault("prefix_cache_blocks", 8)
        return W.SimEngine(clock=clock, name=name, prefix_index=index, **kw)

    def _chain(self, sid=0, uid=0, turn=1):
        a = W.PrefixArrival(
            t=0.0, rid=0, prompt_len=48 + turn * 16, max_tokens=4,
            ttft_slo_s=1.0, tpot_slo_s=1.0, system_id=sid, user_id=uid,
            turn=turn, system_len=48, shared_len=48 + (turn - 1) * 16,
        )
        return a, W.sim_prefix_chain(a, 16)

    def test_local_hit_skips_prefill_time(self):
        clock = W.SimClock()
        eng = self._engine(clock)
        a, chain = self._chain()
        r1 = eng.submit([1, 2, 3], 4, sim_prompt_len=a.prompt_len,
                        prefix_chain=chain)
        cold_prefill = eng._active[r1]["prefill_s"]
        r2 = eng.submit([1, 2, 3], 4, sim_prompt_len=a.prompt_len,
                        prefix_chain=chain)
        warm_prefill = eng._active[r2]["prefill_s"]
        assert cold_prefill == pytest.approx(64 / 100.0)
        assert warm_prefill == pytest.approx((64 - 48) / 100.0)
        assert eng.prefix_hits == {"local": 1, "remote": 0, "cold": 1}

    def test_remote_hit_costs_wire_time_not_prefill(self):
        from k8s_dra_driver_tpu.models.fleet_prefix import FleetPrefixIndex

        clock = W.SimClock()
        index = FleetPrefixIndex(clock=clock)
        owner = self._engine(clock, index, name="A")
        peer = self._engine(clock, index, name="B", pull_gbps=8.0)
        a, chain = self._chain()
        owner.submit([1], 4, sim_prompt_len=a.prompt_len, prefix_chain=chain)
        rid = peer.submit([1], 4, sim_prompt_len=a.prompt_len,
                          prefix_chain=chain)
        wire_s = 48 * peer.kv_bytes_per_token * 8.0 / 8e9
        assert peer._active[rid]["prefill_s"] == pytest.approx(
            (64 - 48) / 100.0 + wire_s
        )
        assert peer.prefix_hits["remote"] == 1
        # the pull landed the rungs locally: the next one is a local hit
        peer.submit([1], 4, sim_prompt_len=a.prompt_len, prefix_chain=chain)
        assert peer.prefix_hits["local"] == 1

    def test_lru_eviction_withdraws_from_index(self):
        from k8s_dra_driver_tpu.models.fleet_prefix import FleetPrefixIndex

        clock = W.SimClock()
        index = FleetPrefixIndex(clock=clock)
        eng = self._engine(clock, index, prefix_cache_blocks=3)
        for sid in range(3):
            _, chain = self._chain(sid=sid)
            eng.submit([1], 4, sim_prompt_len=64, prefix_chain=chain)
        # 3 rungs per prompt at cap 3: each admission evicts the previous
        # prompt's rungs, and the index never outlives the store
        assert len(eng._prefix_store) == 3
        assert len(index) == 3
        _, chain0 = self._chain(sid=0)
        eng.submit([1], 4, sim_prompt_len=64, prefix_chain=chain0)
        assert index.deepest(chain0).n_tokens == 48

    def test_prefix_replay_improves_ttft(self):
        spec = W.SharedPrefixSpec(
            base=_spec(seed=7, duration_s=120.0, base_rate_rps=6.0),
            n_system_prompts=4, system_len_tokens=48, n_users=16,
        )

        def run(with_index):
            from k8s_dra_driver_tpu.models.fleet_prefix import FleetPrefixIndex

            clock = W.SimClock()
            sink = W.SimSink()
            index = FleetPrefixIndex(clock=clock, ttl_s=600.0) if with_index else None
            engines = [
                (n, W.SimEngine(clock=clock, sink=sink, n_slots=8,
                                n_blocks=2048, prefill_tps=400.0,
                                decode_tps=60.0, name=n,
                                prefix_block_tokens=16,
                                prefix_cache_blocks=256,
                                prefix_index=index))
                for n in ("A", "B")
            ]
            router = fleet.FleetRouter(engines, clock=clock)
            if index is not None:
                router.attach_prefix_index(index)
            rep = W.replay(
                W.generate_shared_prefix(spec), router, clock=clock,
                sink=sink, tokens_fn=W.shared_prefix_tokens,
                submit_extra=lambda a: {"prefix_chain": W.sim_prefix_chain(a, 16)},
            )
            hits: dict = {"local": 0, "remote": 0, "cold": 0}
            for _, e in engines:
                for k in hits:
                    hits[k] += e.prefix_hits[k]
            return rep, hits

        solo_rep, _ = run(False)
        fleet_rep, fleet_hits = run(True)
        assert solo_rep.lost == 0 and fleet_rep.lost == 0
        assert fleet_hits["remote"] > 0          # cross-replica pulls happened
        assert fleet_rep.ttft_p50_s < solo_rep.ttft_p50_s
        assert fleet_rep.slo_attainment >= solo_rep.slo_attainment
