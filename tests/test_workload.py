"""Workload generator + simulated-engine unit suite (PR 12).

* Seeded determinism: the same WorkloadSpec seed replays an IDENTICAL
  trace — times, lengths, SLO tiers — and different seeds diverge.
* Distribution moments: the lognormal prompt-length and Pareto
  stream-length samplers hit their documented means (exp(mu + sigma^2/2)
  and xm*alpha/(alpha-1)) within sampling tolerance.
* Rate curve: diurnal modulation, flash-crowd multipliers, and the
  piecewise majorant all bound rate_at correctly.
* SimEngine: satisfies the fleet Engine protocol, generates tokens as a
  pure function of the prompt (bit-equal across snapshot/restore), and
  keeps restore atomic (a refused restore mutates NOTHING — the fleet
  re-parks the whole batch on raise).
* replay(): drives a FleetRouter in simulated time and accounts every
  offered request exactly once (completed + shed + lost == offered).

The closed-loop autoscaler suite lives in tests/test_autoscaler.py; the
fault-injected end-to-end suite is tests/test_autoscale_chaos.py
(`make chaos-autoscale`).
"""

import math

import pytest

from k8s_dra_driver_tpu.models import fleet
from k8s_dra_driver_tpu.models import workload as W
from k8s_dra_driver_tpu.models.telemetry import EngineStats


def _spec(**kw):
    kw.setdefault("seed", 7)
    kw.setdefault("duration_s", 120.0)
    kw.setdefault("base_rate_rps", 20.0)
    return W.WorkloadSpec(**kw)


class TestTraceDeterminism:
    def test_same_seed_identical_trace(self):
        a = list(W.generate(_spec()))
        b = list(W.generate(_spec()))
        assert a == b
        assert len(a) > 100

    def test_different_seed_diverges(self):
        a = list(W.generate(_spec(seed=1)))
        b = list(W.generate(_spec(seed=2)))
        assert a != b

    def test_arrivals_ordered_and_bounded(self):
        trace = list(W.generate(_spec()))
        times = [a.t for a in trace]
        assert times == sorted(times)
        assert all(0.0 <= t < 120.0 for t in times)
        assert [a.rid for a in trace] == list(range(len(trace)))

    def test_arrival_count_tracks_offered_integral(self):
        # Over a full diurnal period the sine integrates to zero, so the
        # expected count is base * duration; allow 5 sigma of Poisson
        # noise.
        spec = _spec(duration_s=600.0, base_rate_rps=30.0,
                     diurnal_period_s=600.0)
        n = sum(1 for _ in W.generate(spec))
        expect = 30.0 * 600.0
        assert abs(n - expect) < 5.0 * math.sqrt(expect)


class TestDistributions:
    def test_prompt_lengths_hit_lognormal_mean(self):
        spec = _spec(duration_s=2000.0, prompt_len_max=100_000)
        lens = [a.prompt_len for a in W.generate(spec)]
        want = math.exp(spec.prompt_len_mu + spec.prompt_len_sigma ** 2 / 2)
        got = sum(lens) / len(lens)
        assert len(lens) > 10_000
        assert got == pytest.approx(want, rel=0.10)

    def test_stream_lengths_hit_pareto_mean(self):
        spec = _spec(duration_s=2000.0, stream_len_max=100_000)
        lens = [a.max_tokens for a in W.generate(spec)]
        a, xm = spec.stream_len_alpha, spec.stream_len_min
        want = xm * a / (a - 1.0)
        got = sum(lens) / len(lens)
        assert got == pytest.approx(want, rel=0.10)
        assert min(lens) >= 1

    def test_slo_tier_mix_matches_weights(self):
        spec = _spec(duration_s=2000.0)
        trace = list(W.generate(spec))
        interactive = sum(1 for a in trace if a.ttft_slo_s == 1.0)
        assert interactive / len(trace) == pytest.approx(0.5, abs=0.03)


class TestRateCurve:
    def test_flash_crowd_multiplies_rate(self):
        spec = _spec(flash_crowds=(W.FlashCrowd(50.0, 10.0, 4.0),),
                     diurnal_amplitude=0.0)
        assert W.rate_at(spec, 55.0) == pytest.approx(4.0 * 20.0)
        assert W.rate_at(spec, 49.0) == pytest.approx(20.0)
        assert W.rate_at(spec, 60.0) == pytest.approx(20.0)

    def test_majorant_bounds_rate_everywhere(self):
        spec = _spec(flash_crowds=(W.FlashCrowd(30.0, 20.0, 3.0),))
        segs = W._majorant_segments(spec)
        assert segs[0][0] == 0.0 and segs[-1][1] == spec.duration_s
        for a, b, m in segs:
            for frac in (0.0, 0.25, 0.5, 0.75, 0.999):
                t = a + (b - a) * frac
                assert W.rate_at(spec, t) <= m + 1e-9
        assert max(m for _, _, m in segs) == pytest.approx(W.peak_rate(spec))

    def test_clock_advances_monotonically(self):
        clock = W.SimClock()
        clock.advance(1.5)
        assert clock() == pytest.approx(1.5)
        with pytest.raises(ValueError):
            clock.advance(-0.1)


class TestSimEngine:
    def _engine(self, clock, **kw):
        kw.setdefault("n_slots", 4)
        kw.setdefault("n_blocks", 256)
        return W.SimEngine(clock=clock, **kw)

    def test_satisfies_fleet_engine_protocol(self):
        assert isinstance(self._engine(W.SimClock()), fleet.Engine)

    def test_stats_contract_and_strict_uptime_advance(self):
        eng = self._engine(W.SimClock())
        s1, s2 = eng.stats(), eng.stats()
        assert isinstance(s1, EngineStats)
        # The router's stale-feed detector needs uptime to STRICTLY
        # advance between consecutive reads even at frozen sim time.
        assert s2.uptime_s > s1.uptime_s

    def test_tokens_are_pure_function_of_prompt(self):
        clock = W.SimClock()
        e1, e2 = self._engine(clock), self._engine(clock)
        r1 = e1.submit([3, 1, 4, 1, 5], max_tokens=12)
        r2 = e2.submit([3, 1, 4, 1, 5], max_tokens=12)
        for _ in range(40):
            clock.advance(0.1)
            e1.step_burst()
            e2.step_burst()
        c1 = {c.request_id: c for c in e1.completions()}[r1]
        c2 = {c.request_id: c for c in e2.completions()}[r2]
        assert c1.generated == c2.generated
        assert len(c1.generated) == 12

    def test_snapshot_restore_continues_bit_equal(self):
        clock = W.SimClock()
        # decode_tps=10 so five 0.1s bursts leave the stream mid-flight.
        ref = self._engine(clock, decode_tps=10.0)
        rid_ref = ref.submit([9, 8, 7], max_tokens=16)

        src = self._engine(clock, decode_tps=10.0)
        dst = self._engine(clock, decode_tps=10.0)
        rid_src = src.submit([9, 8, 7], max_tokens=16)
        for _ in range(5):
            clock.advance(0.1)
            ref.step_burst()
            src.step_burst()
        snap = src.snapshot_active()
        src.release_active()
        restored = dst.restore(snap, merge=True)
        assert restored == [rid_src]  # rids survive the migration
        for _ in range(60):
            clock.advance(0.1)
            ref.step_burst()
            dst.step_burst()
        ref_out = {c.request_id: c for c in ref.completions()}[rid_ref]
        dst_out = {c.request_id: c for c in dst.completions()}[rid_src]
        assert dst_out.generated == ref_out.generated

    def test_restore_is_atomic_on_refusal(self):
        clock = W.SimClock()
        src = self._engine(clock, n_slots=3)
        for p in ([1, 2], [3, 4], [5, 6]):
            src.submit(p, max_tokens=8)
        snap = src.snapshot_active()
        dst = self._engine(clock, n_slots=2)  # one slot short
        before = (dst.free_slots(), dst._free_blocks)
        with pytest.raises(RuntimeError):
            dst.restore(snap, merge=True)
        # The fleet re-parks the WHOLE batch on raise, so a partial
        # restore would duplicate streams: nothing may have landed.
        assert (dst.free_slots(), dst._free_blocks) == before
        assert not dst._active

    def test_submit_raises_when_full(self):
        clock = W.SimClock()
        eng = self._engine(clock, n_slots=1)
        eng.submit([1], max_tokens=4)
        with pytest.raises(RuntimeError):
            eng.submit([2], max_tokens=4)


class TestReplay:
    def _run(self, seed=11, **kw):
        spec = _spec(seed=seed, duration_s=60.0, base_rate_rps=10.0)
        clock = W.SimClock()
        sink = W.SimSink()
        engines = [
            W.SimEngine(clock=clock, n_slots=8, n_blocks=1024, sink=sink)
            for _ in range(2)
        ]
        router = fleet.FleetRouter(engines, clock=clock)
        return W.replay(W.generate(spec), router, clock=clock, sink=sink,
                        dt=0.25, **kw)

    def test_accounts_every_offered_request(self):
        rep = self._run()
        assert rep.offered > 100
        assert rep.lost == 0
        assert rep.completed + rep.shed == rep.offered
        assert 0 <= rep.attained <= rep.offered
        assert rep.slo_attainment == pytest.approx(rep.attained / rep.offered)

    def test_replay_is_deterministic(self):
        a, b = self._run().to_json(), self._run().to_json()
        a.pop("wall_s"), b.pop("wall_s")  # the one wall-clock field
        assert a == b

    def test_bounded_backlog_sheds_overflow(self):
        rep = self._run(seed=12, queue_limit=4)
        assert rep.offered == rep.completed + rep.shed
        assert rep.lost == 0
