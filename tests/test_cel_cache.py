"""CEL compile-cache bounds (scheduler/cel.py).

Selector strings are user-authored; the compile cache must be a bounded
LRU so adversarial or generated expressions cannot grow allocator memory
without limit.  (Lives outside test_cel.py on purpose: that module needs
hypothesis, which this environment does not ship.)"""

from k8s_dra_driver_tpu.scheduler import cel


def _fresh_cache():
    with cel._cache_lock:
        cel._cache.clear()


class TestCompileCacheLRU:
    def test_hit_returns_same_object(self):
        _fresh_cache()
        a = cel.compile_expr("2 + 2")
        b = cel.compile_expr("2 + 2")
        assert a is b

    def test_eviction_bounds_size(self):
        _fresh_cache()
        n = cel._CACHE_CAPACITY + 50
        for i in range(n):
            cel.compile_expr(f"{i} + 1")
        assert len(cel._cache) == cel._CACHE_CAPACITY
        # Newest survive, oldest were evicted.
        assert f"{n - 1} + 1" in cel._cache
        assert "0 + 1" not in cel._cache

    def test_recency_protects_hot_entries(self):
        _fresh_cache()
        cel.compile_expr("1 + 1")
        for i in range(cel._CACHE_CAPACITY - 1):  # fill to capacity
            cel.compile_expr(f"{i} + 2")
        cel.compile_expr("1 + 1")  # touch: most-recently-used again
        cel.compile_expr("9 + 3")  # overflow evicts the LRU entry...
        assert "1 + 1" in cel._cache  # ...which is no longer this one
        assert "0 + 2" not in cel._cache

    def test_evicted_entry_recompiles_correctly(self):
        _fresh_cache()
        assert cel.evaluate("3 * 7", {}) == 21
        for i in range(cel._CACHE_CAPACITY + 1):
            cel.compile_expr(f"{i} + 4")
        assert "3 * 7" not in cel._cache
        assert cel.evaluate("3 * 7", {}) == 21
