"""Flight recorder (utils/journal.py), stall watchdog + diag bundles
(utils/watchdog.py), the /debug/journal + /debug/stacks endpoints, the
tools/diag_bundle.py CLI, and the bench data-plane-timeout bundle path."""

import json
import logging
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer
from k8s_dra_driver_tpu.utils.journal import JOURNAL, Journal
from k8s_dra_driver_tpu.utils.logging import JSONFormatter
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, Registry
from k8s_dra_driver_tpu.utils.tracing import TRACER
from k8s_dra_driver_tpu.utils.watchdog import (
    Watchdog,
    dump_diag_bundle,
    thread_stacks,
)

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))


class TestJournal:
    def test_record_and_tail_newest_last(self):
        j = Journal()
        j.record("allocator", "allocate.ok", correlation="uid-1", node="h0")
        j.record("driver", "prepare.ok", correlation="uid-1")
        events = j.tail()
        assert [e["event"] for e in events] == ["allocate.ok", "prepare.ok"]
        assert events[0]["correlation"] == "uid-1"
        assert events[0]["attrs"] == {"node": "h0"}
        assert events[0]["ts"].endswith("Z")

    def test_correlation_and_component_filters(self):
        j = Journal()
        j.record("allocator", "allocate.ok", correlation="uid-a")
        j.record("allocator", "allocate.ok", correlation="uid-b")
        j.record("driver", "prepare.ok", correlation="uid-a")
        assert len(j.tail(correlation="uid-a")) == 2
        assert [e["component"] for e in j.tail(correlation="uid-a")] == [
            "allocator", "driver",
        ]
        assert len(j.tail(component="driver")) == 1
        assert len(j.tail(correlation="uid-a", component="driver")) == 1
        assert j.tail(correlation="nope") == []

    def test_capacity_drops_oldest(self):
        j = Journal(capacity=4)
        for i in range(10):
            j.record("c", f"e{i}")
        assert len(j) == 4
        events = j.tail()
        assert [e["event"] for e in events] == ["e6", "e7", "e8", "e9"]
        stats = j.stats()
        assert stats == {"capacity": 4, "buffered": 4, "recorded": 10, "dropped": 6}

    def test_limit_takes_newest(self):
        j = Journal()
        for i in range(5):
            j.record("c", f"e{i}")
        assert [e["event"] for e in j.tail(limit=2)] == ["e3", "e4"]

    def test_clear(self):
        j = Journal()
        j.record("c", "e")
        j.clear()
        assert len(j) == 0
        assert j.stats()["recorded"] == 0

    def test_concurrent_recorders_drop_nothing_below_capacity(self):
        j = Journal(capacity=10_000)
        n_threads, per_thread = 8, 500

        def pound(t):
            for i in range(per_thread):
                j.record("hammer", f"t{t}.e{i}", correlation=f"t{t}")

        threads = [threading.Thread(target=pound, args=(t,)) for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        stats = j.stats()
        assert stats["recorded"] == n_threads * per_thread
        assert stats["dropped"] == 0
        for t in range(n_threads):
            assert len(j.tail(limit=per_thread, correlation=f"t{t}")) == per_thread


class TestJournalLazyRecord:
    """record_lazy defers attr construction: a disabled (or sampled-out)
    journal must not pay for building the payload dict on the hot path."""

    def test_disabled_journal_never_builds_attrs(self):
        j = Journal(capacity=8)
        j.set_enabled(False)
        calls = []

        def attrs():
            calls.append(1)
            return {"big": "payload"}

        j.record_lazy("allocator", "allocate.ok", correlation="u", attrs=attrs)
        assert calls == []  # zero per-record payload allocation when off
        assert len(j) == 0

        j.set_enabled(True)
        j.record_lazy("allocator", "allocate.ok", correlation="u", attrs=attrs)
        assert calls == [1]
        events = j.tail()
        assert len(events) == 1
        assert events[0]["attrs"] == {"big": "payload"}

    def test_lazy_without_attrs(self):
        j = Journal(capacity=8)
        j.record_lazy("driver", "prepare.ok", correlation="u")
        assert j.tail()[0]["event"] == "prepare.ok"
        assert "attrs" not in j.tail()[0]  # empty attrs elided from JSON

    def test_sampling_keeps_every_nth_and_skips_attrs(self):
        j = Journal(capacity=32)
        j.set_sampling(4)
        calls = []

        def attrs():
            calls.append(1)
            return {"k": "v"}

        for _ in range(8):
            j.record_lazy("allocator", "allocate.ok", attrs=attrs)
        assert len(j) == 2  # every 4th of 8
        assert len(calls) == 2  # attrs built only for kept events

        # Direct record() ignores sampling: failure paths are never shed.
        j.record("allocator", "allocate.fail")
        assert len(j.tail(component="allocator")) == 3

    def test_disabled_direct_record_is_dropped(self):
        j = Journal(capacity=8)
        j.set_enabled(False)
        j.record("c", "e")
        assert len(j) == 0
        assert j.enabled is False
        j.set_enabled(True)
        assert j.enabled is True


class TestWatchdog:
    def test_beat_keeps_guard_healthy(self, tmp_path):
        wd = Watchdog(bundle_dir=str(tmp_path))
        with wd.guard("healthy", timeout_s=0.05) as g:
            time.sleep(0.06)
            g.beat()
            assert wd.check_now() == []
        assert wd.active() == []  # unregistered on exit

    def test_stall_dumps_bundle_with_stacks_journal_and_spans(self, tmp_path):
        with TRACER.span("prepare", claim="uid-stall"):
            pass
        JOURNAL.record("driver", "prepare.start", correlation="uid-stall")
        wd = Watchdog(bundle_dir=str(tmp_path))
        with wd.guard("serve.step", timeout_s=0.01, correlation="uid-stall"):
            time.sleep(0.02)
            written = wd.check_now()
        assert len(written) == 1
        bundle = json.loads(Path(written[0]).read_text())
        assert bundle["kind"] == "tpu-dra-diag-bundle"
        assert bundle["correlation"] == "uid-stall"
        assert "serve.step" in bundle["reason"]
        # Thread stacks: at least this (MainThread) test frame is present.
        assert any("MainThread" in k for k in bundle["thread_stacks"])
        stack_blob = "\n".join(
            ln for frames in bundle["thread_stacks"].values() for ln in frames
        )
        assert "test_stall_dumps_bundle" in stack_blob
        # Journal tail carries the stalled claim's correlation id...
        assert any(
            e.get("correlation") == "uid-stall" for e in bundle["journal_tail"]
        )
        # ...including the watchdog's own stall.detected event.
        assert any(
            e["event"] == "stall.detected" for e in bundle["journal_tail"]
        )
        # Recent spans ride along.
        assert any(s["name"] == "prepare" for s in bundle["traces"])
        # The armed guard's metadata is in the state section.
        assert any(
            g["name"] == "serve.step" for g in bundle["state"]["watchdog_guards"]
        )
        assert REGISTRY.counter("dra_watchdog_stalls_total").value(
            section="serve.step"
        ) == 1

    def test_one_bundle_per_stall_verdict(self, tmp_path):
        wd = Watchdog(bundle_dir=str(tmp_path))
        with wd.guard("s", timeout_s=0.01) as g:
            time.sleep(0.02)
            assert len(wd.check_now()) == 1
            assert wd.check_now() == []  # still stalled: no re-dump
            g.beat()  # late beat = slow, not dead
            assert wd.check_now() == []
            time.sleep(0.02)  # stalls AGAIN: a fresh verdict, a fresh bundle
            assert len(wd.check_now()) == 1
        assert len(wd.bundles) == 2

    def test_monitor_thread_detects_stall(self, tmp_path):
        wd = Watchdog(bundle_dir=str(tmp_path), poll_interval_s=0.01)
        try:
            with wd.guard("bg", timeout_s=0.03):
                deadline = time.time() + 5.0
                while not wd.bundles and time.time() < deadline:
                    time.sleep(0.01)
            assert wd.bundles, "monitor thread never dumped the stall"
        finally:
            wd.stop()

    def test_bundle_survives_failing_state_provider(self, tmp_path):
        def bad_state():
            raise RuntimeError("wedged lock")

        path = dump_diag_bundle(str(tmp_path), reason="test", state=None)
        bundle = json.loads(Path(path).read_text())
        assert bundle["state"] == {}
        wd = Watchdog(bundle_dir=str(tmp_path), state_provider=bad_state)
        with wd.guard("s", timeout_s=0.01):
            time.sleep(0.02)
            written = wd.check_now()
        assert written  # provider raised; the bundle still landed

    def test_thread_stacks_names_threads(self):
        stacks = thread_stacks()
        assert any("MainThread" in k for k in stacks)
        for frames in stacks.values():
            assert isinstance(frames, list)


class TestJournalEndpoint:
    @pytest.fixture
    def server(self):
        j = Journal()
        srv = DiagnosticsServer(port=0, bind_host="127.0.0.1", journal=j)
        srv.start()
        yield j, f"http://127.0.0.1:{srv.port}"
        srv.stop()

    def test_debug_journal_tail_and_filters(self, server):
        j, base = server
        j.record("allocator", "allocate.ok", correlation="uid-1")
        j.record("driver", "prepare.ok", correlation="uid-1")
        j.record("driver", "prepare.ok", correlation="uid-2")
        doc = json.loads(urllib.request.urlopen(f"{base}/debug/journal").read())
        assert doc["recorded"] == 3
        assert len(doc["events"]) == 3
        doc = json.loads(
            urllib.request.urlopen(f"{base}/debug/journal?correlation=uid-1").read()
        )
        assert len(doc["events"]) == 2
        doc = json.loads(
            urllib.request.urlopen(
                f"{base}/debug/journal?component=driver&limit=1"
            ).read()
        )
        assert len(doc["events"]) == 1
        assert doc["events"][0]["correlation"] == "uid-2"
        # Garbage limit degrades to the default instead of erroring.
        doc = json.loads(
            urllib.request.urlopen(f"{base}/debug/journal?limit=bogus").read()
        )
        assert len(doc["events"]) == 3

    def test_debug_stacks_endpoint(self, server):
        _, base = server
        stacks = json.loads(urllib.request.urlopen(f"{base}/debug/stacks").read())
        assert any("MainThread" in k for k in stacks)


class TestDiagBundleCLI:
    def test_snapshot_of_live_server(self, tmp_path, capsys):
        import diag_bundle

        JOURNAL.record("driver", "prepare.start", correlation="uid-cli")
        with TRACER.span("cli-span"):
            pass
        REGISTRY.counter("dra_claim_errors_total", "x" ).inc(op="prepare")
        srv = DiagnosticsServer(
            port=0, bind_host="127.0.0.1",
            state_provider=lambda: {"node": "tpu-host-0"},
        )
        srv.start()
        try:
            rc = diag_bundle.main(
                ["--url", f"http://127.0.0.1:{srv.port}", "--out", str(tmp_path)]
            )
        finally:
            srv.stop()
        assert rc == 0
        out_path = Path(capsys.readouterr().out.strip())
        assert out_path.parent == tmp_path
        bundle = json.loads(out_path.read_text())
        assert bundle["kind"] == "tpu-dra-diag-bundle"
        assert bundle["healthz"] == "ok"
        assert "dra_claim_errors_total" in bundle["metrics"]
        assert bundle["state"] == {"node": "tpu-host-0"}
        assert any(
            e.get("correlation") == "uid-cli" for e in bundle["journal"]["events"]
        )
        assert any(s["name"] == "cli-span" for s in bundle["traces"])
        assert any("MainThread" in k for k in bundle["thread_stacks"])

    def test_nothing_listening_exits_1(self, tmp_path, capsys):
        import diag_bundle

        # Port 1 is privileged and unbound: every endpoint refuses.
        rc = diag_bundle.main(
            ["--url", "http://127.0.0.1:1", "--out", str(tmp_path), "--timeout-s", "0.2"]
        )
        assert rc == 1
        assert "nothing listening" in capsys.readouterr().err
        assert list(tmp_path.iterdir()) == []

    def test_half_wedged_process_still_bundles(self, tmp_path):
        import diag_bundle

        def bad_state():
            raise RuntimeError("wedged")

        srv = DiagnosticsServer(
            port=0, bind_host="127.0.0.1", state_provider=bad_state
        )
        srv.start()
        try:
            bundle, answered = diag_bundle.build_bundle(
                f"http://127.0.0.1:{srv.port}", timeout_s=5.0
            )
        finally:
            srv.stop()
        assert answered >= 5  # /debug/state 500s; everything else answers
        assert str(bundle["state"]).startswith("error:")
        assert bundle["healthz"] == "ok"


class TestLifecycleJournalWiring:
    def test_claim_path_events_share_the_claim_uid(self, tmp_path):
        from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
        from k8s_dra_driver_tpu.plugin.driver import ClaimRef, Driver, DriverConfig

        cluster = make_cluster(hosts=1, work_dir=str(tmp_path))
        driver = Driver(
            cluster.server,
            DriverConfig(
                node_name="tpu-host-0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
                publish=False,
            ),
        )
        claim = cluster.server.create(simple_claim("m1"))
        allocated = cluster.allocator.allocate(claim, node_name="tpu-host-0")
        uid = allocated.metadata.uid
        driver.node_prepare_resources(
            [ClaimRef(uid=uid, name="m1", namespace="default")]
        )
        driver.node_unprepare_resources(
            [ClaimRef(uid=uid, name="m1", namespace="default")]
        )
        events = [e["event"] for e in JOURNAL.tail(correlation=uid)]
        # One correlation id traces scheduler -> kubelet-plugin lifecycle.
        assert "allocate.ok" in events
        assert "prepare.start" in events
        assert "prepare.ok" in events
        assert "unprepare.ok" in events
        prepare_ok = next(
            e for e in JOURNAL.tail(correlation=uid) if e["event"] == "prepare.ok"
        )
        assert prepare_ok["attrs"]["devices"]
        assert prepare_ok["attrs"]["duration_ms"] >= 0

    def test_allocate_failure_journaled(self, tmp_path):
        from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
        from k8s_dra_driver_tpu.scheduler.allocator import AllocationError

        cluster = make_cluster(hosts=1, work_dir=str(tmp_path))
        # More chips than one fake host publishes: the plan must fail.
        claim = cluster.server.create(simple_claim("greedy", count=1000))
        with pytest.raises(AllocationError):
            cluster.allocator.allocate(claim, node_name="tpu-host-0")
        events = JOURNAL.tail(correlation=claim.metadata.uid)
        assert any(e["event"] == "allocate.fail" for e in events)


class TestServeJournal:
    def test_submit_and_complete_events_carry_request_id(self):
        jax = pytest.importorskip("jax")
        from k8s_dra_driver_tpu.models.burnin import ModelConfig, init_params
        from k8s_dra_driver_tpu.models.serve import ServeEngine

        cfg = ModelConfig(
            vocab_size=32, d_model=16, n_layers=1, n_heads=2, max_seq=32
        )
        params = init_params(jax.random.PRNGKey(0), cfg)
        eng = ServeEngine(params=params, cfg=cfg, n_slots=2, prompt_bucket=8)
        rid = eng.submit([1, 2, 3], max_tokens=2)
        eng.run_until_drained()
        events = [e["event"] for e in JOURNAL.tail(correlation=f"req-{rid}")]
        assert "request.submit" in events
        assert "request.complete" in events


class TestConcurrentScrape:
    def test_hammered_registry_and_tracer_render_parseable(self):
        r = Registry()
        j = Journal()
        srv = DiagnosticsServer(port=0, bind_host="127.0.0.1", registry=r, journal=j)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        stop = threading.Event()
        errors: list = []

        def pound(i):
            c = r.counter("hammer_ops_total", "ops")
            g = r.gauge("hammer_level", "level")
            h = r.histogram("hammer_seconds", "lat")
            n = 0
            while not stop.is_set():
                # Hostile label values exercise the escaping under load.
                c.inc(worker=f'w"{i}\\', op="x\ny")
                g.set(n, worker=str(i))
                h.observe(0.01 * (n % 7))
                with TRACER.span("hammer", worker=str(i)):
                    pass
                j.record("hammer", "tick", correlation=f"w{i}")
                n += 1

        workers = [
            threading.Thread(target=pound, args=(i,), daemon=True) for i in range(4)
        ]
        for w in workers:
            w.start()
        try:
            for _ in range(20):  # scrape loop racing the writers
                text = urllib.request.urlopen(f"{base}/metrics").read().decode()
                for line in text.splitlines():
                    if not line or line.startswith("#"):
                        continue
                    # Every sample line must keep its "name{labels} value"
                    # shape even mid-hammer; raw newlines would break this.
                    assert " " in line, f"unparseable sample {line!r}"
                    float(line.rsplit(" ", 1)[1])
                json.loads(urllib.request.urlopen(f"{base}/debug/traces").read())
                json.loads(urllib.request.urlopen(f"{base}/debug/journal").read())
        finally:
            stop.set()
            for w in workers:
                w.join(timeout=5)
            srv.stop()
        assert not errors


class TestJSONFormatterExceptions:
    def _format(self, record):
        return json.loads(JSONFormatter().format(record))

    def test_exc_info_serialized_structured(self):
        logger = logging.getLogger("fmt-test")
        records = []
        logger.addHandler(logging.NullHandler())
        try:
            raise ValueError("boom")
        except ValueError:
            record = logger.makeRecord(
                "fmt-test", logging.ERROR, __file__, 1, "it broke", (),
                sys.exc_info(),
            )
        doc = self._format(record)
        assert doc["msg"] == "it broke"
        assert doc["exc"]["type"] == "ValueError"
        assert doc["exc"]["message"] == "boom"
        assert any("raise ValueError" in ln for ln in doc["exc"]["traceback"])
        # The whole line stays one JSON object (no raw newlines).
        assert "\n" not in JSONFormatter().format(record)

    def test_cached_exc_text_kept(self):
        record = logging.LogRecord(
            "fmt-test", logging.ERROR, __file__, 1, "cached", (), None
        )
        record.exc_text = "Traceback (most recent call last):\n  boom"
        doc = self._format(record)
        assert doc["exc"]["traceback"] == [
            "Traceback (most recent call last):", "  boom",
        ]

    def test_stack_info_serialized(self):
        record = logging.LogRecord(
            "fmt-test", logging.INFO, __file__, 1, "where", (), None
        )
        record.stack_info = "Stack (most recent call last):\n  File x"
        doc = self._format(record)
        assert doc["stack"] == ["Stack (most recent call last):", "  File x"]


class TestBenchTimeoutBundle:
    def test_data_plane_timeout_reports_bundle_path(self, tmp_path, monkeypatch):
        import bench

        monkeypatch.setenv("TPU_DRA_DIAG_DIR", str(tmp_path))
        release = threading.Event()

        def hang(sink=None):
            sink["partial_block"] = {"ok": True}  # salvage survives the hang
            release.wait(10)

        monkeypatch.setattr(bench, "run_data_plane", hang)
        try:
            result = bench._run_data_plane_guarded(timeout_s=0.2)
        finally:
            release.set()
        assert result["partial_block"] == {"ok": True}
        assert "timed out" in result["error"]
        assert "diag bundle: " in result["error"]
        bundle_path = result["error"].split("diag bundle: ", 1)[1]
        bundle = json.loads(Path(bundle_path).read_text())
        assert bundle["kind"] == "tpu-dra-diag-bundle"
        # The wedged worker thread's stack is in the bundle — the evidence
        # the bare "hung device link?" guess never had.
        stack_blob = "\n".join(
            ln for frames in bundle["thread_stacks"].values() for ln in frames
        )
        assert "hang" in stack_blob
        assert bundle["state"]["salvaged_blocks"] == ["partial_block"]
