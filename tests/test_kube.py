"""Tests for the kube object model, fake API server and slice reconciler."""

import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

from k8s_dra_driver_tpu.kube import objects
from k8s_dra_driver_tpu.kube.fakeserver import Conflict, InMemoryAPIServer, NotFound
from k8s_dra_driver_tpu.kube.objects import (
    BasicDevice,
    Device,
    DeviceAttribute,
    Node,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    ResourceClaim,
    ResourceSlice,
)
from k8s_dra_driver_tpu.kube.quantity import InvalidQuantity, format_bytes, parse
from k8s_dra_driver_tpu.kube.resourceslice_controller import (
    DriverResources,
    Pool,
    ResourceSliceController,
    Slice,
)


def make_device(name: str, **attrs) -> Device:
    return Device(
        name=name,
        basic=BasicDevice(attributes={k: DeviceAttribute.of(v) for k, v in attrs.items()}),
    )


class TestQuantity:
    @pytest.mark.parametrize(
        "s,expected",
        [
            ("16Gi", 16 * 1024**3),
            ("1500M", 1_500_000_000),
            ("7", 7),
            ("0.5Ki", 512),
        ],
    )
    def test_parse(self, s, expected):
        assert parse(s) == expected

    def test_parse_rejects_garbage(self):
        with pytest.raises(InvalidQuantity):
            parse("12xyz")
        with pytest.raises(InvalidQuantity):
            parse("")

    def test_format_roundtrip(self):
        assert format_bytes(16 * 1024**3) == "16Gi"
        assert parse(format_bytes(123456789)) == 123456789


class TestSerde:
    def test_resource_slice_roundtrip(self):
        rs = ResourceSlice(
            metadata=ObjectMeta(name="s1", labels={"a": "b"}),
        )
        rs.spec.driver = "tpu.google.com"
        rs.spec.node_name = "host0"
        rs.spec.devices = [make_device("tpu-0", type="tpu", index=3, healthy=True)]
        data = objects.to_json(rs)
        assert data["kind"] == "ResourceSlice"
        assert data["apiVersion"] == "resource.k8s.io/v1beta1"
        dev = data["spec"]["devices"][0]["basic"]["attributes"]
        assert dev["type"] == {"string": "tpu"}
        assert dev["index"] == {"int": 3}
        assert dev["healthy"] == {"bool": True}
        back = objects.from_json(data)
        assert back.spec.devices[0].basic.attributes["index"].value == 3
        assert back.spec.devices[0].basic.attributes["healthy"].value is True
        assert objects.to_json(back) == data

    def test_unknown_fields_ignored(self):
        data = objects.to_json(ResourceClaim(metadata=ObjectMeta(name="c")))
        data["spec"]["future"] = {"x": 1}
        back = objects.from_json(data)
        assert back.metadata.name == "c"


class TestV1SerdeSeam:
    """resource.k8s.io/v1 wire seam: the same internal objects round-trip
    under BOTH apiVersions (v1 flattens ResourceSlice devices + wraps
    capacity values; ResourceClaim requests move under ``exactly:``)."""

    def _slice(self):
        rs = ResourceSlice(metadata=ObjectMeta(name="s1"))
        rs.spec.driver = "tpu.google.com"
        rs.spec.devices = [make_device("tpu-0", type="tpu", index=3)]
        rs.spec.devices[0].basic.capacity = {"memorySlice0": "16Gi"}
        return rs

    def test_resource_slice_v1_wire_shape(self):
        data = objects.to_json(self._slice(), api_version="resource.k8s.io/v1")
        assert data["apiVersion"] == "resource.k8s.io/v1"
        dev = data["spec"]["devices"][0]
        assert "basic" not in dev  # v1 flattens the one-of wrapper
        assert dev["attributes"]["index"] == {"int": 3}
        assert dev["capacity"]["memorySlice0"] == {"value": "16Gi"}

    def test_resource_slice_roundtrips_both_versions(self):
        rs = self._slice()
        for ver in objects.RESOURCE_API_VERSIONS:
            data = objects.to_json(rs, api_version=ver)
            back = objects.from_json(data)
            assert objects.to_json(back) == objects.to_json(rs), ver

    def test_resource_claim_roundtrips_both_versions(self):
        claim = ResourceClaim(metadata=ObjectMeta(name="c"))
        claim.spec.devices.requests = [
            objects.DeviceRequest(
                name="tpus", device_class_name="tpu.google.com", count=4
            )
        ]
        v1 = objects.to_json(claim, api_version="resource.k8s.io/v1")
        req = v1["spec"]["devices"]["requests"][0]
        assert req["exactly"]["deviceClassName"] == "tpu.google.com"
        assert req["exactly"]["count"] == 4
        assert "deviceClassName" not in req
        for ver in objects.RESOURCE_API_VERSIONS:
            back = objects.from_json(objects.to_json(claim, api_version=ver))
            assert objects.to_json(back) == objects.to_json(claim), ver

    def test_unknown_resource_version_rejected(self):
        with pytest.raises(ValueError, match="unsupported resource.k8s.io"):
            objects.to_json(self._slice(), api_version="resource.k8s.io/v2")

    def test_non_resource_kinds_ignore_version_override(self):
        node = Node(metadata=ObjectMeta(name="n"))
        data = objects.to_json(node, api_version="resource.k8s.io/v1")
        assert data["apiVersion"] == "v1"


class TestNodeSelector:
    def test_terms_or_expressions_and(self):
        sel = NodeSelector(
            node_selector_terms=[
                NodeSelectorTerm(
                    match_expressions=[
                        NodeSelectorRequirement(key="domain", values=["d1"]),
                        NodeSelectorRequirement(key="zone", operator="Exists"),
                    ]
                ),
                NodeSelectorTerm(
                    match_expressions=[NodeSelectorRequirement(key="domain", values=["d2"])]
                ),
            ]
        )
        assert sel.matches({"domain": "d1", "zone": "z"})
        assert not sel.matches({"domain": "d1"})  # second expr fails, term ANDed
        assert sel.matches({"domain": "d2"})  # second term ORed
        assert not sel.matches({"domain": "d3"})


class TestFakeServer:
    def test_crud_and_uid_rv(self):
        s = InMemoryAPIServer()
        n = s.create(Node(metadata=ObjectMeta(name="host0")))
        assert n.metadata.uid and n.metadata.resource_version == "1"
        got = s.get("Node", "host0")
        assert got.metadata.uid == n.metadata.uid
        got.metadata.labels["k"] = "v"
        updated = s.update(got)
        assert updated.metadata.resource_version != n.metadata.resource_version
        s.delete("Node", "host0")
        with pytest.raises(NotFound):
            s.get("Node", "host0")

    def test_conflict_on_stale_rv(self):
        s = InMemoryAPIServer()
        s.create(Node(metadata=ObjectMeta(name="host0")))
        a = s.get("Node", "host0")
        b = s.get("Node", "host0")
        s.update(a)
        with pytest.raises(Conflict):
            s.update(b)

    def test_watch_replays_then_streams(self):
        s = InMemoryAPIServer()
        s.create(Node(metadata=ObjectMeta(name="host0")))
        events = []
        w = s.watch("Node", lambda e: events.append((e.type, e.object.metadata.name)))
        s.create(Node(metadata=ObjectMeta(name="host1")))
        s.delete("Node", "host0")
        assert events == [("ADDED", "host0"), ("ADDED", "host1"), ("DELETED", "host0")]
        w.stop()
        s.create(Node(metadata=ObjectMeta(name="host2")))
        assert len(events) == 3

    def test_label_selected_list(self):
        s = InMemoryAPIServer()
        s.create(Node(metadata=ObjectMeta(name="a", labels={"d": "1"})))
        s.create(Node(metadata=ObjectMeta(name="b", labels={"d": "2"})))
        assert [n.metadata.name for n in s.list("Node", label_selector={"d": "2"})] == ["b"]


class TestResourceSliceController:
    def test_create_update_delete_cycle(self):
        s = InMemoryAPIServer()
        c = ResourceSliceController(s, "tpu.google.com", "host0")
        c.update(
            DriverResources(
                pools={"host0": Pool(slices=[Slice(devices=[make_device("tpu-0")])], node_name="host0")}
            )
        )
        slices = s.list(ResourceSlice.KIND)
        assert len(slices) == 1
        assert slices[0].spec.pool.name == "host0"
        assert slices[0].spec.devices[0].name == "tpu-0"

        # Content change bumps generation in-place.
        c.update(
            DriverResources(
                pools={
                    "host0": Pool(
                        slices=[Slice(devices=[make_device("tpu-0"), make_device("tpu-1")])],
                        node_name="host0",
                    )
                }
            )
        )
        slices = s.list(ResourceSlice.KIND)
        assert len(slices) == 1
        assert len(slices[0].spec.devices) == 2
        assert slices[0].spec.pool.generation == 1

        # No-op update does not churn resourceVersion.
        rv = slices[0].metadata.resource_version
        c.update(
            DriverResources(
                pools={
                    "host0": Pool(
                        slices=[Slice(devices=[make_device("tpu-0"), make_device("tpu-1")])],
                        node_name="host0",
                    )
                }
            )
        )
        assert s.list(ResourceSlice.KIND)[0].metadata.resource_version == rv

        c.stop()
        assert s.list(ResourceSlice.KIND) == []

    def test_pool_generation_is_pool_scoped(self):
        # Changing one slice of a 2-slice pool must rewrite BOTH at the new
        # generation — stale-generation siblings are invisible to the DRA
        # scheduler.
        s = InMemoryAPIServer()
        c = ResourceSliceController(s, "tpu.google.com", "ctrl")
        two = {
            "p": Pool(
                slices=[Slice(devices=[make_device("a")]), Slice(devices=[make_device("b")])]
            )
        }
        c.update(DriverResources(pools=two))
        gens = {x.spec.pool.generation for x in s.list(ResourceSlice.KIND)}
        assert gens == {0}
        two["p"].slices[0].devices = [make_device("a2")]
        c.update(DriverResources(pools=two))
        slices = s.list(ResourceSlice.KIND)
        assert {x.spec.pool.generation for x in slices} == {1}
        assert len(slices) == 2

    def test_does_not_touch_foreign_slices(self):
        s = InMemoryAPIServer()
        foreign = ResourceSlice(metadata=ObjectMeta(name="other"))
        foreign.spec.driver = "gpu.nvidia.com"
        s.create(foreign)
        c = ResourceSliceController(s, "tpu.google.com", "host0")
        c.update(DriverResources(pools={}))
        c.stop()
        assert [x.metadata.name for x in s.list(ResourceSlice.KIND)] == ["other"]


class TestFastDeepcopy:
    def test_isolation_and_fidelity(self):
        from k8s_dra_driver_tpu.kube import objects

        claim = objects.ResourceClaim(
            metadata=objects.ObjectMeta(name="c", labels={"a": "1"}),
            spec=objects.ResourceClaimSpec(
                devices=objects.DeviceClaim(
                    requests=[objects.DeviceRequest(name="r", device_class_name="x")]
                )
            ),
        )
        cp = objects.deepcopy(claim)
        assert cp is not claim and cp == claim
        cp.metadata.labels["a"] = "2"
        cp.spec.devices.requests[0].name = "mut"
        assert claim.metadata.labels["a"] == "1"
        assert claim.spec.devices.requests[0].name == "r"

    def test_subclasses_keep_their_type(self):
        import collections

        from k8s_dra_driver_tpu.kube import objects

        dd = collections.defaultdict(list)
        dd["k"].append(1)
        out = objects.deepcopy({"raw": dd})
        assert isinstance(out["raw"], collections.defaultdict)
        out["raw"]["new"].append(2)  # default_factory survived
        assert "new" not in dd


class TestFuzzQuantityParse:
    """quantity.parse feeds CEL capacity comparison and HBM-limit
    normalization; any string must parse or raise InvalidQuantity."""

    @settings(max_examples=300, deadline=None)
    @given(st.text(alphabet="0123456789.eEkKmMgGtTiI+- x", max_size=16))
    def test_arbitrary_strings(self, s):
        try:
            value = parse(s)
            assert isinstance(value, int)
        except InvalidQuantity:
            pass

    @pytest.mark.parametrize("s", ["9.9e999", "9.9e307M", "1.0e308Ei"])
    def test_overflow_is_typed(self, s):
        # finite-mantissa x multiplier overflow must not leak OverflowError
        with pytest.raises(InvalidQuantity):
            parse(s)

    @settings(max_examples=100, deadline=None)
    @given(st.integers(min_value=0, max_value=2**53))
    def test_format_parse_roundtrip(self, n):
        # format_bytes output must re-parse to the same value
        assert parse(format_bytes(n)) == n
