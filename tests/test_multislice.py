"""Multi-slice / DCN layer tests: slice-GROUP seat publication (the imex
domain-pool pattern one level up), megascale Prepare wiring, the
multislice-test1 spec end to end, and the DCN-aware hybrid-DP mesh."""

from pathlib import Path

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.controller.slice_manager import (
    SLICE_DOMAIN_LABEL,
    SLICE_GROUP_LABEL,
    SLICE_HOST_ID_LABEL,
    SliceManager,
)
from k8s_dra_driver_tpu.e2e.harness import make_cluster
from k8s_dra_driver_tpu.e2e.spec_runner import apply_spec
from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import Node, ObjectMeta, ResourceSlice
from tests.conftest import cpu_devices

SPECS = Path(__file__).parent.parent / "demo" / "specs" / "quickstart"


def add_node(server, name, domain, host_id, group=None):
    labels = {
        "kubernetes.io/hostname": name,
        SLICE_DOMAIN_LABEL: domain,
        SLICE_HOST_ID_LABEL: str(host_id),
    }
    if group:
        labels[SLICE_GROUP_LABEL] = group
    return server.create(Node(metadata=ObjectMeta(name=name, labels=labels)))


def group_slices(server):
    return [
        s
        for s in server.list(ResourceSlice.KIND)
        if s.spec.pool.name.startswith("slicegroup-")
    ]


class TestGroupPublication:
    def test_two_domains_one_group(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        for s in range(2):
            for h in range(2):
                add_node(server, f"n{s}{h}", f"dom-{s}", h, group="job-a")
        slices = group_slices(server)
        # one pool per (group, domain)
        pools = {s.spec.pool.name for s in slices}
        assert pools == {"slicegroup-job-a-dom-0", "slicegroup-job-a-dom-1"}
        by_pool = {s.spec.pool.name: s for s in slices}
        for slice_id in (0, 1):
            s = by_pool[f"slicegroup-job-a-dom-{slice_id}"]
            devices = s.spec.devices
            assert len(devices) == 2  # one seat per host
            for d in devices:
                attrs = d.basic.attributes
                assert attrs["numSlices"].value == 2
                assert attrs["sliceId"].value == slice_id
                # group coordinator = slice 0's worker-0 node
                assert attrs["coordinatorAddress"].value == "n00:8476"
            # node-selected on BOTH labels
            sel = s.spec.node_selector
            assert sel.matches(
                {SLICE_GROUP_LABEL: "job-a", SLICE_DOMAIN_LABEL: f"dom-{slice_id}"}
            )
            assert not sel.matches(
                {SLICE_GROUP_LABEL: "job-a", SLICE_DOMAIN_LABEL: "dom-other"}
            )
        mgr.stop()

    def test_ungrouped_domains_publish_no_group_pool(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        add_node(server, "n0", "dom-0", 0)
        assert group_slices(server) == []
        mgr.stop()

    def test_group_disappears_when_labels_go(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        node = add_node(server, "n0", "dom-0", 0, group="job-a")
        add_node(server, "n1", "dom-1", 0, group="job-a")
        assert len(group_slices(server)) == 2
        del node.metadata.labels[SLICE_GROUP_LABEL]
        server.update(node)
        # dom-0 left the group: job-a is now a 1-slice group
        remaining = group_slices(server)
        assert {s.spec.pool.name for s in remaining} == {"slicegroup-job-a-dom-1"}
        assert remaining[0].spec.devices[0].basic.attributes["numSlices"].value == 1
        mgr.stop()

    def test_conflicting_group_labels_use_worker0(self, caplog):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        add_node(server, "n0", "dom-0", 0, group="job-a")
        add_node(server, "n1", "dom-0", 1, group="job-b")
        pools = {s.spec.pool.name for s in group_slices(server)}
        assert pools == {"slicegroup-job-a-dom-0"}  # worker-0's label wins
        mgr.stop()


class TestMultisliceSpec:
    def test_multislice_test1_end_to_end(self, tmp_path):
        cluster = make_cluster(
            hosts=4, topology="v5e-16", work_dir=str(tmp_path),
            slice_domain="v5e-16-ms", slices=2, slice_group="job-ms",
        )
        manager = SliceManager(cluster.server)
        manager.start()
        pods = apply_spec(cluster, SPECS / "multislice-test1.yaml")
        assert len(pods) == 4
        assert len({p.node for p in pods}) == 4

        from k8s_dra_driver_tpu import consumer

        global_ids = set()
        megascale = set()
        for p in pods:
            assert p.env.get("MEGASCALE_NUM_SLICES") == "2"
            assert p.env.get("MEGASCALE_PORT") == "8081"
            ctx = consumer.attach(environ=p.env, init_distributed=False)
            assert ctx.multi_slice and ctx.num_slices == 2
            assert ctx.host_count == 2  # hosts per slice
            global_ids.add(ctx.global_worker_id)
            megascale.add(ctx.megascale_coordinator)
        # 2 slices x 2 hosts -> distinct global process ids 0..3
        assert global_ids == {0, 1, 2, 3}
        # one cross-slice coordinator, on the config's DCN port
        assert len(megascale) == 1
        assert next(iter(megascale)).endswith(":8081")
        manager.stop()


GROUP_WORKER = r"""
import json
from k8s_dra_driver_tpu import consumer

ctx = consumer.attach(init_distributed=False)
import jax

# Multislice bring-up: ONE global runtime spanning every slice (the role
# megascale plays over DCN on real v5e pods), identities composed from the
# membership seat (intra-slice worker) and the group seat (slice ordinal).
jax.distributed.initialize(
    coordinator_address=ctx.megascale_coordinator,
    num_processes=ctx.num_slices * ctx.host_count,
    process_id=ctx.global_worker_id,
)
import jax.numpy as jnp
from jax.experimental import multihost_utils

gathered = multihost_utils.process_allgather(
    jnp.float32(10 * ctx.slice_id + ctx.worker_id)
)
print(json.dumps({
    "slice_id": ctx.slice_id,
    "worker": ctx.worker_id,
    "global": ctx.global_worker_id,
    "process_count": jax.process_count(),
    "gathered": sorted(float(x) for x in gathered),
}))
"""


class TestMultisliceProcesses:
    def test_two_slice_four_process_collective(self, tmp_path):
        """REAL 2-slice x 2-host data plane: four OS processes, each
        bootstrapped from its pod's driver-injected env, rendezvous over
        one TCP coordinator (standing in for the DCN transport) and run a
        cross-SLICE collective — the imex-test1-style proof one level up,
        with nothing below the k8s layer mocked."""
        import subprocess
        import sys

        from k8s_dra_driver_tpu.e2e.dryrun import force_cpu_env
        from tests.mp_harness import REPO_ROOT, free_port

        cluster = make_cluster(
            hosts=4, topology="v5e-16", work_dir=str(tmp_path),
            slice_domain="v5e-16-mp", slices=2, slice_group="job-mp",
        )
        manager = SliceManager(cluster.server)
        manager.start()
        pods = apply_spec(cluster, SPECS / "multislice-test1.yaml")
        assert len(pods) == 4
        port = free_port()
        children = []
        for pod in pods:
            env = dict(pod.env)
            # the group seat wired slice-0's node name; re-point the DCN
            # coordinator at this test's real TCP port on localhost
            env["MEGASCALE_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
            force_cpu_env(env, n_devices=2)
            env["PYTHONPATH"] = str(REPO_ROOT)
            children.append(subprocess.Popen(
                [sys.executable, "-c", GROUP_WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            ))
        outs = []
        try:
            for child in children:
                out, err = child.communicate(timeout=300)
                assert child.returncode == 0, f"worker failed:\n{err[-3000:]}"
                import json as _json

                outs.append(_json.loads(out.strip().splitlines()[-1]))
        finally:
            for c in children:
                if c.poll() is None:
                    c.kill()
                    c.wait()
            manager.stop()
        assert sorted(o["global"] for o in outs) == [0, 1, 2, 3]
        assert {o["process_count"] for o in outs} == {4}
        # the gather crossed the slice boundary: both slices' tags present
        for o in outs:
            assert o["gathered"] == [0.0, 1.0, 10.0, 11.0]


class TestMultisliceMesh:
    def test_hybrid_dp_train_step(self):
        """2-slice hybrid DP on the 8-CPU mesh: gradient all-reduce spans
        the slice (DCN) axis, TP stays per-slice — the step must compile,
        run, and produce a finite loss."""
        from k8s_dra_driver_tpu.models import burnin
        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_multislice_mesh

        cfg = burnin.TINY
        mesh = build_multislice_mesh(cpu_devices(8), 2, MeshShape(data=2, model=2))
        assert mesh.axis_names == ("slice", "pipe", "data", "seq", "model")
        fns = burnin.build_train_step(cfg, mesh=mesh)
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32),
                NamedSharding(mesh, P(("slice", "data"), None)),
            )
            params, opt_state, loss = fns.step(params, opt_state, tokens)
        assert np.isfinite(float(loss))

    def test_slice_boundary_validation(self):
        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_multislice_mesh

        with pytest.raises(ValueError, match="split into"):
            build_multislice_mesh(cpu_devices(8), 3, MeshShape(data=2))
        with pytest.raises(ValueError, match="per-slice"):
            build_multislice_mesh(cpu_devices(8), 2, MeshShape(data=2))

    def test_env_shape(self):
        from k8s_dra_driver_tpu.parallel.mesh import multislice_env_shape

        assert multislice_env_shape({}) == (1, 0)
        assert multislice_env_shape(
            {"MEGASCALE_NUM_SLICES": "4", "MEGASCALE_SLICE_ID": "2"}
        ) == (4, 2)

    def test_consumer_builds_multislice_mesh(self, monkeypatch):
        """A group-seat claim context turns the global device view into a
        slice-leading mesh (the DCN axis) without the pod knowing the
        topology beyond its injected env."""
        import jax

        from k8s_dra_driver_tpu import consumer

        devs = cpu_devices(8)  # resolve BEFORE patching (it calls jax.devices)
        monkeypatch.setattr(jax, "devices", lambda *a: devs)
        ctx = consumer.attach(
            environ={"MEGASCALE_NUM_SLICES": "2", "MEGASCALE_SLICE_ID": "1"},
            init_distributed=False,
        )
        mesh = ctx.build_mesh()
        assert mesh.axis_names[0] == "slice"
        assert mesh.devices.shape[0] == 2
        # single-slice context keeps the plain mesh
        plain = consumer.attach(environ={}, init_distributed=False).build_mesh()
        assert "slice" not in plain.axis_names
