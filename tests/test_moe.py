"""Expert-parallel Switch MoE tests vs the dropless dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.ops.moe import (
    reference_switch_moe,
    reference_topk_moe,
    switch_moe,
    topk_moe,
    topk_moe_local,
)
from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
from tests.conftest import cpu_devices

T, D, F, E = 64, 16, 32, 8


def host(x):
    return np.asarray(x)


def make_inputs(seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 4)
    return (
        host(jax.random.normal(keys[0], (T, D))),
        host(jax.random.normal(keys[1], (D, E)) * 0.5),
        host(jax.random.normal(keys[2], (E, D, F)) / np.sqrt(D)),
        host(jax.random.normal(keys[3], (E, F, D)) / np.sqrt(F)),
    )


@pytest.fixture(scope="module")
def ep_mesh():
    return build_mesh(cpu_devices(4), MeshShape(data=4))


class TestSwitchMoE:
    def test_matches_oracle_with_ample_capacity(self, ep_mesh):
        x, wr, wu, wd = make_inputs()
        with jax.default_device(cpu_devices(1)[0]):
            want = reference_switch_moe(
                jnp.asarray(x), jnp.asarray(wr), jnp.asarray(wu), jnp.asarray(wd)
            )
        got = jax.jit(
            lambda *a: switch_moe(*a, mesh=ep_mesh, capacity_factor=float(E))
        )(x, wr, wu, wd)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_capacity_drops_are_zero_not_garbage(self, ep_mesh):
        # capacity 1 slot per expert: overflowing tokens contribute exactly 0.
        x, wr, wu, wd = make_inputs(seed=3)
        got = jax.jit(
            lambda *a: switch_moe(*a, mesh=ep_mesh, capacity_factor=0.01)
        )(x, wr, wu, wd)
        with jax.default_device(cpu_devices(1)[0]):
            want = reference_switch_moe(
                jnp.asarray(x), jnp.asarray(wr), jnp.asarray(wu), jnp.asarray(wd)
            )
        got_np = np.asarray(got)
        want_np = np.asarray(want)
        for t in range(T):
            row = got_np[t]
            assert (
                np.allclose(row, 0.0, atol=1e-6)
                or np.allclose(row, want_np[t], atol=2e-5)
            ), f"token {t} is neither dropped nor correctly routed"
        dropped = sum(bool(np.allclose(got_np[t], 0.0, atol=1e-6)) for t in range(T))
        assert 0 < dropped < T  # capacity 1 drops some tokens, not all

    def test_gradients_flow_through_all_to_all(self, ep_mesh):
        x, wr, wu, wd = make_inputs(seed=5)

        def loss(wu_, wd_):
            return jnp.sum(
                switch_moe(jnp.asarray(x), jnp.asarray(wr), wu_, wd_,
                           mesh=ep_mesh, capacity_factor=float(E)) ** 2
            )

        def ref_loss(wu_, wd_):
            return jnp.sum(
                reference_switch_moe(jnp.asarray(x), jnp.asarray(wr), wu_, wd_) ** 2
            )

        got = jax.jit(jax.grad(loss, argnums=(0, 1)))(jnp.asarray(wu), jnp.asarray(wd))
        with jax.default_device(cpu_devices(1)[0]):
            want = jax.jit(jax.grad(ref_loss, argnums=(0, 1)))(
                jnp.asarray(wu), jnp.asarray(wd)
            )
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4)

    def test_expert_divisibility_validated(self, ep_mesh):
        x, wr, wu, wd = make_inputs()
        with pytest.raises(ValueError, match="divisible"):
            switch_moe(x, wr, wu[:6], wd[:6], mesh=ep_mesh)

    def test_router_width_validated(self, ep_mesh):
        x, wr, wu, wd = make_inputs()
        wide_router = np.concatenate([wr, wr], axis=-1)  # 16 outputs, 8 experts
        with pytest.raises(ValueError, match="router emits"):
            switch_moe(x, wide_router, wu, wd, mesh=ep_mesh)


class TestTopKMoE:
    """GShard top-k routing (Switch is the k=1 case)."""

    def test_top2_matches_dropless_oracle(self, ep_mesh):
        mesh = ep_mesh
        keys = jax.random.split(jax.random.PRNGKey(11), 4)
        t, d, f, e = 32, 16, 32, 8
        x = jax.random.normal(keys[0], (t, d))
        wr = jax.random.normal(keys[1], (d, e)) * 0.5
        wu = jax.random.normal(keys[2], (e, d, f)) / d**0.5
        wd = jax.random.normal(keys[3], (e, f, d)) / f**0.5
        want = reference_topk_moe(x, wr, wu, wd, k=2)
        # generous capacity -> no drops -> exact oracle match
        got = jax.jit(
            lambda *a: topk_moe(*a, mesh=mesh, capacity_factor=8.0, k=2)
        )(x, wr, wu, wd)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_top2_gates_normalized_top1_raw(self):
        keys = jax.random.split(jax.random.PRNGKey(1), 4)
        t, d, f, e = 8, 4, 8, 4
        x = jax.random.normal(keys[0], (t, d))
        wr = jax.random.normal(keys[1], (d, e))
        wu = jax.random.normal(keys[2], (e, d, f))
        wd = jax.random.normal(keys[3], (e, f, d))
        # k=1 keeps the raw Switch gate: identical to the classic oracle
        np.testing.assert_allclose(
            np.asarray(reference_topk_moe(x, wr, wu, wd, k=1)),
            np.asarray(reference_switch_moe(x, wr, wu, wd)),
        )

    def test_top2_gradients_flow_through_both_experts(self, ep_mesh):
        mesh = ep_mesh
        keys = jax.random.split(jax.random.PRNGKey(2), 4)
        t, d, f, e = 16, 8, 16, 4
        x = jax.random.normal(keys[0], (t, d))
        wr = jax.random.normal(keys[1], (d, e)) * 0.5
        wu = jax.random.normal(keys[2], (e, d, f)) / d**0.5
        wd = jax.random.normal(keys[3], (e, f, d)) / f**0.5
        grads = jax.jit(
            jax.grad(
                lambda up, down: (
                    topk_moe(x, wr, up, down, mesh=mesh, capacity_factor=8.0, k=2) ** 2
                ).sum(),
                argnums=(0, 1),
            )
        )(wu, wd)
        # with top-2 and ample capacity every expert sees tokens
        assert all(float(jnp.abs(g).sum()) > 0 for g in grads)

    def test_rank_priority_under_tight_capacity(self):
        """First choices get slots before second choices: with capacity 1
        per expert, rank-0 copies survive, rank-1 copies drop."""
        # Both tokens prefer expert 0 first; their SECOND choices differ
        # (token0 -> e1, token1 -> e2).
        x = jnp.array([[1.0, 0.0], [0.0, 1.0]])
        wr = jnp.array([[4.0, 2.0, -4.0, -9.0], [4.0, -4.0, 2.0, -9.0]])
        wu = jnp.ones((4, 2, 2))
        wd = jnp.ones((4, 2, 2))
        import functools

        out = jax.jit(
            functools.partial(_run_local_single, capacity=1, k=2)
        )(x, wr, wu, wd)
        # expert 0's single slot goes to token 0 (rank-0 priority, first in
        # queue); token 1's rank-0 copy drops but its rank-1 copy (expert 2,
        # uncontended) survives — both tokens produce nonzero output.
        assert float(jnp.abs(out[0]).sum()) > 0
        assert float(jnp.abs(out[1]).sum()) > 0


def _run_local_single(x, wr, wu, wd, capacity, k):
    """topk_moe_local on a single-device 'mesh' via shard_map over data=1."""
    import functools

    mesh = build_mesh(cpu_devices(1), MeshShape(data=1))
    from jax.sharding import PartitionSpec as P

    fn = jax.shard_map(
        functools.partial(topk_moe_local, axis_name="data", capacity=capacity, k=k),
        mesh=mesh,
        in_specs=(P("data", None), P(), P("data", None, None), P("data", None, None)),
        out_specs=P("data", None),
    )
    return fn(x, wr, wu, wd)
