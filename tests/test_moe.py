"""Expert-parallel Switch MoE tests vs the dropless dense oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.ops.moe import reference_switch_moe, switch_moe
from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
from tests.conftest import cpu_devices

T, D, F, E = 64, 16, 32, 8


def host(x):
    return np.asarray(x)


def make_inputs(seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 4)
    return (
        host(jax.random.normal(keys[0], (T, D))),
        host(jax.random.normal(keys[1], (D, E)) * 0.5),
        host(jax.random.normal(keys[2], (E, D, F)) / np.sqrt(D)),
        host(jax.random.normal(keys[3], (E, F, D)) / np.sqrt(F)),
    )


@pytest.fixture(scope="module")
def ep_mesh():
    return build_mesh(cpu_devices(4), MeshShape(data=4))


class TestSwitchMoE:
    def test_matches_oracle_with_ample_capacity(self, ep_mesh):
        x, wr, wu, wd = make_inputs()
        with jax.default_device(cpu_devices(1)[0]):
            want = reference_switch_moe(
                jnp.asarray(x), jnp.asarray(wr), jnp.asarray(wu), jnp.asarray(wd)
            )
        got = jax.jit(
            lambda *a: switch_moe(*a, mesh=ep_mesh, capacity_factor=float(E))
        )(x, wr, wu, wd)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_capacity_drops_are_zero_not_garbage(self, ep_mesh):
        # capacity 1 slot per expert: overflowing tokens contribute exactly 0.
        x, wr, wu, wd = make_inputs(seed=3)
        got = jax.jit(
            lambda *a: switch_moe(*a, mesh=ep_mesh, capacity_factor=0.01)
        )(x, wr, wu, wd)
        with jax.default_device(cpu_devices(1)[0]):
            want = reference_switch_moe(
                jnp.asarray(x), jnp.asarray(wr), jnp.asarray(wu), jnp.asarray(wd)
            )
        got_np = np.asarray(got)
        want_np = np.asarray(want)
        for t in range(T):
            row = got_np[t]
            assert (
                np.allclose(row, 0.0, atol=1e-6)
                or np.allclose(row, want_np[t], atol=2e-5)
            ), f"token {t} is neither dropped nor correctly routed"
        dropped = sum(bool(np.allclose(got_np[t], 0.0, atol=1e-6)) for t in range(T))
        assert 0 < dropped < T  # capacity 1 drops some tokens, not all

    def test_gradients_flow_through_all_to_all(self, ep_mesh):
        x, wr, wu, wd = make_inputs(seed=5)

        def loss(wu_, wd_):
            return jnp.sum(
                switch_moe(jnp.asarray(x), jnp.asarray(wr), wu_, wd_,
                           mesh=ep_mesh, capacity_factor=float(E)) ** 2
            )

        def ref_loss(wu_, wd_):
            return jnp.sum(
                reference_switch_moe(jnp.asarray(x), jnp.asarray(wr), wu_, wd_) ** 2
            )

        got = jax.jit(jax.grad(loss, argnums=(0, 1)))(jnp.asarray(wu), jnp.asarray(wd))
        with jax.default_device(cpu_devices(1)[0]):
            want = jax.jit(jax.grad(ref_loss, argnums=(0, 1)))(
                jnp.asarray(wu), jnp.asarray(wd)
            )
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4)

    def test_expert_divisibility_validated(self, ep_mesh):
        x, wr, wu, wd = make_inputs()
        with pytest.raises(ValueError, match="divisible"):
            switch_moe(x, wr, wu[:6], wd[:6], mesh=ep_mesh)

    def test_router_width_validated(self, ep_mesh):
        x, wr, wu, wd = make_inputs()
        wide_router = np.concatenate([wr, wr], axis=-1)  # 16 outputs, 8 experts
        with pytest.raises(ValueError, match="router emits"):
            switch_moe(x, wide_router, wu, wd, mesh=ep_mesh)
