"""The ``make sim-contention`` chaos suite (PR 18 acceptance gate).

Races N real scheduler loops (``Allocator.plan``/``allocate_gang``) as
threads against ONE ``InMemoryAPIServer`` with genuine optimistic-
concurrency semantics — resourceVersion CAS plus a device-marker
admission validator — and pins the contention-plane invariants:

* **Exactly-once commits** — zero lost claims, zero double-committed
  items, zero device-marker overlaps, audited against the STORE, under
  seeded 409 storms and concurrent gang unwinds.
* **Fairness A/B** — the conflict-aware allocator (shuffled score ties,
  sharded work/pools with spill-over, density-shaped backoff that
  resets on success) holds Jain's index >= 0.8 where the naive policy
  (deterministic ordering, head-of-line pickup, never-reset exponential
  backoff) collapses below 0.5 under the same asymmetric 409 burst.
* **Wasted work** — under a symmetric storm the aware policy at least
  halves the wasted-attempt ratio.
* **Starvation detector** — ARMED -> COUNTING -> FIRED fires (diag
  bundle + journal + metric) for a blackout victim and stays silent on
  the fixed path under the default storm.

Budget: everything except the 10k-pool acceptance test is tier-1; the
whole file (the ``make sim-contention`` target) must stay under 60s.
"""

import json
import os

import pytest

from k8s_dra_driver_tpu.scheduler.cluster_sim import (
    ContentionConfig,
    default_contention_storm,
    run_contention,
    run_contention_ab,
    uniform_contention_storm,
)
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, parse_prom_text


def _exactly_once(report):
    assert report.lost_claims == 0, "claims planned but never committed"
    assert report.double_committed == 0, "work item won by two schedulers"
    assert report.marker_overlaps == 0, "device marker held by two claims"
    assert report.committed_claims == report.claims_total


class TestContentionAB:
    """Naive vs conflict-aware on one shared cluster."""

    def test_small_ab_converges_exactly_once(self):
        base = ContentionConfig(
            seed=5, n_nodes=300, n_schedulers=4, work_items=48,
            gang_items=6, storm=default_contention_storm(4),
        )
        naive, aware = run_contention_ab(base)
        _exactly_once(naive)
        _exactly_once(aware)
        assert naive.conflicts_total > 0, "storm never produced a 409"
        assert aware.fairness >= naive.fairness
        # Metrics land in the shared registry with bounded labels.
        doc = parse_prom_text(REGISTRY.render())
        conflicts = doc["dra_sched_conflicts_total"]
        assert any(k == (("scheduler", "sched-0"),) for k in conflicts)
        assert doc["dra_sched_fairness"][()] == aware.fairness
        assert doc["dra_sched_retry_seconds_count"][()] > 0
        # Reports serialize for bench/CI artifacts.
        assert json.loads(aware.to_json())["conflict_aware"] is True

    def test_wasted_work_halved_under_uniform_storm(self):
        base = ContentionConfig(
            seed=7, n_nodes=600, n_schedulers=8, work_items=120,
            gang_items=12, storm=uniform_contention_storm(),
        )
        naive, aware = run_contention_ab(base)
        _exactly_once(naive)
        _exactly_once(aware)
        assert naive.wasted_work_ratio > 0
        assert aware.wasted_work_ratio * 2 <= naive.wasted_work_ratio, (
            f"aware waste {aware.wasted_work_ratio} not at least half of "
            f"naive {naive.wasted_work_ratio}"
        )
        assert aware.gang_conflicts + naive.gang_conflicts >= 0  # typed path

    @pytest.mark.slow
    def test_acceptance_10k_pools_8_schedulers(self):
        """The headline gate: at 10k pools / 8 schedulers under the
        seeded asymmetric 409 storm, conflict-aware converges with
        exactly-once commits and Jain fairness >= 0.8 where naive
        collapses below 0.5."""
        base = ContentionConfig(
            seed=7, n_nodes=10_000, n_schedulers=8, work_items=160,
            gang_items=16, storm=default_contention_storm(8),
        )
        naive, aware = run_contention_ab(base)
        _exactly_once(naive)
        _exactly_once(aware)
        assert naive.fairness < 0.5, (
            f"naive policy unexpectedly fair: J={naive.fairness}"
        )
        assert aware.fairness >= 0.8, (
            f"conflict-aware allocator lost fairness: J={aware.fairness}"
        )
        assert aware.convergence_s < naive.convergence_s
        assert aware.starved == [], "fixed path must not trip the detector"
        assert naive.injected_conflicts <= 100  # per-run budget respected
        assert aware.injected_conflicts <= 100


class TestStarvationDetector:
    def test_fires_for_blackout_victim_with_bundle(self):
        cfg = ContentionConfig(
            seed=5, n_nodes=200, n_schedulers=4, work_items=120,
            gang_items=8, conflict_aware=False, starvation_budget=8,
            naive_base_delay_s=0.002, naive_max_delay_s=0.02,
            storm=(
                FaultProfile(
                    name="sched-blackout", sched_conflict_rate=1.0,
                    schedulers=(0,), limit=400,
                ),
            ),
        )
        report = run_contention(cfg)
        _exactly_once(report)
        assert report.starved == ["sched-0"], (
            "detector must fire exactly once, for the blackout victim only"
        )
        assert len(report.starvation_bundles) == 1
        assert os.path.isfile(report.starvation_bundles[0])
        fired = [
            e for e in JOURNAL.tail(limit=500, component="cluster_sim")
            if e["event"] == "sched.starved"
        ]
        assert len(fired) == 1
        assert fired[0]["correlation"] == "sched-0"
        assert fired[0]["attrs"]["commits"] == 0
        doc = parse_prom_text(REGISTRY.render())
        assert doc["dra_sched_starvation_total"][
            (("scheduler", "sched-0"),)
        ] == 1

    def test_silent_on_fixed_path_under_default_storm(self):
        cfg = ContentionConfig(
            seed=5, n_nodes=200, n_schedulers=4, work_items=60,
            gang_items=6, conflict_aware=True,
            storm=default_contention_storm(4),
        )
        report = run_contention(cfg)
        _exactly_once(report)
        assert report.starved == []
        assert report.starvation_bundles == []
        assert "dra_sched_starvation_total" not in parse_prom_text(
            REGISTRY.render()
        )


class TestSchedulerFaultGrammar:
    def test_from_env_parses_scheduler_scoped_faults(self):
        inj = FaultInjector.from_env(
            "sched_conflict_rate=0.5,schedulers=0+2,limit=5,seed=3"
        )
        (storm,) = inj._profiles
        (latency,) = FaultInjector.from_env(
            "sched_commit_latency_ms=2.5"
        )._profiles
        assert storm.sched_conflict_rate == 0.5
        assert storm.schedulers == (0, 2)
        assert storm.limit == 5
        assert latency.sched_commit_latency_s == pytest.approx(0.0025)
        assert latency.schedulers == ()  # empty scope = every scheduler

    def test_scoped_conflict_respects_budget_and_scope(self):
        from k8s_dra_driver_tpu.kube.fakeserver import Conflict

        inj = FaultInjector(seed=1)
        inj.arm(FaultProfile(
            name="blackout", sched_conflict_rate=1.0, schedulers=(1,),
            limit=3,
        ))
        inj.before_sched_commit(0)  # out of scope: never raises
        hits = 0
        for _ in range(10):
            try:
                inj.before_sched_commit(1)
            except Conflict:
                hits += 1
        assert hits == 3, "shared budget cap must bound injections"
