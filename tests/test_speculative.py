"""Speculative decoding: greedy-exact output, chunk/step parity, stats.

The load-bearing contract: `speculative_decode` returns BIT-IDENTICAL
tokens to plain greedy decode on the target, for any draft — acceptance
rate moves latency, never content (models/speculative.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, decode, speculative
from k8s_dra_driver_tpu.models.quant import quantize_blocks

CFG = burnin.ModelConfig(
    vocab_size=96, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=64
)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def prompt():
    return jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, CFG.vocab_size)


class TestDecodeChunk:
    def test_matches_sequential_steps(self, params, prompt):
        """Scoring S tokens in one chunk == S single-token decode_steps."""
        b, p_len = prompt.shape
        cache_c = decode.init_cache(CFG, b, 16)
        cache_s = decode.init_cache(CFG, b, 16)
        logits_c, cache_c = decode.decode_chunk(
            params, cache_c, prompt, 0, cfg=CFG
        )
        step_logits = []
        for i in range(p_len):
            lg, cache_s = decode.decode_step(
                params, cache_s, prompt[:, i], jnp.int32(i), cfg=CFG
            )
            step_logits.append(lg)
        np.testing.assert_allclose(
            np.asarray(logits_c),
            np.stack([np.asarray(x) for x in step_logits], axis=1),
            rtol=1e-5,
            atol=1e-5,
        )
        np.testing.assert_allclose(
            np.asarray(cache_c.k), np.asarray(cache_s.k), rtol=1e-5, atol=1e-6
        )

    def test_per_row_positions(self, params, prompt):
        """Rows at different depths score/cache at their own offsets."""
        b = prompt.shape[0]
        pos0 = jnp.array([0, 3], jnp.int32)
        cache = decode.init_cache(CFG, b, 16)
        _, cache = decode.decode_chunk(params, cache, prompt, pos0, cfg=CFG)
        k = np.asarray(cache.k)
        # row 0 wrote positions 0..4; row 1 wrote 3..7
        assert np.any(k[0, 0, 0] != 0) and np.all(k[0, 0, 7] == 0)
        assert np.all(k[0, 1, 0] == 0) and np.any(k[0, 1, 7] != 0)

    def test_inactive_rows_do_not_write(self, params, prompt):
        b = prompt.shape[0]
        cache = decode.init_cache(CFG, b, 16)
        active = jnp.array([True, False])
        _, cache = decode.decode_chunk(
            params, cache, prompt, 0, cfg=CFG, active=active
        )
        k = np.asarray(cache.k)
        assert np.any(k[:, 0] != 0)
        assert np.all(k[:, 1] == 0)


class TestSpeculativeDecode:
    def _greedy(self, params, prompt, steps):
        return np.asarray(
            decode.greedy_decode(
                params, prompt, steps, cfg=CFG, batch_prefill=True
            )
        )

    def test_self_draft_is_greedy_exact(self, params, prompt):
        """Draft == target: full acceptance, still byte-identical output."""
        out = speculative.speculative_decode(
            params, params, prompt, 20, CFG, gamma=4
        )
        np.testing.assert_array_equal(
            np.asarray(out), self._greedy(params, prompt, 20)
        )

    def test_int8_self_draft_is_greedy_exact(self, params, prompt):
        """The serving configuration: int8 draft, bf16-exact target output."""
        out, stats = speculative.speculative_decode(
            params,
            quantize_blocks(params),
            prompt,
            20,
            CFG,
            gamma=4,
            return_stats=True,
        )
        np.testing.assert_array_equal(
            np.asarray(out), self._greedy(params, prompt, 20)
        )
        assert int(stats.emitted) == 20 * prompt.shape[0]

    def test_shallow_draft_is_greedy_exact(self, params, prompt):
        """A 1-layer draft of a 2-layer target: low acceptance, same output."""
        draft = dict(params)
        draft["blocks"] = params["blocks"][:1]
        out = speculative.speculative_decode(params, draft, prompt, 16, CFG, gamma=3)
        np.testing.assert_array_equal(
            np.asarray(out), self._greedy(params, prompt, 16)
        )

    def test_adversarial_draft_is_greedy_exact(self, params, prompt):
        """A draft with permuted weights (near-zero acceptance) cannot
        corrupt the output — verification owns content."""
        rng = jax.random.PRNGKey(7)
        draft = jax.tree.map(
            lambda x: jax.random.permutation(rng, x.ravel()).reshape(x.shape),
            params,
        )
        out = speculative.speculative_decode(params, draft, prompt, 12, CFG, gamma=4)
        np.testing.assert_array_equal(
            np.asarray(out), self._greedy(params, prompt, 12)
        )

    @pytest.mark.parametrize("gamma", [1, 2, 5])
    def test_gamma_sweep(self, params, prompt, gamma):
        out = speculative.speculative_decode(
            params, quantize_blocks(params), prompt, 10, CFG, gamma=gamma
        )
        np.testing.assert_array_equal(
            np.asarray(out), self._greedy(params, prompt, 10)
        )

    def test_full_acceptance_stats(self, params, prompt):
        """Self-draft: every proposal accepted; rounds ~= steps/gamma."""
        steps, gamma = 20, 4
        _, stats = speculative.speculative_decode(
            params, params, prompt, steps, CFG, gamma=gamma, return_stats=True
        )
        assert float(stats.acceptance) == pytest.approx(1.0)
        # full acceptance commits gamma+1 per round (bonus token) ->
        # ceil(steps/(gamma+1)) rounds
        assert int(stats.rounds) == -(-steps // (gamma + 1))
        # stats are batch-summed, so the per-round rate carries a factor of B
        assert float(stats.tokens_per_round) == pytest.approx(
            prompt.shape[0] * steps / int(stats.rounds)
        )

    def test_bf16_cache(self, params, prompt):
        """Reduced-precision cache path compiles and emits every token
        (greedy equality is only guaranteed within one cache dtype)."""
        out, stats = speculative.speculative_decode(
            params,
            quantize_blocks(params),
            prompt,
            8,
            CFG,
            gamma=3,
            cache_dtype=jnp.bfloat16,
            return_stats=True,
        )
        assert out.shape == (prompt.shape[0], prompt.shape[1] + 8)
        assert int(stats.emitted) == 8 * prompt.shape[0]

    def test_jit_compatible(self, params, prompt):
        fn = jax.jit(
            lambda p, d, t: speculative.speculative_decode(p, d, t, 8, CFG, gamma=3)
        )
        out = fn(params, quantize_blocks(params), prompt)
        np.testing.assert_array_equal(
            np.asarray(out), self._greedy(params, prompt, 8)
        )

    def test_rejects_overflow(self, params, prompt):
        with pytest.raises(ValueError, match="exceeds"):
            speculative.speculative_decode(
                params, params, prompt, CFG.max_seq, CFG, gamma=4
            )

    def test_rejects_bad_args(self, params, prompt):
        with pytest.raises(ValueError, match="steps"):
            speculative.speculative_decode(params, params, prompt, 0, CFG)
        with pytest.raises(ValueError, match="gamma"):
            speculative.speculative_decode(params, params, prompt, 4, CFG, gamma=0)
