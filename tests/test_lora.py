"""LoRA adapter fine-tuning (models/lora.py).

Contracts: B=0 merges bit-identically to the base; training moves
adapters only; merged weights serve through every downstream path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, decode, lora
from k8s_dra_driver_tpu.models.quant import quantize_blocks

CFG = burnin.ModelConfig(
    vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64, max_seq=32
)
LORA = lora.LoraConfig(rank=4, alpha=8.0)


@pytest.fixture(scope="module")
def base():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def tokens():
    return burnin.sample_tokens(jax.random.PRNGKey(1), CFG, batch=4, seq=16)


def _tree_equal(a, b) -> bool:
    return all(
        bool(jnp.array_equal(x, y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestInitAndMerge:
    def test_fresh_adapters_merge_to_base_bits(self, base):
        ad = lora.init_adapters(jax.random.PRNGKey(2), CFG, LORA)
        assert _tree_equal(lora.merge(base, ad, LORA), base)

    def test_fresh_adapters_do_not_change_forward(self, base, tokens):
        ad = lora.init_adapters(jax.random.PRNGKey(2), CFG, LORA)
        want = burnin.forward(base, tokens, cfg=CFG)
        got = burnin.forward(lora.merge(base, ad, LORA), tokens, cfg=CFG)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_nonzero_b_changes_targeted_weights_only(self, base):
        ad = lora.init_adapters(jax.random.PRNGKey(2), CFG, LORA)
        ad["blocks"][0]["qkv"]["b"] = jnp.ones_like(ad["blocks"][0]["qkv"]["b"])
        merged = lora.merge(base, ad, LORA)
        assert not bool(
            jnp.array_equal(merged["blocks"][0]["qkv"], base["blocks"][0]["qkv"])
        )
        assert bool(
            jnp.array_equal(merged["blocks"][1]["qkv"], base["blocks"][1]["qkv"])
        )
        assert bool(jnp.array_equal(merged["embed"], base["embed"]))

    def test_subset_targets(self, base):
        cfg_sub = lora.LoraConfig(rank=4, targets=("qkv",))
        ad = lora.init_adapters(jax.random.PRNGKey(2), CFG, cfg_sub)
        assert set(ad["blocks"][0]) == {"qkv"}
        assert _tree_equal(lora.merge(base, ad, cfg_sub), base)

    def test_adapter_count_is_small(self, base):
        ad = lora.init_adapters(jax.random.PRNGKey(2), CFG, LORA)
        n_base = sum(x.size for x in jax.tree.leaves(base))
        assert lora.adapter_param_count(ad) < n_base / 4

    def test_bad_configs_rejected(self):
        with pytest.raises(ValueError, match="rank"):
            lora.LoraConfig(rank=0).validate(CFG)
        with pytest.raises(ValueError, match="unknown"):
            lora.LoraConfig(targets=("embed",)).validate(CFG)
        with pytest.raises(ValueError, match="low-rank"):
            lora.LoraConfig(rank=CFG.d_model).validate(CFG)
        with pytest.raises(ValueError, match="at least one"):
            lora.LoraConfig(targets=()).validate(CFG)


class TestTraining:
    def test_loss_decreases_and_base_untouched(self, base, tokens):
        fns = lora.build_lora_train_step(CFG, LORA, lr=5e-2)
        adapters, opt_state = fns.init(jax.random.PRNGKey(3))
        base_before = jax.tree.map(lambda x: np.asarray(x).copy(), base)
        losses = []
        for _ in range(15):
            adapters, opt_state, loss = fns.step(adapters, opt_state, base, tokens)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.9, losses
        assert _tree_equal(base, base_before)  # frozen means frozen

    def test_gradients_hit_every_adapter(self, base, tokens):
        fns = lora.build_lora_train_step(CFG, LORA, lr=5e-2)
        adapters, opt_state = fns.init(jax.random.PRNGKey(3))
        before = jax.tree.map(lambda x: np.asarray(x).copy(), adapters)
        for _ in range(2):  # step 1 trains only B (A@dB); step 2 reaches A
            adapters, opt_state, _ = fns.step(adapters, opt_state, base, tokens)
        moved = [
            not np.array_equal(x, y)
            for x, y in zip(jax.tree.leaves(before), jax.tree.leaves(adapters))
        ]
        assert all(moved), "every A and B must receive updates"

    def test_trained_adapters_transfer_through_merge(self, base, tokens):
        """The served model (merged) computes what training computed."""
        fns = lora.build_lora_train_step(CFG, LORA, lr=5e-2)
        adapters, opt_state = fns.init(jax.random.PRNGKey(3))
        for _ in range(5):
            adapters, opt_state, loss = fns.step(adapters, opt_state, base, tokens)
        merged = lora.merge(base, adapters, LORA)
        served_loss = float(burnin.loss_fn(merged, tokens, CFG))
        # the NEXT step's reported loss is computed from the same adapters
        _, _, train_loss = fns.step(adapters, opt_state, base, tokens)
        assert served_loss == pytest.approx(float(train_loss), rel=1e-3)  # bf16 cross-program fusion noise


class TestDownstreamPaths:
    def test_merged_model_decodes(self, base, tokens):
        ad = lora.init_adapters(jax.random.PRNGKey(4), CFG, LORA)
        ad["blocks"][0]["qkv"]["b"] = (
            jnp.ones_like(ad["blocks"][0]["qkv"]["b"]) * 0.01
        )
        merged = lora.merge(base, ad, LORA)
        prompt = tokens[:2, :6]
        out = decode.greedy_decode(merged, prompt, 8, cfg=CFG, batch_prefill=True)
        want = decode.greedy_decode(merged, prompt, 8, cfg=CFG)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(want))

    def test_merged_model_quantizes(self, base):
        ad = lora.init_adapters(jax.random.PRNGKey(4), CFG, LORA)
        q = quantize_blocks(lora.merge(base, ad, LORA))
        prompt = jnp.zeros((1, 4), jnp.int32)
        out = decode.greedy_decode(q, prompt, 4, cfg=CFG)
        assert out.shape == (1, 8)
