"""The sharing and selectors walkthrough spec families run hermetically
(reference demo/specs/mig+mps/ and demo/specs/selectors/ analogs — the
reference versions are manual, cluster-only, and partly reference deleted
classic-DRA CRDs; here every document is an executable test)."""

from pathlib import Path

import pytest

from k8s_dra_driver_tpu.e2e.harness import make_cluster
from k8s_dra_driver_tpu.e2e.spec_runner import apply_spec

SPECS = Path(__file__).parent.parent / "demo" / "specs"


@pytest.fixture
def cluster(tmp_path):
    # v5e-8: one 2x4 host — big enough for the sharing demo's full claim set
    # (2 chips + a 1x2 + a 2x2 = 8 chips packed disjointly on one node).
    return make_cluster(hosts=1, topology="v5e-8", work_dir=str(tmp_path))


class TestSharingWalkthrough:
    def _run(self, cluster):
        apply_spec(cluster, SPECS / "sharing" / "sharing-demo-claims.yaml")
        return apply_spec(cluster, SPECS / "sharing" / "sharing-demo-job.yaml")

    def test_job_expands_to_parallelism_pods(self, cluster):
        pods = self._run(cluster)
        assert len(pods) == 4
        assert {p.name for p in pods} == {f"sharing-demo-job-{i}" for i in range(4)}

    def test_all_pods_share_the_same_devices(self, cluster):
        pods = self._run(cluster)
        # one allocation per claim, shared by every pod of the Job
        first = {d["device_name"] for d in pods[0].devices}
        for p in pods[1:]:
            assert {d["device_name"] for d in p.devices} == first
        # four claims -> four distinct prepared device sets per pod:
        # 2 chips + a 1x2 subslice + a 2x2 subslice = 4 prepared devices
        assert len(pods[0].devices) == 4

    def test_sharing_wiring_reaches_the_containers(self, cluster):
        pods = self._run(cluster)
        env = pods[0].env
        # TimeSlicing Short (chip) and Medium (subslice) both prepared; the
        # merged pod env carries the quantum + daemon socket wiring.
        assert "TPU_QUEUE_QUANTUM_MS" in env
        assert "TPU_TOPOLOGY_DAEMON_SOCKET" in env
        # SpatialPartition: core fraction + HBM cap
        assert env["TPU_CORE_FRACTION"] == "50"
        assert env["TPU_HBM_LIMIT_MIB"] == "4096"

    def test_subslice_claims_respect_overlap(self, cluster):
        pods = self._run(cluster)
        names = {d["device_name"] for d in pods[0].devices}
        chip_devs = {n for n in names if n.startswith("tpu-") and "slice" not in n}
        slice_devs = names - chip_devs
        assert len(chip_devs) == 2
        assert len(slice_devs) == 2
        # the 1x2 and the 2x2 subslices must not share chips with each other
        # (the allocator's chip-marker non-overlap invariant)
        shapes = {n.split("-")[2] for n in slice_devs}
        assert shapes == {"1x2", "2x2"}


class TestSelectorsWalkthrough:
    def _run(self, cluster):
        apply_spec(cluster, SPECS / "selectors" / "claims.yaml")
        return {
            p.name: p
            for p in apply_spec(cluster, SPECS / "selectors" / "pods.yaml")
        }

    def test_all_recipes_schedule(self, cluster):
        pods = self._run(cluster)
        assert set(pods) == {
            "by-generation-pod",
            "by-capacity-pod",
            "by-position-pod",
            "same-host-pair-pod",
        }

    def test_by_position_gets_the_origin_column(self, cluster):
        pods = self._run(cluster)
        (dev,) = pods["by-position-pod"].devices
        assert dev["device_name"] == "tpu-slice-1x2-0-0"

    def test_same_host_pair_is_co_placed(self, cluster):
        pods = self._run(cluster)
        devs = pods["same-host-pair-pod"].devices
        assert len(devs) == 2
        assert devs[0]["device_name"] != devs[1]["device_name"]

    def test_by_capacity_quantity_comparison_selects_a_chip(self, cluster):
        pods = self._run(cluster)
        (dev,) = pods["by-capacity-pod"].devices
        assert dev["device_name"].startswith("tpu-")
