"""Span tracer tests + end-to-end /debug/traces exposure."""

import json
import urllib.request

from k8s_dra_driver_tpu.utils.tracing import Tracer


class TestTracer:
    def test_nested_spans(self):
        t = Tracer()
        with t.span("outer", claim="default/c1"):
            with t.span("inner-a"):
                pass
            with t.span("inner-b"):
                pass
        (root,) = t.recent()
        assert root["name"] == "outer"
        assert root["attributes"] == {"claim": "default/c1"}
        assert [c["name"] for c in root["children"]] == ["inner-a", "inner-b"]
        assert root["durationMs"] >= 0

    def test_span_survives_exception(self):
        t = Tracer()
        try:
            with t.span("boom"):
                raise RuntimeError("x")
        except RuntimeError:
            pass
        assert t.recent()[0]["name"] == "boom"

    def test_ring_buffer_bounded(self):
        t = Tracer(capacity=5)
        for i in range(10):
            with t.span(f"s{i}"):
                pass
        names = [s["name"] for s in t.recent()]
        assert names == ["s9", "s8", "s7", "s6", "s5"]

    def test_prepare_path_traced_and_exposed(self, tmp_path):
        from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
        from k8s_dra_driver_tpu.plugin.driver import ClaimRef, Driver, DriverConfig
        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        cluster = make_cluster(hosts=1, work_dir=str(tmp_path))
        driver = Driver(
            cluster.server,
            DriverConfig(
                node_name="tpu-host-0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
                publish=False,
            ),
        )
        claim = cluster.server.create(simple_claim("traced"))
        allocated = cluster.allocator.allocate(claim, node_name="tpu-host-0")
        driver.node_prepare_resources(
            [ClaimRef(uid=allocated.metadata.uid, name="traced", namespace="default")]
        )

        srv = DiagnosticsServer(port=0)
        srv.start()
        try:
            traces = json.loads(
                urllib.request.urlopen(
                    f"http://127.0.0.1:{srv.port}/debug/traces"
                ).read()
            )
        finally:
            srv.stop()
        prepare = next(t for t in traces if t["name"] == "NodePrepareResources")
        assert prepare["attributes"]["claim"] == "default/traced"
        child_names = [c["name"] for c in prepare["children"]]
        assert "Prepare.resolveAndApplyConfigs" in child_names
        # Group commit: the durable checkpoint write happens once per
        # NodePrepareResources call, after the per-claim spans close.
        assert "Prepare.writeCheckpoint" not in child_names
        assert any(t["name"] == "Prepare.commitCheckpointBatch" for t in traces)
