"""Unit coverage for the shared retry/backoff/circuit-breaker policy layer
(utils/retry.py) — the machinery every API-facing loop in the tree rides."""

import random
import urllib.error

import pytest

from k8s_dra_driver_tpu.kube.fakeserver import APIError, Conflict, NotFound
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY
from k8s_dra_driver_tpu.utils.retry import (
    Backoff,
    CircuitBreaker,
    ContentionBackoff,
    CircuitOpenError,
    RetryBudget,
    RetryPolicy,
    call_with_retry,
    is_retryable,
)


class TestClassification:
    def test_5xx_and_429_retry(self):
        assert is_retryable(APIError(500, "boom"))
        assert is_retryable(APIError(503, "unavailable"))
        assert is_retryable(APIError(429, "slow down"))

    def test_other_4xx_do_not(self):
        assert not is_retryable(NotFound("gone"))
        assert not is_retryable(Conflict("rv moved"))
        assert not is_retryable(APIError(400, "bad request"))

    def test_transport_errors_retry(self):
        assert is_retryable(urllib.error.URLError("connection refused"))
        assert is_retryable(ConnectionResetError("peer reset"))
        assert is_retryable(TimeoutError("timed out"))
        import http.client

        assert is_retryable(http.client.IncompleteRead(b""))

    def test_http_error_duck_types_on_code(self):
        err = urllib.error.HTTPError("http://x", 502, "bad gateway", {}, None)
        assert is_retryable(err)
        err404 = urllib.error.HTTPError("http://x", 404, "nope", {}, None)
        assert not is_retryable(err404)

    def test_plain_exceptions_do_not(self):
        assert not is_retryable(ValueError("logic bug"))
        assert not is_retryable(KeyError("missing"))

    def test_circuit_open_error_is_retryable_later(self):
        # OSError + code 503: every transient-error guard in the tree
        # already treats it right.
        exc = CircuitOpenError("open")
        assert isinstance(exc, OSError)
        assert is_retryable(exc)


class TestBackoff:
    def test_exponential_growth_and_cap(self):
        bo = Backoff(RetryPolicy(base_delay_s=0.1, max_delay_s=1.0,
                                 multiplier=2.0, jitter=0.0))
        assert [round(bo.next_delay(), 3) for _ in range(6)] == [
            0.1, 0.2, 0.4, 0.8, 1.0, 1.0
        ]

    def test_jitter_stays_in_band(self):
        policy = RetryPolicy(base_delay_s=1.0, max_delay_s=1.0, jitter=0.5)
        bo = Backoff(policy, rng=random.Random(7))
        for _ in range(50):
            d = bo.next_delay()
            assert 0.5 <= d <= 1.0

    def test_reset_restarts_schedule(self):
        bo = Backoff(RetryPolicy(base_delay_s=0.1, max_delay_s=1.0, jitter=0.0))
        bo.next_delay()
        bo.next_delay()
        assert bo.attempts == 2
        bo.reset()
        assert bo.attempts == 0
        assert bo.next_delay() == pytest.approx(0.1)

    def test_sleep_is_injectable(self):
        slept = []
        bo = Backoff(
            RetryPolicy(base_delay_s=0.25, max_delay_s=1.0, jitter=0.0),
            sleep=slept.append,
        )
        bo.sleep()
        bo.sleep()
        assert slept == [0.25, 0.5]


class TestRetryBudget:
    def test_drains_and_refills(self):
        budget = RetryBudget(cap=2.0, refill_per_success=0.5)
        assert budget.take()
        assert budget.take()
        assert not budget.take()  # drained
        budget.on_success()
        budget.on_success()  # +1.0 total
        assert budget.take()
        assert not budget.take()

    def test_refill_caps(self):
        budget = RetryBudget(cap=1.0, refill_per_success=5.0)
        budget.on_success()
        assert budget.remaining() == 1.0


class TestCallWithRetry:
    def test_success_after_transients(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise APIError(503, "unavailable")
            return "ok"

        slept = []
        result = call_with_retry(
            flaky,
            policy=RetryPolicy(max_attempts=5, base_delay_s=0.01, jitter=0.0),
            op="test-op",
            sleep=slept.append,
        )
        assert result == "ok"
        assert calls["n"] == 3
        assert len(slept) == 2
        retries = REGISTRY.counter("dra_api_retries_total")
        assert retries.value(op="test-op", reason="503") == 2
        events = [e for e in JOURNAL.tail(component="retry")
                  if e["event"] == "call.retry"]
        assert len(events) == 2

    def test_non_retryable_raises_immediately(self):
        calls = {"n": 0}

        def wrong():
            calls["n"] += 1
            raise NotFound("no such object")

        with pytest.raises(NotFound):
            call_with_retry(wrong, sleep=lambda _: None)
        assert calls["n"] == 1

    def test_max_attempts_exhausted(self):
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise APIError(500, "down")

        with pytest.raises(APIError):
            call_with_retry(
                always,
                policy=RetryPolicy(max_attempts=3, base_delay_s=0.0, jitter=0.0),
                sleep=lambda _: None,
            )
        assert calls["n"] == 3

    def test_budget_exhaustion_stops_retries(self):
        budget = RetryBudget(cap=1.0, refill_per_success=0.0)
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise APIError(500, "down")

        with pytest.raises(APIError):
            call_with_retry(
                always,
                policy=RetryPolicy(max_attempts=10, base_delay_s=0.0, jitter=0.0),
                budget=budget,
                sleep=lambda _: None,
            )
        # one retry allowed by the single token, then fail fast
        assert calls["n"] == 2


class TestCircuitBreaker:
    def _clock(self):
        state = {"t": 0.0}

        def clock():
            return state["t"]

        return state, clock

    def test_opens_after_threshold_and_fails_fast(self):
        state, clock = self._clock()
        br = CircuitBreaker("slices", failure_threshold=3, reset_timeout_s=10.0,
                            clock=clock)
        for _ in range(3):
            assert br.allow()
            br.on_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow()  # cooling down: fail fast

        def never_called():
            raise AssertionError("breaker must short-circuit")

        with pytest.raises(CircuitOpenError):
            call_with_retry(never_called, breaker=br, sleep=lambda _: None)

    def test_half_open_probe_closes_on_success(self):
        state, clock = self._clock()
        br = CircuitBreaker("slices", failure_threshold=1, reset_timeout_s=5.0,
                            clock=clock)
        br.on_failure()
        assert br.state == CircuitBreaker.OPEN
        state["t"] = 6.0
        assert br.allow()  # the probe
        assert br.state == CircuitBreaker.HALF_OPEN
        assert not br.allow()  # second concurrent probe rejected
        br.on_success()
        assert br.state == CircuitBreaker.CLOSED
        assert br.allow()

    def test_failed_probe_reopens(self):
        state, clock = self._clock()
        br = CircuitBreaker("slices", failure_threshold=1, reset_timeout_s=5.0,
                            clock=clock)
        br.on_failure()
        state["t"] = 6.0
        assert br.allow()
        br.on_failure()
        assert br.state == CircuitBreaker.OPEN
        assert not br.allow()

    def test_observability(self):
        state, clock = self._clock()
        br = CircuitBreaker("pods", failure_threshold=1, reset_timeout_s=5.0,
                            clock=clock)
        gauge = REGISTRY.gauge("dra_circuit_state")
        assert gauge.value(endpoint="pods") == 0
        br.on_failure()
        assert gauge.value(endpoint="pods") == 2
        state["t"] = 6.0
        br.allow()
        assert gauge.value(endpoint="pods") == 1
        br.on_success()
        assert gauge.value(endpoint="pods") == 0
        transitions = REGISTRY.counter("dra_circuit_transitions_total")
        assert transitions.value(endpoint="pods", to="open") == 1
        assert transitions.value(endpoint="pods", to="closed") == 1
        states = [e["event"] for e in JOURNAL.tail(component="retry")
                  if e["event"].startswith("breaker.")]
        assert states == ["breaker.open", "breaker.half_open", "breaker.closed"]

    def test_only_retryable_failures_trip(self):
        # call_with_retry feeds the breaker only retryable-class failures.
        br = CircuitBreaker("claims", failure_threshold=1)

        def wrong():
            raise NotFound("missing")

        with pytest.raises(NotFound):
            call_with_retry(wrong, breaker=br, sleep=lambda _: None)
        assert br.state == CircuitBreaker.CLOSED


class TestContentionBackoff:
    def _fixed_rng(self, value=1.0):
        # rng.random() == 1.0 makes the jitter factor exactly 0.5:
        # deterministic delays without monkeypatching.
        class R:
            def random(self):
                return value
        return R()

    def test_no_delay_without_a_conflict_streak(self):
        b = ContentionBackoff(rng=self._fixed_rng())
        assert b.next_delay() == 0.0
        b.on_conflict()
        b.on_success()
        assert b.next_delay() == 0.0, "success must reset the streak"

    def test_delay_grows_with_streak_and_density(self):
        b = ContentionBackoff(
            base_delay_s=0.001, max_delay_s=10.0, window=8,
            rng=self._fixed_rng(),
        )
        b.on_conflict()
        first = b.next_delay()
        for _ in range(4):
            b.on_conflict()
        later = b.next_delay()
        assert later > first, "streak under full density must compound"
        assert b.density == 1.0
        assert b.streak == 5

    def test_density_discounts_isolated_conflicts(self):
        dense = ContentionBackoff(window=8, rng=self._fixed_rng())
        for _ in range(6):
            dense.on_conflict()
        quiet = ContentionBackoff(window=8, rng=self._fixed_rng())
        for _ in range(5):
            quiet.on_success()
        quiet.on_conflict()
        # Same API, same streak length 1?  No: force equal streaks by
        # rebuilding the dense one's streak to 1 via success+conflict.
        dense.on_success()
        dense.on_conflict()
        assert dense.streak == quiet.streak == 1
        assert dense.density > quiet.density
        assert dense.next_delay() > quiet.next_delay()

    def test_success_resets_streak_but_keeps_density_history(self):
        b = ContentionBackoff(window=4, rng=self._fixed_rng())
        for _ in range(4):
            b.on_conflict()
        b.on_success()
        assert b.streak == 0
        assert b.next_delay() == 0.0
        assert b.density == 0.75, "window keeps the storm in view"

    def test_delay_caps_and_sleep_skips_zero(self):
        slept = []
        b = ContentionBackoff(
            base_delay_s=0.01, max_delay_s=0.05,
            rng=self._fixed_rng(), sleep=slept.append,
        )
        b.sleep()
        assert slept == [], "zero delay must not call sleep at all"
        for _ in range(40):
            b.on_conflict()
        assert b.next_delay() <= 0.05
        b.sleep()
        assert len(slept) == 1 and slept[0] <= 0.05

    def test_window_must_be_positive(self):
        with pytest.raises(ValueError):
            ContentionBackoff(window=0)
