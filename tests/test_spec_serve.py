"""Speculative serving: continuous batching where every greedy slot
advances up to gamma+1 tokens per round — streams bit-equal the plain
engine's."""

import jax
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin
from k8s_dra_driver_tpu.models.quant import quantize_blocks
from k8s_dra_driver_tpu.models.serve import ServeEngine

CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128
)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _prompts(n, rng=7):
    r = np.random.RandomState(rng)
    return [r.randint(0, CFG.vocab_size, size=r.randint(3, 12)).tolist() for _ in range(n)]


def _streams(engine, reqs):
    pending = list(reqs)
    out = {}
    for _ in range(5000):
        while pending:
            prompt, max_tokens = pending[0]
            try:
                engine.submit(prompt, max_tokens)
                pending.pop(0)
            except RuntimeError:
                break
        stepped = engine.step()
        for c in engine.completions():
            out[c.request_id] = c.generated
        if not pending and stepped == 0 and engine.free_slots() == engine.n_slots:
            return out
    raise RuntimeError("queue did not drain")


class TestSpecServe:
    def test_streams_identical_to_plain_engine(self, params):
        """int8 self-draft through the engine: same tokens as the plain
        engine, requests joining and leaving mid-flight."""
        reqs = [(p, 14) for p in _prompts(5)]
        plain = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=16)
        spec = ServeEngine(
            params=params, cfg=CFG, n_slots=2, prompt_bucket=16, spec_gamma=3
        )
        assert _streams(plain, reqs) == _streams(spec, reqs)

    def test_full_acceptance_round_count(self, params):
        """Self-draft with the TARGET weights accepts everything: a
        request commits gamma+1 tokens per round."""
        gamma, steps = 3, 20
        eng = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16,
            spec_gamma=gamma, draft_params=params,
        )
        eng.submit(_prompts(1)[0], steps)
        rounds = 0
        while eng.free_slots() < eng.n_slots:
            eng.step()
            rounds += 1
        # 1 token at admission, then gamma+1 per round
        assert rounds == -(-(steps - 1) // (gamma + 1))
        gen = eng.completions()[0].generated
        plain = ServeEngine(params=params, cfg=CFG, n_slots=1, prompt_bucket=16)
        plain.submit(_prompts(1)[0], steps)
        plain.run_until_drained()
        assert gen == plain.completions()[0].generated

    def test_eos_clips_mid_round(self, params):
        prompt = _prompts(1, rng=3)[0]
        plain = ServeEngine(params=params, cfg=CFG, n_slots=1, prompt_bucket=16)
        plain.submit(prompt, 12)
        plain.run_until_drained()
        stream = plain.completions()[0].generated
        eos = stream[4]  # retire mid-stream (and possibly mid-round)
        want = stream[: stream.index(eos) + 1]
        spec = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16,
            spec_gamma=3, eos_id=eos,
        )
        spec.submit(prompt, 12)
        spec.run_until_drained()
        assert spec.completions()[0].generated == want

    def test_shallow_draft(self, params):
        """Any same-vocab draft works — here the target's first layer."""
        shallow = dict(params, blocks=params["blocks"][:1])
        reqs = [(p, 10) for p in _prompts(3, rng=11)]
        plain = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=16)
        spec = ServeEngine(
            params=params, cfg=CFG, n_slots=2, prompt_bucket=16,
            spec_gamma=2, draft_params=shallow,
        )
        assert _streams(plain, reqs) == _streams(spec, reqs)
        # the draft cache really is shallower
        assert spec._d_cache.k.shape[0] == 1

    def test_int8_draft_is_default(self, params):
        eng = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16, spec_gamma=2
        )
        ref = quantize_blocks(params)
        assert jax.tree.structure(eng.draft_params) == jax.tree.structure(ref)

    def test_validation(self, params):
        eng = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16, spec_gamma=4
        )
        with pytest.raises(ValueError, match="temperature"):
            eng.submit([1, 2, 3], 4, temperature=0.7)
        with pytest.raises(ValueError, match="slack"):
            eng.submit([1, 2, 3], CFG.max_seq - 3)  # no room for gamma

    def test_slack_bound_is_exact(self, params):
        """The verify-window bound admits EXACTLY up to the deepest write:
        a slot's last round starts at pos = plen + max_tokens - 2 and
        writes pos..pos+gamma, so plen + max_tokens + gamma - 1 == max_seq
        must be admissible — and run to completion without tripping the
        completion-path cache-overrun assertion."""
        gamma = 4
        eng = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16,
            spec_gamma=gamma,
        )
        plen = 3
        max_tokens = CFG.max_seq - plen - gamma + 1  # exactly at the bound
        eng.submit([1, 2, 3], max_tokens)
        for _ in range(5000):
            eng.step()
            done = eng.completions()
            if done:
                assert len(done[0].generated) == max_tokens
                break
        else:
            raise AssertionError("request did not complete")
        # one past the bound is rejected
        with pytest.raises(ValueError, match="slack"):
            eng.submit([1, 2, 3], max_tokens + 1)

    def test_draft_cache_isolated_per_slot(self, params):
        """A retiring slot's stale draft rows never leak into a new
        request admitted to the same slot."""
        eng = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16,
            spec_gamma=2, draft_params=params,
        )
        for prompt in _prompts(3, rng=5):
            eng.submit(prompt, 8)
            eng.run_until_drained()
        streams = {c.request_id: c.generated for c in eng.completions()}
        plain = ServeEngine(params=params, cfg=CFG, n_slots=1, prompt_bucket=16)
        for prompt in _prompts(3, rng=5):
            plain.submit(prompt, 8)
            plain.run_until_drained()
        want = {c.request_id: c.generated for c in plain.completions()}
        assert streams == want



class TestSpecComposition:
    """The round-5 composition closes: speculative rounds on the DENSE
    engine now compose with the slot-sharded mesh and with the prefix
    cache — streams stay bit-equal the plain engine's either way."""

    def _mesh(self, n):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices("cpu")[:n]), ("data",))

    def test_spec_mesh_streams_identical(self, params):
        reqs = [(p, 12) for p in _prompts(5)]
        plain = ServeEngine(params=params, cfg=CFG, n_slots=4, prompt_bucket=16)
        spec = ServeEngine(
            params=params, cfg=CFG, n_slots=4, prompt_bucket=16,
            spec_gamma=3, mesh=self._mesh(4), slot_axis="data",
        )
        assert _streams(plain, reqs) == _streams(spec, reqs)

    def test_spec_prefix_streams_identical(self, params):
        """Shared system prompt + speculation: the prefix-hit admission
        path feeds the draft cache exactly like the miss path."""
        sys_prefix = list(range(1, 9))  # 8 tokens = the prefix bucket
        reqs = [(sys_prefix + p, 10) for p in _prompts(6, rng=3)]
        plain = ServeEngine(
            params=params, cfg=CFG, n_slots=2, prompt_bucket=32
        )
        spec = ServeEngine(
            params=params, cfg=CFG, n_slots=2, prompt_bucket=32,
            spec_gamma=2, prefix_bucket=8, prefix_cache_entries=4,
        )
        want = _streams(plain, reqs)
        assert _streams(spec, reqs) == want
        assert spec.prefix_hits > 0  # the cache actually served hits

    def test_spec_mesh_prefix_lora_all_at_once(self, params):
        """Everything the dense engine offers in one configuration."""
        from k8s_dra_driver_tpu.models import lora

        lcfg = lora.LoraConfig(rank=2, alpha=4.0)
        bank = lora.stack_adapters(
            CFG, lcfg,
            [lora.init_adapters(jax.random.PRNGKey(5), CFG, lcfg)],
        )
        sys_prefix = list(range(1, 9))
        reqs = [(sys_prefix + p, 8) for p in _prompts(4, rng=11)]

        def drive(**kw):
            eng = ServeEngine(
                params=params, cfg=CFG, n_slots=4, prompt_bucket=32,
                adapter_bank=bank, **kw,
            )
            pending = list(reqs)
            out = {}
            for _ in range(5000):
                while pending:
                    prompt, mt = pending[0]
                    try:
                        eng.submit(prompt, mt, adapter=1)
                        pending.pop(0)
                    except RuntimeError:
                        break
                stepped = eng.step()
                for c in eng.completions():
                    out[c.request_id] = c.generated
                if (not pending and stepped == 0
                        and eng.free_slots() == eng.n_slots):
                    return out
            raise RuntimeError("queue did not drain")

        want = drive()
        got = drive(
            spec_gamma=2, prefix_bucket=8, mesh=self._mesh(4),
            slot_axis="data",
        )
        assert got == want
