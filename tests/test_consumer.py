"""Consumer-side runtime: ClaimContext resolution, daemon cooperation, and
the closed loop from a PREPARED claim's CDI env to an attached consumer.

The reference's consumer story is `nvidia-smi -L` in pod logs; ours is
`consumer.attach()` → mesh/lease, so the whole env contract gets an
executable consumer-side test."""

import json
import threading
import time

import pytest

from k8s_dra_driver_tpu import consumer
from k8s_dra_driver_tpu.plugin.topology_daemon import TopologyDaemonServer


class TestAttach:
    def test_exclusive_defaults(self):
        ctx = consumer.attach(environ={}, init_distributed=False)
        assert ctx.sharing_strategy == "exclusive"
        assert not ctx.shared and not ctx.multi_host
        assert ctx.visible_devices == []

    def test_full_wiring_resolution(self):
        env = {
            "TPU_VISIBLE_DEVICES": "1,3",
            "TPU_CHIPS_PER_PROCESS_BOUNDS": "1,1,1",
            "TPU_PROCESS_BOUNDS": "2,2,1",
            "TPU_PROCESS_COORD": "1,0,0",
            "TPU_PARTITION_INDEX": "1",
            "TPU_SHARING_STRATEGY": "spatial-partition",
            "TPU_HBM_LIMIT_MIB": "4096",
            "TPU_TOPOLOGY_DAEMON_SOCKET": "/run/tpu-topology/u.sock",
            "TPU_WORKER_ID": "2",
            "TPU_HOST_COUNT": "4",
            "JAX_COORDINATOR_ADDRESS": "h0:8476",
        }
        ctx = consumer.attach(environ=env, init_distributed=False)
        assert ctx.visible_devices == [1, 3]
        assert ctx.partition_index == 1
        assert ctx.shared and ctx.multi_host
        assert ctx.hbm_limit_mib == 4096
        doc = ctx.to_json()
        assert doc["process_coord"] == "1,0,0"
        assert "queue_quantum_ms" not in doc  # empty fields dropped


class TestDaemonCooperation:
    @pytest.fixture
    def daemon(self, tmp_path):
        server = TopologyDaemonServer(
            str(tmp_path / "claim.sock"),
            claim_uid="uid-c",
            partition_spec="2,1,1",
            partitions=[
                {"index": 0, "visible_devices": "0", "process_coord": "0,0,0"},
                {"index": 1, "visible_devices": "1", "process_coord": "1,0,0"},
            ],
            quantum_ms=10,
        )
        server.start()
        yield server
        server.stop()

    def ctx(self, daemon, strategy, **extra):
        env = {
            "TPU_SHARING_STRATEGY": strategy,
            "TPU_TOPOLOGY_DAEMON_SOCKET": daemon.socket_path,
            **extra,
        }
        return consumer.attach(environ=env, init_distributed=False)

    def test_spatial_consumer_observes_partition(self, daemon):
        ctx = self.ctx(daemon, "spatial-partition", TPU_PARTITION_INDEX="1")
        reg = ctx.register(consumer_id="container-b")
        assert reg["ok"]
        assert reg["partition"]["visible_devices"] == "1"

    def test_lease_roundtrip_and_scoping(self, daemon):
        ctx0 = self.ctx(
            daemon, "time-slicing",
            TPU_VISIBLE_DEVICES="0", TPU_QUEUE_QUANTUM_MS="1000",
        )
        ctx1 = self.ctx(
            daemon, "time-slicing",
            TPU_VISIBLE_DEVICES="1", TPU_QUEUE_QUANTUM_MS="10",
        )
        with ctx0.lease(consumer_id="a") as grant:
            assert grant["ok"]
            # a different chip's consumer is not serialized behind us
            start = time.time()
            with ctx1.lease(consumer_id="b") as g2:
                assert g2["ok"]
            assert time.time() - start < 1.0
        # after release the same scope can be re-acquired immediately
        with ctx0.lease(consumer_id="c") as g3:
            assert g3["ok"]

    def test_lease_contention_blocks_same_scope(self, daemon):
        ctx = self.ctx(
            daemon, "time-slicing",
            TPU_VISIBLE_DEVICES="0", TPU_QUEUE_QUANTUM_MS="2000",
        )
        entered = []

        def holder():
            with ctx.lease(consumer_id="holder"):
                entered.append("holder")
                time.sleep(0.3)

        t = threading.Thread(target=holder)
        t.start()
        time.sleep(0.05)
        start = time.time()
        with ctx.lease(consumer_id="waiter", timeout_ms=5000) as g:
            assert g["ok"]
        assert time.time() - start > 0.1  # actually waited for the release
        t.join()

    def test_exclusive_lease_is_noop(self):
        ctx = consumer.attach(environ={}, init_distributed=False)
        with ctx.lease() as grant:
            assert grant is None


class TestClosedLoop:
    def test_prepared_claim_env_attaches(self, api_server, tmp_path):
        """claim → allocate → prepare → CDI env → consumer.attach():
        the full env contract, both sides."""
        from k8s_dra_driver_tpu import DRIVER_NAME
        from k8s_dra_driver_tpu.kube.objects import DeviceRequest
        from tests.test_prepare import allocate, daemon_controller, opaque
        from tests.test_allocator import install_classes, publish_host, TPU_CLASS
        from k8s_dra_driver_tpu.api import API_VERSION
        from k8s_dra_driver_tpu.plugin.device_state import DeviceState, DeviceStateConfig

        install_classes(api_server)
        publish_host(api_server)
        state = DeviceState(
            api_server,
            DeviceStateConfig(
                node_name="host0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
                daemon_backoff_initial=0.001,
                daemon_backoff_steps=2,
            ),
        )
        watch = daemon_controller(api_server)
        claim = allocate(
            api_server,
            "consumer-loop",
            [DeviceRequest(name="t", device_class_name=TPU_CLASS, count=2)],
            config=[
                opaque(
                    {
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {"strategy": "SpatialPartition"},
                    }
                )
            ],
        )
        state.prepare(claim)
        spec = json.loads(
            (tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json").read_text()
        )
        # each CDI device entry is one container's env: attach both
        coords = set()
        for dev in spec["devices"]:
            env = dict(e.split("=", 1) for e in dev["containerEdits"]["env"])
            ctx = consumer.attach(environ=env, init_distributed=False)
            assert ctx.sharing_strategy == "spatial-partition"
            assert len(ctx.visible_devices) == 1
            assert ctx.daemon_socket.endswith(f"{claim.metadata.uid}.sock")
            coords.add(ctx.process_coord)
        assert len(coords) == 2  # disjoint slots
        watch.stop()


class TestConsumerIdentity:
    def test_same_pod_containers_get_distinct_ids(self, tmp_path):
        """HOSTNAME is the POD name — identical across a pod's containers;
        default consumer ids must still differ or same-pod TimeSlicing
        sharers would alias into one lease holder."""
        server = TopologyDaemonServer(str(tmp_path / "c.sock"), quantum_ms=1000)
        server.start()
        try:
            env = {
                "TPU_SHARING_STRATEGY": "time-slicing",
                "TPU_TOPOLOGY_DAEMON_SOCKET": server.socket_path,
                "TPU_VISIBLE_DEVICES": "0",
                "TPU_QUEUE_QUANTUM_MS": "1000",
            }
            a = consumer.attach(environ=env, init_distributed=False)
            b = consumer.attach(environ=env, init_distributed=False)
            assert a._consumer_id != b._consumer_id
            # and the daemon really serializes them on the same chip scope
            with a.lease() as g1:
                assert g1["ok"]
                client = b.daemon_client()
                try:
                    resp = client.acquire(quantum_ms=1000, timeout_ms=50, scope="0")
                    assert not resp["ok"] and resp["error"] == "timeout"
                finally:
                    client.close()
        finally:
            server.stop()


class TestDaemonConnectRetry:
    def test_client_waits_for_late_daemon(self, tmp_path):
        """The daemon Deployment may start after the consumer container:
        daemon_client retries instead of crash-looping the pod."""
        import threading

        sock = tmp_path / "late.sock"
        env = {
            "TPU_SHARING_STRATEGY": "spatial-partition",
            "TPU_TOPOLOGY_DAEMON_SOCKET": str(sock),
        }
        ctx = consumer.attach(environ=env, init_distributed=False)
        server = TopologyDaemonServer(str(sock), quantum_ms=5)

        t = threading.Timer(0.4, server.start)
        t.start()
        try:
            client = ctx.daemon_client(retries=20, retry_delay_s=0.1)
            assert client.info()["ok"]
            client.close()
        finally:
            t.join()
            server.stop()

    def test_absent_daemon_fails_loudly(self, tmp_path):
        import pytest

        env = {
            "TPU_SHARING_STRATEGY": "spatial-partition",
            "TPU_TOPOLOGY_DAEMON_SOCKET": str(tmp_path / "never.sock"),
        }
        ctx = consumer.attach(environ=env, init_distributed=False)
        with pytest.raises(ConnectionError, match="not reachable"):
            ctx.daemon_client(retries=2, retry_delay_s=0.05)


class TestServeDemo:
    def test_serve_demo_runs_to_completion(self, capsys):
        """`consumer --serve-demo` drains the paged engine on whatever
        devices the claim wired (CPU here) and prints one JSON summary —
        the inference analog of the nvidia-smi pod-log check."""
        rc = consumer.main(["--serve-demo"])
        assert rc == 0
        lines = [json.loads(line) for line in capsys.readouterr().out.splitlines()]
        summary = next(d["serve_demo"] for d in lines if "serve_demo" in d)
        assert summary["completed"] == 4
        assert summary["generated_tokens"] == 12 + 10 + 8 + 6
        assert summary["prefix_block_hits"] > 0  # the shared block paid off
        assert summary["pool_free_blocks"] > 0
        # the 8-device CPU mesh means the demo ran the SHARDED engine
        assert summary["sharded_over"] == 2
