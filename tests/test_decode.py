"""Incremental decode vs the full forward pass (models/decode.py).

Correctness contract: the KV-cache step is algebraically the same model —
teacher-forced decode must reproduce burnin.forward logits, and greedy
generation must match an (expensive) full-recompute reference loop."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_dra_driver_tpu.models import burnin, decode


def setup(batch=2, seq=16, f32=True):
    cfg = burnin.TINY
    params = burnin.init_params(jax.random.PRNGKey(0), cfg)
    if f32:
        # bf16 accumulation-order noise would mask real bugs; the
        # equivalence contract is pinned in f32, bf16 gets a smoke test.
        params = jax.tree.map(lambda a: a.astype(jnp.float32), params)
    tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=batch, seq=seq)
    return cfg, params, tokens


class TestDecode:
    def test_teacher_forced_matches_forward(self):
        cfg, params, tokens = setup()
        b, s = tokens.shape
        want = burnin.forward(params, tokens, cfg)  # [B, S, V]

        cache = decode.init_cache(cfg, b, s)
        step = jax.jit(lambda c, t, p: decode.decode_step(params, c, t, p, cfg=cfg))
        got = []
        for pos in range(s):
            logits, cache = step(cache, tokens[:, pos], pos)
            got.append(logits)
        got = jnp.stack(got, axis=1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-4)

    def test_greedy_matches_full_recompute_reference(self):
        cfg, params, tokens = setup(batch=2, seq=20)
        prompt = tokens[:, :6]
        steps = 6

        got = jax.jit(
            lambda p: decode.greedy_decode(params, p, steps, cfg=cfg)
        )(prompt)

        # reference: recompute the whole forward each step, argmax the tail
        ref = prompt
        for _ in range(steps):
            logits = burnin.forward(params, ref, cfg)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(ref.dtype)
            ref = jnp.concatenate([ref, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_prompt_positions_unmodified(self):
        cfg, params, tokens = setup()
        prompt = tokens[:, :5]
        out = decode.greedy_decode(params, prompt, 3, cfg=cfg)
        np.testing.assert_array_equal(np.asarray(out[:, :5]), np.asarray(prompt))
        assert out.shape == (2, 8)

    def test_bf16_cache_tracks_f32_path(self):
        cfg, params, tokens = setup()  # f32 params isolate the CACHE dtype
        prompt = tokens[:, :6]
        f32_out = decode.greedy_decode(params, prompt, 6, cfg=cfg)
        bf16_out = decode.greedy_decode(
            params, prompt, 6, cfg=cfg, cache_dtype=jnp.bfloat16
        )
        assert bf16_out.shape == f32_out.shape
        # bf16 cache may flip argmax on near-ties, but most tokens agree
        agreement = float(jnp.mean((bf16_out == f32_out).astype(jnp.float32)))
        assert agreement >= 0.75, f"bf16 cache diverged: {agreement:.2f} agreement"

    def test_overlong_generation_rejected(self):
        import pytest

        cfg, params, tokens = setup()
        with pytest.raises(ValueError, match="exceeds max_seq"):
            decode.greedy_decode(params, tokens, cfg.max_seq, cfg=cfg)

    def test_decode_with_tp_sharded_params(self):
        """Serving-style decode: params sharded over the model axis, GSPMD
        partitions the step — same tokens as the single-device path."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
        from tests.conftest import cpu_devices

        cfg, params, tokens = setup(seq=20)
        prompt = tokens[:, :6]
        want = decode.greedy_decode(params, prompt, 5, cfg=cfg)

        mesh = build_mesh(cpu_devices(4), MeshShape(data=1, seq=1, model=4))
        shardings = jax.tree.map(
            lambda spec: NamedSharding(mesh, spec),
            burnin.param_pspecs(cfg),
            is_leaf=lambda x: isinstance(x, P),
        )
        sharded = jax.device_put(params, shardings)
        with mesh:
            got = jax.jit(
                lambda p, t: decode.greedy_decode(p, t, 5, cfg=cfg)
            )(sharded, prompt)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


class TestSampling:
    def test_temperature_zero_is_greedy(self):
        cfg, params, tokens = setup(seq=20)
        prompt = tokens[:, :6]
        greedy = decode.greedy_decode(params, prompt, 5, cfg=cfg)
        sampled = decode.sample_decode(
            params, prompt, 5, cfg=cfg, key=jax.random.PRNGKey(0), temperature=0.0
        )
        np.testing.assert_array_equal(np.asarray(sampled), np.asarray(greedy))

    def test_sampling_is_seeded_and_in_vocab(self):
        cfg, params, tokens = setup(seq=20)
        prompt = tokens[:, :4]
        a = decode.sample_decode(
            params, prompt, 8, cfg=cfg, key=jax.random.PRNGKey(7), temperature=1.5
        )
        b = decode.sample_decode(
            params, prompt, 8, cfg=cfg, key=jax.random.PRNGKey(7), temperature=1.5
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # deterministic
        assert int(a.max()) < cfg.vocab_size and int(a.min()) >= 0

    def test_top_k_restricts_to_topk_of_distribution(self):
        cfg, params, tokens = setup(seq=20)
        prompt = tokens[:, :4]
        out = decode.sample_decode(
            params, prompt, 6, cfg=cfg, key=jax.random.PRNGKey(3),
            temperature=2.0, top_k=1,
        )
        # top_k=1 forces the argmax regardless of temperature
        greedy = decode.greedy_decode(params, prompt, 6, cfg=cfg)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(greedy))


class TestBatchPrefill:
    def test_prefill_cache_matches_incremental(self):
        """One parallel forward must fill the cache with the same k/v the
        sequential steps would (f32, exact to accumulation tolerance)."""
        cfg, params, tokens = setup(seq=12)
        b, s = tokens.shape
        cache_seq = decode.init_cache(cfg, b, s)
        for pos in range(s):
            _, cache_seq = decode.decode_step(
                params, cache_seq, tokens[:, pos], pos, cfg=cfg
            )
        cache_par, last_logits = decode.prefill(params, tokens, cfg, max_seq=s)
        np.testing.assert_allclose(
            np.asarray(cache_par.k), np.asarray(cache_seq.k), atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(cache_par.v), np.asarray(cache_seq.v), atol=2e-5
        )
        # and the last-position logits match the full forward
        want = burnin.forward(params, tokens, cfg)[:, -1]
        np.testing.assert_allclose(np.asarray(last_logits), np.asarray(want), atol=2e-4)

    def test_batch_prefill_generation_matches_sequential(self):
        cfg, params, tokens = setup(seq=20)
        prompt = tokens[:, :8]
        seq_out = decode.greedy_decode(params, prompt, 6, cfg=cfg)
        par_out = decode.sample_decode(
            params, prompt, 6, cfg=cfg, key=jax.random.PRNGKey(0),
            temperature=0.0, batch_prefill=True,
        )
        np.testing.assert_array_equal(np.asarray(par_out), np.asarray(seq_out))

    def test_batch_prefill_sampling_matches_sequential(self):
        # position-indexed keys: both prefill modes sample the same tokens
        cfg, params, tokens = setup(seq=20)
        prompt = tokens[:, :8]
        kwargs = dict(cfg=cfg, key=jax.random.PRNGKey(5), temperature=1.3)
        a = decode.sample_decode(params, prompt, 6, **kwargs)
        b = decode.sample_decode(params, prompt, 6, batch_prefill=True, **kwargs)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_zero_steps_returns_prompt(self):
        cfg, params, tokens = setup()
        out = decode.sample_decode(
            params, tokens[:, :5], 0, cfg=cfg, key=jax.random.PRNGKey(0),
            batch_prefill=True,
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(tokens[:, :5]))
