"""Chart render tests through the first-party renderer (tools/helm_render).

The dev image has no helm binary; these tests close the "template output is
only exercised on a real cluster" gap by rendering the chart hermetically —
the render-test slot of the reference's CI (the reference itself only
validates via `helm install` on a live kind cluster,
demo/clusters/kind/scripts/install-dra-driver.sh)."""

from __future__ import annotations

import pathlib

import pytest
import yaml

from tools.helm_crosscheck import CONFIGS as CROSSCHECK_CONFIGS
from tools.helm_render import (
    ChartFail,
    RenderError,
    render_chart,
    render_chart_docs,
)

CHART = pathlib.Path(__file__).resolve().parent.parent / "deployments/helm/tpu-dra-driver"


def _by_kind(docs):
    out = {}
    for d in docs:
        out.setdefault(d["kind"], []).append(d)
    return out


@pytest.fixture(scope="module")
def default_docs():
    return render_chart_docs(CHART)


class TestDefaultRender:
    def test_all_templates_emit_valid_yaml(self, default_docs):
        assert len(default_docs) >= 8

    def test_expected_kinds_present(self, default_docs):
        kinds = _by_kind(default_docs)
        for kind in (
            "DaemonSet",
            "Deployment",
            "DeviceClass",
            "ServiceAccount",
            "ClusterRole",
            "ClusterRoleBinding",
            "ValidatingAdmissionPolicy",
            "ValidatingAdmissionPolicyBinding",
        ):
            assert kind in kinds, f"missing {kind}"

    def test_four_deviceclasses_with_driver_cel(self, default_docs):
        classes = _by_kind(default_docs)["DeviceClass"]
        names = {c["metadata"]["name"] for c in classes}
        assert names == {
            "tpu.google.com",
            "subslice.tpu.google.com",
            "membership.tpu.google.com",
            "slicegroup.tpu.google.com",
        }
        for c in classes:
            exprs = [s["cel"]["expression"] for s in c["spec"]["selectors"]]
            assert any("device.driver == 'tpu.google.com'" in e for e in exprs)

    def test_daemonset_wiring(self, default_docs):
        ds = _by_kind(default_docs)["DaemonSet"][0]
        assert ds["metadata"]["name"] == "tpu-dra-driver-kubelet-plugin"
        assert ds["metadata"]["namespace"] == "tpu-dra-driver"
        spec = ds["spec"]["template"]["spec"]
        names = [c["name"] for c in spec["containers"]]
        assert names == ["plugin", "topology-daemon"]
        plugin = spec["containers"][0]
        assert plugin["securityContext"]["privileged"] is True
        env = {e["name"]: e.get("value") for e in plugin["env"]}
        assert env["CDI_ROOT"] == "/var/run/cdi"
        assert env["LIBTPU_PATH"] == "/lib/libtpu.so"
        assert "TPUINFO_FAKE_TOPOLOGY" not in env  # real mode by default
        # helpers resolved inside labels
        assert ds["metadata"]["labels"]["app.kubernetes.io/name"] == "tpu-dra-driver"
        assert ds["metadata"]["labels"]["app.kubernetes.io/instance"] == "tpu-dra-driver"
        # toYaml|nindent blocks round-trip as structures
        assert spec["tolerations"] == [{"operator": "Exists", "effect": "NoSchedule"}]
        affinity = spec["affinity"]["nodeAffinity"]
        terms = affinity["requiredDuringSchedulingIgnoredDuringExecution"]["nodeSelectorTerms"]
        assert len(terms) == 2
        # volumes referenced by mounts all exist
        volumes = {v["name"] for v in spec["volumes"]}
        for c in spec["containers"]:
            for m in c.get("volumeMounts", []):
                assert m["name"] in volumes, f"dangling mount {m['name']}"

    def test_probes_rendered_when_port_enabled(self, default_docs):
        plugin = _by_kind(default_docs)["DaemonSet"][0]["spec"]["template"]["spec"]["containers"][0]
        assert plugin["livenessProbe"]["httpGet"]["path"] == "/healthz"
        assert plugin["ports"][0]["containerPort"] == 8080

    def test_vap_scopes_to_service_account_and_handles_delete(self, default_docs):
        vap = _by_kind(default_docs)["ValidatingAdmissionPolicy"][0]
        cond = vap["spec"]["matchConditions"][0]["expression"]
        assert (
            "system:serviceaccount:tpu-dra-driver:tpu-dra-driver-service-account"
            in cond
        )
        validation = vap["spec"]["validations"][0]["expression"]
        assert "DELETE" in validation and "oldObject" in validation

    def test_rbac_binds_the_rendered_service_account(self, default_docs):
        kinds = _by_kind(default_docs)
        sa = kinds["ServiceAccount"][0]["metadata"]
        binding = kinds["ClusterRoleBinding"][0]
        subject = binding["subjects"][0]
        assert subject["name"] == sa["name"]
        assert subject["namespace"] == sa["namespace"]
        assert binding["roleRef"]["name"] == kinds["ClusterRole"][0]["metadata"]["name"]

    def test_controller_env_joins_device_classes(self, default_docs):
        dep = _by_kind(default_docs)["Deployment"][0]
        env = {
            e["name"]: e.get("value")
            for e in dep["spec"]["template"]["spec"]["containers"][0]["env"]
        }
        assert env["DEVICE_CLASSES"] == "tpu,subslice,membership,slicegroup"


class TestVariants:
    def test_openshift_rolebinding_off_by_default(self, default_docs):
        kinds = _by_kind(default_docs)
        assert "RoleBinding" not in kinds  # explicit opt-in, never implicit

    def test_openshift_rolebinding_binds_privileged_scc(self):
        docs = render_chart_docs(
            CHART, values_override={"openshift": {"enabled": True}}
        )
        rb = _by_kind(docs)["RoleBinding"][0]
        assert rb["metadata"]["name"].endswith("-openshift-privileged")
        assert rb["roleRef"]["name"] == "system:openshift:scc:privileged"
        subject = rb["subjects"][0]
        assert subject["kind"] == "ServiceAccount"
        assert subject["namespace"] == rb["metadata"]["namespace"]

    def test_extender_disabled_by_default(self, default_docs):
        kinds = _by_kind(default_docs)
        assert "Service" not in kinds
        controller = next(
            d for d in kinds["Deployment"]
            if d["metadata"]["name"].endswith("-controller")
        )
        env = controller["spec"]["template"]["spec"]["containers"][0]["env"]
        assert all(e["name"] != "EXTENDER_PORT" for e in env)

    def test_extender_port_renders_service_and_env(self):
        docs = render_chart_docs(CHART, values_override={"extenderPort": 8090})
        kinds = _by_kind(docs)
        svc = next(
            d for d in kinds["Service"]
            if d["metadata"]["name"].endswith("-extender")
        )
        assert svc["spec"]["ports"][0]["port"] == 8090
        # the Service must select the controller pods that serve the webhook
        assert svc["spec"]["selector"]["app.kubernetes.io/component"] == "controller"
        controller = next(
            d for d in kinds["Deployment"]
            if d["metadata"]["name"].endswith("-controller")
        )
        env = {
            e["name"]: e["value"]
            for e in controller["spec"]["template"]["spec"]["containers"][0]["env"]
        }
        assert env["EXTENDER_PORT"] == "8090"

    def test_extender_tls_secret_mounts_and_env(self):
        docs = render_chart_docs(
            CHART,
            values_override={"extenderPort": 8090, "extenderTLSSecret": "ext-tls"},
        )
        kinds = _by_kind(docs)
        controller = next(
            d for d in kinds["Deployment"]
            if d["metadata"]["name"].endswith("-controller")
        )
        spec = controller["spec"]["template"]["spec"]
        env = {e["name"]: e["value"] for e in spec["containers"][0]["env"]}
        assert env["EXTENDER_TLS_CERT"] == "/etc/tpu-dra-extender-tls/tls.crt"
        assert env["EXTENDER_TLS_KEY"] == "/etc/tpu-dra-extender-tls/tls.key"
        mounts = spec["containers"][0]["volumeMounts"]
        assert any(
            m["mountPath"] == "/etc/tpu-dra-extender-tls" and m["readOnly"]
            for m in mounts
        )
        assert any(
            v.get("secret", {}).get("secretName") == "ext-tls"
            for v in spec["volumes"]
        )

    def test_tls_secret_inert_while_extender_disabled(self):
        """extenderTLSSecret with extenderPort=-1 must not mount the secret:
        a missing secret would wedge the pod for a feature that is off."""
        docs = render_chart_docs(
            CHART, values_override={"extenderTLSSecret": "ext-tls"}
        )
        controller = next(
            d for d in _by_kind(docs)["Deployment"]
            if d["metadata"]["name"].endswith("-controller")
        )
        spec = controller["spec"]["template"]["spec"]
        assert "volumes" not in spec
        assert "volumeMounts" not in spec["containers"][0]

    def test_extender_cidrs_render_networkpolicy(self):
        docs = render_chart_docs(
            CHART,
            values_override={
                "extenderPort": 8090,
                "extenderAllowedCIDRs": ["10.0.0.0/28", "10.0.1.0/28"],
            },
        )
        kinds = _by_kind(docs)
        np = next(
            d for d in kinds["NetworkPolicy"]
            if d["metadata"]["name"].endswith("-extender")
        )
        assert np["spec"]["podSelector"]["matchLabels"][
            "app.kubernetes.io/component"
        ] == "controller"
        rule = np["spec"]["ingress"][0]
        assert [p["ipBlock"]["cidr"] for p in rule["from"]] == [
            "10.0.0.0/28", "10.0.1.0/28",
        ]
        assert rule["ports"][0]["port"] == 8090
        # selecting the pod default-denies everything else, so the policy
        # must carry a second rule keeping the diagnostics port scrapeable
        diag = np["spec"]["ingress"][1]
        assert "from" not in diag
        assert diag["ports"][0]["port"] == 8080

    def test_no_networkpolicy_without_cidrs(self, default_docs):
        assert "NetworkPolicy" not in _by_kind(default_docs)

    def test_membership_disabled_drops_controller(self):
        docs = render_chart_docs(
            CHART, values_override={"deviceClasses": ["tpu", "subslice"]}
        )
        kinds = _by_kind(docs)
        assert "Deployment" not in kinds
        names = {c["metadata"]["name"] for c in kinds["DeviceClass"]}
        assert "membership.tpu.google.com" not in names
        assert len(names) == 2

    def test_fake_topology_env_injected(self):
        docs = render_chart_docs(
            CHART, values_override={"fakeTopology": "v5e-16", "fakeCluster": True}
        )
        plugin = _by_kind(docs)["DaemonSet"][0]["spec"]["template"]["spec"]["containers"][0]
        env = {e["name"]: e.get("value") for e in plugin["env"]}
        assert env["TPUINFO_FAKE_TOPOLOGY"] == "v5e-16"
        assert env["FAKE_CLUSTER"] == "true"

    def test_http_port_disabled_drops_probes(self):
        docs = render_chart_docs(CHART, values_override={"httpPort": -1})
        plugin = _by_kind(docs)["DaemonSet"][0]["spec"]["template"]["spec"]["containers"][0]
        assert "ports" not in plugin
        assert "livenessProbe" not in plugin

    def test_name_override_truncates_and_propagates(self):
        docs = render_chart_docs(
            CHART, values_override={"nameOverride": "x" * 70}
        )
        ds = _by_kind(docs)["DaemonSet"][0]
        assert ds["metadata"]["name"].startswith("x" * 63)
        assert len(ds["metadata"]["name"]) == 63 + len("-kubelet-plugin")

    def test_namespace_override_beats_release_namespace(self):
        docs = render_chart_docs(
            CHART, values_override={"namespaceOverride": "tpu-system"}, namespace="other"
        )
        assert _by_kind(docs)["DaemonSet"][0]["metadata"]["namespace"] == "tpu-system"


class TestValidationGuards:
    """validation.yaml must fail the render with actionable messages
    (reference templates/validation.yaml:17-63 behavior)."""

    def test_default_values_pass(self):
        render_chart(CHART)  # no ChartFail

    @pytest.mark.parametrize(
        "override,needle",
        [
            ({"deviceClasses": []}, "at least one class"),
            ({"deviceClasses": ["tpu", "bogus"]}, "invalid deviceClasses entry"),
            ({"deviceClasses": "tpu"}, "must be a list"),
            ({"namespace": "oops"}, "not supported"),
            ({"image": {"tag": ""}}, "image.tag"),
            ({"image": {"repository": ""}}, "image.repository"),
            ({"socketDir": "relative/path"}, "socketDir"),
            ({"cdiRoot": "no-slash"}, "cdiRoot"),
            ({"partedStateDir": "x"}, "partedStateDir"),
            ({"fakeTopology": "not-a-slice"}, "fakeTopology"),
        ],
    )
    def test_bad_values_fail_with_message(self, override, needle):
        with pytest.raises(ChartFail) as exc:
            render_chart(CHART, values_override=override)
        assert needle in str(exc.value)

    def test_default_namespace_guard_and_bypass(self):
        with pytest.raises(ChartFail) as exc:
            render_chart(CHART, namespace="default")
        assert "default" in str(exc.value)
        render_chart(
            CHART, namespace="default", values_override={"allowDefaultNamespace": True}
        )
        render_chart(
            CHART, namespace="default", values_override={"namespaceOverride": "ok-ns"}
        )


class TestRendererEngine:
    """The template-language subset itself (unit level)."""

    def test_unsupported_function_is_loud(self, tmp_path):
        chart = tmp_path / "c"
        (chart / "templates").mkdir(parents=True)
        (chart / "Chart.yaml").write_text("name: c\nversion: 0.1.0\nappVersion: 1\n")
        (chart / "values.yaml").write_text("x: 1\n")
        (chart / "templates" / "t.yaml").write_text("a: {{ sha256sum .Values.x }}\n")
        with pytest.raises(RenderError, match="unknown function"):
            render_chart(chart)

    def test_go_printf_list_formatting(self):
        from tools.helm_render import _go_printf

        assert _go_printf("got: %v", [["a", "b"]]) == "got: [a b]"
        assert _go_printf("%q", ["x"]) == '"x"'
        assert _go_printf("%d items", [3]) == "3 items"

    def test_pipe_appends_final_argument(self, tmp_path):
        chart = tmp_path / "c"
        (chart / "templates").mkdir(parents=True)
        (chart / "Chart.yaml").write_text("name: c\nversion: 0.1.0\nappVersion: 1\n")
        (chart / "values.yaml").write_text("name: verylongname\n")
        (chart / "templates" / "t.yaml").write_text(
            'a: {{ .Values.name | trunc 4 | quote }}\n'
        )
        out = render_chart(chart)["t.yaml"]
        assert yaml.safe_load(out) == {"a": "very"}

    def test_whitespace_trim_markers(self, tmp_path):
        chart = tmp_path / "c"
        (chart / "templates").mkdir(parents=True)
        (chart / "Chart.yaml").write_text("name: c\nversion: 0.1.0\nappVersion: 1\n")
        (chart / "values.yaml").write_text("enabled: true\n")
        (chart / "templates" / "t.yaml").write_text(
            "a: 1\n{{- if .Values.enabled }}\nb: 2\n{{- end }}\n"
        )
        assert yaml.safe_load(render_chart(chart)["t.yaml"]) == {"a": 1, "b": 2}

    def test_range_rebinds_dot_and_keeps_vars(self, tmp_path):
        chart = tmp_path / "c"
        (chart / "templates").mkdir(parents=True)
        (chart / "Chart.yaml").write_text("name: c\nversion: 0.1.0\nappVersion: 1\n")
        (chart / "values.yaml").write_text("items: [a, b]\n")
        (chart / "templates" / "t.yaml").write_text(
            '{{- $pfx := "p" }}\n'
            "{{- range .Values.items }}\n"
            "- {{ $pfx }}{{ . }}\n"
            "{{- end }}\n"
        )
        assert yaml.safe_load(render_chart(chart)["t.yaml"]) == ["pa", "pb"]

    def test_cli_smoke(self, capsys):
        from tools.helm_render import main

        rc = main([str(CHART), "--set", "fakeTopology=v5e-16"])
        assert rc == 0
        out = capsys.readouterr().out
        docs = [d for d in yaml.safe_load_all(out) if d]
        assert any(d["kind"] == "DaemonSet" for d in docs)

    def test_cli_fail_exits_nonzero(self, capsys):
        from tools.helm_render import main

        rc = main([str(CHART), "--set", "deviceClasses=[]"])
        assert rc == 1
        assert "at least one class" in capsys.readouterr().err


class TestSelftestKnob:
    def test_selftest_env_rendered_when_enabled(self):
        docs = render_chart_docs(CHART, values_override={"selftestIntervalS": 300})
        plugin = _by_kind(docs)["DaemonSet"][0]["spec"]["template"]["spec"]["containers"][0]
        env = {e["name"]: e.get("value") for e in plugin["env"]}
        assert env["TPU_SELFTEST_INTERVAL_S"] == "300"

    def test_selftest_env_absent_by_default(self, default_docs):
        plugin = _by_kind(default_docs)["DaemonSet"][0]["spec"]["template"]["spec"]["containers"][0]
        env = {e["name"]: e.get("value") for e in plugin["env"]}
        assert "TPU_SELFTEST_INTERVAL_S" not in env


class TestGoldenRender:
    """Full-output golden comparison (the VERDICT-r4 golden-render check):
    each pinned values configuration must render EXACTLY the canonical
    document stream vendored under tests/goldens/helm/.  The goldens pin
    the renderer's semantics against regression here; the CI
    helm-crosscheck job compares the same configs against REAL
    ``helm template`` (tools/helm_crosscheck.py) — whitespace is out of
    scope by construction (comparison is post-YAML-parse).  Regenerate
    after an intended change: python tests/goldens/helm/regen.py."""

    GOLDEN_DIR = pathlib.Path(__file__).resolve().parent / "goldens" / "helm"

    def _configs(self):
        return CROSSCHECK_CONFIGS

    def test_every_config_has_a_golden(self):
        names = {p.stem for p in self.GOLDEN_DIR.glob("*.yaml")}
        assert names == set(self._configs())

    @pytest.mark.parametrize("name", sorted(CROSSCHECK_CONFIGS))
    def test_render_matches_golden(self, name):
        import importlib

        regen = importlib.import_module("tests.goldens.helm.regen")
        want = (self.GOLDEN_DIR / f"{name}.yaml").read_text()
        got = regen.canonical(self._configs()[name])
        assert got == want, (
            f"{name} render diverged from its golden; if intended, "
            f"regenerate via python tests/goldens/helm/regen.py"
        )

    def test_goldens_parse_and_carry_core_kinds(self):
        docs = [
            d
            for d in yaml.safe_load_all(
                (self.GOLDEN_DIR / "default.yaml").read_text()
            )
            if d
        ]
        kinds = {d["kind"] for d in docs}
        assert {"DaemonSet", "Deployment", "DeviceClass", "ClusterRole"} <= kinds
