"""REST client against the mock k8s API: the client-go-analog transport."""

import time

import pytest

from k8s_dra_driver_tpu.e2e.mock_api import MockKubeAPI
from k8s_dra_driver_tpu.kube.fakeserver import APIError, Conflict, NotFound
from k8s_dra_driver_tpu.kube.objects import Node, ObjectMeta, ResourceClaim
from k8s_dra_driver_tpu.kube.restclient import KubeClientConfig, RESTClient


@pytest.fixture
def api():
    mock = MockKubeAPI(token="sekrit").start()
    yield mock
    mock.stop()


@pytest.fixture
def client(api):
    return RESTClient(
        KubeClientConfig(server=api.url, token="sekrit", qps=1000, burst=1000)
    )


class TestRESTClient:
    def test_crud_roundtrip(self, api, client):
        created = client.create(Node(metadata=ObjectMeta(name="n1", labels={"a": "b"})))
        assert created.metadata.uid
        got = client.get("Node", "n1")
        assert got.metadata.labels == {"a": "b"}
        got.metadata.labels["c"] = "d"
        updated = client.update(got)
        assert updated.metadata.labels["c"] == "d"
        client.delete("Node", "n1")
        with pytest.raises(NotFound):
            client.get("Node", "n1")

    def test_namespaced_resource(self, client):
        claim = ResourceClaim(metadata=ObjectMeta(name="c1", namespace="team-a"))
        client.create(claim)
        got = client.get("ResourceClaim", "c1", "team-a")
        assert got.metadata.namespace == "team-a"
        assert client.list("ResourceClaim", namespace="team-b") == []
        assert len(client.list("ResourceClaim", namespace="team-a")) == 1

    def test_label_selected_list(self, client):
        client.create(Node(metadata=ObjectMeta(name="a", labels={"d": "1"})))
        client.create(Node(metadata=ObjectMeta(name="b", labels={"d": "2"})))
        names = [n.metadata.name for n in client.list("Node", label_selector={"d": "2"})]
        assert names == ["b"]

    def test_conflict_and_wrong_token(self, api, client):
        client.create(Node(metadata=ObjectMeta(name="n1")))
        a = client.get("Node", "n1")
        b = client.get("Node", "n1")
        client.update(a)
        with pytest.raises(Conflict):
            client.update(b)
        bad = RESTClient(KubeClientConfig(server=api.url, token="wrong", qps=1000, burst=1000))
        with pytest.raises(APIError) as exc:
            bad.get("Node", "n1")
        assert exc.value.code == 401

    def test_watch_replay_and_stream(self, api, client):
        client.create(Node(metadata=ObjectMeta(name="pre")))
        events = []
        w = client.watch("Node", lambda e: events.append((e.type, e.object.metadata.name)))
        deadline = time.time() + 5
        # replay is synchronous; the stream subscription lands when the mock
        # handles the GET — wait for it before mutating.
        while not api.server._watches and time.time() < deadline:
            time.sleep(0.02)
        # cluster-side mutation arrives over the stream
        api.server.create(Node(metadata=ObjectMeta(name="live")))
        api.server.delete("Node", "pre")
        while len(events) < 3 and time.time() < deadline:
            time.sleep(0.02)
        w.stop()
        assert events[0] == ("ADDED", "pre")
        assert ("ADDED", "live") in events
        assert ("DELETED", "pre") in events

    def test_driver_stack_over_rest(self, api, client, tmp_path):
        """The real point: the plugin driver + slice reconciler run unchanged
        over HTTP."""
        from k8s_dra_driver_tpu.e2e.harness import install_device_classes
        from k8s_dra_driver_tpu.plugin.driver import Driver, DriverConfig

        install_device_classes(api.server)
        driver = Driver(
            client,
            DriverConfig(
                node_name="rest-host",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
            ),
        )
        slices = api.server.list("ResourceSlice")
        assert len(slices) == 1
        assert len(slices[0].spec.devices) == 9
        # and claims prepare over the same transport
        from k8s_dra_driver_tpu.e2e.harness import simple_claim
        from k8s_dra_driver_tpu.plugin.driver import ClaimRef
        from k8s_dra_driver_tpu.scheduler.allocator import Allocator

        claim = client.create(simple_claim("rest-claim"))
        allocated = Allocator(client).allocate(claim, node_name="rest-host")
        result = driver.node_prepare_resources(
            [ClaimRef(uid=allocated.metadata.uid, name="rest-claim", namespace="default")]
        )
        assert result[allocated.metadata.uid].error == ""
        assert len(result[allocated.metadata.uid].devices) == 1


class TestWatchRecovery:
    def test_no_lost_event_between_list_and_watch(self, api, client):
        # Objects created between the client's list and its watch stream
        # connection must still be delivered (watch_since closes the gap).
        client.create(Node(metadata=ObjectMeta(name="pre")))
        events = []
        # Snapshot rv, then mutate BEFORE the stream could possibly connect.
        w = client.watch("Node", lambda e: events.append((e.type, e.object.metadata.name)))
        api.server.create(Node(metadata=ObjectMeta(name="gap")))
        deadline = time.time() + 5
        while not any(n == "gap" for _, n in events) and time.time() < deadline:
            time.sleep(0.02)
        w.stop()
        assert any(n == "gap" for _, n in events)

    def test_probe(self, client):
        assert client.probe()["major"] == "1"

    def test_relist_synthesizes_deleted_for_vanished_objects(self, api, client):
        """Reflector Replace semantics: objects removed during a watch
        outage must surface as DELETED on recovery, or consumers like
        SliceManager keep stale membership seats forever (round-1 advisor
        finding, medium)."""
        from k8s_dra_driver_tpu.kube.fakeserver import Watch, WatchEvent

        events = []
        w = Watch(api.server, "Node", lambda e: events.append((e.type, e.object.metadata.name)))
        client.create(Node(metadata=ObjectMeta(name="stale")))
        client.create(Node(metadata=ObjectMeta(name="kept")))
        for obj in client.list("Node"):  # delivered before the gap
            client._deliver(w, WatchEvent("ADDED", obj))
        client.delete("Node", "stale")  # vanishes during the outage
        client._relist(w, "Node")
        deleted = [n for t, n in events if t == "DELETED"]
        assert deleted == ["stale"]  # synthesized; survivor not deleted
        added = [n for t, n in events if t == "ADDED"]
        assert added.count("kept") == 2  # level-triggered replay
        # a second relist is stable: nothing further vanished
        client._relist(w, "Node")
        assert [n for t, n in events if t == "DELETED"] == ["stale"]

    def test_relist_failure_counted_not_swallowed(self, api, client, monkeypatch):
        """A failed recovery relist must be observable (counter + journal)
        and must not kill the watch thread: the old rv is kept so the next
        connect 410s again and the relist is retried."""
        from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
        from k8s_dra_driver_tpu.utils.journal import JOURNAL
        from k8s_dra_driver_tpu.utils.metrics import REGISTRY

        client.create(Node(metadata=ObjectMeta(name="n0")))
        events = []
        w = client.watch("Node", lambda e: events.append((e.type, e.object.metadata.name)))
        deadline = time.time() + 5
        while not api.server._watches and time.time() < deadline:
            time.sleep(0.02)

        fail = {"on": True}
        real_relist = RESTClient._relist

        def flaky_relist(watch, kind):
            if fail["on"]:
                fail["on"] = False  # fail once, then heal
                raise APIError(500, "relist blown")
            return real_relist(client, watch, kind)

        monkeypatch.setattr(client, "_relist", flaky_relist)
        # Force a watch outage: the next two connects answer 410 Gone, so
        # the client relists twice — first fails, second succeeds.
        api.server.faults = FaultInjector()
        api.server.faults.arm(FaultProfile(name="outage", watch_gone=2))
        for sw in list(api.server._watches):
            sw.stop()

        while fail["on"] and time.time() < deadline:
            time.sleep(0.02)
        api.server.create(Node(metadata=ObjectMeta(name="after")))
        while not any(n == "after" for _, n in events) and time.time() < deadline:
            time.sleep(0.05)
        w.stop()
        assert any(n == "after" for _, n in events)  # watch survived
        assert REGISTRY.counter("dra_watch_relist_errors_total").value(kind="Node") == 1
        fails = [e for e in JOURNAL.tail(component="restclient")
                 if e["event"] == "watch.relist_fail"]
        assert len(fails) == 1

    def test_error_frame_triggers_relist(self, api, client):
        # An ERROR frame (expired rv) must not kill the watch thread: the
        # client re-lists and keeps streaming.
        client.create(Node(metadata=ObjectMeta(name="n0")))
        events = []
        w = client.watch("Node", lambda e: events.append((e.type, e.object.metadata.name)))
        deadline = time.time() + 5
        while not api.server._watches and time.time() < deadline:
            time.sleep(0.02)
        # Simulate apiserver-side expiry by injecting an ERROR frame through
        # the mock's subscription path: drop all server watches (stream ends),
        # forcing a reconnect; then mutate.
        for sw in list(api.server._watches):
            sw.stop()
        api.server.create(Node(metadata=ObjectMeta(name="after")))
        while not any(n == "after" for _, n in events) and time.time() < deadline:
            time.sleep(0.05)
        w.stop()
        assert any(n == "after" for _, n in events)


class TestControllerOverREST:
    def test_slice_manager_watches_nodes_over_http(self, api, client):
        """The cluster controller stack runs unchanged over the REST
        transport: node events stream in, membership pools publish out."""
        from k8s_dra_driver_tpu.controller.slice_manager import (
            SLICE_DOMAIN_LABEL,
            SLICE_HOST_ID_LABEL,
            SliceManager,
        )

        mgr = SliceManager(client)
        mgr.start()
        try:
            deadline = time.time() + 5
            while not api.server._watches and time.time() < deadline:
                time.sleep(0.02)
            # cluster-side node creation must reach the manager over the stream
            api.server.create(
                Node(
                    metadata=ObjectMeta(
                        name="h0",
                        labels={SLICE_DOMAIN_LABEL: "d", SLICE_HOST_ID_LABEL: "0"},
                    )
                )
            )
            slices = []
            while not slices and time.time() < deadline:
                slices = [
                    s
                    for s in api.server.list("ResourceSlice")
                    if s.spec.pool.name == "slice-d"
                ]
                time.sleep(0.05)
            assert slices, "membership pool never published over the stream"
            assert slices[0].spec.devices[0].basic.attributes["workerId"].value == 0
        finally:
            mgr.stop()
        assert [
            s for s in api.server.list("ResourceSlice") if s.spec.pool.name == "slice-d"
        ] == []


class TestKubeConfigLoading:
    def test_kubeconfig_parsing(self, tmp_path):
        import base64

        ca = base64.b64encode(b"fake-ca-pem").decode()
        (tmp_path / "kubeconfig").write_text(
            f"""
apiVersion: v1
kind: Config
current-context: ctx
contexts:
  - name: ctx
    context: {{cluster: c, user: u}}
clusters:
  - name: c
    cluster:
      server: https://1.2.3.4:6443
      certificate-authority-data: {ca}
users:
  - name: u
    user:
      token: tok123
"""
        )
        cfg = KubeClientConfig.from_kubeconfig(tmp_path / "kubeconfig")
        assert cfg.server == "https://1.2.3.4:6443"
        assert cfg.token == "tok123"
        assert open(cfg.ca_file, "rb").read() == b"fake-ca-pem"

    def test_load_precedence_env(self, tmp_path, monkeypatch):
        (tmp_path / "kc").write_text(
            "current-context: x\ncontexts: [{name: x, context: {cluster: c, user: u}}]\n"
            "clusters: [{name: c, cluster: {server: http://env-server}}]\n"
            "users: [{name: u, user: {token: t}}]\n"
        )
        monkeypatch.setenv("KUBECONFIG", str(tmp_path / "kc"))
        assert KubeClientConfig.load().server == "http://env-server"

    def test_rate_limiter_enforces_qps(self):
        from k8s_dra_driver_tpu.kube.restclient import _RateLimiter

        rl = _RateLimiter(qps=50, burst=2)
        start = time.monotonic()
        for _ in range(6):
            rl.wait()
        # 2 burst + 4 refills at 50/s ≈ 80ms minimum
        assert time.monotonic() - start >= 0.06
