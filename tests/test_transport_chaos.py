"""KV transport chaos suite (`make chaos-transport`): the REAL wire under
injected socket faults and worker-process crashes.

tests/test_disagg_chaos.py storms the in-process HandoffChannel; this
suite storms models/transport.py — the same payloads over framed byte
pipes and localhost sockets between worker processes:

* **Socket storms** (in-process, seeded, LoopbackConn): sock_truncate /
  sock_reset / sock_latency_ms faults break frames mid-flight between a
  prefill pool and a PoolWorker-hosted decode pool.  Acceptance: every
  stream completes BIT-EQUAL via the fallback ladder, zero lost or
  duplicated completions, per-pool block accounting balanced, and
  ``tpu_disagg_inflight_bytes`` drains to zero.
* **Liveness and degradation**: a silent peer (ACK never comes) surfaces
  as a typed ``hang`` within the ack deadline — never a test-long block;
  a dead transport opens the per-peer breaker and the router collapses
  to unified serving on the local pool; a reconnect closes the breaker
  and remote serving resumes.
* **Harness hardening**: a worker that dies early fails the test with
  its own stderr tail and a supervisor diag bundle, instead of its
  sibling blocking out the full init timeout.
* **ONE real two-process test**: prefill pool in this process, decode
  pool in a spawned worker (``python -m ...models.transport``), KV over
  real localhost sockets.  The decode worker is SIGKILLed mid-transfer
  (streams placed but held undecoded), then restarted: zero lost
  streams, bit-equal recovery, breaker open → reconnect → remote
  serving resumes, in-flight bytes at zero.

Latency faults are ACCOUNTED, never slept; every in-process storm draws
from a seeded injector and replays from its seed.
"""

import json
import os
import sys
import time

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, paged, transport as T
from k8s_dra_driver_tpu.models.disagg import ChannelClaim, DisaggRouter
from k8s_dra_driver_tpu.models.fleet import FleetRouter
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.utils.faults import FaultInjector
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, parse_prom_text
from k8s_dra_driver_tpu.utils.retry import CircuitBreaker
from tests.mp_harness import (
    REPO_ROOT,
    SupervisedWorker,
    supervise,
    wait_ready,
)

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)
CFG_DOC = {
    "vocab_size": 64, "d_model": 32, "n_heads": 2, "n_layers": 1,
    "d_ff": 64, "max_seq": 64,
}


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 41)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


# Explicit per-request seeds: router-minted ids differ from the unified
# reference, so sampling keys must come from the request, never the id.
REQS = [
    {"prompt": [7, 8, 9], "max_tokens": 6, "seed": 5},
    {"prompt": [3, 4], "max_tokens": 6, "temperature": 0.7, "seed": 9},
    {"prompt": [11, 12, 13, 14], "max_tokens": 6, "seed": 21},
    {"prompt": [1, 2], "max_tokens": 6, "seed": 33},
    {"prompt": [21, 22, 23], "max_tokens": 6, "seed": 44},
]


def _by_prompt(completions):
    out = {}
    for c in completions:
        out[tuple(c.tokens[: len(c.tokens) - len(c.generated)])] = tuple(
            c.generated
        )
    return out


@pytest.fixture(scope="module")
def reference(params):
    return _by_prompt(_dense(params).pump([dict(r) for r in REQS]))


def _assert_no_lost_or_dup(done, reference):
    assert len(done) == len(REQS)
    assert [c.status for c in done].count("ok") == len(REQS)
    rids = [c.request_id for c in done]
    assert len(rids) == len(set(rids)), "duplicated completion ids"
    assert _by_prompt(done) == reference


class _Rig:
    """Local prefill pool + in-process PoolWorker decode pool behind a
    LoopbackConn transport, with conn-level reconnect (a new pipe pair is
    re-homed onto the SAME worker — the worker process survived, only its
    connection died)."""

    def __init__(self, params, *, spec="", kind=_dense, hold_ticks=False,
                 reconnect=True, ack_timeout_s=0.5):
        self.inj = FaultInjector.from_env(spec) if spec else None
        a, b = T.LoopbackConn.pair(fault_injector=self.inj)
        self.pre_engine = kind(params)
        self.dec_engine = kind(params)
        self.worker = T.PoolWorker(
            b, FleetRouter([self.dec_engine]), role="decode",
            hold_ticks=hold_ticks,
        )
        self.link = T.PeerLink(
            "decode-w", a,
            connect_fn=self._redial if reconnect else None,
            heartbeat_interval_s=0.02,
            liveness_timeout_s=1.0,
            ack_timeout_s=ack_timeout_s,
            breaker=CircuitBreaker(
                endpoint="transport/decode-w", reset_timeout_s=0.01
            ),
        )
        self.channel = T.TransportChannel(
            self.link, peer_pump=self.worker.pump_once,
            claim=ChannelClaim(
                bandwidth_gbps=1000.0, transfer_deadline_s=10.0
            ),
            fault_injector=self.inj,
        )
        self.pool = T.RemotePool(self.link, peer_pump=self.worker.pump_once)
        self.router = DisaggRouter(
            prefill=[self.pre_engine], decode=self.pool, channel=self.channel,
            fault_injector=self.inj,
        )

    def _redial(self):
        a, b = T.LoopbackConn.pair(fault_injector=self.inj)
        self.worker.conn = b
        self.worker.frames = T.FrameBuffer()
        self.worker.dead = False
        return a


class TestSocketStorms:
    def test_truncate_storm_streams_survive(self, params, reference):
        rig = _Rig(params, spec="sock_truncate=0.15,limit=4,seed=3")
        done = rig.router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        assert rig.channel.in_flight_bytes == 0

    def test_reset_storm_reconnects_and_survives(self, params, reference):
        rig = _Rig(params, spec="sock_reset=0.2,limit=4,seed=11")
        done = rig.router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        assert rig.channel.in_flight_bytes == 0
        # at least one conn death must have been survived via redial or
        # local fallback (the seed arms 4 resets at 20%)
        total = sum(rig.channel.counts.values())
        assert total >= len(REQS)

    def test_latency_storm_is_accounted_never_slept(self, params, reference):
        rig = _Rig(params, spec="sock_latency_ms=60000,limit=3,seed=7")
        rig.channel.transfer_deadline_s = 0.25
        t0 = time.monotonic()
        done = rig.router.pump([dict(r) for r in REQS])
        wall = time.monotonic() - t0
        _assert_no_lost_or_dup(done, reference)
        # the budget is drawn per FRAME (heartbeats included), so not
        # every injection lands on a KV transfer — but at least one
        # 60-simulated-second transfer must go stale on the deadline
        # ladder, all three draws must fire, and the storm still runs in
        # wall-milliseconds because latency is accounted, never slept
        assert rig.channel.counts.get("deadline", 0) >= 1
        assert rig.inj.stats().get("sock_latency", 0) == 3
        assert wall < 30.0
        assert rig.channel.in_flight_bytes == 0

    def test_paged_block_accounting_balanced_after_storm(self, params,
                                                         reference):
        rig = _Rig(params, spec="sock_truncate=0.2,limit=3,seed=5",
                   kind=_paged)
        free0 = (rig.pre_engine.free_blocks, rig.dec_engine.free_blocks)
        done = rig.router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        free1 = (rig.pre_engine.free_blocks, rig.dec_engine.free_blocks)
        assert free0 == free1, "leaked KV blocks across the storm"

    def test_transport_metrics_scraped(self, params, reference):
        rig = _Rig(params, spec="sock_reset=0.2,limit=2,seed=19")
        rig.router.pump([dict(r) for r in REQS])
        doc = parse_prom_text(REGISTRY.render())
        frames = doc["tpu_transport_frames_total"]
        assert sum(frames.values()) > 0
        assert any(("outcome", "ok") in labels for labels in frames)
        up = doc["tpu_transport_peer_up"]
        assert (("endpoint", "transport/decode-w"),) in up
        assert doc["tpu_transport_rtt_seconds_count"][()] > 0
        assert doc["tpu_disagg_inflight_bytes"][()] == 0.0
        if rig.link.reconnects:
            assert doc["tpu_transport_reconnects_total"][()] >= 1.0

    def test_debug_transport_doc_and_endpoint(self, params, reference):
        import urllib.request

        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        rig = _Rig(params)
        rig.router.pump([dict(r) for r in REQS])
        doc = T.debug_transport_doc()
        mine = [c for c in doc["channels"]
                if c["link"]["peer"] == "decode-w"]
        assert mine and mine[0]["link"]["breaker"] in (
            CircuitBreaker.CLOSED, CircuitBreaker.OPEN,
            CircuitBreaker.HALF_OPEN,
        )
        assert any(p["kind"] == "remote_pool" for p in doc["remote_pools"])
        srv = DiagnosticsServer(port=0)
        srv.start()
        try:
            served = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/transport").read())
        finally:
            srv.stop()
        assert any(c["link"]["peer"] == "decode-w"
                   for c in served["channels"])


class TestLivenessAndDegradation:
    def test_silent_peer_is_a_typed_hang_not_a_block(self, params, reference):
        rig = _Rig(params, reconnect=False, ack_timeout_s=0.05)
        rig.channel.peer_pump = lambda: 0  # frames land, ACKs never come
        rig.pool.peer_pump = None
        t0 = time.monotonic()
        done = rig.router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        # every transfer either hung past its ack deadline (typed, rid-
        # attributed) or died with the link the hang eventually killed
        hangs = rig.channel.counts.get("hang", 0)
        assert hangs >= 1
        assert rig.channel.in_flight_bytes == 0
        assert time.monotonic() - t0 < 60.0

    def test_peer_hang_budget_stalls_then_recovers(self, params, reference):
        rig = _Rig(params, spec="peer_hang=6,seed=2")
        rig.worker.fault_injector = rig.inj
        done = rig.router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        assert rig.inj.stats().get("peer_hang", 0) == 6

    def test_transport_down_collapses_to_unified(self, params, reference):
        rig = _Rig(params, reconnect=False, hold_ticks=True)
        rids = [rig.router.submit(r["prompt"], r["max_tokens"],
                                  seed=r["seed"],
                                  temperature=r.get("temperature", 0.0))
                for r in REQS]
        for _ in range(12):
            rig.router.tick()
        assert len(rig.pool._resident) + len(rig.pool._pending) > 0
        rig.worker.conn.close()  # the whole transport goes down
        done = []
        for _ in range(600):
            rig.router.tick()
            done += rig.router.completions()
            if len(done) == len(REQS):
                break
        _assert_no_lost_or_dup(done, reference)
        assert sorted(c.request_id for c in done) == sorted(rids)
        assert rig.link.breaker.state == CircuitBreaker.OPEN
        assert rig.channel.in_flight_bytes == 0
        assert rig.router.stats()["channel"]["link"]["alive"] is False

    def test_reconnect_closes_breaker_and_resumes_remote(self, params,
                                                         reference):
        rig = _Rig(params)
        rig.worker.conn.close()
        # drive until the link notices the EOF, then redials through the
        # breaker cooldown + jittered backoff
        deadline = time.monotonic() + 10.0
        while rig.link.reconnects < 1 and time.monotonic() < deadline:
            rig.router.tick()
            time.sleep(0.005)
        assert rig.link.reconnects >= 1
        assert not rig.link.dead
        assert rig.link.breaker.state == CircuitBreaker.CLOSED
        done = rig.router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        assert rig.channel.counts.get("ok", 0) >= 1


class TestHarnessHardening:
    def test_early_worker_death_fails_fast_with_evidence(self, tmp_path):
        env = dict(os.environ)
        env["PYTHONPATH"] = str(REPO_ROOT)
        crasher = SupervisedWorker(
            "crasher",
            [sys.executable, "-c",
             "import sys; sys.stderr.write('boom: injected failure\\n');"
             "sys.exit(3)"],
            env,
        )
        sleeper = SupervisedWorker(
            "sleeper",
            [sys.executable, "-c", "import time; time.sleep(120)"],
            env,
        )
        t0 = time.monotonic()
        with pytest.raises(AssertionError) as exc:
            supervise([crasher, sleeper], timeout=90, bundle_dir=tmp_path)
        wall = time.monotonic() - t0
        # fails on the crasher's evidence, within seconds — NOT after the
        # sleeper's 120s or the harness's 90s
        assert wall < 30.0
        msg = str(exc.value)
        assert "crasher" in msg and "rc=3" in msg
        assert "boom: injected failure" in msg
        assert "diag bundle" in msg
        bundle_path = msg.split("diag bundle: ")[1].split(" ---")[0].strip()
        bundle = json.loads(open(bundle_path).read())
        assert bundle["workers"]["crasher"]["returncode"] == 3
        assert "thread_stacks" in bundle
        assert sleeper.poll() is not None, "sibling was left running"


def _worker_cfg(tmp_path, name, port, hold_ticks, peer="decode-w"):
    path = tmp_path / f"{name}.json"
    path.write_text(json.dumps({
        "cfg": CFG_DOC,
        "engines": [{"kind": "dense", "n_slots": 3, "prompt_bucket": 16}],
        "seed": 0,
        "host": "127.0.0.1",
        "port": port,
        "name": peer,
        "role": "decode",
        "hold_ticks": hold_ticks,
    }))
    return path


def _spawn_worker(tag, cfg_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO_ROOT)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DRA_FAULTS", None)
    return SupervisedWorker(
        tag,
        [sys.executable, "-m", "k8s_dra_driver_tpu.models.transport",
         str(cfg_path)],
        env,
    )


WAVE2 = [
    {"prompt": [31, 32, 33], "max_tokens": 6, "seed": 51},
    {"prompt": [41, 42], "max_tokens": 6, "seed": 52},
    {"prompt": [5, 6, 7, 8], "max_tokens": 6, "seed": 53},
]


class TestTwoProcessTransport:
    def test_sigkill_mid_transfer_then_reconnect(self, params, reference,
                                                 tmp_path):
        """The PR's keystone: REAL sockets, REAL worker process, REAL
        SIGKILL.  Wave 1 is placed on the worker (held undecoded) and the
        worker is killed mid-flight: every stream recovers bit-equal on
        the local pool, the peer breaker opens, in-flight bytes drain.  A
        restarted worker re-dials the hub: the link reconnects, the
        breaker closes, and wave 2 serves REMOTELY bit-equal."""
        hub = T.TransportHub(
            heartbeat_interval_s=0.1, liveness_timeout_s=3.0,
            ack_timeout_s=5.0,
        )
        w1 = _spawn_worker("decode-w1",
                           _worker_cfg(tmp_path, "w1", hub.port, True))
        w2 = None
        try:
            link = hub.link_for("decode-w", timeout_s=120.0)
            channel = T.TransportChannel(
                link,
                claim=ChannelClaim(
                    bandwidth_gbps=1000.0, transfer_deadline_s=10.0
                ),
            )
            pool = T.RemotePool(link, name="sigkill-pool")
            dis = DisaggRouter(prefill=[_dense(params)], decode=pool,
                               channel=channel)

            rids1 = [dis.submit(r["prompt"], r["max_tokens"],
                                seed=r["seed"],
                                temperature=r.get("temperature", 0.0))
                     for r in REQS]
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                hub.poll()
                dis.tick()
                if len(pool._resident) + len(pool._pending) >= len(REQS):
                    break
                time.sleep(0.01)
            resident_at_kill = len(pool._resident) + len(pool._pending)
            assert resident_at_kill == len(REQS)
            assert channel.counts.get("ok", 0) >= 1  # KV crossed the wire

            w1.proc.kill()  # SIGKILL mid-transfer: streams held undecoded

            done1 = []
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                hub.poll()
                dis.tick()
                done1 += dis.completions()
                if len(done1) == len(REQS):
                    break
                time.sleep(0.005)
            _assert_no_lost_or_dup(done1, reference)
            assert sorted(c.request_id for c in done1) == sorted(rids1)
            assert link.breaker.state == CircuitBreaker.OPEN
            assert channel.in_flight_bytes == 0
            doc = parse_prom_text(REGISTRY.render())
            assert doc["tpu_disagg_inflight_bytes"][()] == 0.0

            w2 = _spawn_worker("decode-w2",
                               _worker_cfg(tmp_path, "w2", hub.port, False))
            deadline = time.monotonic() + 120.0
            while link.dead and time.monotonic() < deadline:
                hub.poll()
                dis.tick()
                time.sleep(0.01)
            assert not link.dead, "restarted worker never reconnected"
            assert link.reconnects == 1
            assert link.breaker.state == CircuitBreaker.CLOSED

            ref2 = _by_prompt(_dense(params).pump([dict(r) for r in WAVE2]))
            ok_before = channel.counts.get("ok", 0)
            for r in WAVE2:
                dis.submit(r["prompt"], r["max_tokens"], seed=r["seed"])
            done2 = []
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                hub.poll()
                dis.tick()
                done2 += dis.completions()
                if len(done2) == len(WAVE2):
                    break
                time.sleep(0.005)
            assert len(done2) == len(WAVE2)
            assert _by_prompt(done2) == ref2
            # wave 2 physically crossed the reconnected socket
            assert channel.counts.get("ok", 0) >= ok_before + len(WAVE2)
            assert channel.in_flight_bytes == 0
            assert pool.idle()
            tdoc = T.debug_transport_doc()
            # earlier tests' pools may still be alive in the WeakSet —
            # select ours by name
            (mine,) = [p for p in tdoc["remote_pools"]
                       if p["name"] == "sigkill-pool"]
            assert mine["link"]["reconnects"] == 1
        finally:
            for w in (w1, w2):
                if w is not None:
                    w.kill()
            hub.close()


def _spans_of(tree):
    """Flatten one fleet_traces_doc tree into its span node list."""
    out, stack = [], list(tree["roots"])
    while stack:
        node = stack.pop()
        out.append(node)
        stack.extend(node["children"])
    return out


class TestObservabilityFederation:
    def test_sigkill_federation_merged_tree_and_dead_hop(self, params,
                                                         reference,
                                                         tmp_path):
        """PR 16's keystone: two REAL worker processes federate their
        journals/spans/metrics over TELEM frames, and a SIGKILL mid-hold
        loses neither the pre-death spans nor the attribution.  Wave 1
        serves remotely on decode-w and its hop.decode spans federate into
        ONE merged tree with the supervisor's hop.prefill/hop.wire (both
        processes' clocks skew-normalized); decode-w is then held (streams
        placed, undecoded) and SIGKILLed with wave 2 resident: wave 2
        recovers bit-equal locally, every lost hop is attributed to
        decode-w as a synthetic hop.dead span under its wire span, and
        the dead worker's wave-1 spans are STILL in the fleet view.  A
        second worker, decode-w2, federates the whole time — the
        federated /metrics render carries both instance labels."""
        from k8s_dra_driver_tpu.models.obs_plane import FLEET

        hub = T.TransportHub(
            heartbeat_interval_s=0.1, liveness_timeout_s=3.0,
            ack_timeout_s=5.0,
        )
        w1 = _spawn_worker(
            "decode-w", _worker_cfg(tmp_path, "w1", hub.port, False))
        w2 = _spawn_worker(
            "decode-w2",
            _worker_cfg(tmp_path, "w2", hub.port, False, peer="decode-w2"))
        workers = [w1, w2]
        try:
            link = wait_ready(
                workers,
                lambda: (hub.poll(), hub.links.get("decode-w"))[1],
                timeout=120, bundle_dir=tmp_path,
            )
            link2 = wait_ready(
                workers,
                lambda: (hub.poll(), hub.links.get("decode-w2"))[1],
                timeout=120, bundle_dir=tmp_path,
            )
            channel = T.TransportChannel(
                link,
                claim=ChannelClaim(
                    bandwidth_gbps=1000.0, transfer_deadline_s=10.0
                ),
            )
            pool = T.RemotePool(link, name="fed-pool")
            # decode-w2 serves nothing; its RemotePool exists to drain the
            # TELEM frames it ships on its own cadence.
            pool2 = T.RemotePool(link2, name="fed-pool2")
            dis = DisaggRouter(prefill=[_dense(params)], decode=pool,
                               channel=channel)

            rids1 = [dis.submit(r["prompt"], r["max_tokens"],
                                seed=r["seed"],
                                temperature=r.get("temperature", 0.0))
                     for r in REQS]
            done1 = []

            def _wave1_served():
                hub.poll()
                dis.tick()
                pool2.tick()
                done1.extend(dis.completions())
                return len(done1) == len(REQS)

            wait_ready(workers, _wave1_served, timeout=120,
                       bundle_dir=tmp_path)
            _assert_no_lost_or_dup(done1, reference)

            # Completions beat the 0.25s telemetry cadence — keep pumping
            # until BOTH workers' snapshots federate and wave 1's remote
            # decode hop is in the merged tree.
            def _federated():
                hub.poll()
                dis.tick()
                pool2.tick()
                if "decode-w2" not in FLEET.stats()["instances"]:
                    return False
                doc = FLEET.fleet_traces_doc(trace_id=f"req-{rids1[0]}")
                return any(
                    s["name"] == "hop.decode" and s["instance"] == "decode-w"
                    for tree in doc["traces"] for s in _spans_of(tree)
                )

            wait_ready(workers, _federated, timeout=60, bundle_dir=tmp_path)

            # Every wave-1 request merged into ONE tree spanning both
            # processes, skew-normalized: the worker's decode hop starts
            # after the supervisor's wire hop within the offset-estimate
            # error (shared CLOCK_MONOTONIC epoch keeps it near zero).
            for rid in rids1:
                doc = FLEET.fleet_traces_doc(trace_id=f"req-{rid}")
                (tree,) = doc["traces"]
                assert {"supervisor", "decode-w"} <= set(tree["instances"])
                spans = _spans_of(tree)
                wires = {s["span_id"]: s for s in spans
                         if s["name"] == "hop.wire"}
                pres = {s["span_id"]: s for s in spans
                        if s["name"] == "hop.prefill"}
                (dec,) = [s for s in spans if s["name"] == "hop.decode"]
                assert dec["instance"] == "decode-w"
                wire = wires[dec["parent_id"]]
                pre = pres[wire["parent_id"]]
                assert pre["t0"] <= wire["t0"] + 1e-6
                assert dec["t0"] >= wire["t0"] - 0.5

            # Pin wave 2 resident on decode-w (placed, undecoded), then
            # SIGKILL it mid-hold.
            link.send_json(T.CONTROL, {"op": "hold"})
            rids2 = [dis.submit(r["prompt"], r["max_tokens"],
                                seed=r["seed"]) for r in WAVE2]
            wait_ready(
                workers,
                lambda: (hub.poll(), dis.tick(),
                         len(pool._resident) >= len(WAVE2))[2],
                timeout=120, bundle_dir=tmp_path,
            )
            w1.proc.kill()

            ref2 = _by_prompt(_dense(params).pump([dict(r) for r in WAVE2]))
            done2 = []
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                hub.poll()
                dis.tick()
                pool2.tick()
                done2 += dis.completions()
                if len(done2) == len(WAVE2):
                    break
                time.sleep(0.005)
            assert len(done2) == len(WAVE2)
            assert _by_prompt(done2) == ref2

            # The corpse's hops are attributed: every wave-2 stream gets a
            # synthetic hop.dead span naming decode-w, stitched under the
            # wire span that delivered it.
            for rid in rids2:
                doc = FLEET.fleet_traces_doc(trace_id=f"req-{rid}")
                (tree,) = doc["traces"]
                spans = _spans_of(tree)
                (dead,) = [s for s in spans if s["name"] == "hop.dead"]
                assert dead["attrs"]["instance"] == "decode-w"
                parents = {s["span_id"]: s for s in spans}
                assert parents[dead["parent_id"]]["name"] == "hop.wire"
            # Pre-death spans survive the death: wave 1's decode hops are
            # still in the fleet view after decode-w was SIGKILLed.
            doc = FLEET.fleet_traces_doc(trace_id=f"req-{rids1[0]}")
            assert any(
                s["name"] == "hop.decode" and s["instance"] == "decode-w"
                for tree in doc["traces"] for s in _spans_of(tree)
            )
            # Federated /metrics: both workers under distinct instance
            # labels in one render (the /metrics federation body).
            text = FLEET.render_federated()
            assert 'instance="decode-w"' in text
            assert 'instance="decode-w2"' in text
            assert sorted(FLEET.stats()["instances"]) == [
                "decode-w", "decode-w2",
            ]
            # The serving worker's flight recorder merged into the fleet
            # journal (idle decode-w2 has nothing to journal — its
            # federation is proven by the instance set above).
            jd = FLEET.fleet_journal_doc(limit=4096)
            assert "decode-w" in {e["instance"] for e in jd["events"]}
        finally:
            for w in (w1, w2):
                if w is not None:
                    w.kill()
            hub.close()

    def test_latency_storm_skew_normalization_keeps_spans_ordered(
            self, params, reference):
        """In-process skew rig: the decode worker's clock runs 5 SECONDS
        behind the supervisor's while a seeded sock_latency_ms storm
        batters the link.  PING/PONG half-rtt estimation recovers the
        offset, and the fleet merger's normalization keeps the merged
        span trees causally ordered — unnormalized, every decode hop
        would appear to START ~5s before the wire hop that delivered
        it."""
        from k8s_dra_driver_tpu.models.obs_plane import FLEET
        from k8s_dra_driver_tpu.utils.tracing import TraceBuffer

        inj = FaultInjector.from_env("sock_latency_ms=800,limit=5,seed=7")
        a, b = T.LoopbackConn.pair(fault_injector=inj)
        worker = T.PoolWorker(
            b, FleetRouter([_dense(params)]), role="decode",
            name="skew-w", clock=lambda: time.monotonic() - 5.0,
            telem_interval_s=0.0, traces=TraceBuffer(),
        )
        link = T.PeerLink(
            "skew-w", a,
            heartbeat_interval_s=0.02,
            liveness_timeout_s=5.0,
            ack_timeout_s=0.5,
            breaker=CircuitBreaker(
                endpoint="transport/skew-w", reset_timeout_s=0.01
            ),
        )
        channel = T.TransportChannel(
            link, peer_pump=worker.pump_once,
            claim=ChannelClaim(
                bandwidth_gbps=1000.0, transfer_deadline_s=10.0
            ),
            fault_injector=inj,
        )
        pool = T.RemotePool(link, peer_pump=worker.pump_once)
        router = DisaggRouter(prefill=[_dense(params)], decode=pool,
                              channel=channel, fault_injector=inj)
        done = router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        assert inj.stats().get("sock_latency", 0) == 5  # the storm fired
        # The NTP half-rtt estimate recovered the injected -5s skew.
        assert link.clock_offset_s is not None
        assert abs(link.clock_offset_s + 5.0) < 1.0
        assert "skew-w" in FLEET.stats()["instances"]
        # Worker spans live in a PRIVATE ring — they reached the fleet
        # view only through TELEM frames, and arrive skew-normalized.
        decode_spans = 0
        for tree in FLEET.fleet_traces_doc()["traces"]:
            spans = _spans_of(tree)
            wires = {s["span_id"]: s for s in spans
                     if s["name"] == "hop.wire"}
            pres = {s["span_id"]: s for s in spans
                    if s["name"] == "hop.prefill"}
            for dec in spans:
                if dec["name"] != "hop.decode":
                    continue
                assert dec["instance"] == "skew-w"
                decode_spans += 1
                wire = wires[dec["parent_id"]]
                pre = pres[wire["parent_id"]]
                assert pre["t0"] <= wire["t0"] + 1e-6
                # Normalized causal order, to within the EWMA estimate
                # error; the RAW timestamps would put dec ~5s earlier.
                assert dec["t0"] >= wire["t0"] - 1.0
        assert decode_spans == len(REQS)


# -- fleet prefix pull under owner death -------------------------------------


PREFIX_PROMPT = list(range(1, 15))  # 14 tokens -> 3 storable blocks of 4


def _prefix_worker_cfg(tmp_path, name, port, peer="prefix-w",
                       redial_attempts=0):
    path = tmp_path / f"{name}.json"
    path.write_text(json.dumps({
        "cfg": CFG_DOC,
        "engines": [{
            "kind": "paged", "n_slots": 3, "n_blocks": 41, "block_size": 4,
            "prompt_bucket": 16, "attn_impl": "xla",
            "prefix_cache_blocks": 24,
        }],
        "seed": 0,
        "host": "127.0.0.1",
        "port": port,
        "name": peer,
        "role": "decode",
        "hold_ticks": False,
        "redial_attempts": redial_attempts,
    }))
    return path


class TestTwoProcessPrefixPull:
    def test_owner_sigkill_mid_pull_walks_fallback_ladder(self, params,
                                                          tmp_path):
        """Fleet prefix tier over REAL sockets and a REAL SIGKILL.  The
        worker serves the shared prompt once (warming ITS paged prefix
        store) and GOSSIPS the rungs over PREFIXPUB frames — the index
        learns the wire way, no supervisor-side hints — and a cold local
        engine remote-pulls the prefix over PREFIXREQ/PREFIXKV, decoding
        BIT-EQUAL to the worker's own cold prefill.  Then the owner is
        SIGKILLed and the next admission's pull walks the fallback
        ladder: owner-death detected mid-pull, its index footprint
        invalidated, nothing left pinned, and the stream completes via
        cold prefill — degraded, never lost."""
        from k8s_dra_driver_tpu.models import fleet_prefix as FP

        hub = T.TransportHub(
            heartbeat_interval_s=0.1, liveness_timeout_s=3.0,
            ack_timeout_s=5.0,
        )
        w = _spawn_worker("prefix-w1",
                          _prefix_worker_cfg(tmp_path, "pw", hub.port))
        try:
            link = hub.link_for("prefix-w", timeout_s=120.0)
            pool = T.RemotePool(link, name="prefix-pool")
            # 2-before-1: attach the tier FIRST so the resync handshake
            # assigns the owner epoch before the warm serve publishes.
            index = FP.FleetPrefixIndex()
            tier = FP.FleetPrefixTier(index, pull_timeout_s=8.0)
            tier.attach_remote_owner("prefix-w", link, pull_timeout_s=8.0)
            # 1. Warm the owner through a REAL remote serve of the prompt.
            pool.submit(PREFIX_PROMPT, 6, seed=3)
            done = []
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline and not done:
                hub.poll()
                pool.tick()
                tier.tick()
                done += pool.completions()
                time.sleep(0.005)
            assert len(done) == 1 and done[0].status == "ok"
            ref = list(done[0].generated)  # the owner's own cold decode

            # 2. The owner gossips its rungs over the wire (PREFIXPUB,
            # CRC'd, epoch-stamped) — entries are still HINTS: the owner
            # re-walks its store on PREFIXREQ (a stale entry is one
            # PREFIXMISS, never a wrong KV).
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                hub.poll()
                link.pump()
                tier.tick()
                ents = [e for e in index._entries.values()
                        if e.owner == "prefix-w"]
                if any(e.n_tokens >= 12 for e in ents):
                    break
                time.sleep(0.005)
            ents = [e for e in index._entries.values()
                    if e.owner == "prefix-w"]
            assert any(e.n_tokens >= 12 for e in ents), \
                "gossip never delivered the deepest rung"
            epoch = index.owner_epoch["prefix-w"]
            assert epoch >= 1
            assert all(e.epoch == epoch for e in ents)

            # 3. Happy path: remote pull over the wire, bit-equal decode.
            puller = _paged(params, prefix_cache_blocks=24)
            got = tier.prepare("local", puller, PREFIX_PROMPT, max_tokens=6)
            assert got == "remote"
            assert puller.local_prefix_depth(PREFIX_PROMPT) == 12
            assert index.ledger().pinned == 0
            (c,) = puller.pump([{"prompt": list(PREFIX_PROMPT),
                                 "max_tokens": 6, "seed": 3}])
            assert list(c.generated) == ref  # bit-equal across the socket

            # 4. SIGKILL the owner; the next pull discovers death mid-pull.
            w.proc.kill()
            cold = _paged(params, prefix_cache_blocks=24)
            got = tier.prepare("local2", cold, PREFIX_PROMPT, max_tokens=6)
            assert got == "cold"
            assert tier.fallbacks.get("owner_dead") == 1
            assert len(index) == 0          # owner footprint invalidated
            assert index.ledger().pinned == 0  # partial pull left no pins
            assert "prefix-w" not in tier._sources
            # 5. The stream itself is never lost: cold prefill serves it.
            (c,) = cold.pump([{"prompt": list(PREFIX_PROMPT),
                               "max_tokens": 6, "seed": 3}])
            assert c.status == "ok" and list(c.generated) == ref
        finally:
            w.kill()
            hub.close()


# -- three-process leg: partition, owner replacement, stale-hint storm -------


PROMPT_B = list(range(21, 35))  # 14 tokens, disjoint from PREFIX_PROMPT


class TestThreeProcessPrefixGossip:
    def test_partition_epoch_fence_and_stale_storm(self, params, tmp_path):
        """The tentpole proof on REAL processes: supervisor + two gossiping
        owner workers.  (a) A one-way ``sock_partition`` mid-gossip kills
        supervisor→A frames: liveness expires, the breaker opens, placement
        degrades to local-only (reason-coded, stream served cold — never
        lost); on heal the worker redials, the owner epoch bumps, and the
        anti-entropy digest reconverges the index — pulls resume
        bit-equal.  (b) Owner B is SIGKILLed mid-pull and REPLACED by a
        fresh process under the same name: the epoch bump + empty digest
        fence every stale entry (zero wrong-KV injections), and a
        stale-hint storm at the dead epoch bounces off whole.  Balanced
        ledgers and one journal correlation per pull throughout."""
        from k8s_dra_driver_tpu.models import fleet_prefix as FP
        from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
        from k8s_dra_driver_tpu.utils.journal import JOURNAL

        inj = FaultInjector()  # armed mid-test; hub conns hold the reference
        hub = T.TransportHub(
            heartbeat_interval_s=0.1, liveness_timeout_s=10.0,
            ack_timeout_s=5.0, fault_injector=inj,
        )
        wa = _spawn_worker("prefix-a1", _prefix_worker_cfg(
            tmp_path, "pa1", hub.port, peer="prefix-a", redial_attempts=5))
        wb = _spawn_worker("prefix-b1", _prefix_worker_cfg(
            tmp_path, "pb1", hub.port, peer="prefix-b"))
        wb2 = None
        journal_cursor = JOURNAL.export_since(0)[0]
        try:
            link_a = hub.link_for("prefix-a", timeout_s=120.0)
            link_b = hub.link_for("prefix-b", timeout_s=120.0)
            # B's process startup can exceed the liveness window while A
            # sits unpumped — restart both pong clocks now that both links
            # exist, so neither starts life already expired.
            link_a._last_pong_at = link_a.clock()
            link_b._last_pong_at = link_b.clock()
            pool_a = T.RemotePool(link_a, name="prefix-pool-a")
            pool_b = T.RemotePool(link_b, name="prefix-pool-b")
            index = FP.FleetPrefixIndex()
            tier = FP.FleetPrefixTier(index, pull_timeout_s=8.0)
            tier.attach_remote_owner("prefix-a", link_a, pull_timeout_s=8.0)
            tier.attach_remote_owner("prefix-b", link_b, pull_timeout_s=8.0)

            def drive(cond, timeout=60.0, msg=""):
                deadline = time.monotonic() + timeout
                while time.monotonic() < deadline:
                    hub.poll()
                    for p in (pool_a, pool_b):
                        p.tick()
                    tier.tick()
                    if cond():
                        return
                    time.sleep(0.005)
                raise AssertionError(f"drive timed out: {msg}")

            def deepest(owner):
                return max([e.n_tokens for e in index._entries.values()
                            if e.owner == owner], default=0)

            # Warm both owners via REAL remote serves; refs are each
            # owner's own cold decode of its prompt.
            done_a, done_b = [], []
            pool_a.submit(PREFIX_PROMPT, 6, seed=3)
            pool_b.submit(PROMPT_B, 6, seed=7)
            drive(lambda: (done_a.extend(pool_a.completions()) or
                           done_b.extend(pool_b.completions()) or
                           (done_a and done_b)), 120.0, "warm serves")
            ref_a = list(done_a[0].generated)
            ref_b = list(done_b[0].generated)
            # ... and the wire gossip populates the index (mid-gossip from
            # here on: publishes are still in flight when the partition
            # lands).
            drive(lambda: deepest("prefix-a") >= 12 and
                  deepest("prefix-b") >= 12, 60.0, "gossip warm-up")
            epoch_a1 = index.owner_epoch["prefix-a"]
            epoch_b1 = index.owner_epoch["prefix-b"]

            # (a) One-way partition supervisor→A mid-gossip: A's frames
            # still arrive, ours silently vanish -> liveness expiry.
            inj.arm(FaultProfile(sock_partition_rate=1.0,
                                 peers=("prefix-a",)))
            drive(lambda: link_a.dead, 60.0, "partition liveness expiry")
            assert link_a.breaker.state == CircuitBreaker.OPEN
            assert not tier.owner_available("prefix-a")
            # Degraded, reason-coded, never lost: placement skips the
            # unreachable owner and the stream serves cold bit-equal.
            part = _paged(params, prefix_cache_blocks=24)
            got = tier.prepare("local-p", part, PREFIX_PROMPT, max_tokens=6)
            assert got == "cold"
            assert tier.fallbacks.get("breaker_open", 0) >= 1
            (c,) = part.pump([{"prompt": list(PREFIX_PROMPT),
                               "max_tokens": 6, "seed": 3}])
            assert c.status == "ok" and list(c.generated) == ref_a

            # Heal: disarm the partition; the worker survived (only its
            # conn died), redials, and the reconnect bumps the epoch and
            # requests the anti-entropy digest.
            inj.disarm()
            drive(lambda: not link_a.dead and
                  index.owner_epoch["prefix-a"] > epoch_a1 and
                  deepest("prefix-a") >= 12 and
                  all(e.epoch == index.owner_epoch["prefix-a"]
                      for e in index._entries.values()
                      if e.owner == "prefix-a"),
                  60.0, "anti-entropy heal")
            assert link_a.reconnects >= 1
            assert index.fenced_total > 0  # stale epoch-1 entries fenced
            # Pulls resume bit-equal across the healed link.
            healed = _paged(params, prefix_cache_blocks=24)
            got = tier.prepare("local-h", healed, PREFIX_PROMPT, max_tokens=6)
            assert got == "remote"
            assert healed.local_prefix_depth(PREFIX_PROMPT) == 12
            (c,) = healed.pump([{"prompt": list(PREFIX_PROMPT),
                                 "max_tokens": 6, "seed": 3}])
            assert list(c.generated) == ref_a  # bit-equal after heal

            # (b) SIGKILL owner B; the next pull discovers death mid-pull
            # and walks the ladder — degraded, never lost.
            wb.proc.kill()
            coldb = _paged(params, prefix_cache_blocks=24)
            got = tier.prepare("local-c", coldb, PROMPT_B, max_tokens=6)
            assert got == "cold"
            assert tier.fallbacks.get("owner_dead", 0) >= 1
            assert deepest("prefix-b") == 0  # footprint invalidated
            (c,) = coldb.pump([{"prompt": list(PROMPT_B),
                                "max_tokens": 6, "seed": 7}])
            assert c.status == "ok" and list(c.generated) == ref_b

            # Replacement process, SAME name, EMPTY store: reconnect bumps
            # the epoch and its empty digest keeps the index clean.
            wb2 = _spawn_worker("prefix-b2", _prefix_worker_cfg(
                tmp_path, "pb2", hub.port, peer="prefix-b"))
            drive(lambda: not link_b.dead and
                  index.owner_epoch["prefix-b"] > epoch_b1, 120.0,
                  "replacement reconnect")
            epoch_b2 = index.owner_epoch["prefix-b"]

            # Stale-hint storm at the dead epoch: every event fences off
            # the index whole — zero wrong-KV routes possible.
            fenced_before = index.fenced_total
            for i in range(50):
                ok = index.ingest_publish("prefix-b", epoch_b1, {
                    "key": f"stale-{i}", "n_tokens": 12, "block_size": 4,
                    "kv_dtype": "float32",
                })
                assert ok is False
            assert index.fenced_total == fenced_before + 50
            assert deepest("prefix-b") == 0
            doc = parse_prom_text(REGISTRY.render())
            assert doc["tpu_fleet_prefix_epoch_fences_total"][()] >= 50.0
            assert doc["tpu_fleet_prefix_pub_total"][
                (("outcome", "fenced"),)] >= 50.0

            # The replacement serves and gossips at the NEW epoch; a pull
            # from it is bit-equal to the dead owner's decode (same params
            # and seed — the epoch fences state, not determinism).
            done_b2 = []
            pool_b.submit(PROMPT_B, 6, seed=7)
            drive(lambda: (done_b2.extend(pool_b.completions()) or
                           done_b2), 120.0, "replacement warm serve")
            assert list(done_b2[0].generated) == ref_b
            drive(lambda: deepest("prefix-b") >= 12, 60.0,
                  "replacement gossip")
            replaced = _paged(params, prefix_cache_blocks=24)
            got = tier.prepare("local-r", replaced, PROMPT_B, max_tokens=6)
            assert got == "remote"
            (c,) = replaced.pump([{"prompt": list(PROMPT_B),
                                   "max_tokens": 6, "seed": 7}])
            assert list(c.generated) == ref_b

            # Balanced ledgers: nothing pinned, nothing leaked.
            assert index.ledger().pinned == 0
            # One journal correlation per pull: every prefix.pull event
            # this test produced carries a unique prefix-pull-N correlation.
            _, since = JOURNAL.export_since(journal_cursor)
            pulls = [e for e in since if e["event"] == "prefix.pull"]
            assert pulls, "pulls left no journal trail"
            corrs = [e["correlation"] for e in pulls]
            assert all(c.startswith("prefix-pull-") for c in corrs)
            assert len(corrs) == len(set(corrs))
        finally:
            for w in (wa, wb, wb2):
                if w is not None:
                    w.kill()
            hub.close()
