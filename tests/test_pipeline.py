"""Pipeline-parallelism tests: the GPipe ring and the pp×dp×tp train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.models import burnin, pp_burnin
from k8s_dra_driver_tpu.ops.pipeline import pipeline_apply
from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
from tests.conftest import cpu_devices


def host(x):
    """Uncommitted host copy: usable as input on any mesh, while oracle
    computations run under a CPU default_device scope (the default backend
    may be a tunneled TPU whose bf16 matmuls would skew the f32 oracle)."""
    return np.asarray(x)


def cpu_scope():
    return jax.default_device(cpu_devices(1)[0])


class TestPipelineApply:
    def test_matches_sequential_composition(self):
        # 4 stages each multiplying by a stage-specific matrix: the pipeline
        # must equal the plain composition, for every microbatch.
        mesh = build_mesh(cpu_devices(4), MeshShape(pipe=4))
        n_micro, mb, d = 8, 2, 16
        key = jax.random.PRNGKey(0)
        ws = host(jax.random.normal(key, (4, d, d)) / np.sqrt(d))
        xs = host(jax.random.normal(jax.random.PRNGKey(1), (n_micro, mb, d)))

        def stage_fn(w, x):  # one matrix per stage
            return jnp.tanh(x @ w[0])

        body = jax.shard_map(
            lambda w, x: pipeline_apply(stage_fn, w, x),
            mesh=mesh,
            in_specs=(P("pipe"), P()),
            out_specs=P(),
            check_vma=False,
        )
        got = jax.jit(body)(ws, xs)

        with cpu_scope():
            want = jnp.asarray(xs)
            for i in range(4):
                want = jnp.tanh(want @ jnp.asarray(ws[i]))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)

    def test_gradients_flow_through_ring(self):
        mesh = build_mesh(cpu_devices(2), MeshShape(pipe=2))
        ws = host(jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8)) / 3)
        xs = host(jax.random.normal(jax.random.PRNGKey(1), (2, 2, 8)))

        def stage_fn(w, x):
            return jnp.tanh(x @ w[0])

        def loss(w):
            body = jax.shard_map(
                lambda w_: pipeline_apply(stage_fn, w_, jnp.asarray(xs)),
                mesh=mesh, in_specs=(P("pipe"),), out_specs=P(),
                check_vma=False,
            )
            return jnp.sum(body(w) ** 2)

        def ref_loss(w):
            y = jnp.asarray(xs)
            for i in range(2):
                y = jnp.tanh(y @ w[i])
            return jnp.sum(y ** 2)

        got = jax.jit(jax.grad(loss))(ws)
        with cpu_scope():
            want = jax.jit(jax.grad(ref_loss))(jnp.asarray(ws))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4)


class TestPPBurnin:
    def test_pp_loss_matches_dense(self):
        cfg = burnin.TINY  # 2 layers -> 1 per stage
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        tokens = host(burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32))
        dense = jax.tree.map(host, burnin.init_params(jax.random.PRNGKey(0), cfg))
        with cpu_scope():
            ref = float(jax.jit(lambda p, t: burnin.loss_fn(p, t, cfg))(dense, tokens))

        fns = pp_burnin.build_pp_train_step(cfg, mesh)
        with mesh:
            params = pp_burnin.pp_params_from_dense(
                jax.tree.map(jnp.asarray, dense), cfg
            )
            opt_state = burnin.make_optimizer().init(params)
            sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
            _, _, loss = fns.step(params, opt_state, sharded_tokens)
        assert abs(float(loss) - ref) < 0.05

    def test_pp_training_reduces_loss(self):
        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(4), MeshShape(pipe=2, data=2, model=1))
        fns = pp_burnin.build_pp_train_step(cfg, mesh, lr=1e-2)
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32),
                NamedSharding(mesh, P("data", None)),
            )
            first = None
            for _ in range(4):
                params, opt_state, loss = fns.step(params, opt_state, tokens)
                first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_validation_errors(self):
        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        with pytest.raises(ValueError, match="pipe >= 2"):
            pp_burnin.build_pp_train_step(
                cfg, build_mesh(cpu_devices(8), MeshShape(data=2, seq=1, model=4))
            )
        bad_layers = burnin.ModelConfig(n_layers=3)
        with pytest.raises(ValueError, match="stages"):
            pp_burnin.build_pp_train_step(bad_layers, mesh)
        seq_mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=1, seq=2, model=2))
        with pytest.raises(ValueError, match="data/model"):
            pp_burnin.build_pp_train_step(cfg, seq_mesh)

class TestPPGqaRope:
    """Pipeline TP over the modern serving geometry: GQA (whole KV groups
    per TP shard — _groupmajor_qkv) + RoPE rotated inside the stage scan.
    Round 3 rejected both with NotImplementedError; the flagship config
    (burnin.FLAGSHIP_MODERN's shape) must train in every tp_mode."""

    CFG = burnin.ModelConfig(n_kv_heads=2, rope=True)  # TINY + gqa + rope

    @pytest.mark.parametrize("tp_mode", ["megatron", "megatron-sp"])
    def test_gqa_rope_loss_matches_dense(self, tp_mode):
        cfg = self.CFG
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        tokens = host(burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32))
        dense = jax.tree.map(host, burnin.init_params(jax.random.PRNGKey(0), cfg))
        with cpu_scope():
            ref = float(jax.jit(lambda p, t: burnin.loss_fn(p, t, cfg))(dense, tokens))

        fns = pp_burnin.build_pp_train_step(cfg, mesh, tp_mode=tp_mode)
        with mesh:
            params = pp_burnin.pp_params_from_dense(
                jax.tree.map(jnp.asarray, dense), cfg
            )
            assert "pos_embed" not in params  # RoPE carries no table
            opt_state = burnin.make_optimizer().init(params)
            sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
            _, _, loss = fns.step(params, opt_state, sharded_tokens)
        assert abs(float(loss) - ref) < 0.05

    def test_gqa_rope_training_reduces_loss(self):
        cfg = self.CFG
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        fns = pp_burnin.build_pp_train_step(cfg, mesh, lr=1e-2, tp_mode="megatron-sp")
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32),
                NamedSharding(mesh, P("data", None)),
            )
            first = None
            for _ in range(4):
                params, opt_state, loss = fns.step(params, opt_state, tokens)
                first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_kv_head_divisibility_validated(self):
        cfg = burnin.ModelConfig(n_kv_heads=1, rope=True)  # 1 kv head, tp=2
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        with pytest.raises(ValueError, match="n_kv_heads"):
            pp_burnin.build_pp_train_step(cfg, mesh)


class TestMegatronSP:
    def test_sp_mode_matches_dense_loss(self):
        """megatron-sp (seq-sharded residual + overlapped collective-matmul
        rings) must reproduce the dense oracle loss like classic megatron."""
        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        tokens = host(burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32))
        dense = jax.tree.map(host, burnin.init_params(jax.random.PRNGKey(0), cfg))
        with cpu_scope():
            ref = float(jax.jit(lambda p, t: burnin.loss_fn(p, t, cfg))(dense, tokens))

        fns = pp_burnin.build_pp_train_step(cfg, mesh, tp_mode="megatron-sp")
        with mesh:
            params = pp_burnin.pp_params_from_dense(
                jax.tree.map(jnp.asarray, dense), cfg
            )
            opt_state = burnin.make_optimizer().init(params)
            sharded_tokens = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
            _, _, loss = fns.step(params, opt_state, sharded_tokens)
        assert abs(float(loss) - ref) < 0.05

    def test_sp_training_reduces_loss(self):
        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        fns = pp_burnin.build_pp_train_step(cfg, mesh, lr=1e-2, tp_mode="megatron-sp")
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32),
                NamedSharding(mesh, P("data", None)),
            )
            first = None
            for _ in range(4):
                params, opt_state, loss = fns.step(params, opt_state, tokens)
                first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_sp_validates_seq_divisibility(self):
        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(8), MeshShape(pipe=2, data=2, model=2))
        fns = pp_burnin.build_pp_train_step(cfg, mesh, tp_mode="megatron-sp")
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=31),
                NamedSharding(mesh, P("data", None)),
            )
            with pytest.raises(ValueError, match="divisible"):
                fns.step(params, opt_state, tokens)

    def test_bad_mode_rejected(self):
        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(4), MeshShape(pipe=2, data=2))
        with pytest.raises(ValueError, match="tp_mode"):
            pp_burnin.build_pp_train_step(cfg, mesh, tp_mode="colossal")
