"""JAX data-plane tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.models import burnin
from k8s_dra_driver_tpu.ops import collectives
from k8s_dra_driver_tpu.parallel.mesh import (
    MeshShape,
    auto_mesh_shape,
    build_mesh,
    mesh_for,
    validate_claimed_mesh,
)
from tests.conftest import cpu_devices


@pytest.fixture(scope="module")
def mesh8():
    return mesh_for(cpu_devices(8))  # data=2, seq=1, model=4


class TestMesh:
    def test_auto_shape_factors(self):
        assert auto_mesh_shape(8) == MeshShape(data=2, seq=1, model=4)
        assert auto_mesh_shape(8, want_seq=True) == MeshShape(data=1, seq=2, model=4)
        assert auto_mesh_shape(1) == MeshShape(1, 1, 1)
        assert auto_mesh_shape(6) == MeshShape(data=3, seq=1, model=2)

    def test_build_mesh_validates_count(self):
        with pytest.raises(ValueError, match="needs 8 devices"):
            build_mesh(cpu_devices(4), MeshShape(2, 1, 4))

    def test_validate_claimed_mesh(self, mesh8):
        validate_claimed_mesh(mesh8, {"TPU_CHIPS_PER_PROCESS_BOUNDS": "2,2,2"})
        with pytest.raises(ValueError, match="imply 4"):
            validate_claimed_mesh(mesh8, {"TPU_CHIPS_PER_PROCESS_BOUNDS": "2,2,1"})


class TestBurninModel:
    def test_forward_shapes_single_device(self):
        cfg = burnin.TINY
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=2, seq=16)
        logits = jax.jit(lambda p, t: burnin.forward(p, t, cfg))(params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_loss_decreases_single_device(self):
        cfg = burnin.TINY
        fns = burnin.build_train_step(cfg, lr=1e-2)
        params, opt_state = fns.init(jax.random.PRNGKey(0))
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32)
        first = None
        for _ in range(5):
            params, opt_state, loss = fns.step(params, opt_state, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first  # memorizing one batch must reduce loss

    def test_remat_policy_changes_time_not_numerics(self):
        """'blocks' / 'dots' / 'none' rematerialization must produce the
        same losses and gradients up to bf16 rounding (XLA may fuse the
        recompute differently, so saved-vs-rematerialized intermediates
        can differ in the last bf16 bit) — only step time and peak HBM
        move; the bench's before/after measurement depends on this."""
        cfg = burnin.TINY
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=2, seq=16)
        ref_loss, ref_grads = None, None
        for remat in ("blocks", "dots", "none"):
            loss, grads = jax.jit(
                jax.value_and_grad(
                    lambda p, t, r=remat: burnin.loss_fn(p, t, cfg, remat=r)
                )
            )(params, tokens)
            if ref_loss is None:
                ref_loss, ref_grads = loss, grads
                continue
            np.testing.assert_allclose(
                float(loss), float(ref_loss), rtol=1e-4
            )
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a, np.float32), np.asarray(b, np.float32),
                    atol=4e-3, rtol=0.02,  # bf16-epsilon scale
                ),
                grads, ref_grads,
            )

    def test_remat_policy_validated(self):
        cfg = burnin.TINY
        params = burnin.init_params(jax.random.PRNGKey(3), cfg)
        tokens = burnin.sample_tokens(jax.random.PRNGKey(4), cfg, batch=1, seq=8)
        with pytest.raises(ValueError, match="remat"):
            burnin.forward(params, tokens, cfg, remat="everything")

    def test_sharded_train_step(self, mesh8):
        cfg = burnin.TINY
        fns = burnin.build_train_step(cfg, mesh=mesh8)
        with mesh8:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            # TP layout realized: qkv column-sharded over `model`
            qkv = params["blocks"][0]["qkv"]
            assert qkv.sharding.spec == P(None, "model")
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32),
                NamedSharding(mesh8, P("data", None)),
            )
            params, opt_state, loss = fns.step(params, opt_state, tokens)
        assert jnp.isfinite(loss)

    def test_sharded_matches_single_device_loss(self, mesh8):
        cfg = burnin.TINY
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32)
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        ref = float(jax.jit(lambda p, t: burnin.loss_fn(p, t, cfg))(params, tokens))
        with mesh8:
            sharded_params = jax.device_put(
                params,
                jax.tree.map(
                    lambda spec: NamedSharding(mesh8, spec),
                    burnin.param_pspecs(cfg),
                    is_leaf=lambda x: isinstance(x, P),
                ),
            )
            sharded_tokens = jax.device_put(tokens, NamedSharding(mesh8, P("data", None)))
            got = float(
                jax.jit(
                    lambda p, t: burnin.loss_fn(
                        p, t, cfg, NamedSharding(mesh8, P("data", "seq", None))
                    )
                )(sharded_params, sharded_tokens)
            )
        assert abs(got - ref) < 0.05  # bf16 + reduction-order tolerance


class TestCollectives:
    def test_psum_bandwidth(self, mesh8):
        r = collectives.psum_bandwidth(mesh8, axis="model", mib=1, iters=3)
        assert r.n_devices == 4
        assert r.algbw_gbps > 0

    def test_all_gather_bandwidth(self, mesh8):
        r = collectives.all_gather_bandwidth(mesh8, axis="model", mib=1, iters=3)
        assert r.algbw_gbps > 0

    def test_all_to_all_bandwidth(self, mesh8):
        # the expert-parallel dispatch/return collective
        r = collectives.all_to_all_bandwidth(mesh8, axis="data", mib=1, iters=3)
        assert r.collective == "all_to_all"
        assert r.algbw_gbps > 0

    def test_ring_latency(self, mesh8):
        assert collectives.ring_latency_us(mesh8, axis="model", iters=5) > 0

    def test_matmul_tflops(self):
        assert collectives.matmul_tflops(cpu_devices(1)[0], size=256, chain=4) > 0


class TestGraftEntry:
    def test_dryrun_multichip(self, capsys):
        import __graft_entry__ as ge

        ge.dryrun_multichip(8)
        assert "dryrun_multichip: mesh" in capsys.readouterr().out

    def test_entry_compiles_tiny_analog(self):
        # entry() uses the flagship config (slow on CPU); validate the same
        # path with the tiny config here, flagship is exercised by the driver.
        import __graft_entry__ as ge

        fn, (params, tokens) = ge.entry()
        assert callable(fn) and tokens.ndim == 2


class TestGradientAccumulation:
    def test_accumulated_step_matches_full_batch(self):
        """accum_steps=2 over half-size microbatches must equal the one-shot
        full-batch step (mean loss, averaged grads) to accumulation
        tolerance — the large-batch recipe when activations exceed HBM."""
        import jax

        from k8s_dra_driver_tpu.models import burnin

        cfg = burnin.TINY
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        params = jax.tree.map(lambda a: a.astype(jnp.float32), params)
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32)

        # SGD: the param delta is LINEAR in the gradient, so this compares
        # the accumulated gradient itself.  (Through adamw a near-zero grad
        # element can flip sign under accumulation-order noise and the
        # normalized update flips with it — that would test float luck.)
        import optax

        opt = optax.sgd(0.1)
        loss_fn_ = lambda p, t: burnin.loss_fn(p, t, cfg)  # noqa: E731
        full = jax.jit(burnin.make_sgd_step(loss_fn_, opt))
        acc = jax.jit(burnin.make_sgd_step(loss_fn_, opt, accum_steps=2))
        opt_state = opt.init(params)
        p1, _, l1 = full(params, opt_state, tokens)
        p2, _, l2 = acc(params, opt_state, tokens)
        assert abs(float(l1) - float(l2)) < 1e-5
        for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4
            )

    def test_indivisible_batch_rejected(self):
        import jax
        import pytest

        from k8s_dra_driver_tpu.models import burnin

        cfg = burnin.TINY
        fns = burnin.build_train_step(cfg, accum_steps=3)
        params, opt_state = fns.init(jax.random.PRNGKey(0))
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32)
        with pytest.raises(ValueError, match="not divisible"):
            fns.step(params, opt_state, tokens)

    def test_sharded_accumulation_runs(self):
        import jax
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from k8s_dra_driver_tpu.models import burnin
        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh

        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        fns = burnin.build_train_step(burnin.TINY, mesh=mesh, accum_steps=2)
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), burnin.TINY, batch=8, seq=64),
                NamedSharding(mesh, P("data", None)),
            )
            _, _, loss = fns.step(params, opt_state, tokens)
        assert np.isfinite(float(loss))


class TestOptimizerKnobs:
    def test_warmup_cosine_schedule_shapes_lr(self):
        from k8s_dra_driver_tpu.models import burnin

        cfg = burnin.TINY
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        opt = burnin.make_optimizer(1e-2, warmup_steps=2, decay_steps=10)
        state = opt.init(params)
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=2, seq=16)
        step = jax.jit(
            burnin.make_sgd_step(lambda p, t: burnin.loss_fn(p, t, cfg), opt)
        )
        # warmup: the very first update is ~zero (lr starts at 0)
        p1, state, _ = step(params, state, tokens)
        d1 = max(
            float(jnp.abs(a - b).max())
            for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(params))
        )
        p2, state, _ = step(p1, state, tokens)
        d2 = max(
            float(jnp.abs(a - b).max())
            for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(p1))
        )
        assert d1 < d2  # lr ramped up between step 0 and step 1

    def test_grad_clip_changes_the_update(self):
        """Clipping must actually engage: with plain SGD the param delta is
        the (clipped) gradient times lr, so a tiny clip bounds the global
        update norm where the unclipped step exceeds it."""
        import optax

        from k8s_dra_driver_tpu.models import burnin

        cfg = burnin.TINY
        params = jax.tree.map(
            lambda a: a.astype(jnp.float32),
            burnin.init_params(jax.random.PRNGKey(0), cfg),
        )
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=2, seq=16)
        loss_fn_ = lambda p, t: burnin.loss_fn(p, t, cfg)  # noqa: E731
        clip = 1e-3

        def delta_norm(opt):
            state = opt.init(params)
            p1, _, _ = jax.jit(burnin.make_sgd_step(loss_fn_, opt))(
                params, state, tokens
            )
            return float(
                optax.global_norm(jax.tree.map(lambda a, b: a - b, p1, params))
            )

        unclipped = delta_norm(optax.sgd(1.0))
        clipped = delta_norm(
            optax.chain(optax.clip_by_global_norm(clip), optax.sgd(1.0))
        )
        via_factory_sees_clip = burnin.make_optimizer(1e-2, grad_clip=clip)
        assert unclipped > clip * 2  # the clip is actually binding here
        assert clipped <= clip * 1.01
        # and the factory wires the same transform (structural check)
        assert delta_norm(via_factory_sees_clip) < delta_norm(
            burnin.make_optimizer(1e-2)
        )

    def test_partial_schedule_spec_rejected(self):
        import pytest

        from k8s_dra_driver_tpu.models import burnin

        with pytest.raises(ValueError, match="decay_steps > "):
            burnin.make_optimizer(1e-3, warmup_steps=100)
        with pytest.raises(ValueError, match="warmup_steps > 0"):
            burnin.make_optimizer(1e-3, decay_steps=100)
