"""Rebalance chaos suite: the deadlock-proof disaggregated control plane
under injected link death, decode starvation and replica crashes
(`make chaos-rebalance`, <20s, CPU, seeded).

The three acceptance scenarios this PR pins:

* **Link death mid-transfer** — a ``channel_down`` fault kills the
  carrying interconnect link between ``begin`` and ``complete``; the
  transfer fails over to a sibling link in the bound :class:`ChannelSet`
  and the stream completes BIT-EQUAL with zero re-prefill fallbacks.
  Only when EVERY link is gone does the fallback ladder run.
* **Decode starvation** — full-stream KV demand exceeds the decode
  pool's reservable blocks; over-demand handoffs park at the prefill
  side (typed backpressure, gauge + journal) and re-admit FIFO as
  completions free capacity — no deadlock, no lost stream.  When the
  pool provably can NEVER hold a stream, the deadlock detector fires a
  diag bundle and force-collapses it to unified service on the prefill
  pool — degraded beats wedged.
* **Pool move under replica crash** — ``FleetAutoscaler.scale_move``
  live-drains a replica out of one pool and merge-restores it into the
  other under one ``scale-<seq>-<n>`` correlation; a fault crashes the
  moved replica mid-load and the fleet machinery still delivers every
  stream exactly once with balanced block accounting.

Every fault draws from a seeded injector, latency is accounted (never
slept), and each scenario replays from its seed.
"""

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, paged
from k8s_dra_driver_tpu.models.autoscaler import (
    FleetAutoscaler,
    PoolRebalancer,
    RebalancePolicy,
)
from k8s_dra_driver_tpu.models.disagg import ChannelClaim, DisaggRouter
from k8s_dra_driver_tpu.models.fleet import DRAINED, FleetRouter
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.utils.faults import FaultInjector
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, parse_prom_text

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 41)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


def _inj(spec: str) -> FaultInjector:
    return FaultInjector.from_env(spec)


# Explicit per-request seeds: router-minted ids differ from the unified
# reference, so sampling keys must come from the request, never the id.
REQS = [
    {"prompt": [7, 8, 9], "max_tokens": 6, "seed": 5},
    {"prompt": [3, 4], "max_tokens": 6, "temperature": 0.7, "seed": 9},
    {"prompt": [11, 12, 13, 14], "max_tokens": 6, "seed": 21},
    {"prompt": [1, 2], "max_tokens": 6, "seed": 33},
    {"prompt": [21, 22, 23], "max_tokens": 6, "seed": 44},
]

# Two-link channel set: selection prefers ici-0 (more bandwidth) when
# idle, so killing it exercises the mid-transfer failover path.
LINKS = (
    dict(name="ici-0", bandwidth_gbps=100.0),
    dict(name="ici-1", bandwidth_gbps=50.0),
)


def _links():
    return [ChannelClaim(**kw) for kw in LINKS]


def _by_prompt(completions):
    out = {}
    for c in completions:
        out[tuple(c.tokens[: len(c.tokens) - len(c.generated)])] = tuple(
            c.generated
        )
    return out


@pytest.fixture(scope="module")
def reference(params):
    """Fault-free streams for REQS — the bit-equality baseline."""
    return _by_prompt(_dense(params).pump([dict(r) for r in REQS]))


def _storm(params, spec, *, channel=None, decode_kw=None):
    inj = _inj(spec) if spec else None
    pre, dec = _paged(params), _paged(params, **(decode_kw or {}))
    free0 = (pre.free_blocks, dec.free_blocks)
    router = DisaggRouter(
        prefill=[pre], decode=[dec],
        channel=channel if channel is not None else _links(),
        fault_injector=inj,
    )
    done = router.pump([dict(r) for r in REQS])
    free1 = (pre.free_blocks, dec.free_blocks)
    return router, done, free0, free1


def _assert_no_lost_or_dup(done, reference):
    assert len(done) == len(REQS)
    assert [c.status for c in done].count("ok") == len(REQS)
    rids = [c.request_id for c in done]
    assert len(rids) == len(set(rids)), "duplicated completion ids"
    assert _by_prompt(done) == reference


class TestRebalanceFaultHooks:
    def test_from_env_parses_link_kinds(self):
        inj = _inj(
            "channel_down=1.0,channel_degrade=0.25,channels=ici-0,"
            "limit=3,seed=7"
        )
        (p,) = inj._profiles
        assert p.channel_down_rate == 1.0
        assert p.channel_degrade == 0.25
        assert p.channels == ("ici-0",)
        assert p.limit == 3

    def test_channel_scope_and_budget(self):
        inj = _inj("channel_down=1.0,channels=ici-0,limit=1,seed=3")
        assert not inj.take_channel_down("ici-1")  # out of scope: silent
        assert inj.take_channel_down("ici-0")
        assert not inj.take_channel_down("ici-0")  # budget spent

    def test_degrade_scales_only_scoped_links(self):
        inj = _inj("channel_degrade=0.25,channels=ici-0,limit=2,seed=7")
        assert inj.channel_bandwidth_factor("ici-0") == pytest.approx(0.25)
        assert inj.channel_bandwidth_factor("ici-1") == pytest.approx(1.0)
        assert inj.channel_bandwidth_factor("ici-0") == pytest.approx(0.25)
        # budget exhausted: the link browns back in
        assert inj.channel_bandwidth_factor("ici-0") == pytest.approx(1.0)


class TestLinkDeathFailover:
    """Scenario 1: the carrying link dies mid-transfer; the sibling takes
    the payload and the fallback ladder never runs."""

    def test_sibling_failover_bit_equal_no_fallback(self, params, reference):
        JOURNAL.clear()
        router, done, free0, free1 = _storm(
            params, "channel_down=1.0,channels=ici-0,limit=1,seed=3"
        )
        _assert_no_lost_or_dup(done, reference)
        assert router.fallbacks == 0, "failover must not burn a re-prefill"
        # every transfer already in flight on the dead link hops once
        hops = router.channel.failovers
        assert hops >= 1
        counts = router.channel.counts
        assert counts["channel_down"] == hops
        assert counts["ok"] == len(REQS)
        assert free1 == free0
        events = JOURNAL.tail(limit=400, component="disagg")
        hopped = [e for e in events if e["event"] == "transfer.failover"]
        assert len(hopped) == hops
        assert all(
            e["attrs"]["from_channel"] == "ici-0"
            and e["attrs"]["to_channel"] == "ici-1"
            for e in hopped
        )
        assert any(e["event"] == "channel.down" for e in events)

    def test_failover_metrics_rendered(self, params, reference):
        router, done, _, _ = _storm(
            params, "channel_down=1.0,channels=ici-0,limit=1,seed=3"
        )
        _assert_no_lost_or_dup(done, reference)
        doc = parse_prom_text(REGISTRY.render())
        up = doc["tpu_disagg_channel_up"]
        assert up[(("channel", "ici-0"),)] == 0.0
        assert up[(("channel", "ici-1"),)] == 1.0
        hops = doc["tpu_disagg_channel_failover_total"]
        assert hops[(("reason", "channel_down"),)] >= 1.0
        # the per-channel /debug/disagg table shows the dead link
        table = router.stats()["channel"]["channels"]
        by_name = {row["claim"]["name"]: row for row in table}
        assert by_name["ici-0"]["up"] is False
        assert by_name["ici-0"]["forced_down"] == "fault"
        assert by_name["ici-1"]["up"] is True

    def test_browned_out_link_hops_without_fallback(self, params, reference):
        # channel_degrade shrinks ici-0's bandwidth so far every transfer
        # on it goes stale — each one hops to the healthy sibling instead
        # of falling back to re-prefill.
        router, done, free0, free1 = _storm(
            params, "channel_degrade=0.00000001,channels=ici-0,seed=7"
        )
        _assert_no_lost_or_dup(done, reference)
        assert router.fallbacks == 0
        assert router.channel.failovers >= 1
        counts = router.channel.counts
        assert counts["ok"] == len(REQS)
        assert counts.get("deadline", 0) >= 1
        assert free1 == free0

    def test_all_links_down_falls_back_to_reprefill(self, params, reference):
        # Both links die: the SET reports down and every staged payload
        # lands on the KV-less fallback rung — degraded, never lost.
        router, done, free0, free1 = _storm(
            params, "channel_down=1.0,limit=2,seed=3"
        )
        _assert_no_lost_or_dup(done, reference)
        assert router.channel.down
        assert router.fallbacks >= 1
        assert free1 == free0

    def test_storm_replays_from_seed(self, params):
        spec = "channel_down=1.0,channels=ici-0,limit=1,seed=11"
        a = _storm(params, spec)[0].channel.counts
        b = _storm(params, spec)[0].channel.counts
        assert a == b


class TestAdmissionBackpressure:
    """Scenario 2: KV demand beyond decode capacity parks at the prefill
    side and re-admits as capacity frees — starvation is backpressure,
    not deadlock."""

    def test_starved_handoffs_park_then_complete(self, params, reference):
        JOURNAL.clear()
        # reservable = n_blocks - 1 = 7 decode blocks vs 13 blocks of
        # full-stream demand across REQS: some streams must park.
        router, done, free0, free1 = _storm(
            params, "", decode_kw=dict(n_blocks=8)
        )
        _assert_no_lost_or_dup(done, reference)
        assert free1 == free0
        events = JOURNAL.tail(limit=600, component="disagg")
        kinds = [e["event"] for e in events]
        assert kinds.count("admission.parked") >= 1
        assert kinds.count("admission.unparked") >= 1
        adm = router.stats()["admission"]
        assert adm["parked"] == 0
        assert adm["ledger_streams"] == 0
        assert adm["deadlock_fired"] == 0
        doc = parse_prom_text(REGISTRY.render())
        assert doc["tpu_disagg_admission_parked"][()] == 0.0

    def test_impossible_stream_fires_deadlock_collapse(
        self, params, tmp_path, monkeypatch
    ):
        from k8s_dra_driver_tpu.utils.watchdog import WATCHDOG

        monkeypatch.setattr(
            WATCHDOG, "_bundle_dir", str(tmp_path), raising=False
        )
        JOURNAL.clear()
        req = {"prompt": list(range(20, 34)), "max_tokens": 16, "seed": 3}
        ref = _by_prompt(_dense(params).pump([dict(req)]))
        pre, dec = _paged(params), _paged(params, n_blocks=5)
        # full-stream demand = ceil(30 / 4) = 8 blocks vs 4 reservable:
        # NOTHING will ever free enough — the detector must fire.
        router = DisaggRouter(
            prefill=[pre], decode=[dec], channel=_links(), deadlock_ticks=5
        )
        done = router.pump([dict(req)])
        assert len(done) == 1 and done[0].status == "ok"
        assert _by_prompt(done) == ref, "collapsed stream must stay bit-equal"
        assert router.deadlock_fired == 1
        assert router.fallbacks == 1
        assert REGISTRY.counter("tpu_disagg_fallback_total").value(
            reason="deadlock_collapse"
        ) == 1
        events = JOURNAL.tail(limit=400, component="disagg")
        kinds = [e["event"] for e in events]
        assert kinds.count("admission.deadlock") == 1
        assert kinds.count("handoff.deadlock_collapse") == 1
        bundles = list(tmp_path.iterdir())
        assert bundles, "deadlock must dump a diag bundle"

    def test_deadlock_replays_from_seed(self, params):
        req = {"prompt": list(range(20, 34)), "max_tokens": 16, "seed": 3}

        def run():
            router = DisaggRouter(
                prefill=[_paged(params)],
                decode=[_paged(params, n_blocks=5)],
                channel=_links(), deadlock_ticks=5,
            )
            done = router.pump([dict(req)])
            return [tuple(c.generated) for c in done], router.deadlock_fired

        assert run() == run()


class TestScaleMove:
    """The zero-loss pool-rebalancing actuator, fault-free."""

    def test_move_replica_between_pools(self, params):
        JOURNAL.clear()
        src = FleetRouter([_dense(params), _dense(params)])
        dst = FleetRouter([_dense(params)])
        scaler = FleetAutoscaler(src, lambda: _dense(params))
        corr = scaler.scale_move(dst)
        assert corr is not None and corr.startswith("scale-")
        assert len(src.replicas) == 1
        assert len(dst.replicas) == 2
        assert REGISTRY.counter("tpu_autoscale_events_total").value(
            direction="move", reason="rebalance"
        ) == 1
        events = JOURNAL.tail(limit=100, component="autoscale")
        spans = {
            e["event"]: e["correlation"]
            for e in events
            if e["event"] in ("scale_move.begin", "scale_move.resumed")
        }
        assert spans == {
            "scale_move.begin": corr, "scale_move.resumed": corr,
        }

    def test_move_refused_at_min_replicas(self, params):
        src = FleetRouter([_dense(params)])
        dst = FleetRouter([_dense(params)])
        scaler = FleetAutoscaler(src, lambda: _dense(params))
        assert scaler.scale_move(dst) is None
        assert len(src.replicas) == 1 and len(dst.replicas) == 1

    def test_remove_replica_requires_drained(self, params):
        router = FleetRouter([_dense(params), _dense(params)])
        name = router.replicas[0].name
        with pytest.raises(ValueError):
            router.remove_replica(name)

    def test_pool_move_under_replica_crash_zero_loss(self, params, reference):
        """Scenario 3: move a prefill replica into the decode pool
        mid-load, then crash the moved replica — every stream still
        delivers exactly once, bit-equal, blocks balanced."""
        JOURNAL.clear()
        # replicas=1 scopes the crash to pool index 1: after the move
        # only the DECODE pool has a second replica — the moved engine.
        inj = _inj("replica_crash_rate=1.0,replicas=1,steps=8,limit=1,seed=2")
        e1, e2, d1 = _paged(params), _paged(params), _paged(params)
        free0 = (e1.free_blocks, e2.free_blocks, d1.free_blocks)
        router = DisaggRouter(prefill=[e1, e2], decode=[d1],
                              channel=_links(), fault_injector=inj)
        scaler = FleetAutoscaler(router.prefill, lambda: _paged(params))
        for r in REQS:
            req = dict(r)
            router.submit(req.pop("prompt"), req.pop("max_tokens"), **req)
        done, corr = [], None
        for i in range(400):
            router.tick()
            done.extend(router.completions())
            if i == 2:
                corr = scaler.scale_move(router.decode)
                assert corr is not None
                assert len(router.prefill.replicas) == 1
                assert len(router.decode.replicas) == 2
            if (
                len(done) == len(REQS)
                and router.prefill.idle() and router.decode.idle()
            ):
                break
        _assert_no_lost_or_dup(done, reference)
        assert inj.stats().get("replica_crash") == 1
        assert any(r.state == DRAINED for r in router.decode.replicas)
        assert (e1.free_blocks, e2.free_blocks, d1.free_blocks) == free0
        assert REGISTRY.counter("tpu_autoscale_events_total").value(
            direction="move", reason="rebalance"
        ) == 1
        adm = router.stats()["admission"]
        assert adm["ledger_streams"] == 0 and adm["parked"] == 0


class TestPoolRebalancer:
    """TTFT-stage-driven control law over scale_move."""

    def _setup(self, params, **pol):
        now = [0.0]
        pol.setdefault("dominance", 2.0)
        pol.setdefault("min_samples", 4)
        pol.setdefault("vote_ticks", 2)
        pol.setdefault("cooldown_s", 60.0)
        clock = lambda: now[0]
        router = DisaggRouter(
            prefill=[_dense(params), _dense(params)],
            decode=[_dense(params)], channel=_links(), clock=clock,
        )
        pre_s = FleetAutoscaler(
            router.prefill, lambda: _dense(params), clock=clock
        )
        dec_s = FleetAutoscaler(
            router.decode, lambda: _dense(params), clock=clock
        )
        rb = PoolRebalancer(
            router, pre_s, dec_s, RebalancePolicy(**pol), clock=clock
        )
        return router, rb, now

    def _feed(self, router, pre_mean, dec_mean, n=4):
        for _ in range(n):
            router._observe_stage("prefill", pre_mean)
            router._observe_stage("decode", dec_mean)

    def test_vote_needs_dominance_and_samples(self):
        rb = PoolRebalancer.__new__(PoolRebalancer)
        rb.policy = RebalancePolicy(dominance=2.0, min_samples=4)
        v = rb._vote
        pre = lambda m, n=8: {"mean_s": m, "n": n, "sum_s": m * n}
        assert v({"prefill": pre(0.01), "decode": pre(0.1)}) == "to_decode"
        assert v({"prefill": pre(0.1), "decode": pre(0.01)}) == "to_prefill"
        assert v({"prefill": pre(0.01), "decode": pre(0.015)}) == ""
        assert v({"prefill": pre(0.01, n=2), "decode": pre(0.1)}) == ""
        assert v({}) == ""

    def test_sustained_decode_starvation_moves_a_replica(self, params):
        router, rb, _ = self._setup(params)
        self._feed(router, 0.01, 0.1)
        d1 = rb.tick()
        assert d1["vote"] == "to_decode" and d1["corr"] is None
        self._feed(router, 0.01, 0.1)
        d2 = rb.tick()
        assert d2["corr"] is not None
        assert rb.moves == 1
        assert len(router.prefill.replicas) == 1
        assert len(router.decode.replicas) == 2

    def test_single_slow_window_does_not_slosh(self, params):
        router, rb, _ = self._setup(params)
        self._feed(router, 0.01, 0.1)
        rb.tick()
        rb.tick()  # empty window: streak resets
        self._feed(router, 0.01, 0.1)
        rb.tick()
        assert rb.moves == 0
        assert len(router.prefill.replicas) == 2

    def test_cooldown_blocks_immediate_counter_move(self, params):
        router, rb, now = self._setup(params)
        for _ in range(2):
            self._feed(router, 0.01, 0.1)
            rb.tick()
        assert rb.moves == 1
        # mirror-image pressure inside the cooldown window: no slosh
        for _ in range(3):
            self._feed(router, 0.1, 0.01)
            rb.tick()
        assert rb.moves == 1
        assert rb.last_decision["cooldown"] is True
        # window passes: the counter-move is allowed again
        now[0] += 61.0
        for _ in range(2):
            self._feed(router, 0.1, 0.01)
            rb.tick()
        assert rb.moves == 2
        assert len(router.prefill.replicas) == 2
        assert len(router.decode.replicas) == 1
