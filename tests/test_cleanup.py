"""Orphan-cleanup loop + tpu-ctl CLI tests."""

import subprocess
from pathlib import Path

import pytest

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.api import API_VERSION
from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
from k8s_dra_driver_tpu.kube.objects import (
    Deployment,
    DeviceClaimConfiguration,
    OpaqueDeviceConfiguration,
)
from k8s_dra_driver_tpu.plugin.driver import Driver, DriverConfig

CPP_DIR = Path(__file__).parent.parent / "k8s_dra_driver_tpu" / "tpuinfo" / "cpp"


@pytest.fixture
def rig(tmp_path):
    cluster = make_cluster(hosts=1, work_dir=str(tmp_path / "work"))
    driver = Driver(
        cluster.server,
        DriverConfig(
            node_name="tpu-host-0",
            cdi_root=str(tmp_path / "cdi"),
            checkpoint_path=str(tmp_path / "checkpoint.json"),
            topology_env={"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"},
            publish=False,
            daemon_backoff_initial=0.001,
        ),
    )
    return cluster, driver


def spatial_config():
    return [
        DeviceClaimConfiguration(
            opaque=OpaqueDeviceConfiguration(
                driver=DRIVER_NAME,
                parameters={
                    "apiVersion": API_VERSION,
                    "kind": "TpuConfig",
                    "sharing": {"strategy": "SpatialPartition"},
                },
            )
        )
    ]


class TestOrphanCleanup:
    def prepare_claim(self, cluster, driver, name, config=None):
        claim = cluster.server.create(simple_claim(name))
        if config:
            claim.spec.devices.config = config
            claim = cluster.server.update(claim)
        allocated = cluster.allocator.allocate(claim, node_name="tpu-host-0")
        driver.state.prepare(allocated)
        return allocated

    def test_deleted_claim_is_fully_unprepared(self, rig):
        cluster, driver = rig
        claim = self.prepare_claim(cluster, driver, "gone", config=spatial_config())
        assert cluster.server.list(Deployment.KIND, namespace="tpu-dra-driver")
        cluster.server.delete("ResourceClaim", "gone", "default")
        cleaned = driver.cleanup_orphans()
        assert cleaned["claims"] == [claim.metadata.uid]
        assert driver.state.prepared_claim_uids() == []
        assert cluster.server.list(Deployment.KIND, namespace="tpu-dra-driver") == []
        assert not driver.state.cdi.claim_spec_path(claim.metadata.uid).exists()

    def test_live_claim_untouched(self, rig):
        cluster, driver = rig
        claim = self.prepare_claim(cluster, driver, "live")
        cleaned = driver.cleanup_orphans()
        assert cleaned == {"claims": [], "cdi_specs": [], "daemons": []}
        assert driver.state.prepared_claim_uids() == [claim.metadata.uid]

    def test_stray_cdi_spec_removed(self, rig):
        cluster, driver = rig
        stray = driver.state.cdi.claim_spec_path("dead-uid")
        stray.write_text("{}")
        cleaned = driver.cleanup_orphans()
        assert cleaned["cdi_specs"] == ["dead-uid"]
        assert not stray.exists()

    def test_stray_daemon_removed(self, rig):
        cluster, driver = rig
        # Simulate a crash between daemon create and checkpoint write: daemon
        # exists, checkpoint has no claim.
        from k8s_dra_driver_tpu.kube.objects import ObjectMeta

        cluster.server.create(
            Deployment(
                metadata=ObjectMeta(
                    name="tpu-topology-daemon-deadbeef",
                    namespace="tpu-dra-driver",
                    labels={
                        "app.kubernetes.io/name": "tpu-topology-daemon",
                        "resourceclaim.tpu.google.com/uid": "dead-uid",
                        "tpu.google.com/node": "tpu-host-0",
                    },
                )
            )
        )
        cleaned = driver.cleanup_orphans()
        assert cleaned["daemons"] == ["tpu-topology-daemon-deadbeef"]

    def test_other_nodes_daemons_untouched(self, rig):
        # A daemon owned by another node's plugin must never look like an
        # orphan to this node's sweep.
        cluster, driver = rig
        from k8s_dra_driver_tpu.kube.objects import ObjectMeta

        cluster.server.create(
            Deployment(
                metadata=ObjectMeta(
                    name="tpu-topology-daemon-othernode",
                    namespace="tpu-dra-driver",
                    labels={
                        "app.kubernetes.io/name": "tpu-topology-daemon",
                        "resourceclaim.tpu.google.com/uid": "foreign-uid",
                        "tpu.google.com/node": "tpu-host-9",
                    },
                )
            )
        )
        cleaned = driver.cleanup_orphans()
        assert cleaned["daemons"] == []
        assert cluster.server.get(
            Deployment.KIND, "tpu-topology-daemon-othernode", "tpu-dra-driver"
        )

    def test_uid_reuse_is_detected(self, rig):
        # Claim deleted and recreated with the same name but a new uid: the
        # old prepared state must be cleaned.
        cluster, driver = rig
        old = self.prepare_claim(cluster, driver, "reused")
        cluster.server.delete("ResourceClaim", "reused", "default")
        cluster.server.create(simple_claim("reused"))
        cleaned = driver.cleanup_orphans()
        assert cleaned["claims"] == [old.metadata.uid]


class TestTpuCtl:
    @pytest.fixture(scope="class", autouse=True)
    def build(self):
        subprocess.run(["make", "-s", "-C", str(CPP_DIR), "tpu-ctl"], check=True)

    def run_ctl(self, *args, topo="v5e-16", host="1", extra_env=None):
        env = {
            "TPUINFO_FAKE_TOPOLOGY": topo,
            "TPUINFO_FAKE_HOST_ID": host,
            "PATH": "/usr/bin",
            **(extra_env or {}),
        }
        return subprocess.run(
            [str(CPP_DIR / "tpu-ctl"), *args],
            env=env,
            capture_output=True,
            text=True,
        )

    def test_list(self):
        r = self.run_ctl("list")
        assert r.returncode == 0
        assert r.stdout.count("TPU ") == 4
        assert "topology 4x4, host 1, 4 local chip(s)" in r.stdout
        assert "UNHEALTHY" not in r.stdout

    def test_list_shows_unhealthy_reason(self):
        # nvidia-smi -L style inline degraded-state display
        r = self.run_ctl("list", extra_env={"TPUINFO_FAKE_DEAD_CHIPS": "2"})
        assert r.returncode == 0
        lines = r.stdout.splitlines()
        assert "[UNHEALTHY: fault-injected]" in lines[2]
        assert sum("[UNHEALTHY" in ln for ln in lines) == 1

    def test_topology_json(self):
        import json

        r = self.run_ctl("topology")
        doc = json.loads(r.stdout)
        assert doc["generation"] == "v5e" and len(doc["chips"]) == 4

    def test_error_path(self):
        r = self.run_ctl("list", topo="nope")
        assert r.returncode == 1
        assert "invalid TPUINFO_FAKE_TOPOLOGY" in r.stderr

    def test_bad_command(self):
        r = self.run_ctl("frobnicate")
        assert r.returncode == 2
