"""Soak test: randomized claim churn must leak nothing.

Hundreds of interleaved create/schedule/prepare/delete cycles against one
cluster; at every quiescent point the node must hold exactly the state of
the live pods — no stray checkpoint entries, CDI spec files, topology
daemons, reservations, or allocator usage.  This is the long-running-node
confidence the reference's manual kind demos cannot give (SURVEY.md §4).
"""

import random

import pytest

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.e2e.harness import make_cluster
from k8s_dra_driver_tpu.e2e.spec_runner import SpecError
from k8s_dra_driver_tpu.kube import serde
from k8s_dra_driver_tpu.kube.objects import ObjectMeta, ResourceClaim, ResourceClaimSpec

POD_TEMPLATES = [
    ("chip", {"requests": [{"name": "r", "deviceClassName": "tpu.google.com"}]}),
    (
        "pair",
        {"requests": [{"name": "r", "deviceClassName": "tpu.google.com", "count": 2}]},
    ),
    (
        "slice12",
        {
            "requests": [
                {
                    "name": "r",
                    "deviceClassName": "subslice.tpu.google.com",
                    "selectors": [
                        {
                            "cel": {
                                "expression": "device.attributes['tpu.google.com'].shape == '1x2'"
                            }
                        }
                    ],
                }
            ]
        },
    ),
    (
        "shared-ts",
        {
            "requests": [{"name": "r", "deviceClassName": "tpu.google.com"}],
            "config": [
                {
                    "requests": ["r"],
                    "opaque": {
                        "driver": DRIVER_NAME,
                        "parameters": {
                            "apiVersion": "resource.tpu.google.com/v1alpha1",
                            "kind": "TpuConfig",
                            "sharing": {"strategy": "TimeSlicing"},
                        },
                    },
                }
            ],
        },
    ),
    (
        "spatial",
        {
            "requests": [{"name": "r", "deviceClassName": "tpu.google.com"}],
            "config": [
                {
                    "requests": ["r"],
                    "opaque": {
                        "driver": DRIVER_NAME,
                        "parameters": {
                            "apiVersion": "resource.tpu.google.com/v1alpha1",
                            "kind": "TpuConfig",
                            "sharing": {"strategy": "SpatialPartition"},
                        },
                    },
                }
            ],
        },
    ),
]


def make_pod_doc(name, claim_name):
    return {
        "kind": "Pod",
        "metadata": {"namespace": "churn", "name": name},
        "spec": {
            "containers": [{"name": "c", "resources": {"claims": [{"name": "r"}]}}],
            "resourceClaims": [{"name": "r", "resourceClaimName": claim_name}],
        },
    }


@pytest.mark.parametrize("seed", [0, 7])
def test_churn_leaves_no_residue(tmp_path, seed):
    rng = random.Random(seed)
    cluster = make_cluster(hosts=2, topology="v5e-16", work_dir=str(tmp_path))
    from k8s_dra_driver_tpu.e2e.spec_runner import _run_pod

    live: list[str] = []
    counter = 0
    for step in range(150):
        if live and (rng.random() < 0.45 or len(live) >= 6):
            victim = rng.choice(live)
            live.remove(victim)
            cluster.delete_pod(victim, "churn")
            cluster.server.delete("ResourceClaim", f"claim-{victim}", "churn")
            continue
        counter += 1
        kind, claim_spec = rng.choice(POD_TEMPLATES)
        pod_name = f"p{counter}-{kind}"
        cluster.server.create(
            ResourceClaim(
                metadata=ObjectMeta(name=f"claim-{pod_name}", namespace="churn"),
                spec=serde.from_json(ResourceClaimSpec, {"devices": claim_spec}),
            )
        )
        try:
            _run_pod(cluster, make_pod_doc(pod_name, f"claim-{pod_name}"), {})
            live.append(pod_name)
        except SpecError:
            # capacity rejection: clean up the claim we just created
            cluster.server.delete("ResourceClaim", f"claim-{pod_name}", "churn")

    # drain everything
    for pod_name in list(live):
        cluster.delete_pod(pod_name, "churn")
        cluster.server.delete("ResourceClaim", f"claim-{pod_name}", "churn")

    # --- invariants at quiescence ---
    for node in cluster.nodes.values():
        assert node.state.prepared_claim_uids() == []
        assert node.state.cdi.list_claim_spec_uids() == []
    assert cluster.server.list("Deployment", namespace="tpu-dra-driver") == []
    assert cluster.server.list("ResourceClaim", namespace="churn") == []
    assert cluster.server.list("Pod", namespace="churn") == []
    # the whole inventory is allocatable again
    from k8s_dra_driver_tpu.kube.objects import DeviceClaim, DeviceRequest
    from k8s_dra_driver_tpu.scheduler.allocator import Allocator

    final = cluster.server.create(
        ResourceClaim(
            metadata=ObjectMeta(name="final", namespace="churn"),
            spec=ResourceClaimSpec(
                devices=DeviceClaim(
                    requests=[
                        DeviceRequest(
                            name="all", device_class_name="tpu.google.com", count=4
                        )
                    ]
                )
            ),
        )
    )
    granted = Allocator(cluster.server).allocate(final, node_name="tpu-host-0")
    assert len(granted.status.allocation.devices.results) == 4
