"""CEL-subset evaluator tests."""

import pytest

from k8s_dra_driver_tpu.scheduler.cel import AttrBag, CELError, evaluate


ENV = {
    "device": AttrBag(
        driver="tpu.google.com",
        attributes=AttrBag(
            {
                "tpu.google.com": AttrBag(
                    type="tpu",
                    index=3,
                    productName="tpu-v5e",
                    healthy=True,
                    shape="2x2",
                )
            }
        ),
        capacity=AttrBag({"tpu.google.com": AttrBag(hbm="16Gi")}),
    )
}


@pytest.mark.parametrize(
    "expr,expected",
    [
        ("device.driver == 'tpu.google.com'", True),
        ('device.driver == "gpu.nvidia.com"', False),
        ("device.attributes['tpu.google.com'].type == 'tpu'", True),
        (
            "device.driver == 'tpu.google.com' && device.attributes['tpu.google.com'].type == 'tpu'",
            True,
        ),
        ("device.attributes['tpu.google.com'].index in [0, 1, 3]", True),
        ("device.attributes['tpu.google.com'].index in [0, 1]", False),
        ("device.attributes['tpu.google.com'].productName.matches('v5e|v6e')", True),
        ("device.attributes['tpu.google.com'].productName.startsWith('tpu-')", True),
        ("device.attributes['tpu.google.com'].productName.endsWith('v4')", False),
        ("device.attributes['tpu.google.com'].productName.contains('5e')", True),
        ("size(device.attributes['tpu.google.com'].shape) == 3", True),
        ("device.attributes['tpu.google.com'].index >= 2", True),
        ("device.attributes['tpu.google.com'].index + 1 == 4", True),
        ("!device.attributes['tpu.google.com'].healthy", False),
        ("device.attributes['tpu.google.com'].healthy ? 1 : 2", 1),
        ("1 < 2 || 3 < 2", True),
        ("10 % 3", 1),
        ("-(2 * 3) + 7", 1),
        ("[1, 2][1]", 2),
    ],
)
def test_eval(expr, expected):
    assert evaluate(expr, ENV) == expected


@pytest.mark.parametrize(
    "expr",
    [
        "unknownVar == 1",
        "device.attributes['other.domain'].type == 'x'",  # missing key
        "device.attributes['tpu.google.com'].nope == 1",
        "device.driver ==",  # syntax
        "device.driver == 'a' &&",  # syntax
        "1 +",  # syntax
        "device.attributes['tpu.google.com'].index.matches('x')",  # non-string recv
        "'a'.matches('[')",  # bad regex
        "1 && true",  # non-bool operand
        "quantity()",  # arity
        "quantity(1.5)",  # non-string/int arg
        "quantity(true)",  # no bool->int coercion in CEL
        "size()",  # arity
        "size(5)",  # unsized argument
        "quantity('bananas')",  # malformed quantity
        "'abc'.contains()",  # method arity
        "'abc'.startsWith('a', 'b')",  # method arity
        # neg / in must raise CELError, not a raw TypeError that escapes
        # the allocator's non-matching-selector handling (advisor, round 1)
        "-device.attributes['tpu.google.com'].type == 1",  # negate a string
        "1 in 5",  # unsized container
    ],
)
def test_errors(expr):
    with pytest.raises(CELError):
        evaluate(expr, ENV)


def test_quantity_function():
    assert evaluate("quantity('16Gi')", ENV) == 16 * 1024**3
    assert evaluate("quantity('1500M') > quantity('1Gi')", ENV) is True


def test_short_circuit_does_not_mask_type_sanity():
    # && short-circuits like CEL: the erroring RHS is never evaluated.
    assert evaluate("false && unknownVar == 1", ENV) is False
    assert evaluate("true || unknownVar == 1", ENV) is True
