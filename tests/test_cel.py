"""CEL-subset evaluator tests."""

import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

from k8s_dra_driver_tpu.scheduler.cel import AttrBag, CELError, evaluate


ENV = {
    "device": AttrBag(
        driver="tpu.google.com",
        attributes=AttrBag(
            {
                "tpu.google.com": AttrBag(
                    type="tpu",
                    index=3,
                    productName="tpu-v5e",
                    healthy=True,
                    shape="2x2",
                )
            }
        ),
        capacity=AttrBag({"tpu.google.com": AttrBag(hbm="16Gi")}),
    )
}


@pytest.mark.parametrize(
    "expr,expected",
    [
        ("device.driver == 'tpu.google.com'", True),
        ('device.driver == "gpu.nvidia.com"', False),
        ("device.attributes['tpu.google.com'].type == 'tpu'", True),
        (
            "device.driver == 'tpu.google.com' && device.attributes['tpu.google.com'].type == 'tpu'",
            True,
        ),
        ("device.attributes['tpu.google.com'].index in [0, 1, 3]", True),
        ("device.attributes['tpu.google.com'].index in [0, 1]", False),
        ("device.attributes['tpu.google.com'].productName.matches('v5e|v6e')", True),
        ("device.attributes['tpu.google.com'].productName.startsWith('tpu-')", True),
        ("device.attributes['tpu.google.com'].productName.endsWith('v4')", False),
        ("device.attributes['tpu.google.com'].productName.contains('5e')", True),
        ("size(device.attributes['tpu.google.com'].shape) == 3", True),
        ("device.attributes['tpu.google.com'].index >= 2", True),
        ("device.attributes['tpu.google.com'].index + 1 == 4", True),
        ("!device.attributes['tpu.google.com'].healthy", False),
        ("device.attributes['tpu.google.com'].healthy ? 1 : 2", 1),
        ("1 < 2 || 3 < 2", True),
        ("10 % 3", 1),
        ("-(2 * 3) + 7", 1),
        ("[1, 2][1]", 2),
    ],
)
def test_eval(expr, expected):
    assert evaluate(expr, ENV) == expected


@pytest.mark.parametrize(
    "expr",
    [
        "unknownVar == 1",
        "device.attributes['other.domain'].type == 'x'",  # missing key
        "device.attributes['tpu.google.com'].nope == 1",
        "device.driver ==",  # syntax
        "device.driver == 'a' &&",  # syntax
        "1 +",  # syntax
        "device.attributes['tpu.google.com'].index.matches('x')",  # non-string recv
        "'a'.matches('[')",  # bad regex
        "1 && true",  # non-bool operand
        "quantity()",  # arity
        "quantity(1.5)",  # non-string/int arg
        "quantity(true)",  # no bool->int coercion in CEL
        "size()",  # arity
        "size(5)",  # unsized argument
        "quantity('bananas')",  # malformed quantity
        "'abc'.contains()",  # method arity
        "'abc'.startsWith('a', 'b')",  # method arity
        # neg / in must raise CELError, not a raw TypeError that escapes
        # the allocator's non-matching-selector handling (advisor, round 1)
        "-device.attributes['tpu.google.com'].type == 1",  # negate a string
        "1 in 5",  # unsized container
        # fuzz findings: evaluation errors that leaked as raw exceptions
        "1 / 0",  # ZeroDivisionError
        "1 % (1 - 1)",  # ZeroDivisionError (modulo)
        "(" * 500 + "1" + ")" * 500,  # RecursionError (parser depth)
        "'%' % 1",  # ValueError from Python str-formatting
        "'%d' % 2",  # CEL % is numeric-only (Python would format silently)
        "'a'.startsWith(1)",  # method arg type -> raw TypeError
        "'a'.matches(1)",
        "'a'.contains(1)",
        "device[[1,2]]",  # unhashable map key -> raw TypeError
    ],
)
def test_errors(expr):
    with pytest.raises(CELError):
        evaluate(expr, ENV)


def test_quantity_function():
    assert evaluate("quantity('16Gi')", ENV) == 16 * 1024**3
    assert evaluate("quantity('1500M') > quantity('1Gi')", ENV) is True


def test_short_circuit_does_not_mask_type_sanity():
    # && short-circuits like CEL: the erroring RHS is never evaluated.
    assert evaluate("false && unknownVar == 1", ENV) is False
    assert evaluate("true || unknownVar == 1", ENV) is True


class TestFuzzOnlyCELErrorEscapes:
    """The allocator's selector handling catches exactly CELError
    (allocator._matches_selectors); any other exception type crashing out
    of evaluate() would take down allocation for every claim.  Fuzz the
    full pipeline: arbitrary garbage must parse-or-CELError, never leak
    TypeError/AttributeError/RecursionError/etc."""

    @settings(max_examples=300, deadline=None)
    @given(
        st.text(
            # full lowercase so method names (matches/startsWith/size/
            # quantity...) are reachable — a narrower alphabet left the
            # method-call region unfuzzed and its leaks unfound
            alphabet="abcdefghijklmnopqrstuvwxyzSW.att rs[]()'\"0123456789+-*/%&|!<>=,?:_",
            min_size=1,
            max_size=60,
        )
    )
    def test_arbitrary_source(self, src):
        try:
            evaluate(src, dict(ENV))
        except CELError:
            pass

    @settings(max_examples=150, deadline=None)
    @given(
        st.recursive(
            st.sampled_from(
                ["1", "'a'", "true", "device.driver", "[1,2]",
                 "device.attributes['tpu.google.com'].index"]
            ),
            lambda inner: st.tuples(
                inner,
                st.sampled_from(["+", "-", "*", "/", "%", "==", "<", "in", "&&", "||"]),
                inner,
            ).map(lambda t: f"({t[0]} {t[1]} {t[2]})"),
            max_leaves=6,
        )
    )
    def test_structured_expressions(self, src):
        try:
            evaluate(src, dict(ENV))
        except CELError:
            pass


class TestRegexGuard:
    def test_catastrophic_patterns_rejected(self):
        for bad in (
            "(a+)+b", "(a*)*", "((a+)b)+", "(\\d+)*x", "a" * 300,
            "(a|a)+", "(a|ab)*x",  # alternation-overlap ReDoS shape
        ):
            with pytest.raises(CELError):
                evaluate(f"'aaaaaaaaaaaaaaaaaaaaaaaaaaaaaa'.matches('{bad}')", ENV)

    def test_legitimate_patterns_pass(self):
        assert evaluate("'tpu-v5e'.matches('v5e|v6e')", ENV) is True
        assert evaluate("'tpu-v5e'.matches('tpu-.*')", ENV) is True
        assert evaluate("'tpu-v5e'.matches('^tpu-v[0-9]+e$')", ENV) is True
        assert evaluate("'abab'.matches('(ab)+')", ENV) is True
        assert evaluate("'xy'.matches('a{2,3}')", ENV) is False
        # literal '+' inside a character class is NOT a quantifier
        assert evaluate("'1+2'.matches('([0-9+])+')", ENV) is True
