"""FleetAutoscaler state-machine unit suite (PR 12).

The control law, exercised over simulated engines without faults unless
a test arms them explicitly:

* Hysteresis: an action fires only after ``up_ticks``/``down_ticks``
  CONSECUTIVE votes; an interrupted streak starts over.
* Cooldown: no two actions inside ``cooldown_s`` — except the
  min-replicas floor, which restores the minimum immediately.
* Clamps: replica count never leaves ``[min_replicas, max_replicas]``.
* Victim selection: scale-down drains the least-loaded ADMITTABLE
  replica; SUSPECT/EVACUATING/DRAINED replicas are never picked.
* Spawn faults: ``spawn_fail`` backs off without half-registering;
  ``spawn_latency_ms`` defers registration until the (accounted)
  latency elapses on the shared clock.
* Wiring: attach() drives the loop from router ticks; metrics land in
  the registry; /debug/autoscale renders the control-law state.

The fault-injected end-to-end suite (flash crowds + replica kills) is
tests/test_autoscale_chaos.py (`make chaos-autoscale`).
"""

import pytest

from k8s_dra_driver_tpu.models import fleet
from k8s_dra_driver_tpu.models import workload as W
from k8s_dra_driver_tpu.models.autoscaler import (
    AutoscalerPolicy,
    FleetAutoscaler,
    debug_autoscale_doc,
)
from k8s_dra_driver_tpu.models.fleet import EVACUATING, SUSPECT
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, parse_prom_text


def _build(n=2, *, policy=None, injector=None, n_slots=4):
    clock = W.SimClock()

    def factory():
        return W.SimEngine(clock=clock, n_slots=n_slots, n_blocks=512)

    router = fleet.FleetRouter(
        [factory() for _ in range(n)], clock=clock, fault_injector=injector
    )
    asc = FleetAutoscaler(
        router,
        engine_factory=factory,
        policy=policy or AutoscalerPolicy(
            min_replicas=1, max_replicas=4, up_ticks=2, down_ticks=3,
            cooldown_s=5.0,
        ),
        clock=clock,
    )
    return clock, router, asc, factory


def _fill(router, n):
    """Occupy n slots across the fleet so utilization reads high."""
    for i in range(n):
        router.submit([1, i + 2], max_tokens=64)


def _live(router):
    return sum(1 for r in router.replicas if r.state != "drained")


class TestHysteresis:
    def test_up_needs_consecutive_votes(self):
        clock, router, asc, _ = _build()
        _fill(router, 8)  # 8/8 slots busy -> vote up
        d1 = asc.tick()
        assert d1["vote"] == "up" and d1["action"] == "none"
        assert _live(router) == 2
        clock.advance(1.0)
        d2 = asc.tick()
        assert d2["action"] == "up"
        assert _live(router) == 3

    def test_interrupted_streak_starts_over(self):
        clock, router, asc, _ = _build()
        _fill(router, 8)
        asc.tick()  # streak 1
        # Neutral tick: mid utilization (free half the fleet's slots by
        # voting with an explicit shallow queue on an idle twin is messy;
        # simplest neutral signal is util between low and high).
        for rep in router.replicas:
            rep.engine.release_active()
        router.submit([9, 9], max_tokens=64)  # 1/8 busy... still <= low
        _fill(router, 3)  # 4/8 busy: between 0.30 and 0.85 -> hold
        clock.advance(1.0)
        d = asc.tick()
        assert d["vote"] == "hold" and d["up_streak"] == 0
        _fill(router, 4)  # back to full pressure
        clock.advance(1.0)
        assert asc.tick()["action"] == "none"  # streak restarted at 1
        clock.advance(1.0)
        assert asc.tick()["action"] == "up"

    def test_down_needs_longer_streak(self):
        clock, router, asc, _ = _build()
        acted = []
        for _ in range(3):
            clock.advance(2.0)
            acted.append(asc.tick()["action"])
        assert acted == ["none", "none", "down"]
        assert _live(router) == 1


class TestCooldownAndClamps:
    def test_cooldown_blocks_consecutive_actions(self):
        clock, router, asc, _ = _build()
        _fill(router, 8)
        asc.tick()
        clock.advance(1.0)
        assert asc.tick()["action"] == "up"
        _fill(router, 4)  # keep the new 3-replica fleet saturated
        for _ in range(3):  # still inside cooldown_s=5
            clock.advance(1.0)
            assert asc.tick()["action"] == "none"
        clock.advance(3.0)  # past cooldown; streak long since satisfied
        assert asc.tick()["action"] == "up"
        assert _live(router) == 4

    def test_max_replicas_clamps_growth(self):
        clock, router, asc, _ = _build(
            policy=AutoscalerPolicy(min_replicas=1, max_replicas=2,
                                    up_ticks=1, cooldown_s=0.0)
        )
        for _ in range(5):
            _fill(router, 1)
            clock.advance(1.0)
            d = asc.tick(queue_depth=100)  # maximal pressure forever
        assert _live(router) == 2
        assert d["target"] == 2

    def test_min_floor_restores_without_hysteresis(self):
        clock, router, asc, _ = _build(
            n=2,
            policy=AutoscalerPolicy(min_replicas=2, max_replicas=4,
                                    up_ticks=99, cooldown_s=1e9),
        )
        router.drain(router.replicas[0].name, reason="test")
        assert _live(router) == 1
        d = asc.tick()
        # Neither the 99-tick hysteresis nor the infinite cooldown may
        # block restoring the floor.
        assert d["action"] == "up" and d["reason"] == "min_replicas"
        assert _live(router) == 2

    def test_min_replicas_blocks_scale_down(self):
        clock, router, asc, _ = _build(
            n=1,
            policy=AutoscalerPolicy(min_replicas=1, max_replicas=4,
                                    down_ticks=1, cooldown_s=0.0),
        )
        for _ in range(4):
            clock.advance(1.0)
            assert asc.tick()["action"] == "none"
        assert _live(router) == 1

    def test_policy_rejects_bad_bounds(self):
        with pytest.raises(ValueError):
            AutoscalerPolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscalerPolicy(min_replicas=0)


class TestVictimSelection:
    def _down_ready(self, n=3):
        clock, router, asc, _ = _build(
            n=n,
            policy=AutoscalerPolicy(min_replicas=1, max_replicas=4,
                                    down_ticks=1, cooldown_s=0.0),
        )
        return clock, router, asc

    def test_least_loaded_is_drained(self):
        clock, router, asc = self._down_ready()
        r0, r1, r2 = router.replicas
        for j, (rep, streams) in enumerate(((r0, 2), (r1, 1), (r2, 3))):
            for i in range(streams):
                rep.engine.submit([j, i], max_tokens=64)
        # All busy -> no down vote; empty the queue and let util sit low:
        # 6/12 = 0.5 is a hold, so force the vote via an idle fleet is
        # wrong here — drive _scale_down directly through a real tick by
        # loosening the low-water mark instead.
        asc.policy = AutoscalerPolicy(
            min_replicas=1, max_replicas=4, down_ticks=1, cooldown_s=0.0,
            target_util_low=0.60,
        )
        clock.advance(1.0)
        d = asc.tick()
        assert d["action"] == "down"
        assert r1.state == "drained"  # 1 resident stream = least loaded
        assert r0.state != "drained" and r2.state != "drained"

    def test_suspect_and_evacuating_never_picked(self):
        clock, router, asc = self._down_ready()
        r0, r1, r2 = router.replicas
        r0.state = SUSPECT
        r1.state = EVACUATING
        clock.advance(1.0)
        d = asc.tick()
        # r2 is the only admittable replica and min_replicas=1: draining
        # it would leave zero admittable capacity -> no victim.
        assert d["action"] == "none" or r2.state != "drained"
        assert asc._pick_victim() is None

    def test_scale_down_threads_one_correlation(self):
        clock, router, asc = self._down_ready()
        rid = router.submit([7, 7, 7], max_tokens=32)
        clock.advance(1.0)
        d = asc.tick()
        assert d["action"] == "down"
        corrs = {
            e["correlation"]
            for e in JOURNAL.tail(limit=200)
            if str(e.get("correlation", "")).startswith("scale-")
        }
        assert len(corrs) == 1
        corr = corrs.pop()
        events = [e["event"] for e in JOURNAL.tail(limit=200, correlation=corr)]
        assert "scale_down.begin" in events
        assert "scale_down.resumed" in events
        # The drain's whole evacuation rides under the SAME correlation.
        assert "replica.evacuating" in events
        assert "replica.drained" in events


class TestSpawnFaults:
    def _pressure_policy(self):
        return AutoscalerPolicy(min_replicas=1, max_replicas=4,
                                up_ticks=1, cooldown_s=0.0,
                                spawn_backoff_s=10.0)

    def test_spawn_fail_backs_off_without_half_registering(self):
        inj = FaultInjector(seed=0)
        inj.arm(FaultProfile(name="boom", spawn_fail_rate=1.0, limit=1))
        clock, router, asc, _ = _build(
            n=1, policy=self._pressure_policy(), injector=inj
        )
        _fill(router, 4)
        clock.advance(1.0)
        d = asc.tick()
        assert asc.spawn_failures == 1
        assert _live(router) == 1  # nothing half-registered
        events = [e["event"] for e in JOURNAL.tail(limit=50)]
        assert "scale_up.spawn_failed" in events
        # Inside the backoff window: pressure is ignored.
        clock.advance(1.0)
        assert asc.tick()["backing_off"] is True
        assert _live(router) == 1
        # Past the backoff (and the profile's limit=1 budget): retry wins.
        clock.advance(10.0)
        d = asc.tick()
        assert d["action"] == "up"
        assert _live(router) == 2

    def test_spawn_latency_defers_registration(self):
        inj = FaultInjector(seed=0)
        inj.arm(FaultProfile(name="slow", spawn_latency_s=5.0))
        clock, router, asc, _ = _build(
            n=1, policy=self._pressure_policy(), injector=inj
        )
        _fill(router, 4)
        clock.advance(1.0)
        d = asc.tick()
        assert d["action"] == "up"
        assert _live(router) == 1  # factory latency still accounting
        assert d["pending_spawns"] == 0 or asc._pending_spawns
        clock.advance(2.0)
        asc.tick()
        assert _live(router) == 1
        clock.advance(4.0)  # past ready_at
        asc.tick()
        assert _live(router) == 2
        assert any(r.name.startswith("as") for r in router.replicas)


class TestWiring:
    def test_attach_drives_from_router_ticks(self):
        clock, router, asc, _ = _build()
        asc.attach()
        asc.attach()  # idempotent: one hook, not two
        assert router.tick_hooks.count(asc._on_router_tick) == 1
        before = asc.ticks
        router.tick()
        assert asc.ticks == before + 1

    def test_metrics_land_in_registry(self):
        clock, router, asc, _ = _build(
            policy=AutoscalerPolicy(min_replicas=1, max_replicas=4,
                                    up_ticks=1, cooldown_s=0.0)
        )
        _fill(router, 8)
        clock.advance(1.0)
        asc.tick()
        asc.record_slo(attained=9, offered=10)
        doc = parse_prom_text(REGISTRY.render())
        assert doc["tpu_autoscale_events_total"][
            (("direction", "up"), ("reason", "overload"))
        ] == 1
        assert doc["tpu_autoscale_replicas"][(("kind", "actual"),)] == 3
        assert doc["tpu_autoscale_slo_attainment"][()] == pytest.approx(0.9)
        assert any(
            k == "tpu_autoscale_decision_seconds_count"
            for k in doc
        )

    def test_debug_autoscale_doc_renders_state(self):
        clock, router, asc, _ = _build()
        asc.tick()
        doc = debug_autoscale_doc()
        ours = [
            a for a in doc["autoscalers"] if a["router_seq"] == router.seq
        ]
        assert len(ours) == 1
        st = ours[0]
        assert st["ticks"] == 1
        assert st["policy"]["max_replicas"] == 4
        assert st["last_decision"]["action"] == "none"

    def test_record_slo_accumulates(self):
        clock, router, asc, _ = _build()
        asc.record_slo(5, 10)
        asc.record_slo(5, 10)
        assert asc.stats()["slo"]["attainment"] == pytest.approx(0.5)
