"""Training-state checkpoint/resume (models/train_checkpoint.py).

The driver's claim checkpoint is covered in test_prepare; this covers the
data-plane half: a preempted training job resumes bit-exact, including on
a sharded mesh with restore-under-shardings."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.models import burnin
from k8s_dra_driver_tpu.models.train_checkpoint import TrainCheckpointer
from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
from tests.conftest import cpu_devices


class TestTrainCheckpoint:
    def test_single_device_roundtrip_resumes_bit_exact(self, tmp_path):
        cfg = burnin.TINY
        fns = burnin.build_train_step(cfg, lr=1e-2)
        params, opt_state = fns.init(jax.random.PRNGKey(0))
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=2, seq=32)

        # run 2 steps, checkpoint, run 1 more -> loss L3
        for _ in range(2):
            params, opt_state, loss = fns.step(params, opt_state, tokens)
        ckpt = TrainCheckpointer(tmp_path / "ckpt", keep=2)
        ckpt.save(2, (params, opt_state))
        params, opt_state, l3 = fns.step(params, opt_state, tokens)

        # resume from the checkpoint and repeat step 3: bit-exact
        assert ckpt.latest_step() == 2
        r_params, r_opt = ckpt.restore(like=(params, opt_state))
        _, _, l3b = fns.step(r_params, r_opt, tokens)
        assert float(l3) == float(l3b)
        ckpt.close()

    def test_keep_limit_garbage_collects(self, tmp_path):
        ckpt = TrainCheckpointer(tmp_path / "ckpt", keep=2)
        state = {"w": jnp.arange(4.0)}
        for step in (1, 2, 3):
            ckpt.save(step, state)
        assert ckpt.all_steps() == [2, 3]
        ckpt.close()

    def test_restore_missing_raises(self, tmp_path):
        ckpt = TrainCheckpointer(tmp_path / "empty")
        with pytest.raises(FileNotFoundError, match="no checkpoints"):
            ckpt.restore()
        ckpt.close()

    def test_sharded_save_restore_under_mesh(self, tmp_path):
        """Sharded params round-trip with their shardings intact — the
        multi-host resume pattern (each host writes its own shards)."""
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        cfg = burnin.TINY
        fns = burnin.build_train_step(cfg, mesh=mesh)
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            ckpt = TrainCheckpointer(tmp_path / "ckpt")
            ckpt.save(0, params)
            restored = ckpt.restore(0, like=params)
        flat, _ = jax.tree.flatten(params)
        rflat, _ = jax.tree.flatten(restored)
        for a, b in zip(flat, rflat):
            assert a.sharding == b.sharding, (a.sharding, b.sharding)
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and the restored state trains
        with mesh:
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=64),
                NamedSharding(mesh, P("data", None)),
            )
            _, _, loss = fns.step(restored, opt_state, tokens)
        assert np.isfinite(float(loss))
        ckpt.close()
