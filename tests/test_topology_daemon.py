"""tpu-topology-daemon: the program behind templates/topology-daemon.tmpl.yaml.

Round 1 shipped the Deployment template with a ghost command (VERDICT.md
missing #1) — these tests pin that the program exists, speaks the socket
protocol, arbitrates leases, and that the spatial-partition division it
serves is the same disjoint per-container split the CDI spec carries
(reference daemon counterpart: nvidia-cuda-mps-control, started by
cmd/nvidia-dra-plugin/sharing.go:185-344).
"""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from k8s_dra_driver_tpu.plugin.topology_daemon import (
    TopologyDaemonClient,
    TopologyDaemonServer,
    claim_socket_path,
    main,
)

PARTITIONS = [
    {"index": 0, "device": "tpu-0", "uuid": "u0", "visible_devices": "0",
     "process_coord": "0,0,0", "hbm_limit_mib": 4096},
    {"index": 1, "device": "tpu-1", "uuid": "u1", "visible_devices": "1",
     "process_coord": "1,0,0", "hbm_limit_mib": None},
]


NATIVE_DIR = Path(__file__).resolve().parent.parent / "k8s_dra_driver_tpu/tpuinfo/cpp"


@pytest.fixture(scope="session")
def native_daemon_bin():
    """Build (once) the C++ daemon — the binary the container image ships."""
    subprocess.run(
        ["make", "-C", str(NATIVE_DIR), "tpu-topology-daemon"],
        check=True, capture_output=True,
    )
    return NATIVE_DIR / "tpu-topology-daemon"


@pytest.fixture(params=["python", "native"])
def daemon(request, tmp_path):
    """Both daemon implementations behind one fixture: every protocol and
    lease-arbitration test below runs against the in-process Python server
    AND the native C++ binary — the wire-compatibility contract, enforced."""
    if request.param == "python":
        server = TopologyDaemonServer(
            str(tmp_path / "claim.sock"),
            claim_uid="uid-1",
            partition_spec="2,1,1",
            partitions=PARTITIONS,
            hbm_limits={"u0": "4096Mi"},
            quantum_ms=10,
        )
        server.start()
        yield server
        server.stop()
        return
    binary = request.getfixturevalue("native_daemon_bin")
    env = {
        "TPU_PARTITION_SPEC": "2,1,1",
        "TPU_PARTITIONS": json.dumps(PARTITIONS),
        "TPU_HBM_LIMITS": "u0=4096Mi",
        "TPU_QUEUE_QUANTUM_MS": "10",
        "PATH": "/usr/bin:/bin",
    }
    # '=' flag form on purpose: it is what the deployment templates pass
    # (topology-daemon.tmpl.yaml) — a parser accepting only spaced flags
    # would pass spaced-form tests and CrashLoop in production.
    proc = subprocess.Popen(
        [str(binary), "--claim-uid=uid-1", f"--socket-dir={tmp_path}"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    sock = claim_socket_path(str(tmp_path), "uid-1")
    deadline = time.time() + 10
    while time.time() < deadline and not Path(sock).exists():
        if proc.poll() is not None:
            raise RuntimeError(f"native daemon died: {proc.stdout.read()!r}")
        time.sleep(0.02)

    class Native:
        socket_path = sock

    try:
        yield Native()
    finally:
        proc.terminate()
        proc.wait(timeout=10)


class TestPerClaimProtocol:
    def test_consumer_observes_its_partition(self, daemon):
        client = TopologyDaemonClient(daemon.socket_path, "container-a")
        resp = client.register(partition=0)
        assert resp["ok"]
        assert resp["partition"]["visible_devices"] == "0"
        assert resp["partition"]["process_coord"] == "0,0,0"
        assert resp["partition"]["hbm_limit_mib"] == 4096
        assert resp["hbm_limits"] == {"u0": "4096Mi"}
        client.close()

    def test_unknown_partition_rejected(self, daemon):
        client = TopologyDaemonClient(daemon.socket_path, "container-a")
        resp = client.register(partition=7)
        assert not resp["ok"]
        assert "no partition 7" in resp["error"]
        client.close()

    def test_info_reflects_claim_and_consumers(self, daemon):
        a = TopologyDaemonClient(daemon.socket_path, "a")
        b = TopologyDaemonClient(daemon.socket_path, "b")
        a.register(partition=0)
        b.register(partition=1)
        info = a.info()
        assert info["claim_uid"] == "uid-1"
        assert info["partition_spec"] == "2,1,1"
        assert info["consumers"] == ["a", "b"]
        a.close(), b.close()

    def test_malformed_request_does_not_kill_daemon(self, daemon):
        import socket as socketlib

        s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
        s.connect(daemon.socket_path)
        s.sendall(b"this is not json\n")
        resp = json.loads(s.makefile("rb").readline())
        assert not resp["ok"]
        s.close()
        # daemon still serves
        client = TopologyDaemonClient(daemon.socket_path, "after")
        assert client.info()["ok"]
        client.close()

    @pytest.mark.parametrize(
        "payload",
        [
            b'{"op": "info", "x": 12-3}',   # interior sign / residue
            b'{"op": "info", "x": +1}',     # leading plus
            b'{"op": "info", "x": 01}',     # leading zero
            b'{"op": "info", "x": 1.}',     # bare decimal point
        ],
    )
    def test_malformed_numbers_rejected(self, daemon, payload):
        """Strict JSON number grammar on BOTH implementations: the native
        parser must not silently misread `12-3` as 12 (round-2 advisor
        finding) — it must error exactly like Python's json module."""
        import socket as socketlib

        s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
        s.connect(daemon.socket_path)
        s.sendall(payload + b"\n")
        resp = json.loads(s.makefile("rb").readline())
        assert not resp["ok"]
        s.close()

    def test_huge_integer_accepted(self, daemon):
        """Python parses arbitrary-precision ints; the native daemon must not
        error on them either (it degrades >int64 to double)."""
        import socket as socketlib

        s = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
        s.connect(daemon.socket_path)
        s.sendall(b'{"op": "info", "x": 123456789012345678901234567890}\n')
        resp = json.loads(s.makefile("rb").readline())
        assert resp["ok"]
        s.close()


class TestLeaseArbitration:
    def test_second_consumer_blocks_until_release(self, daemon):
        a = TopologyDaemonClient(daemon.socket_path, "a")
        b = TopologyDaemonClient(daemon.socket_path, "b")
        assert a.acquire(quantum_ms=2000)["ok"]

        granted = {}

        def contend():
            granted.update(b.acquire(quantum_ms=10, timeout_ms=5000))

        t = threading.Thread(target=contend)
        t.start()
        time.sleep(0.05)
        assert not granted  # b is parked while a holds the lease
        a.release()
        t.join(timeout=5)
        assert granted.get("ok")
        a.close(), b.close()

    def test_acquire_timeout_reports_holder(self, daemon):
        a = TopologyDaemonClient(daemon.socket_path, "a")
        b = TopologyDaemonClient(daemon.socket_path, "b")
        assert a.acquire(quantum_ms=60000)["ok"]
        resp = b.acquire(quantum_ms=10, timeout_ms=50)
        assert not resp["ok"]
        assert resp["error"] == "timeout"
        assert resp["holder"] == "a"
        a.close(), b.close()

    def test_expired_lease_is_reclaimed_from_crashed_holder(self, daemon):
        a = TopologyDaemonClient(daemon.socket_path, "a")
        b = TopologyDaemonClient(daemon.socket_path, "b")
        # a takes a 10ms lease and never releases (crash): grace is
        # 4 quanta, so b must be granted within ~40ms, not block forever.
        assert a.acquire(quantum_ms=10)["ok"]
        a.close()
        start = time.time()
        resp = b.acquire(quantum_ms=10, timeout_ms=5000)
        assert resp["ok"]
        assert time.time() - start < 2.0
        b.close()

    def test_disjoint_chip_scopes_do_not_contend(self, daemon):
        """Two TimeSlicing claims on DIFFERENT chips share the one host
        daemon but must not serialize: leases are per chip-set scope."""
        a = TopologyDaemonClient(daemon.socket_path, "a")
        b = TopologyDaemonClient(daemon.socket_path, "b")
        assert a.acquire(quantum_ms=60000, scope="0")["ok"]
        # b is on chip 1: granted immediately despite a's long hold on chip 0
        start = time.time()
        assert b.acquire(quantum_ms=10, scope="1", timeout_ms=5000)["ok"]
        assert time.time() - start < 1.0
        info = a.info()
        assert info["lease_holders"] == {"0": "a", "1": "b"}
        # same-scope contention still applies
        c = TopologyDaemonClient(daemon.socket_path, "c")
        resp = c.acquire(quantum_ms=10, scope="0", timeout_ms=50)
        assert not resp["ok"] and resp["holder"] == "a"
        a.close(), b.close(), c.close()

    def test_reacquire_by_holder_renews(self, daemon):
        a = TopologyDaemonClient(daemon.socket_path, "a")
        assert a.acquire(quantum_ms=10)["ok"]
        assert a.acquire(quantum_ms=10)["ok"]  # renewal, not deadlock
        a.close()


class TestProgram:
    def test_cli_requires_exactly_one_mode(self):
        with pytest.raises(SystemExit):
            main(["--claim-uid=x", "--host-mode"])
        with pytest.raises(SystemExit):
            main([])

    def test_real_program_serves_partition_table(self, tmp_path):
        """End to end: the actual `python -m` program a container would run,
        with the template's env contract, served over a real unix socket."""
        env = {
            "TPU_PARTITION_SPEC": "2,1,1",
            "TPU_PARTITIONS": json.dumps(PARTITIONS),
            "TPU_HBM_LIMITS": "u0=4096Mi",
            "PATH": "/usr/bin:/bin",
        }
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "k8s_dra_driver_tpu.plugin.topology_daemon",
                "--claim-uid=uid-e2e",
                f"--socket-dir={tmp_path}",
            ],
            env={**env, "PYTHONPATH": str(Path(__file__).parent.parent)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
        )
        try:
            sock = claim_socket_path(str(tmp_path), "uid-e2e")
            deadline = time.time() + 10
            while time.time() < deadline and not Path(sock).exists():
                time.sleep(0.05)
            client = TopologyDaemonClient(sock, "pod-container")
            resp = client.register(partition=1)
            assert resp["ok"]
            assert resp["partition"]["visible_devices"] == "1"
            info = client.info()
            assert info["claim_uid"] == "uid-e2e"
            assert info["hbm_limits"] == {"u0": "4096Mi"}
            client.close()
        finally:
            proc.terminate()
            proc.wait(timeout=10)

    def test_template_command_is_shipped_binary(self):
        """Guards the round-1 ghost: the template's command must be the
        binary the Dockerfile ships (the NATIVE daemon, copied from the
        build stage) / pyproject's console script."""
        repo = Path(__file__).parent.parent
        template = (repo / "templates" / "topology-daemon.tmpl.yaml").read_text()
        assert 'command: ["tpu-topology-daemon"]' in template
        dockerfile = (repo / "deployments" / "container" / "Dockerfile").read_text()
        assert "/usr/local/bin/tpu-topology-daemon" in dockerfile
        assert "cpp/tpu-topology-daemon" in dockerfile  # native, not a shim
        pyproject = (repo / "pyproject.toml").read_text()
        assert 'tpu-topology-daemon = "k8s_dra_driver_tpu.plugin.topology_daemon:main"' in pyproject

    def test_native_cli_rejects_bad_modes(self, native_daemon_bin):
        """Same CLI contract as the Python program: exactly one mode."""
        for args in ([], ["--claim-uid=x", "--host-mode"], ["--bogus"]):
            proc = subprocess.run(
                [str(native_daemon_bin), *args],
                capture_output=True, timeout=10,
            )
            assert proc.returncode == 2, args

    def test_native_sigterm_with_inflight_acquire_exits_clean(
        self, native_daemon_bin, tmp_path
    ):
        """SIGTERM while a worker thread is parked in acquire()'s cond-wait:
        the daemon must stop(), unblock, JOIN the worker and exit 0 promptly
        — not leave a detached thread racing Daemon destruction (the round-2
        advisor's shutdown use-after-free)."""
        import socket as socketlib

        proc = subprocess.Popen(
            [str(native_daemon_bin), "--host-mode", "--socket-dir", str(tmp_path)],
            env={"PATH": "/usr/bin:/bin", "TPU_QUEUE_QUANTUM_MS": "10"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        try:
            sock = str(tmp_path / "host.sock")
            deadline = time.time() + 10
            while time.time() < deadline and not Path(sock).exists():
                time.sleep(0.02)
            holder = TopologyDaemonClient(sock, "holder")
            assert holder.acquire(quantum_ms=60000, scope="z")["ok"]
            waiter = socketlib.socket(socketlib.AF_UNIX, socketlib.SOCK_STREAM)
            waiter.connect(sock)
            waiter.sendall(
                json.dumps(
                    {"op": "acquire", "consumer": "w", "scope": "z",
                     "timeout_ms": 30000}
                ).encode() + b"\n"
            )
            time.sleep(0.3)  # park the worker in the cond-wait
            start = time.time()
            proc.terminate()
            rc = proc.wait(timeout=10)
            # prompt (stop() wakes the waiter; no 30s timeout drain), clean
            assert rc == 0
            assert time.time() - start < 5
            waiter.close()
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

    def test_native_program_serves_host_mode(self, native_daemon_bin, tmp_path):
        """The C++ binary's host mode: lease arbitration over the host
        socket — the sidecar configuration the DaemonSet runs."""
        proc = subprocess.Popen(
            [str(native_daemon_bin), "--host-mode", "--socket-dir", str(tmp_path)],
            env={"PATH": "/usr/bin:/bin", "TPU_QUEUE_QUANTUM_MS": "10"},
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        try:
            sock = str(tmp_path / "host.sock")
            # generous deadline: the suite may share the box with a bench
            # run, and a slow fork is not a daemon bug (observed flake)
            deadline = time.time() + 30
            while time.time() < deadline and not Path(sock).exists():
                if proc.poll() is not None:
                    raise RuntimeError(f"daemon died: {proc.stdout.read()!r}")
                time.sleep(0.02)
            assert Path(sock).exists(), "daemon socket never appeared"
            a = TopologyDaemonClient(sock, "a")
            b = TopologyDaemonClient(sock, "b")
            try:
                got = a.acquire(quantum_ms=60000, scope="0")
                assert got["ok"], got
                resp = b.acquire(quantum_ms=10, scope="0", timeout_ms=50)
                assert not resp["ok"] and resp["holder"] == "a", resp
                got = b.acquire(quantum_ms=10, scope="1", timeout_ms=500)
                assert got["ok"], got
            finally:
                # close BEFORE terminate even when an assert failed, so
                # teardown never depends on the daemon draining open
                # connections under load
                a.close(), b.close()
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                out = proc.stdout.read()
                proc.wait(timeout=10)
                raise AssertionError(
                    f"daemon did not exit after SIGTERM; output: {out!r}"
                ) from None
