"""Device-health monitoring: dead-chip fault injection end to end."""

import pytest

from k8s_dra_driver_tpu.e2e.harness import (
    SUBSLICE_CLASS,
    make_cluster,
    simple_claim,
)
from k8s_dra_driver_tpu.plugin.device_state import PrepareError
from k8s_dra_driver_tpu.plugin.driver import Driver, DriverConfig
from k8s_dra_driver_tpu.scheduler.allocator import AllocationError, Allocator
from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology


def fake_env(dead=""):
    env = {"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"}
    if dead:
        env["TPUINFO_FAKE_DEAD_CHIPS"] = dead
    return env


class TestDeadChipEnumeration:
    def test_shim_marks_dead_chips(self):
        t = enumerate_topology(env=fake_env(dead="1,3"))
        assert [c.healthy for c in t.chips] == [True, False, True, False]

    def test_no_dead_env_all_healthy(self):
        t = enumerate_topology(env=fake_env())
        assert all(c.healthy for c in t.chips)


@pytest.fixture
def rig(tmp_path):
    cluster = make_cluster(hosts=1, work_dir=str(tmp_path / "w"))
    driver = Driver(
        cluster.server,
        DriverConfig(
            node_name="tpu-host-0",
            cdi_root=str(tmp_path / "cdi"),
            checkpoint_path=str(tmp_path / "cp.json"),
            topology_env=fake_env(),
        ),
    )
    return cluster, driver


class TestHealthSweep:
    def test_dead_chip_unschedulable_after_refresh(self, rig):
        cluster, driver = rig
        # chip 1 dies between sweeps
        driver.config.topology_env = fake_env(dead="1")
        assert driver.refresh_inventory() is True
        assert driver.refresh_inventory() is False  # stable now

        # tpu-1 is published but health-gated out of the DeviceClass CEL
        slices = [
            s for s in cluster.server.list("ResourceSlice")
            if s.spec.pool.name == "tpu-host-0"
        ]
        devices = {d.name: d for s in slices for d in s.spec.devices}
        assert devices["tpu-1"].basic.attributes["healthy"].value is False
        assert devices["tpu-0"].basic.attributes["healthy"].value is True
        # subslices covering chip 1 are unhealthy too
        assert devices["tpu-slice-2x2-0-0"].basic.attributes["healthy"].value is False
        assert devices["tpu-slice-1x2-0-0"].basic.attributes["healthy"].value is True

        # only 3 chips allocatable: a 4-chip claim must fail...
        claim = cluster.server.create(simple_claim("four", count=4))
        with pytest.raises(AllocationError):
            Allocator(cluster.server).allocate(claim, node_name="tpu-host-0")
        # ...while 3 chips still fit
        claim3 = cluster.server.create(simple_claim("three", count=3))
        updated = Allocator(cluster.server).allocate(claim3, node_name="tpu-host-0")
        got = {r.device for r in updated.status.allocation.devices.results}
        assert "tpu-1" not in got

    def test_publish_failure_retried_next_sweep(self, rig, monkeypatch):
        # refresh() commits the new topology before publish; a failed publish
        # must NOT crash the sweep — it marks the inventory stale and retries
        # on the next sweep even though nothing changed again.
        from k8s_dra_driver_tpu.utils.metrics import REGISTRY

        cluster, driver = rig
        driver.config.topology_env = fake_env(dead="1")

        calls = {"n": 0}
        real_publish = driver.publish_resources

        def flaky():
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient API error")
            return real_publish()

        monkeypatch.setattr(driver, "publish_resources", flaky)
        assert driver.refresh_inventory() is True  # topology DID change
        stale = REGISTRY.gauge("dra_inventory_stale")
        assert stale.value(node="tpu-host-0") == 1.0
        # next sweep: no topology change, but the pending publish retries
        assert driver.refresh_inventory() is False
        assert calls["n"] == 2
        devices = {
            d.name: d
            for s in cluster.server.list("ResourceSlice")
            if s.spec.pool.name == "tpu-host-0"
            for d in s.spec.devices
        }
        assert devices["tpu-1"].basic.attributes["healthy"].value is False

    def test_recovery_republishes(self, rig):
        cluster, driver = rig
        driver.config.topology_env = fake_env(dead="0")
        driver.refresh_inventory()
        driver.config.topology_env = fake_env()
        assert driver.refresh_inventory() is True
        slices = cluster.server.list("ResourceSlice")
        devices = {d.name: d for s in slices for d in s.spec.devices}
        assert devices["tpu-0"].basic.attributes["healthy"].value is True

    def test_prepare_rejects_stale_allocation_on_dead_chip(self, rig):
        # Allocation happened while healthy; the chip dies before Prepare.
        cluster, driver = rig
        claim = cluster.server.create(simple_claim("stale", count=4))
        allocated = Allocator(cluster.server).allocate(claim, node_name="tpu-host-0")
        driver.config.topology_env = fake_env(dead="2")
        driver.refresh_inventory()
        with pytest.raises(PrepareError, match="unhealthy chip"):
            driver.state.prepare(allocated)

    def test_subslice_class_health_gated(self, rig):
        cluster, driver = rig
        driver.config.topology_env = fake_env(dead="0,1,2,3")
        driver.refresh_inventory()
        claim = cluster.server.create(
            simple_claim("slice", device_class=SUBSLICE_CLASS)
        )
        with pytest.raises(AllocationError):
            Allocator(cluster.server).allocate(claim, node_name="tpu-host-0")


class TestHealthReason:
    def test_fault_injected_reason_published(self):
        """A dead chip's reason flows C++ shim -> binding -> published
        device attributes, so CEL/operators can tell WHY it is out."""
        from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices
        from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology

        topo = enumerate_topology(
            env={
                "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                "TPUINFO_FAKE_HOST_ID": "0",
                "TPUINFO_FAKE_DEAD_CHIPS": "2",
            }
        )
        assert topo.chips[2].health_reason == "fault-injected"
        assert topo.chips[0].health_reason == ""
        devices = {d.name: d.get_device() for d in AllocatableDevices.from_topology(topo)}
        dead = devices["tpu-2"]
        assert dead.basic.attributes["healthy"].value is False
        assert dead.basic.attributes["healthReason"].value == "fault-injected"
        alive = devices["tpu-0"]
        assert alive.basic.attributes["healthy"].value is True
        assert "healthReason" not in alive.basic.attributes

    def test_health_reason_selectable_in_cel(self, api_server):
        from k8s_dra_driver_tpu import DRIVER_NAME
        from k8s_dra_driver_tpu.kube.objects import DeviceRequest
        from k8s_dra_driver_tpu.kube.resourceslice_controller import (
            DriverResources,
            Pool,
            ResourceSliceController,
            Slice,
        )
        from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices
        from k8s_dra_driver_tpu.scheduler.allocator import Allocator
        from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology
        from tests.test_allocator import TPU_CLASS, install_classes, make_claim, sel

        install_classes(api_server)
        topo = enumerate_topology(
            env={
                "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                "TPUINFO_FAKE_HOST_ID": "0",
                "TPUINFO_FAKE_DEAD_CHIPS": "1",
            }
        )
        devices = AllocatableDevices.from_topology(topo).get_devices()
        ResourceSliceController(api_server, DRIVER_NAME, "host0").update(
            DriverResources(
                pools={"host0": Pool(slices=[Slice(devices=devices)], node_name="host0")}
            )
        )
        claim = make_claim(
            api_server,
            "diagnose-dead",
            [
                DeviceRequest(
                    name="t",
                    device_class_name=TPU_CLASS,
                    selectors=[
                        sel(
                            f"device.attributes['{DRIVER_NAME}'].healthReason"
                            " == 'fault-injected'"
                        )
                    ],
                )
            ],
        )
        # the reason attribute is matchable: a diagnostics claim can target
        # exactly the fault-injected chip
        allocated = Allocator(api_server).allocate(claim, node_name="host0")
        assert allocated.status.allocation.devices.results[0].device == "tpu-1"

    def test_subslice_aggregates_health_reason(self):
        from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices
        from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology

        topo = enumerate_topology(
            env={
                "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                "TPUINFO_FAKE_HOST_ID": "0",
                "TPUINFO_FAKE_DEAD_CHIPS": "1",
            }
        )
        devices = {d.name: d.get_device() for d in AllocatableDevices.from_topology(topo)}
        block = devices["tpu-slice-2x2-0-0"]  # covers the dead chip
        assert block.basic.attributes["healthy"].value is False
        assert block.basic.attributes["healthReason"].value == "fault-injected"
