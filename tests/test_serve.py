"""Continuous-batching serving engine (models/serve.py).

The load-bearing contract: scheduling requests through slots changes
WHEN tokens are computed, never WHAT tokens come out — every request must
match sequential `greedy_decode` exactly."""

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, decode
from k8s_dra_driver_tpu.models.serve import ServeEngine

CFG = burnin.ModelConfig(
    vocab_size=128, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=64
)
PARAMS = burnin.init_params(jax.random.PRNGKey(0), CFG)


def _prompt(seed, length):
    return [
        int(t)
        for t in burnin.sample_tokens(jax.random.PRNGKey(seed), CFG, 1, length)[0]
    ]


def _reference(prompt, steps):
    out = decode.greedy_decode(
        PARAMS, jax.numpy.asarray([prompt], jax.numpy.int32), steps, cfg=CFG
    )
    return [int(t) for t in out[0]]


def _engine(**kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(PARAMS, CFG, **kw)


class TestExactness:
    def test_single_request_matches_sequential_decode(self):
        eng = _engine()
        prompt = _prompt(1, 8)
        eng.submit(prompt, max_tokens=12)
        eng.run_until_drained()
        (done,) = eng.completions()
        assert done.tokens == _reference(prompt, 12)
        assert done.generated == done.tokens[8:]

    def test_concurrent_requests_each_match(self):
        eng = _engine()
        prompts = {0: _prompt(2, 6), 1: _prompt(3, 9), 2: _prompt(4, 4)}
        ids = {eng.submit(p, max_tokens=10): k for k, p in prompts.items()}
        eng.run_until_drained()
        done = {c.request_id: c for c in eng.completions()}
        assert len(done) == 3
        for rid, key in ids.items():
            assert done[rid].tokens == _reference(prompts[key], 10), key

    def test_mid_flight_submit_matches(self):
        # A request joining while others are generating must not perturb
        # them (active-masked cache writes) nor itself (per-slot positions).
        eng = _engine()
        p0 = _prompt(5, 8)
        r0 = eng.submit(p0, max_tokens=12)
        for _ in range(5):
            eng.step()
        p1 = _prompt(6, 5)
        r1 = eng.submit(p1, max_tokens=6)
        eng.run_until_drained()
        done = {c.request_id: c for c in eng.completions()}
        assert done[r0].tokens == _reference(p0, 12)
        assert done[r1].tokens == _reference(p1, 6)

    def test_slot_reuse_after_completion(self):
        eng = _engine(n_slots=1)
        p0, p1 = _prompt(7, 4), _prompt(8, 6)
        r0 = eng.submit(p0, max_tokens=3)
        with pytest.raises(RuntimeError, match="no free slot"):
            eng.submit(p1, max_tokens=3)
        eng.run_until_drained()
        r1 = eng.submit(p1, max_tokens=5)  # reuses the freed slot
        eng.run_until_drained()
        done = {c.request_id: c for c in eng.completions()}
        assert done[r0].tokens == _reference(p0, 3)
        assert done[r1].tokens == _reference(p1, 5)


class TestScheduling:
    def test_step_counts_active(self):
        eng = _engine()
        assert eng.step() == 0
        eng.submit(_prompt(9, 4), max_tokens=5)
        eng.submit(_prompt(10, 4), max_tokens=2)
        assert eng.step() == 2
        # second request retires after its 2nd token (1 from prefill + 1)
        assert eng.step() == 1

    def test_eos_stops_early(self):
        prompt = _prompt(11, 6)
        ref = _reference(prompt, 20)
        eos = ref[8]  # a token the model will emit mid-stream
        eng = _engine(eos_id=eos)
        eng.submit(prompt, max_tokens=20)
        eng.run_until_drained()
        (done,) = eng.completions()
        assert done.tokens[-1] == eos
        assert done.tokens == ref[: len(done.tokens)]  # prefix of the ref

    def test_free_slots_accounting(self):
        eng = _engine()
        assert eng.free_slots() == 3
        eng.submit(_prompt(12, 4), max_tokens=4)
        assert eng.free_slots() == 2
        eng.run_until_drained()
        assert eng.free_slots() == 3


class TestValidation:
    def test_rejects_oversized_prompt(self):
        eng = _engine(prompt_bucket=8)
        with pytest.raises(ValueError, match="bucket"):
            eng.submit(list(range(9)), max_tokens=1)

    def test_rejects_overflow_of_max_seq(self):
        eng = _engine()
        with pytest.raises(ValueError, match="max_seq"):
            eng.submit(_prompt(13, 10), max_tokens=CFG.max_seq)

    def test_rejects_empty_prompt(self):
        with pytest.raises(ValueError, match="empty"):
            _engine().submit([], max_tokens=1)


class TestSampling:
    def test_same_seed_reproduces(self):
        outs = []
        for _ in range(2):
            eng = _engine()
            eng.submit(_prompt(20, 6), max_tokens=10, temperature=0.8, seed=42)
            eng.run_until_drained()
            outs.append(eng.completions()[0].tokens)
        assert outs[0] == outs[1]

    def test_different_seeds_diverge(self):
        def run(seed):
            eng = _engine()
            eng.submit(_prompt(21, 6), max_tokens=16, temperature=1.5, seed=seed)
            eng.run_until_drained()
            return eng.completions()[0].generated

        assert run(1) != run(2)  # 16 draws at temp 1.5: collision ~impossible

    def test_sampled_neighbor_does_not_perturb_greedy_rows(self):
        prompt = _prompt(22, 6)
        eng = _engine()
        r_greedy = eng.submit(prompt, max_tokens=10)  # temperature 0
        eng.submit(_prompt(23, 6), max_tokens=10, temperature=1.0, seed=7)
        eng.run_until_drained()
        done = {c.request_id: c for c in eng.completions()}
        assert done[r_greedy].tokens == _reference(prompt, 10)

    def test_top_k_filter_stays_in_top_k(self):
        # With top_k=1, sampling at any temperature IS greedy.
        eng = _engine(top_k=1)
        prompt = _prompt(24, 6)
        eng.submit(prompt, max_tokens=10, temperature=2.0, seed=3)
        eng.run_until_drained()
        assert eng.completions()[0].tokens == _reference(prompt, 10)

    def test_rejects_out_of_range_top_k(self):
        with pytest.raises(ValueError, match="top_k"):
            _engine(top_k=CFG.vocab_size + 1)


class TestShardedServing:
    """DP-sharded engine (slots over a mesh axis) must be bit-identical to
    the unsharded engine — row-axis sharding cannot change per-row math."""

    def _mesh(self, n=4):
        import numpy as np
        from jax.sharding import Mesh

        from tests.conftest import cpu_devices

        return Mesh(np.array(cpu_devices(n)), ("data",))

    def test_matches_unsharded_engine_exactly(self):
        mesh = self._mesh()
        prompts = [_prompt(30 + i, 4 + i) for i in range(3)]
        results = []
        for m in (None, mesh):
            eng = _engine(n_slots=4, mesh=m)
            ids = [eng.submit(p, max_tokens=8) for p in prompts]
            eng.run_until_drained()
            done = {c.request_id: c.tokens for c in eng.completions()}
            results.append([done[i] for i in ids])
        assert results[0] == results[1]

    def test_mid_flight_join_sharded(self):
        eng = _engine(n_slots=4, mesh=self._mesh())
        p0, p1 = _prompt(40, 6), _prompt(41, 5)
        r0 = eng.submit(p0, max_tokens=10)
        eng.step(); eng.step()
        r1 = eng.submit(p1, max_tokens=6)
        eng.run_until_drained()
        done = {c.request_id: c for c in eng.completions()}
        assert done[r0].tokens == _reference(p0, 10)
        assert done[r1].tokens == _reference(p1, 6)

    def test_slot_count_must_divide_axis(self):
        with pytest.raises(ValueError, match="divide"):
            _engine(n_slots=3, mesh=self._mesh())

    def test_unknown_slot_axis_is_a_clear_error(self):
        with pytest.raises(ValueError, match="slot_axis"):
            _engine(n_slots=4, mesh=self._mesh(), slot_axis="model")


class TestPrefixCaching:
    """Shared-prefix admission: the prefix's prefill compute is paid once;
    token streams are BIT-IDENTICAL with caching on or off."""

    SYS = _prompt(40, 6)  # the shared "system prompt" (prefix_bucket=6)

    def _drain(self, eng, prompts, max_tokens=8):
        ids = {eng.submit(p, max_tokens=max_tokens): p for p in prompts}
        eng.run_until_drained()
        return {c.request_id: c.tokens for c in eng.completions()}, ids

    def test_hit_is_bit_identical_to_reference(self):
        eng = _engine(prefix_bucket=6)
        prompts = [self.SYS + _prompt(s, 4) for s in (41, 42, 43)]
        done, ids = self._drain(eng, prompts)
        assert eng.prefix_misses == 1 and eng.prefix_hits == 2
        for rid, prompt in ids.items():
            assert done[rid] == _reference(prompt, 8)

    def test_on_off_streams_identical(self):
        prompts = [self.SYS + _prompt(s, 4) for s in (44, 45)]
        on, _ = self._drain(_engine(prefix_bucket=6), prompts)
        off, _ = self._drain(_engine(), prompts)
        assert [on[r] for r in sorted(on)] == [off[r] for r in sorted(off)]

    def test_short_prompt_bypasses_store(self):
        eng = _engine(prefix_bucket=6)
        eng.submit(_prompt(46, 5), max_tokens=4)  # shorter than the prefix
        eng.run_until_drained()
        assert eng.prefix_hits == 0 and eng.prefix_misses == 0
        assert len(eng._prefix_store) == 0

    def test_lru_eviction(self):
        eng = _engine(prefix_bucket=6, prefix_cache_entries=2)
        a, b, c = (_prompt(s, 6) for s in (47, 48, 49))

        def serve(pre):
            eng.submit(pre + _prompt(50, 3), max_tokens=2)
            eng.run_until_drained()

        serve(a), serve(b)              # store: {a, b} (2 misses)
        serve(a)                        # HIT a -> LRU order b, a
        serve(c)                        # cap 2: evicts b (oldest)
        assert len(eng._prefix_store) == 2
        assert eng.prefix_misses == 3 and eng.prefix_hits == 1
        serve(a)                        # a survived the eviction: hit
        assert eng.prefix_hits == 2
        serve(b)                        # b did not: miss again
        assert eng.prefix_misses == 4

    def test_hit_with_sampling_matches_unsuffixed_engine(self):
        """Sampled requests through the hit path reproduce the no-cache
        engine exactly (same stateless step keys)."""
        prompts = [self.SYS + _prompt(s, 4) for s in (53, 54)]

        def run(eng):
            ids = [eng.submit(p, max_tokens=6, temperature=0.8, seed=7) for p in prompts]
            eng.run_until_drained()
            return {c.request_id: c.tokens for c in eng.completions()}

        assert run(_engine(prefix_bucket=6)) == run(_engine())

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError, match="prefix_bucket"):
            _engine(prefix_bucket=16)  # == prompt_bucket
        with pytest.raises(ValueError, match="prefix_cache_entries"):
            _engine(prefix_bucket=4, prefix_cache_entries=0)


    def test_sharded_engine_prefix_hits_match_unsharded(self):
        """Prefix caching on the DP-sharded engine: hit-path streams equal
        the unsharded engine's, and the stored prefix entries replicate
        cleanly across the mesh (the hit path mixes sharded cache rows with
        replicated prefix arrays)."""
        mesh = TestShardedServing._mesh(TestShardedServing(), 4)
        sys_p = _prompt(60, 6)
        prompts = [sys_p + _prompt(61 + i, 3) for i in range(3)]
        results = []
        for m in (None, mesh):
            eng = _engine(n_slots=4, mesh=m, prefix_bucket=6)
            ids = [eng.submit(p, max_tokens=6) for p in prompts]
            eng.run_until_drained()
            done = {c.request_id: c.tokens for c in eng.completions()}
            results.append([done[i] for i in ids])
            assert eng.prefix_misses == 1 and eng.prefix_hits == 2
        assert results[0] == results[1]


class TestServingMetrics:
    def test_engine_activity_lands_in_registry(self):
        """Serving counters surface through the shared /metrics registry
        (utils/diagnostics.py scrape path) — the data-plane counterpart of
        the driver's claim-latency histogram."""
        from k8s_dra_driver_tpu.utils.metrics import REGISTRY

        def sample():
            out = {}
            for line in REGISTRY.render().splitlines():
                if line.startswith("tpu_serve_") and " " in line:
                    name, val = line.rsplit(" ", 1)
                    out[name] = float(val)
            return out

        before = sample()
        eng = _engine(prefix_bucket=6)
        sys_p = _prompt(70, 6)
        eng.submit(sys_p + _prompt(71, 3), max_tokens=4)
        eng.submit(sys_p + _prompt(72, 3), max_tokens=4)
        eng.run_until_drained()
        after = sample()

        def delta(name):
            return after.get(name, 0) - before.get(name, 0)

        assert delta("tpu_serve_requests_total") == 2
        assert delta("tpu_serve_completions_total") == 2
        assert delta("tpu_serve_tokens_total") == 8  # 4 generated each
        assert delta('tpu_serve_prefix_cache_total{outcome="hit"}') == 1
        assert delta('tpu_serve_prefix_cache_total{outcome="miss"}') == 1
