"""Allocation-index cache coherence (scheduler/index.py).

The snapshot cache must be exactly as fresh as the API server: a pool
republished at a higher generation (inventory changed) or deleted outright
must be re-read on the very next plan — stale candidates allocated from a
cache would double-book hardware.  Also pins the exported hit/miss
counters, consumed-set correctness across independent Allocator instances,
and the Plan.tightness() reuse of the precomputed marker union."""

import pytest

from k8s_dra_driver_tpu.kube.resourceslice_controller import DriverResources
from k8s_dra_driver_tpu.scheduler.allocator import AllocationError, Allocator, Plan
from k8s_dra_driver_tpu.utils.metrics import REGISTRY
from tests.test_allocator import (
    TPU_CLASS,
    DeviceRequest,
    ResourceSliceController,
    install_classes,
    make_claim,
    publish_host,
)
from k8s_dra_driver_tpu import DRIVER_NAME


def chip_request(name="tpu", count=1):
    return DeviceRequest(name=name, device_class_name=TPU_CLASS, count=count)


class TestGenerationBumpInvalidation:
    def test_republish_with_changed_inventory_is_seen(self, api_server):
        install_classes(api_server)
        # v5e-16 host 0 publishes 4 local chips.
        publish_host(api_server, spec="v5e-16", host_id=0, node="host0")
        alloc = Allocator(api_server)
        c1 = make_claim(api_server, "warm", [chip_request()])
        alloc.allocate(c1, node_name="host0")  # populates the index
        with pytest.raises(AllocationError):
            # 8 chips cannot exist in the cached 4-chip inventory.
            alloc.plan(
                make_claim(api_server, "too-big", [chip_request(count=8)]),
                node_name="host0",
            )
        alloc.deallocate(api_server.get("ResourceClaim", "warm", "default"))

        # Same pool republished with DIFFERENT inventory (8 chips): the
        # controller bumps the pool generation; the next plan must see the
        # new devices, never the cached ones.
        publish_host(api_server, spec="v5e-8", host_id=0, node="host0")
        updated = alloc.allocate(
            make_claim(api_server, "all-eight", [chip_request(count=8)]),
            node_name="host0",
        )
        devices = {r.device for r in updated.status.allocation.devices.results}
        assert len(devices) == 8
        # tpu-4..7 exist only in the new inventory.
        assert any(d.startswith("tpu-") and int(d.split("-")[1]) >= 4 for d in devices)

    def test_deleted_pool_disappears(self, api_server):
        install_classes(api_server)
        publish_host(api_server, spec="v5e-8", host_id=0, node="host0")
        alloc = Allocator(api_server)
        claim = make_claim(api_server, "pre-delete", [chip_request()])
        alloc.allocate(claim, node_name="host0")
        alloc.deallocate(api_server.get("ResourceClaim", "pre-delete", "default"))

        # Withdraw the pool entirely (empty desired set deletes the slices).
        ctrl = ResourceSliceController(api_server, DRIVER_NAME, "host0")
        ctrl.update(DriverResources(pools={}))
        with pytest.raises(AllocationError):
            alloc.plan(
                make_claim(api_server, "post-delete", [chip_request()]),
                node_name="host0",
            )


class TestIndexCounters:
    def test_steady_state_hits_without_misses(self, api_server):
        install_classes(api_server)
        publish_host(api_server, spec="v5e-8", host_id=0, node="host0")
        alloc = Allocator(api_server)
        hits = REGISTRY.counter("dra_alloc_index_hits_total")
        misses = REGISTRY.counter("dra_alloc_index_misses_total")
        evals = REGISTRY.counter("dra_cel_evals_total")

        alloc.allocate(
            make_claim(api_server, "n1", [chip_request()]), node_name="host0"
        )
        h1, m1, e1 = hits.value(), misses.value(), evals.value()
        assert m1 >= 1  # first plan built the pool snapshot
        for i in range(5):
            alloc.allocate(
                make_claim(api_server, f"n{i + 2}", [chip_request()]),
                node_name="host0",
            )
        assert misses.value() == m1  # unchanged inventory: zero rebuilds
        assert hits.value() > h1
        # Verdict memo: the SAME candidates answer the same selectors with
        # zero further CEL evaluation — O(changed pools), not O(claims).
        assert evals.value() == e1

    def test_republish_costs_one_miss(self, api_server):
        install_classes(api_server)
        publish_host(api_server, spec="v5e-8", host_id=0, node="host0")
        alloc = Allocator(api_server)
        alloc.allocate(
            make_claim(api_server, "m1", [chip_request()]), node_name="host0"
        )
        misses = REGISTRY.counter("dra_alloc_index_misses_total")
        m1 = misses.value()
        publish_host(api_server, spec="v5e-16", host_id=0, node="host0")
        with pytest.raises(AllocationError):
            # the republished inventory has only 4 chips
            alloc.plan(
                make_claim(api_server, "m2", [chip_request(count=8)]),
                node_name="host0",
            )
        assert misses.value() == m1 + 1  # exactly the changed pool rebuilt


class TestConsumedAcrossAllocators:
    def test_second_allocator_sees_existing_allocations(self, api_server):
        install_classes(api_server)
        publish_host(api_server, spec="v5e-8", host_id=0, node="host0")
        a = Allocator(api_server)
        taken = set()
        for i in range(2):
            updated = a.allocate(
                make_claim(api_server, f"a{i}", [chip_request()]), node_name="host0"
            )
            taken |= {r.device for r in updated.status.allocation.devices.results}
        assert len(taken) == 2

        b = Allocator(api_server)  # fresh index, same server
        updated = b.allocate(
            make_claim(api_server, "b-rest", [chip_request(count=6)]),
            node_name="host0",
        )
        rest = {r.device for r in updated.status.allocation.devices.results}
        assert len(rest) == 6
        assert not (rest & taken)
        with pytest.raises(AllocationError):
            b.plan(
                make_claim(api_server, "b-over", [chip_request()]),
                node_name="host0",
            )

    def test_deallocation_frees_for_other_allocator(self, api_server):
        install_classes(api_server)
        publish_host(api_server, spec="v5e-8", host_id=0, node="host0")
        a = Allocator(api_server)
        b = Allocator(api_server)
        a.allocate(
            make_claim(api_server, "churn", [chip_request(count=8)]),
            node_name="host0",
        )
        with pytest.raises(AllocationError):
            b.plan(make_claim(api_server, "blocked", [chip_request()]), node_name="host0")
        a.deallocate(api_server.get("ResourceClaim", "churn", "default"))
        b.allocate(
            make_claim(api_server, "after", [chip_request(count=8)]),
            node_name="host0",
        )


class TestTightnessReuse:
    def test_scores_pinned_and_legacy_equivalent(self, api_server):
        install_classes(api_server)
        # 8 chips, markers chip0..chip7: the tightness denominator is 8
        # available markers before any allocation.
        publish_host(api_server, spec="v5e-8", host_id=0, node="host0")
        alloc = Allocator(api_server)
        p1 = alloc.plan(
            make_claim(api_server, "t1", [chip_request(count=2)]), node_name="host0"
        )
        assert p1.node_markers  # precomputed union flowed through
        assert p1.tightness() == pytest.approx(2 / 8)

        alloc.allocate(
            api_server.get("ResourceClaim", "t1", "default"), node_name="host0"
        )
        p2 = alloc.plan(
            make_claim(api_server, "t2", [chip_request(count=2)]), node_name="host0"
        )
        # 2 markers consumed: 6 available, this plan takes 2 of them.
        assert p2.tightness() == pytest.approx(2 / 6)

        # The precomputed-union fast path must agree exactly with the
        # legacy free-scan fallback (hand-built Plans without node_markers).
        for p in (p1, p2):
            legacy = Plan(
                chosen=p.chosen,
                admin_results=p.admin_results,
                free=p.free,
                classes=p.classes,
                used_markers=p.used_markers,
            )
            assert p.tightness() == pytest.approx(legacy.tightness())
