"""Pipelined decode loop (sync_interval > 1): the fused burst changes WHEN
tokens are read back from the device, never WHAT tokens come out.  Every
case here pins bit-equality between the synchronous loop (sync_interval=1,
one host sync per token) and the pipelined loop across both engines and
every feature that composes with decode: sampling, eos, spec decode, LoRA
banks, prefix caches, paged pools.  Plus the pump() continuous-batching
contract (no slot/block leaks) and the wedge -> diag-bundle tail."""

import json

import jax
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, lora, paged
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128
)
LORA = lora.LoraConfig(rank=4, alpha=8.0)
BS = 16


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _prompts(n, rng=7, lo=3, hi=12):
    r = np.random.RandomState(rng)
    return [
        r.randint(0, CFG.vocab_size, size=r.randint(lo, hi)).tolist()
        for _ in range(n)
    ]


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 40)
    kw.setdefault("block_size", BS)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


def _drain(eng, reqs):
    """Pump the queue through and return id -> full token stream.  pump
    admits FIFO and ids assign in submit order, so the dicts compare
    across engines (and across sync_interval settings)."""
    return {c.request_id: tuple(c.tokens) for c in eng.pump(list(reqs))}


class TestDenseBitEquality:
    def test_greedy_nondivisor_interval(self, params):
        # 5 does not divide 12 generated tokens: the trailing burst runs
        # past every retirement and the replay's pre-step active mask must
        # drop exactly the post-stop lanes.
        reqs = [(p, 12) for p in _prompts(5)]
        sync = _drain(_dense(params), reqs)
        eng = _dense(params, sync_interval=5)
        assert _drain(eng, reqs) == sync
        # the point of the burst: strictly fewer readbacks than tokens
        assert eng.host_syncs < 5 * 12

    def test_sampled_streams_bit_equal(self, params):
        # Sampling keys derive from (request seed, pos), both host-free
        # state inside the scan carry — temperature must not break parity.
        reqs = [
            {"prompt": p, "max_tokens": 9, "temperature": 0.8, "seed": 100 + i}
            for i, p in enumerate(_prompts(4, rng=11))
        ]
        assert _drain(_dense(params, sync_interval=4), reqs) == _drain(
            _dense(params), reqs
        )

    def test_eos_mid_burst_retires_exactly(self, params):
        # Pick an eos the greedy stream actually emits, mid-burst, so the
        # on-device stop mask (not max_tokens) ends the stream.
        (p,) = _prompts(1, rng=3)
        probe = _dense(params)
        probe.submit(p, max_tokens=12)
        probe.run_until_drained()
        (ref,) = probe.completions()
        eos = ref.generated[2]
        reqs = [(p, 12)]
        sync = _drain(_dense(params, eos_id=eos), reqs)
        pipe = _drain(_dense(params, eos_id=eos, sync_interval=8), reqs)
        assert pipe == sync
        (stream,) = pipe.values()
        assert len(stream) < len(p) + 12  # eos actually cut it short

    def test_lora_bank_bit_equal(self, params):
        bank = lora.stack_adapters(
            CFG, LORA, [_trained_adapter(1), _trained_adapter(2)]
        )
        reqs = [
            {"prompt": p, "max_tokens": 10, "adapter": i % 3}
            for i, p in enumerate(_prompts(5, rng=13))
        ]
        assert _drain(_dense(params, adapter_bank=bank, sync_interval=6), reqs) == (
            _drain(_dense(params, adapter_bank=bank), reqs)
        )

    def test_prefix_cache_hit_bit_equal(self, params):
        # Shared system prompt fills the prefix bucket; later requests hit
        # the store and skip the prefix prefill — admission-side state the
        # burst must neither see nor disturb.
        sys_p = _prompts(1, rng=40, lo=6, hi=7)[0]
        reqs = [(sys_p + p, 10) for p in _prompts(4, rng=41, lo=2, hi=8)]
        sync = _drain(_dense(params, prefix_bucket=6), reqs)
        assert _drain(_dense(params, prefix_bucket=6, sync_interval=4), reqs) == sync
        # and the cache itself changed nothing (existing contract, repinned
        # here because the burst replays commits the cache path never sees)
        assert _drain(_dense(params, sync_interval=4), reqs) == sync

    def test_spec_decode_delegates_and_matches(self, params):
        # Speculative rounds already advance multiple tokens per sync, so
        # step_burst() delegates to the spec step; a sync_interval on a
        # spec engine must be a no-op for the streams.
        reqs = [(p, 12) for p in _prompts(3, rng=17)]
        plain = _drain(_dense(params), reqs)
        spec_sync = _drain(_dense(params, spec_gamma=2), reqs)
        spec_burst = _drain(_dense(params, spec_gamma=2, sync_interval=8), reqs)
        assert spec_sync == plain
        assert spec_burst == plain


def _trained_adapter(seed: int) -> dict:
    """Nonzero-B adapter (init is the identity), deterministic per seed."""
    ad = lora.init_adapters(jax.random.PRNGKey(seed), CFG, LORA)
    for li, blk in enumerate(ad["blocks"]):
        for name, ab in blk.items():
            tag = li * 1000 + sum(ord(c) for c in name)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), tag)
            ab["b"] = 0.3 * jax.random.normal(key, ab["b"].shape, jax.numpy.float32)
    return ad


class TestPagedBitEquality:
    def test_greedy_bit_equal(self, params):
        reqs = [(p, 12) for p in _prompts(5)]
        sync = _drain(_paged(params), reqs)
        eng = _paged(params, sync_interval=6)
        assert _drain(eng, reqs) == sync
        assert eng.host_syncs < 5 * 12

    def test_matches_dense_engine(self, params):
        # Transitivity with the existing parity suite: pipelined paged ==
        # sync dense, so ALL engines emit one stream per request.
        reqs = [(p, 10) for p in _prompts(4, rng=23)]
        assert _drain(_paged(params, sync_interval=4), reqs) == _drain(
            _dense(params), reqs
        )

    def test_tight_pool_falls_back_without_divergence(self, params):
        # A pool too small for K-1 lookahead forces the K=1 burst fallback
        # mid-drain; streams must still match the roomy sync engine's.
        reqs = [(p, 14) for p in _prompts(3, rng=29)]
        sync = _drain(_paged(params, n_blocks=40), reqs)
        # 22 blocks of 4 hold the 3-slot resident set exactly (<= 25
        # tokens/stream -> 7 blocks each, +1 reserved) with NO room for
        # the K-1=5 lookahead near the tail — the fallback must engage.
        tight = _paged(
            params, n_blocks=22, block_size=4, sync_interval=6,
            preempt_on_stall=False,
        )
        assert _drain(tight, reqs) == sync

    def test_chunked_prefill_and_prefix_cache_bit_equal(self, params):
        # Chunked admission keeps slots in _admitting across bursts; the
        # prefix store pins blocks.  Both must survive pipelining intact.
        sys_p = _prompts(1, rng=50, lo=BS, hi=BS + 1)[0]  # one full block
        reqs = [(sys_p + p, 10) for p in _prompts(4, rng=51, lo=2, hi=8)]
        kw = dict(
            n_blocks=60, prompt_bucket=48, prefill_chunk_blocks=1,
            prefix_cache_blocks=4,
        )
        assert _drain(_paged(params, sync_interval=5, **kw), reqs) == _drain(
            _paged(params, **kw), reqs
        )


class TestPump:
    def test_mid_flight_admission_no_slot_leak(self, params):
        # 8 requests through 3 slots: later requests are admitted only as
        # earlier ones retire mid-pump, and every slot must come back.
        prompts = _prompts(8, rng=31)
        reqs = [(p, 10) for p in prompts]
        sync = _drain(_dense(params), [(p, 10) for p in prompts[:3]])
        eng = _dense(params, sync_interval=4)
        done = eng.pump(reqs)
        assert len(done) == 8
        assert eng.free_slots() == eng.n_slots
        streams = {c.request_id: tuple(c.tokens) for c in done}
        # first wave ids line up with the plain drain's ids
        for rid, stream in sync.items():
            assert streams[rid] == stream

    def test_pump_paged_no_block_leak(self, params):
        eng = _paged(params, sync_interval=4, n_blocks=24)
        before = eng.free_blocks
        done = eng.pump(
            [
                {"prompt": p, "max_tokens": 8, "seed": i}
                for i, p in enumerate(_prompts(7, rng=37))
            ]
        )
        assert len(done) == 7
        assert eng.free_slots() == eng.n_slots
        assert eng.free_blocks == before
        assert not eng._admitting

    def test_pump_sets_rate_gauge_and_counts_syncs(self, params):
        eng = _dense(params, sync_interval=8)
        done = eng.pump([(p, 12) for p in _prompts(4, rng=43)])
        generated = sum(len(c.generated) for c in done)
        assert REGISTRY.gauge("tpu_serve_tokens_per_second").value() > 0
        assert eng.host_syncs == REGISTRY.counter(
            "tpu_serve_host_syncs_total"
        ).value()
        assert eng.host_syncs < generated

    def test_pump_heartbeats_and_queue_stats(self, params):
        # Every pump iteration must heartbeat its watchdog guard and keep
        # the queue-depth/shed tallies an operator reads after the fact.
        eng = _dense(params, sync_interval=4)
        done = eng.pump(
            [(p, 6) for p in _prompts(6, rng=53)], queue_limit=2
        )
        stats = eng.pump_stats
        assert set(stats) >= {"queue_depth", "sheds", "heartbeats"}
        assert stats["heartbeats"] >= 1
        assert stats["queue_depth"] == 0  # drained
        assert stats["sheds"] == sum(1 for c in done if c.status == "shed")
        assert stats["sheds"] == eng.shed_count == 1
        assert REGISTRY.gauge("tpu_serve_queue_depth").value() == 0


class TestWedgeDiagBundle:
    """run_until_drained exhaustion must leave a diag bundle carrying the
    active-slot state (the PR 1 machinery) — a wedged engine with no
    bundle is undebuggable after the process dies."""

    def _point_bundles_at(self, monkeypatch, tmp_path):
        from k8s_dra_driver_tpu.utils.watchdog import WATCHDOG

        monkeypatch.setattr(WATCHDOG, "_bundle_dir", str(tmp_path))

    def _bundles(self, tmp_path):
        # the wedge path writes the drain snapshot NEXT TO the bundle;
        # keep only actual diag bundles
        return sorted(
            p for p in tmp_path.glob("*.json")
            if "drain-snapshot" not in p.name
        )

    def test_dense_exhaustion_emits_bundle(self, params, tmp_path, monkeypatch):
        self._point_bundles_at(monkeypatch, tmp_path)
        eng = _dense(params, sync_interval=4)
        rid = eng.submit(_prompts(1)[0], max_tokens=60)
        with pytest.raises(RuntimeError, match="diag bundle") as exc:
            eng.run_until_drained(max_steps=2)
        bundles = self._bundles(tmp_path)
        assert bundles, "no diag bundle written"
        state = json.loads(bundles[-1].read_text())["state"]
        assert state["engine"] == "ServeEngine"
        assert state["sync_interval"] == 4
        assert [s["request_id"] for s in state["slots"]] == [rid]
        assert str(bundles[-1]) in str(exc.value)

    def test_paged_exhaustion_emits_bundle(self, params, tmp_path, monkeypatch):
        self._point_bundles_at(monkeypatch, tmp_path)
        eng = _paged(params, sync_interval=4)
        eng.submit(_prompts(1)[0], max_tokens=60)
        with pytest.raises(RuntimeError, match="diag bundle"):
            eng.run_until_drained(max_steps=2)
        state = json.loads(self._bundles(tmp_path)[-1].read_text())["state"]
        assert state["engine"] == "PagedServeEngine"
        assert state["slots"] and state["free_blocks"] is not None

    def test_wedge_embeds_admission_queue_and_snapshot(
        self, params, tmp_path, monkeypatch
    ):
        # Wedge while a chunked prefill is mid-flight: the bundle must
        # carry the admission-queue table AND a restorable drain snapshot.
        self._point_bundles_at(monkeypatch, tmp_path)
        eng = _paged(
            params, block_size=4, n_blocks=24, prefill_chunk_blocks=1
        )
        eng.submit(_prompts(1, lo=11, hi=12)[0], max_tokens=20)
        assert eng._admitting
        with pytest.raises(RuntimeError, match="drain snapshot") as exc:
            eng.run_until_drained(max_steps=1)
        state = json.loads(self._bundles(tmp_path)[-1].read_text())["state"]
        assert state["admission_queue"], "mid-admission row missing"
        row = state["admission_queue"][0]
        assert set(row) == {"slot", "prompt_len", "done_tokens"}
        assert 0 < row["done_tokens"] < row["prompt_len"]
        snap_path = state["drain_snapshot_path"]
        assert snap_path and snap_path in str(exc.value)
        snap = json.loads((tmp_path / snap_path.split("/")[-1]).read_text())
        assert state["drain_snapshot_requests"] == len(snap["requests"]) == 1

    def test_pump_wedge_embeds_queue_depth(self, params, tmp_path, monkeypatch):
        self._point_bundles_at(monkeypatch, tmp_path)
        eng = _dense(params)
        with pytest.raises(RuntimeError, match="did not drain"):
            eng.pump([(p, 30) for p in _prompts(5, rng=59)], max_steps=1)
        state = json.loads(self._bundles(tmp_path)[-1].read_text())["state"]
        assert state["pump_queue_depth"] >= 1  # overload forensics
        assert state["shed_count"] == 0 and state["quarantined"] == []


class TestServeMetrics:
    def test_scrape_exposes_pipelining_metrics(self, params):
        # REGISTRY resets between tests (conftest autouse), so absolute
        # asserts hold: one drain's worth of tokens/syncs/occupancy.
        eng = _dense(params, sync_interval=4)
        streams = _drain(eng, [(p, 10) for p in _prompts(3, rng=47)])
        generated = sum(len(s) for s in streams.values()) - sum(
            len(p) for p in _prompts(3, rng=47)
        )
        assert REGISTRY.counter("tpu_serve_tokens_total").value() == generated
        assert REGISTRY.counter("tpu_serve_host_syncs_total").value() == (
            eng.host_syncs
        )
        assert REGISTRY.gauge("tpu_serve_slot_occupancy").value() == 0
        assert REGISTRY.histogram("tpu_serve_step_seconds").count() == (
            eng.host_syncs
        )
        text = REGISTRY.render()
        for name, kind in (
            ("tpu_serve_host_syncs_total", "counter"),
            ("tpu_serve_step_seconds", "histogram"),
            ("tpu_serve_tokens_per_second", "gauge"),
        ):
            # label hygiene: HELP + TYPE lines present, name well-formed
            assert f"# TYPE {name} {kind}" in text
            assert f"# HELP {name} " in text
        assert "tpu_serve_step_seconds_bucket{le=" in text
