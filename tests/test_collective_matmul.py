"""Collective matmul (latency-hiding TP rings) — correctness on the CPU mesh.

Equivalence contracts: each overlapped kernel must match its naive
`collective; matmul` reference up to addition-reorder rounding (the ring
changes summation order and tiling) and stay differentiable end to end."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.ops.collective_matmul import (
    all_gather_matmul,
    matmul_reduce_scatter,
    sharded_tp_mlp,
    tp_mlp,
)
from tests.conftest import cpu_devices


def _mesh(n=8, axis="ring"):
    return Mesh(np.array(cpu_devices(n)), (axis,))


def _shard_mapped(fn, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
    )


class TestAllGatherMatmul:
    @pytest.mark.parametrize("bidirectional", [False, True])
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_gather_then_matmul(self, n, bidirectional):
        mesh = _mesh(n)
        s, k, cols = 16 * n, 32, 24
        x = jax.random.normal(jax.random.PRNGKey(0), (s, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, cols), jnp.float32)

        fn = _shard_mapped(
            functools.partial(
                all_gather_matmul, axis_name="ring", bidirectional=bidirectional
            ),
            mesh, (P("ring", None), P(None, None)), P(None, None),
        )
        # out_specs P(None,...) asserts replication: every device must hold
        # the full gathered product.
        np.testing.assert_allclose(fn(x, w), x @ w, rtol=1e-5, atol=1e-5)

    def test_sharded_weight_cols(self):
        # column-parallel: each device's w shard produces its own columns
        mesh = _mesh(4)
        s, k, cols = 32, 16, 32
        x = jax.random.normal(jax.random.PRNGKey(0), (s, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, cols), jnp.float32)
        fn = _shard_mapped(
            functools.partial(all_gather_matmul, axis_name="ring"),
            mesh, (P("ring", None), P(None, "ring")), P(None, "ring"),
        )
        np.testing.assert_allclose(fn(x, w), x @ w, rtol=1e-5, atol=1e-5)

    def test_grad_flows(self):
        mesh = _mesh(4)
        x = jax.random.normal(jax.random.PRNGKey(0), (32, 16), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (16, 8), jnp.float32)

        def loss(x, w):
            fn = jax.shard_map(
                functools.partial(all_gather_matmul, axis_name="ring"),
                mesh=mesh, in_specs=(P("ring", None), P(None, None)),
                out_specs=P(None, None), check_vma=False,
            )
            return jnp.sum(fn(x, w) ** 2)

        gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
        ref = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gx, ref[0], rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(gw, ref[1], rtol=1e-4, atol=1e-4)

    def test_odd_local_rows_reject_bidirectional(self):
        mesh = _mesh(2)
        x = jnp.ones((6, 4))  # s_loc=3, odd
        w = jnp.ones((4, 4))
        fn = _shard_mapped(
            functools.partial(
                all_gather_matmul, axis_name="ring", bidirectional=True
            ),
            mesh, (P("ring", None), P(None, None)), P(None, None),
        )
        with pytest.raises(ValueError, match="even s_loc"):
            fn(x, w)


class TestMatmulReduceScatter:
    @pytest.mark.parametrize("bidirectional", [False, True])
    @pytest.mark.parametrize("n", [2, 4, 8])
    def test_matches_matmul_then_reduce_scatter(self, n, bidirectional):
        mesh = _mesh(n)
        s, k, cols = 8 * n, 16 * n, 24
        x = jax.random.normal(jax.random.PRNGKey(0), (s, k), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, cols), jnp.float32)

        fn = _shard_mapped(
            functools.partial(
                matmul_reduce_scatter, axis_name="ring", bidirectional=bidirectional
            ),
            mesh, (P(None, "ring"), P("ring", None)), P("ring", None),
        )
        np.testing.assert_allclose(fn(x, w), x @ w, rtol=1e-4, atol=1e-4)

    def test_grad_flows(self):
        mesh = _mesh(4)
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 32), jnp.float32)
        w = jax.random.normal(jax.random.PRNGKey(1), (32, 8), jnp.float32)

        def loss(x, w):
            fn = jax.shard_map(
                functools.partial(matmul_reduce_scatter, axis_name="ring"),
                mesh=mesh, in_specs=(P(None, "ring"), P("ring", None)),
                out_specs=P("ring", None), check_vma=False,
            )
            return jnp.sum(fn(x, w) ** 2)

        gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
        ref = jax.grad(lambda x, w: jnp.sum((x @ w) ** 2), argnums=(0, 1))(x, w)
        np.testing.assert_allclose(gx, ref[0], rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(gw, ref[1], rtol=1e-4, atol=1e-4)


class TestTpMlp:
    def test_matches_dense_mlp(self):
        mesh = _mesh(4)
        s, d, ff = 32, 16, 64
        key = jax.random.PRNGKey(0)
        x = jax.random.normal(key, (s, d), jnp.float32)
        w_in = jax.random.normal(jax.random.PRNGKey(1), (d, ff), jnp.float32) / 4
        w_out = jax.random.normal(jax.random.PRNGKey(2), (ff, d), jnp.float32) / 8

        fn = _shard_mapped(
            functools.partial(tp_mlp, axis_name="ring"),
            mesh,
            (P("ring", None), P(None, "ring"), P("ring", None)),
            P("ring", None),
        )
        ref = jax.nn.gelu(x @ w_in) @ w_out
        np.testing.assert_allclose(fn(x, w_in, w_out), ref, rtol=1e-4, atol=1e-4)

    def test_sharded_wrapper_batched(self):
        mesh = _mesh(4, axis="model")
        b, s, d, ff = 2, 32, 16, 64
        x = jax.random.normal(jax.random.PRNGKey(0), (b, s, d), jnp.float32)
        w_in = jax.random.normal(jax.random.PRNGKey(1), (d, ff), jnp.float32) / 4
        w_out = jax.random.normal(jax.random.PRNGKey(2), (ff, d), jnp.float32) / 8
        out = jax.jit(
            functools.partial(sharded_tp_mlp, mesh=mesh)
        )(x, w_in, w_out)
        ref = jax.nn.gelu(x @ w_in) @ w_out
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_bf16_inputs_accumulate_in_f32(self):
        # The rotating accumulator must be f32: with bf16 accumulation the
        # 8-step ring sum visibly drifts from the dense product.
        mesh = _mesh(8)
        s, k, cols = 64, 256, 16
        x = jax.random.normal(jax.random.PRNGKey(0), (s, k)).astype(jnp.bfloat16)
        w = jax.random.normal(jax.random.PRNGKey(1), (k, cols)).astype(jnp.bfloat16)
        fn = _shard_mapped(
            functools.partial(matmul_reduce_scatter, axis_name="ring"),
            mesh, (P(None, "ring"), P("ring", None)), P("ring", None),
        )
        out = fn(x, w).astype(jnp.float32)
        ref = jnp.dot(x, w, preferred_element_type=jnp.float32)
        np.testing.assert_allclose(out, ref, rtol=2e-2, atol=1e-2)
