"""Regenerate the helm render goldens in this directory.

Run after an INTENDED chart or renderer change, review the diff, commit:
    python tests/goldens/helm/regen.py
The configs live in tools/helm_crosscheck.py (one source of truth for the
goldens here and the real-helm comparison in CI).
"""

import pathlib
import sys

import yaml

REPO = pathlib.Path(__file__).resolve().parents[3]
sys.path.insert(0, str(REPO))

from tools.helm_crosscheck import CHART, CONFIGS, _key  # noqa: E402
from tools.helm_render import _parse_set, render_chart_docs  # noqa: E402

HEADER = """\
# GOLDEN render of the tpu-dra-driver chart — canonical (parsed, kind/name-
# sorted, yaml.safe_dump) form, pinning tools/helm_render.py's semantics.
# Regenerate: python tests/goldens/helm/regen.py
# Cross-checked against REAL `helm template` by tools/helm_crosscheck.py
# wherever a helm binary exists (the CI helm-crosscheck job); this hermetic
# environment has none, so divergences surface there, regressions here.
"""


def canonical(sets: list[str]) -> str:
    docs = render_chart_docs(CHART, values_override=_parse_set(sets))
    docs = sorted(docs, key=lambda d: str(_key(d)))
    return HEADER + "\n".join(
        "---\n" + yaml.safe_dump(d, sort_keys=True) for d in docs
    )


if __name__ == "__main__":
    here = pathlib.Path(__file__).parent
    for name, sets in CONFIGS.items():
        (here / f"{name}.yaml").write_text(canonical(sets))
        print(f"wrote {name}.yaml")
