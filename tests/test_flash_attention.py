"""Pallas flash-attention numerics (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.ops.flash_attention import flash_attention
from k8s_dra_driver_tpu.ops.ring_attention import reference_attention
from tests.conftest import cpu_devices


def make_qkv(b=1, s=128, h=2, d=64, dtype=jnp.float32, seed=3):
    cpu = cpu_devices(1)[0]
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(
        jax.device_put(jax.random.normal(key, (b, s, h, d), dtype), cpu)
        for key in keys
    )


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        q, k, v = make_qkv()
        want = reference_attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_multi_block_and_uneven_block_sizes(self):
        q, k, v = make_qkv(b=2, s=256, h=1, d=32)
        want = reference_attention(q, k, v)
        got = flash_attention(q, k, v, block_q=64, block_k=32, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_bf16_inputs(self):
        q, k, v = make_qkv(dtype=jnp.bfloat16)
        want = reference_attention(q, k, v)
        got = flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)
        assert got.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32), atol=3e-2
        )

    def test_rejects_indivisible_sequence(self):
        q, k, v = make_qkv(s=96)
        with pytest.raises(ValueError, match="not divisible"):
            flash_attention(q, k, v, block_q=64, block_k=64, interpret=True)

    def test_single_block(self):
        q, k, v = make_qkv(s=32)
        want = reference_attention(q, k, v)
        got = flash_attention(q, k, v, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


class TestFlashAttentionVJP:
    @pytest.mark.parametrize("causal", [True, False])
    def test_gradients_match_reference(self, causal):
        q, k, v = make_qkv(s=64, h=2, d=32)

        def flash_loss(a, b, c):
            o = flash_attention(a, b, c, causal=causal, block_q=32, block_k=32, interpret=True)
            return jnp.sum(o * o)

        def ref_loss(a, b, c):
            o = reference_attention(a, b, c, causal=causal)
            return jnp.sum(o * o)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w, name in zip(got, want, "qkv"):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), atol=5e-4, err_msg=f"d{name}"
            )

    def test_gradients_multiblock_uneven(self):
        q, k, v = make_qkv(b=2, s=128, h=1, d=16)

        def flash_loss(a, b, c):
            return jnp.sum(
                flash_attention(a, b, c, block_q=64, block_k=32, interpret=True) ** 2
            )

        def ref_loss(a, b, c):
            return jnp.sum(reference_attention(a, b, c) ** 2)

        got = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
        want = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, w in zip(got, want):
            np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=5e-4)

    def test_burnin_flash_attention_training(self):
        # attention='flash' routes the burn-in train step through the pallas
        # kernels (interpret mode off-TPU) and the loss still decreases.
        from k8s_dra_driver_tpu.models import burnin

        cfg = burnin.ModelConfig(
            vocab_size=256, d_model=64, n_heads=4, n_layers=1, d_ff=128, max_seq=32
        )
        fns = burnin.build_train_step(cfg, lr=1e-2, attention="flash")
        params, opt_state = fns.init(jax.random.PRNGKey(0))
        tokens = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=2, seq=32)
        first = None
        for _ in range(3):
            params, opt_state, loss = fns.step(params, opt_state, tokens)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_flash_composes_with_seq_sharded_mesh(self):
        """flash + seq sharding = flash RING attention (round 1 rejected the
        combination; the composition is the long-context flagship path)."""
        from k8s_dra_driver_tpu.models import burnin
        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
        from tests.conftest import cpu_devices

        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, seq=2, model=2))
        fns = burnin.build_train_step(burnin.TINY, mesh=mesh, attention="flash")
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = burnin.sample_tokens(
                jax.random.PRNGKey(1), burnin.TINY, batch=4, seq=64
            )
            params, opt_state, loss = fns.step(params, opt_state, tokens)
        assert np.isfinite(float(loss))

    def test_sharded_flash_matches_reference(self):
        from k8s_dra_driver_tpu.ops.flash_attention import sharded_flash_attention
        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
        from tests.conftest import cpu_devices

        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, model=4))
        q, k, v = make_qkv(b=2, s=64, h=4, d=32)
        want = reference_attention(q, k, v)
        # uncommitted host copies: the pinned CPU arrays above would conflict
        # with the 8-device mesh placement
        q8, k8, v8 = (np.asarray(x) for x in (q, k, v))
        got = jax.jit(
            lambda a, b, c: sharded_flash_attention(
                a, b, c, mesh=mesh, block_q=32, block_k=32, interpret=True
            )
        )(q8, k8, v8)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_sharded_flash_train_step(self):
        from k8s_dra_driver_tpu.models import burnin
        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
        from tests.conftest import cpu_devices
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        cfg = burnin.TINY
        mesh = build_mesh(cpu_devices(8), MeshShape(data=2, model=4))
        fns = burnin.build_train_step(cfg, mesh=mesh, attention="flash")
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            tokens = jax.device_put(
                burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=8, seq=32),
                NamedSharding(mesh, P("data", None)),
            )
            _, _, loss = fns.step(params, opt_state, tokens)
        assert jnp.isfinite(loss)

    def test_trains_in_jit(self):
        # The whole point: a jitted train step through the pallas kernels.
        q, k, v = make_qkv(s=32, h=1, d=16)

        @jax.jit
        def step(a, b, c):
            return jax.grad(
                lambda x, y, z: jnp.sum(
                    flash_attention(x, y, z, interpret=True)
                )
            )(a, b, c)

        g = step(q, k, v)
        assert jnp.all(jnp.isfinite(g))


class TestAttentionSpeedupBench:
    def test_speedup_probe_runs_on_cpu_interpret(self):
        """The bench's flash-vs-dense probe (collectives.attention_speedup)
        must execute and return well-formed numbers; speed itself is only
        meaningful on the real chip."""
        from k8s_dra_driver_tpu.ops.collectives import attention_speedup

        out = attention_speedup(
            batch=1, heads=1, seq=128, d=64, chain=2,
            block_q=64, block_k=64, interpret=True,
        )
        assert out["flash_ms"] > 0 and out["dense_ms"] > 0
        assert out["speedup"] == round(out["dense_ms"] / out["flash_ms"], 2)

    def test_block_sweep_reports_best(self):
        from k8s_dra_driver_tpu.ops.collectives import attention_speedup

        out = attention_speedup(
            batch=1, heads=1, seq=128, d=64, chain=2, interpret=True,
            block_candidates=[(32, 32), (64, 64)],
        )
        assert set(out["block_sweep_ms"]) == {"32x32", "64x64"}
        assert out["blocks"] in out["block_sweep_ms"]
        assert out["flash_ms"] == min(out["block_sweep_ms"].values())


class TestAutoBlock:
    def test_picks_swept_optimum_and_divisors(self):
        from k8s_dra_driver_tpu.ops.flash_attention import auto_block

        assert auto_block(2048) == 512
        assert auto_block(384) == 128
        assert auto_block(96) == 96  # short: one block
        assert auto_block(512) == 512

    def test_long_indivisible_sequence_fails_loudly(self):
        from k8s_dra_driver_tpu.ops.flash_attention import auto_block

        with pytest.raises(ValueError, match="pad S upstream"):
            auto_block(4160)
