"""REAL multi-host data plane: two OS processes over jax.distributed.

The strongest multi-host proof this environment can produce: the fake
cluster prepares a 2-host membership claim (slice controller seats +
subslice chips), each prepared pod's CDI env is handed to a SEPARATE
python process, and each process runs the real consumer bootstrap —
``consumer.attach()`` → ``jax.distributed.initialize`` over an actual TCP
coordinator — then performs a cross-process collective.  Nothing is
mocked below the k8s layer: the rendezvous, the global device view, and
the collective all run the same code a v5e-32 pod fleet runs (CPU
backend standing in for the chips).

Reference parity: imex-test1 is only ever verified by pod logs on a real
cluster (demo/specs/quickstart/README.md); this test closes that loop
hermetically.
"""

import json
import socket
import subprocess
import sys
from pathlib import Path


from k8s_dra_driver_tpu.controller.slice_manager import SliceManager
from k8s_dra_driver_tpu.e2e.dryrun import force_cpu_env
from k8s_dra_driver_tpu.e2e.harness import make_cluster
from k8s_dra_driver_tpu.e2e.spec_runner import apply_spec

REPO_ROOT = Path(__file__).resolve().parent.parent
SPECS = REPO_ROOT / "demo" / "specs" / "quickstart"

# What each worker process runs: the slice-test1 container command's core
# (consumer bootstrap) + a cross-process collective the pod-log check
# can't do.  Prints ONE json line for the parent to assert on.
WORKER = r"""
import json, sys
from k8s_dra_driver_tpu import consumer

ctx = consumer.attach()  # real jax.distributed.initialize over TCP
import jax
import jax.numpy as jnp
from jax.experimental import multihost_utils

gathered = multihost_utils.process_allgather(jnp.float32(ctx.worker_id + 1))
print(json.dumps({
    "worker": ctx.worker_id,
    "host_count": ctx.host_count,
    "process_count": jax.process_count(),
    "global_devices": len(jax.devices()),
    "local_devices": len(jax.local_devices()),
    "gathered": sorted(float(x) for x in gathered),
}))
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_membership_claim_runs_cross_process_collective(tmp_path):
    cluster = make_cluster(
        hosts=2, topology="v5e-16", work_dir=str(tmp_path), slice_domain="mp-demo"
    )
    manager = SliceManager(cluster.server)
    manager.start()
    try:
        # slice-test1 scaled to this 2-host cluster
        spec = (SPECS / "slice-test1.yaml").read_text().replace(
            "replicas: 4", "replicas: 2"
        )
        spec_path = tmp_path / "slice-test1-2host.yaml"
        spec_path.write_text(spec)
        pods = apply_spec(cluster, spec_path)
        assert len(pods) == 2

        port = _free_port()
        children = []
        for pod in pods:
            env = dict(pod.env)
            # the seat wired tpu-host-0:8476; re-point at this test's real
            # TCP port on localhost (the cluster DNS name cannot resolve here)
            env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
            force_cpu_env(env, n_devices=2)  # 2 virtual chips per "host"
            env["PYTHONPATH"] = str(REPO_ROOT)
            children.append(
                subprocess.Popen(
                    [sys.executable, "-c", WORKER],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE,
                    text=True,
                )
            )
        outs = []
        try:
            for child in children:
                out, err = child.communicate(timeout=180)
                assert child.returncode == 0, f"worker failed:\n{err[-2000:]}"
                outs.append(json.loads(out.strip().splitlines()[-1]))
        finally:
            # one worker failing must not orphan its sibling: the survivor
            # would block in jax.distributed.initialize for its full init
            # timeout waiting on a coordinator that will never answer
            for c in children:
                if c.poll() is None:
                    c.kill()
                    c.wait()

        workers = sorted(o["worker"] for o in outs)
        assert workers == [0, 1]  # distinct driver-assigned identities
        for o in outs:
            assert o["host_count"] == 2
            assert o["process_count"] == 2      # real distributed runtime
            assert o["global_devices"] == 4     # 2 hosts x 2 local devices
            assert o["local_devices"] == 2
            # the collective really crossed the process boundary: each
            # process contributed worker_id+1 and both see [1.0, 2.0]
            assert o["gathered"] == [1.0, 2.0]
    finally:
        manager.stop()
