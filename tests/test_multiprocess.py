"""REAL multi-host data plane: two OS processes over jax.distributed.

The strongest multi-host proof this environment can produce: the fake
cluster prepares a 2-host membership claim (slice controller seats +
subslice chips), each prepared pod's CDI env is handed to a SEPARATE
python process, and each process runs the real consumer bootstrap —
``consumer.attach()`` → ``jax.distributed.initialize`` over an actual TCP
coordinator — then performs a cross-process collective.  Nothing is
mocked below the k8s layer: the rendezvous, the global device view, and
the collective all run the same code a v5e-32 pod fleet runs (CPU
backend standing in for the chips).

Reference parity: imex-test1 is only ever verified by pod logs on a real
cluster (demo/specs/quickstart/README.md); this test closes that loop
hermetically.
"""

from k8s_dra_driver_tpu.controller.slice_manager import SliceManager
from k8s_dra_driver_tpu.e2e.harness import make_cluster
from tests.mp_harness import run_two_process_workers

# What each worker process runs: the slice-test1 container command's core
# (consumer bootstrap) + a cross-process collective the pod-log check
# can't do.  Prints ONE json line for the parent to assert on.
WORKER = r"""
import json, sys
from k8s_dra_driver_tpu import consumer

ctx = consumer.attach()  # real jax.distributed.initialize over TCP
import jax
import jax.numpy as jnp
from jax.experimental import multihost_utils

gathered = multihost_utils.process_allgather(jnp.float32(ctx.worker_id + 1))
print(json.dumps({
    "worker": ctx.worker_id,
    "host_count": ctx.host_count,
    "process_count": jax.process_count(),
    "global_devices": len(jax.devices()),
    "local_devices": len(jax.local_devices()),
    "gathered": sorted(float(x) for x in gathered),
}))
"""


def test_two_process_membership_claim_runs_cross_process_collective(tmp_path):
    cluster = make_cluster(
        hosts=2, topology="v5e-16", work_dir=str(tmp_path), slice_domain="mp-demo"
    )
    manager = SliceManager(cluster.server)
    manager.start()
    try:
        outs = run_two_process_workers(cluster, tmp_path, WORKER, timeout=180)
        workers = sorted(o["worker"] for o in outs)
        assert workers == [0, 1]  # distinct driver-assigned identities
        for o in outs:
            assert o["host_count"] == 2
            assert o["process_count"] == 2      # real distributed runtime
            assert o["global_devices"] == 4     # 2 hosts x 2 local devices
            assert o["local_devices"] == 2
            # the collective really crossed the process boundary: each
            # process contributed worker_id+1 and both see [1.0, 2.0]
            assert o["gathered"] == [1.0, 2.0]
    finally:
        manager.stop()
