"""Disaggregation chaos suite: the KV-handoff channel under injected
transfer faults (`make chaos-disagg`, <15s, CPU, seeded).

The channel twin of tests/test_fleet_chaos.py — utils/faults.py's
CHANNEL-scoped kinds (handoff_drop, handoff_latency_ms, handoff_corrupt)
break transfers mid-flight between a prefill pool and a decode pool, and
these tests pin the PR's acceptance property:

    a transfer dropped / corrupted / past-deadline mid-flight -> the
    stream still completes BIT-EQUAL via re-prefill fallback on the
    decode pool, zero lost or duplicated completions, per-pool block
    accounting balanced, and corrupted or stale KV bytes NEVER injected
    into a decode replica.

Latency faults are ACCOUNTED into deadline arithmetic, never slept — a
60-simulated-second transfer storm finishes in wall-milliseconds.  Every
fault draws from a seeded injector: a failure replays from its seed, and
the whole suite is armable from the environment via DRA_FAULTS.
"""

import time

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, paged
from k8s_dra_driver_tpu.models.disagg import DisaggRouter
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.utils.faults import ENV_VAR, FaultInjector
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 41)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


def _inj(spec: str) -> FaultInjector:
    return FaultInjector.from_env(spec)


# Explicit per-request seeds: router-minted ids differ from the unified
# reference, so sampling keys must come from the request, never the id.
REQS = [
    {"prompt": [7, 8, 9], "max_tokens": 6, "seed": 5},
    {"prompt": [3, 4], "max_tokens": 6, "temperature": 0.7, "seed": 9},
    {"prompt": [11, 12, 13, 14], "max_tokens": 6, "seed": 21},
    {"prompt": [1, 2], "max_tokens": 6, "seed": 33},
    {"prompt": [21, 22, 23], "max_tokens": 6, "seed": 44},
]


def _by_prompt(completions):
    out = {}
    for c in completions:
        out[tuple(c.tokens[: len(c.tokens) - len(c.generated)])] = tuple(
            c.generated
        )
    return out


@pytest.fixture(scope="module")
def reference(params):
    """Fault-free streams for REQS — the bit-equality baseline every
    fallback re-prefill must reproduce on the decode pool."""
    return _by_prompt(_dense(params).pump([dict(r) for r in REQS]))


def _storm(params, spec_or_injector, *, channel=None, kind=_paged):
    inj = (
        spec_or_injector
        if isinstance(spec_or_injector, FaultInjector)
        else _inj(spec_or_injector)
    )
    pre, dec = kind(params), kind(params)
    free0 = tuple(
        e.free_blocks for e in (pre, dec) if hasattr(e, "free_blocks")
    )
    router = DisaggRouter(
        prefill=[pre], decode=[dec], channel=channel, fault_injector=inj
    )
    done = router.pump([dict(r) for r in REQS])
    free1 = tuple(
        e.free_blocks for e in (pre, dec) if hasattr(e, "free_blocks")
    )
    return router, done, free0, free1


def _assert_no_lost_or_dup(done, reference):
    assert len(done) == len(REQS)
    assert [c.status for c in done].count("ok") == len(REQS)
    rids = [c.request_id for c in done]
    assert len(rids) == len(set(rids)), "duplicated completion ids"
    assert _by_prompt(done) == reference


class TestChannelFaultHooks:
    def test_from_env_parses_channel_kinds(self):
        inj = _inj(
            "handoff_drop=1.0,handoff_latency_ms=250,handoff_corrupt=0.5,"
            "limit=2,seed=7"
        )
        (p,) = inj._profiles
        assert p.handoff_drop_rate == 1.0
        assert p.handoff_latency_s == pytest.approx(0.25)
        assert p.handoff_corrupt_rate == 0.5
        assert p.limit == 2

    def test_injection_budget_caps_channel_kinds(self):
        inj = _inj("handoff_drop=1.0,limit=1")
        assert inj.take_handoff_drop(0)
        assert not inj.take_handoff_drop(1)  # budget spent

    def test_latency_hook_accounts_without_sleeping(self):
        inj = _inj("handoff_latency_ms=60000")
        t0 = time.perf_counter()
        assert inj.take_handoff_latency() == pytest.approx(60.0)
        assert time.perf_counter() - t0 < 0.05


class TestDropStorm:
    """The acceptance run: transfers dropped mid-flight between the
    pools."""

    def test_zero_lost_streams_bit_equal_fallback(self, params, reference):
        JOURNAL.clear()
        router, done, free0, free1 = _storm(
            params, "handoff_drop=1.0,limit=2,seed=3"
        )
        _assert_no_lost_or_dup(done, reference)
        assert router.handoffs == len(REQS)
        assert router.fallbacks == 2
        assert router.channel.counts["dropped"] == 2
        assert router.channel.counts["ok"] == len(REQS) - 2
        assert free1 == free0, "block accounting unbalanced after drops"
        # dropped payload bytes never count as moved
        events = JOURNAL.tail(limit=400, component="disagg")
        kinds = [e["event"] for e in events]
        assert kinds.count("transfer.dropped") == 2
        assert kinds.count("handoff.fallback") == 2
        assert REGISTRY.counter("tpu_disagg_fallback_total").value(
            reason="dropped"
        ) == 2

    def test_total_drop_storm_every_stream_survives(self, params, reference):
        # 100% drop, no budget: the channel NEVER delivers a payload and
        # the whole workload still completes via re-prefill.
        router, done, free0, free1 = _storm(params, "handoff_drop=1.0,seed=3")
        _assert_no_lost_or_dup(done, reference)
        assert router.fallbacks == len(REQS)
        assert router.channel.counts == {"dropped": len(REQS)}
        assert router.channel.bytes_moved == 0
        assert free1 == free0

    def test_storm_replays_from_seed(self, params):
        # Determinism of the chaos itself: same spec, same outcomes.
        spec = "handoff_drop=0.5,seed=11"
        a = _storm(params, spec, kind=_dense)[0].channel.counts
        b = _storm(params, spec, kind=_dense)[0].channel.counts
        assert a == b
        assert a.get("dropped", 0) >= 1


class TestCorruptStorm:
    def test_corrupt_payload_never_injected(self, params, reference):
        router, done, free0, free1 = _storm(
            params, "handoff_corrupt=1.0,limit=2,seed=5"
        )
        # bit-equality IS the proof: had corrupted KV reached a decode
        # slot, the streams would diverge from the reference
        _assert_no_lost_or_dup(done, reference)
        assert router.channel.counts["corrupt"] == 2
        assert router.fallbacks == 2
        assert free1 == free0


class TestLatencyStorm:
    def test_past_deadline_transfers_fall_back_fast(self, params, reference):
        # 60 SIMULATED seconds per transfer vs a 250ms deadline: every
        # transfer is stale.  Wall time stays in milliseconds because
        # channel latency is accounted, never slept.
        t0 = time.perf_counter()
        router, done, free0, free1 = _storm(
            params, "handoff_latency_ms=60000,seed=5", kind=_dense
        )
        wall = time.perf_counter() - t0
        _assert_no_lost_or_dup(done, reference)
        assert router.channel.counts == {"deadline": len(REQS)}
        assert router.fallbacks == len(REQS)
        assert wall < 60.0, "simulated latency leaked into wall clock"


class TestMixedStormFromEnv:
    def test_env_armed_mixed_storm(self, params, reference, monkeypatch):
        # The DRA_FAULTS path end to end: the router arms itself from the
        # environment (no injector plumbed) and shares ONE budget across
        # drop + corrupt + latency kinds.
        monkeypatch.setenv(
            ENV_VAR,
            "handoff_drop=0.4,handoff_corrupt=0.4,handoff_latency_ms=500,"
            "seed=17",
        )
        pre, dec = _paged(params), _paged(params)
        free0 = (pre.free_blocks, dec.free_blocks)
        router = DisaggRouter(prefill=[pre], decode=[dec])
        assert router.fault_injector is not None
        done = router.pump([dict(r) for r in REQS])
        _assert_no_lost_or_dup(done, reference)
        assert (pre.free_blocks, dec.free_blocks) == free0
        # with a 500ms injected latency vs the 250ms default deadline,
        # any transfer that dodges drop/corrupt still goes stale: the
        # channel delivers NOTHING and every stream re-prefills
        assert router.fallbacks == len(REQS)
        assert router.channel.counts.get("ok", 0) == 0
        outcomes = set(router.channel.counts)
        assert outcomes <= {"dropped", "corrupt", "deadline"}
        assert router.fault_injector.stats(), "no faults recorded"
