"""SliceManager (multi-host controller) tests — IMEX-manager behaviors
mapped to TPU slice domains."""

import itertools

from k8s_dra_driver_tpu.controller.slice_manager import (
    MEMBERSHIP_PER_SLICE_LIMIT,
    SLICE_DOMAIN_LABEL,
    SLICE_HOST_ID_LABEL,
    SliceManager,
)
from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import Node, ObjectMeta, ResourceSlice


def add_node(server, name, domain=None, host_id=0):
    labels = {"kubernetes.io/hostname": name}
    if domain:
        labels[SLICE_DOMAIN_LABEL] = domain
        labels[SLICE_HOST_ID_LABEL] = str(host_id)
    return server.create(Node(metadata=ObjectMeta(name=name, labels=labels)))


def membership_slices(server):
    return [
        s
        for s in server.list(ResourceSlice.KIND)
        if s.spec.pool.name.startswith("slice-")
    ]


class TestSliceManager:
    def test_domain_appears_with_first_node(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        assert membership_slices(server) == []
        add_node(server, "h0", domain="v5e-32-a", host_id=0)
        slices = membership_slices(server)
        assert len(slices) == 1
        devices = slices[0].spec.devices
        assert len(devices) == 1
        assert devices[0].basic.attributes["workerId"].value == 0
        assert devices[0].basic.attributes["coordinatorAddress"].value == "h0:8476"
        # gated on the domain label
        sel = slices[0].spec.node_selector
        assert sel.matches({SLICE_DOMAIN_LABEL: "v5e-32-a"})
        assert not sel.matches({SLICE_DOMAIN_LABEL: "other"})
        mgr.stop()

    def test_all_hosts_get_seats_and_coordinator_is_worker0(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        for hid in (2, 0, 1, 3):
            add_node(server, f"h{hid}", domain="d", host_id=hid)
        slices = membership_slices(server)
        devices = slices[0].spec.devices
        assert [d.basic.attributes["workerId"].value for d in devices] == [0, 1, 2, 3]
        assert all(
            d.basic.attributes["coordinatorAddress"].value == "h0:8476" for d in devices
        )
        assert all(d.basic.attributes["hostCount"].value == 4 for d in devices)
        mgr.stop()

    def test_large_domain_chunks_into_128_device_slices(self):
        """>128 hosts in a domain must split across several ResourceSlices:
        the upstream API server rejects slices over 128 devices, which
        would park the whole pool (advisor, round 1; reference splits the
        same way, imex.go:43)."""
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        n = MEMBERSHIP_PER_SLICE_LIMIT + 7  # 135 hosts
        for hid in range(n):
            add_node(server, f"h{hid}", domain="big", host_id=hid)
        slices = membership_slices(server)
        assert len(slices) == 2
        sizes = sorted(len(s.spec.devices) for s in slices)
        assert sizes == [7, MEMBERSHIP_PER_SLICE_LIMIT]
        assert all(len(s.spec.devices) <= MEMBERSHIP_PER_SLICE_LIMIT for s in slices)
        # every worker id is published exactly once across the chunks
        ids = sorted(
            d.basic.attributes["workerId"].value
            for s in slices
            for d in s.spec.devices
        )
        assert ids == list(range(n))
        assert all(
            s.spec.pool.resource_slice_count == 2 for s in slices
        )
        # Scale-down returns budget: dropping below one window's worth of
        # seats must release the extra window, not strand it.
        for hid in range(8, n):
            server.delete("Node", f"h{hid}")
        assert len(mgr._offsets["big"]) == 1
        mgr.stop()

    def test_large_domain_reserves_windows_proportional_to_seats(self):
        """A 135-seat domain must charge ceil(135/128)=2 windows against the
        2048-seat global budget — chunked publication must not let big
        domains bust the cap the window accounting enforces."""
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        for hid in range(MEMBERSHIP_PER_SLICE_LIMIT + 7):
            add_node(server, f"big{hid}", domain="big", host_id=hid)
        assert len(mgr._offsets["big"]) == 2
        # 14 windows remain: 14 singleton domains are admitted, the 15th parks
        for i in range(14):
            add_node(server, f"s{i}", domain=f"small{i}", host_id=0)
        add_node(server, "sx", domain="overflow", host_id=0)
        names = {s.spec.pool.name for s in membership_slices(server)}
        assert "slice-small13" in names
        assert "slice-overflow" not in names
        mgr.stop()

    def test_domain_disappears_with_last_node(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        add_node(server, "h0", domain="d", host_id=0)
        add_node(server, "h1", domain="d", host_id=1)
        server.delete("Node", "h0")
        assert len(membership_slices(server)[0].spec.devices) == 1
        server.delete("Node", "h1")
        assert membership_slices(server) == []
        mgr.stop()

    def test_informer_replay_on_late_start(self):
        server = InMemoryAPIServer()
        add_node(server, "h0", domain="d", host_id=0)  # exists before start
        mgr = SliceManager(server)
        mgr.start()
        assert len(membership_slices(server)) == 1
        mgr.stop()

    def test_stop_cleans_owned_slices(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        add_node(server, "h0", domain="d", host_id=0)
        mgr.stop()
        assert membership_slices(server) == []

    def test_node_relabel_moves_domain(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        node = add_node(server, "h0", domain="d1", host_id=0)
        node.metadata.labels[SLICE_DOMAIN_LABEL] = "d2"
        server.update(node)
        slices = membership_slices(server)
        assert len(slices) == 1
        assert slices[0].spec.devices[0].basic.attributes["sliceDomain"].value == "d2"
        mgr.stop()

    def test_malformed_host_id_label_is_ignored_not_fatal(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        add_node(server, "h0", domain="d", host_id=0)
        # Node with garbage host-id: must not crash the watch, must not mint
        # a duplicate worker-0 seat.
        bad = Node(
            metadata=ObjectMeta(
                name="hbad",
                labels={SLICE_DOMAIN_LABEL: "d", SLICE_HOST_ID_LABEL: "host-1"},
            )
        )
        server.create(bad)
        devices = membership_slices(server)[0].spec.devices
        assert [d.basic.attributes["workerId"].value for d in devices] == [0]
        mgr.stop()

    def test_duplicate_host_ids_deduped(self):
        server = InMemoryAPIServer()
        mgr = SliceManager(server)
        mgr.start()
        add_node(server, "h0", domain="d", host_id=0)
        add_node(server, "h0b", domain="d", host_id=0)  # mislabel: same seat
        devices = membership_slices(server)[0].spec.devices
        assert [d.name for d in devices] == ["membership-0"]  # no dup names
        mgr.stop()

    def test_window_exhaustion_is_transient_and_retries(self):
        server = InMemoryAPIServer()
        fake_time = itertools.count(0, 120.0)  # 120s per clock() call
        clock = lambda: next(fake_time)  # noqa: E731
        mgr = SliceManager(server, retry_timeout_s=60.0, clock=clock)
        mgr.start()
        limit = 2048 // MEMBERSHIP_PER_SLICE_LIMIT  # 16 windows
        for i in range(limit):
            add_node(server, f"h{i}", domain=f"d{i}", host_id=0)
        assert len(membership_slices(server)) == limit
        # 17th domain: parked on transient error
        add_node(server, "hx", domain="overflow", host_id=0)
        assert len(membership_slices(server)) == limit
        # free a window, then retry after the timeout elapses
        server.delete("Node", "h3")
        mgr.retry_pending()
        names = {s.spec.pool.name for s in membership_slices(server)}
        assert "slice-overflow" in names
        mgr.stop()
