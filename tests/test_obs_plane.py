"""Fleet observability plane (PR 16): TELEM codec and budget, shipper
cursors, skew-normalized span-tree merge, dead-hop attribution, SLO
burn-rate windows, federated /metrics rendering, the /debug/fleet-*
endpoints, diag-bundle fleet mode, autoscaler/rebalancer burn coupling,
and an in-process PoolWorker federation rig (LoopbackConn standing in
for the real socket; tests/test_transport_chaos.py covers the real
two-process wire)."""

import json
import sys
import urllib.request
from pathlib import Path

import pytest

from k8s_dra_driver_tpu.models import obs_plane as O
from k8s_dra_driver_tpu.models.obs_plane import (
    FLEET,
    FleetObservability,
    SloBurnRateMonitor,
    TelemetryShipper,
    decode_telem,
    encode_telem,
)
from k8s_dra_driver_tpu.utils.journal import Journal
from k8s_dra_driver_tpu.utils.metrics import (
    REGISTRY,
    Registry,
    parse_prom_text,
)
from k8s_dra_driver_tpu.utils.tracing import TraceBuffer

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))


def _metric(name):
    return parse_prom_text(REGISTRY.render()).get(name, {})


class TestTelemCodec:
    def test_roundtrip(self):
        doc = {"instance": "w1", "journal": [{"event": "x"}], "mono": 1.5}
        assert decode_telem(encode_telem(doc)) == doc

    def test_crc_flip_is_counted_drop_never_fatal(self):
        body = bytearray(encode_telem({"instance": "w1", "metrics": "a 1"}))
        body[-1] ^= 0x40  # flip a payload byte; CRC rides up front
        assert decode_telem(bytes(body)) is None
        drops = _metric("tpu_obs_telem_frames_total")
        assert drops[(("outcome", "crc_drop"),)] == 1.0

    def test_short_and_malformed_frames_drop(self):
        assert decode_telem(b"\x01") is None
        import zlib as _z
        bad = b"not json"
        framed = O._CRC.pack(_z.crc32(bad)) + bad
        assert decode_telem(framed) is None
        drops = _metric("tpu_obs_telem_frames_total")
        assert drops[(("outcome", "decode_drop"),)] == 2.0


class TestShipper:
    def _shipper(self, sent, **kw):
        jr, tb, reg = Journal(), TraceBuffer(), Registry()
        reg.counter("tpu_serve_test_total", "test").inc()
        kw.setdefault("interval_s", 0.0)
        return (
            TelemetryShipper(
                sent.append, "w1", journal=jr, traces=tb, registry=reg, **kw
            ),
            jr, tb, reg,
        )

    def test_cursor_exports_are_exactly_once(self):
        sent = []
        shipper, jr, tb, _ = self._shipper(sent)
        jr.record("serve", "admit", correlation="req-1")
        tb.record("req-1", "serve.request", 0.0, 1.0)
        assert shipper.maybe_ship(force=True) > 0
        first = decode_telem(sent[-1])
        assert [e["event"] for e in first["journal"]] == ["admit"]
        assert [s["name"] for s in first["spans"]] == ["serve.request"]
        # Nothing new: the next ship carries empty deltas, but the
        # registry re-renders every time (idempotent full snapshot).
        shipper.maybe_ship(force=True)
        second = decode_telem(sent[-1])
        assert second["journal"] == [] and second["spans"] == []
        assert "tpu_serve_test_total" in second["metrics"]
        # New events after the cursor ship exactly once.
        jr.record("serve", "retire", correlation="req-1")
        shipper.maybe_ship(force=True)
        assert [e["event"] for e in decode_telem(sent[-1])["journal"]] == [
            "retire"
        ]

    def test_budget_truncation_sheds_and_marks(self):
        sent = []
        shipper, jr, _, _ = self._shipper(sent, budget_bytes=2048)
        for i in range(400):
            jr.record("serve", "admit", correlation=f"req-{i}", pad="y" * 64)
        shipper.maybe_ship(force=True, include_stacks=True)
        assert len(sent[-1]) <= 2048
        doc = decode_telem(sent[-1])
        assert doc["truncated"] is True
        assert "stacks" not in doc  # shed first
        # Oldest-first shed: whatever journal survived is the newest tail.
        if doc["journal"]:
            assert doc["journal"][-1]["correlation"] == "req-399"

    def test_cadence_holds_fire_between_intervals(self):
        sent = []
        t = [0.0]
        jr, tb, reg = Journal(), TraceBuffer(), Registry()
        shipper = TelemetryShipper(
            sent.append, "w1", clock=lambda: t[0], interval_s=1.0,
            journal=jr, traces=tb, registry=reg,
        )
        assert shipper.maybe_ship() > 0
        assert shipper.maybe_ship() == 0  # same instant: cadence holds
        t[0] = 0.5
        assert shipper.maybe_ship() == 0
        t[0] = 1.1
        assert shipper.maybe_ship() > 0
        assert shipper.shipped_frames == 2


class TestFleetMerge:
    def _worker_doc(self, instance, spans=(), journal=(), metrics="",
                    mono=0.0):
        return {
            "instance": instance, "mono": mono, "spans": list(spans),
            "journal": list(journal), "metrics": metrics,
        }

    def test_federated_render_has_distinct_instance_labels(self):
        plane = FleetObservability()
        plane.ingest("w1", self._worker_doc(
            "w1", metrics='tpu_serve_x_total{status="ok"} 3\nbare_metric 1'))
        plane.ingest("w2", self._worker_doc("w2", metrics="tpu_serve_x_total 7"))
        text = plane.render_federated()
        parsed = parse_prom_text(text)
        series = parsed["tpu_serve_x_total"]
        assert (("instance", "w1"), ("status", "ok")) in series
        assert (("instance", "w2"),) in series
        assert parsed["bare_metric"][(("instance", "w1"),)] == 1.0

    def test_skew_normalized_merge_is_one_ordered_tree(self):
        plane = FleetObservability()
        sup = TraceBuffer()
        # Control plane records the prefill hop and the wire hop at its
        # own clock; the worker's decode hop arrives with a +100s skew
        # and an estimated offset of exactly +100.
        pre = sup.record("req-1", "hop.prefill", 10.0, 10.4)
        wire = sup.record("req-1", "hop.wire", 10.4, 10.6,
                          parent_id=pre.span_id)
        plane.ingest("w1", self._worker_doc("w1", spans=[{
            "trace_id": "req-1", "span_id": "w1.decode.1",
            "parent_id": wire.span_id, "name": "hop.decode",
            "t0": 110.6, "t1": 111.0,
        }]), clock_offset_s=100.0)
        doc = plane.fleet_traces_doc(trace_id="req-1", traces=sup)
        (tree,) = doc["traces"]
        assert tree["instances"] == [O.SUPERVISOR, "w1"]
        (root,) = tree["roots"]
        assert root["name"] == "hop.prefill"
        (wire_node,) = root["children"]
        (decode_node,) = wire_node["children"]
        assert decode_node["instance"] == "w1"
        # Skew-normalized into the supervisor's domain: 110.6 - 100.
        assert decode_node["t0"] == pytest.approx(10.6)
        assert root["t0"] <= wire_node["t0"] <= decode_node["t0"]

    def test_orphan_spans_become_extra_roots_not_losses(self):
        plane = FleetObservability()
        plane.ingest("w1", self._worker_doc("w1", spans=[{
            "trace_id": "req-2", "span_id": "w1.s1",
            "parent_id": "never-federated", "name": "hop.decode",
            "t0": 1.0, "t1": 2.0,
        }]))
        doc = plane.fleet_traces_doc(trace_id="req-2", traces=TraceBuffer())
        (tree,) = doc["traces"]
        assert [r["name"] for r in tree["roots"]] == ["hop.decode"]

    def test_dead_hop_attribution_lands_in_tree(self):
        plane = FleetObservability()
        buf = TraceBuffer()
        span = buf.record("req-3", "hop.wire", 0.0, 0.5)
        plane.note_hop(3, "req-3", span.span_id, instance="w1")
        plane.attribute_dead_hop(3, "w1", reason="peer_reset", traces=buf)
        assert plane.hop_ctx(3) is None  # consumed
        doc = plane.fleet_traces_doc(trace_id="req-3", traces=buf)
        (tree,) = doc["traces"]
        (root,) = tree["roots"]
        (dead,) = root["children"]
        assert dead["name"] == "hop.dead"
        assert dead["attrs"]["instance"] == "w1"
        assert dead["attrs"]["reason"] == "peer_reset"

    def test_fleet_journal_merges_instance_tagged_and_filters(self):
        plane = FleetObservability()
        plane.ingest("w1", self._worker_doc("w1", journal=[
            {"component": "serve", "event": "admit", "ts_s": 2.0,
             "correlation": "req-9"},
        ]))
        plane.ingest("w2", self._worker_doc("w2", journal=[
            {"component": "transport", "event": "kv.installed", "ts_s": 1.0},
        ]))
        doc = plane.fleet_journal_doc()
        assert doc["instances"] == ["w1", "w2"]
        assert [e["instance"] for e in doc["events"]] == ["w2", "w1"]  # ts order
        only = plane.fleet_journal_doc(instance="w1")
        assert [e["event"] for e in only["events"]] == ["admit"]
        corr = plane.fleet_journal_doc(correlation="req-9")
        assert len(corr["events"]) == 1

    def test_bundle_doc_keeps_dead_instances(self):
        plane = FleetObservability()
        plane.ingest("corpse", self._worker_doc(
            "corpse", metrics="x 1",
            journal=[{"component": "serve", "event": "admit", "ts_s": 1.0}]))
        doc = plane.bundle_doc()
        assert doc["instances"]["corpse"]["metrics"] == "x 1"
        assert doc["instances"]["corpse"]["journal_tail"][0]["event"] == "admit"


class TestBurnMonitor:
    def test_classify_tier_matches_workload_defaults(self):
        m = SloBurnRateMonitor
        assert m.classify_tier(1.0) == O.INTERACTIVE
        assert m.classify_tier(3.0) == O.STANDARD
        assert m.classify_tier(10.0) == O.BATCH

    def test_multi_window_guard_and_journaled_transitions(self):
        jr = Journal()
        m = SloBurnRateMonitor(journal=jr, timeline_every_s=10.0)
        # An hour of clean traffic: no burn anywhere.
        for t in range(0, 3600, 5):
            m.observe(float(t), O.INTERACTIVE, True, count=4)
        burn = m.tick(3600.0)
        assert not m.alerting
        assert burn[O.INTERACTIVE]["5m"] == 0.0
        # A hot five minutes: the 5m window burns far past threshold but
        # the 1h window still holds the alert back (multi-window guard).
        for t in range(3600, 3900, 5):
            m.observe(float(t), O.INTERACTIVE, False, count=4)
        burn = m.tick(3900.0)
        assert burn[O.INTERACTIVE]["5m"] > m.alert_threshold
        if burn[O.INTERACTIVE]["1h"] <= m.alert_threshold:
            assert not m.alerting
        # Keep burning until BOTH windows agree.
        t = 3900
        while not m.alerting and t < 3600 * 3:
            m.observe(float(t), O.INTERACTIVE, False, count=4)
            m.tick(float(t))
            t += 5
        assert m.alerting and m.alerting_tiers == [O.INTERACTIVE]
        fired = [e for e in jr.tail() if e["event"] == "slo.burn.fired"]
        assert fired and fired[0]["correlation"] == "slo-interactive"
        # Recovery: clean traffic long enough clears every window.
        while m.alerting and t < 3600 * 6:
            m.observe(float(t), O.INTERACTIVE, True, count=40)
            m.tick(float(t))
            t += 5
        assert not m.alerting
        assert any(e["event"] == "slo.burn.cleared" for e in jr.tail())
        assert m.stats()["transitions"] == 2
        assert m.timeline()  # sampled along the way
        gauges = _metric("tpu_slo_burn_rate")
        assert any(("window", "5m") in labels and ("tier", "interactive")
                   in labels for labels in gauges)

    def test_ingest_federated_bucket_diff_is_idempotent(self):
        plane = FleetObservability()
        reg = Registry()
        h = reg.histogram("tpu_serve_ttft_seconds", "ttft",
                          buckets=(0.5, 1.0, 2.0))
        for v in (0.2, 0.3, 1.7, 1.9):  # 2 ok (<=1.0 SLO), 2 miss
            h.observe(v)
        m = SloBurnRateMonitor()
        plane.ingest("w1", {"instance": "w1", "metrics": reg.render()})
        assert m.ingest_federated(10.0, fleet=plane, slo_s=1.0) == 4
        # Same cumulative snapshot again: the diff is zero, not double.
        plane.ingest("w1", {"instance": "w1", "metrics": reg.render()})
        assert m.ingest_federated(11.0, fleet=plane, slo_s=1.0) == 0
        h.observe(0.1)  # one more ok
        plane.ingest("w1", {"instance": "w1", "metrics": reg.render()})
        assert m.ingest_federated(12.0, fleet=plane, slo_s=1.0) == 1
        burn = m.tick(12.0)
        # 2 misses out of 5 → miss fraction .4 / budget .05 = burn 8.
        assert burn[O.FLEET_TIER]["5m"] == pytest.approx(8.0)


class _StubAlert:
    def __init__(self, alerting):
        self.alerting = alerting
        self.alerting_tiers = ["interactive"] if alerting else []


class TestControlLoopCoupling:
    def _fleet(self):
        from k8s_dra_driver_tpu.models import workload
        from k8s_dra_driver_tpu.models.fleet import FleetRouter

        clock = workload.SimClock()
        sink = workload.SimSink()

        def factory():
            return workload.SimEngine(clock=clock, sink=sink, n_slots=4)

        return FleetRouter([factory()], clock=clock), factory, clock

    def test_burn_alert_forces_scale_up(self):
        from k8s_dra_driver_tpu.models.autoscaler import (
            AutoscalerPolicy,
            FleetAutoscaler,
        )

        router, factory, clock = self._fleet()
        asc = FleetAutoscaler(
            router, engine_factory=factory, clock=clock,
            policy=AutoscalerPolicy(
                min_replicas=1, max_replicas=4, up_ticks=1, cooldown_s=0.0,
            ),
            burn_monitor=_StubAlert(True),
        )
        decision = asc.tick()
        assert decision["action"] == "up"
        assert decision["reason"] == "slo_burn"
        assert decision["burn_alert"] is True

    def test_no_alert_leaves_idle_fleet_alone(self):
        from k8s_dra_driver_tpu.models.autoscaler import (
            AutoscalerPolicy,
            FleetAutoscaler,
        )

        router, factory, clock = self._fleet()
        asc = FleetAutoscaler(
            router, engine_factory=factory, clock=clock,
            policy=AutoscalerPolicy(
                min_replicas=1, max_replicas=4, up_ticks=1, cooldown_s=0.0,
            ),
            burn_monitor=_StubAlert(False),
        )
        decision = asc.tick()
        assert decision["action"] != "up"
        assert decision["burn_alert"] is False

    def test_rebalancer_burn_alert_drops_hysteresis(self):
        from k8s_dra_driver_tpu.models.autoscaler import (
            PoolRebalancer,
            RebalancePolicy,
        )

        class _Disagg:
            def take_stage_attribution(self):
                # Decode dominates prefill 10x with plenty of samples.
                return {
                    "prefill": {"n": 20, "mean_s": 0.1},
                    "decode": {"n": 20, "mean_s": 1.0},
                }

        class _Scaler:
            def __init__(self):
                self.router = object()
                self.reasons = []

            def scale_move(self, taker, reason=""):
                self.reasons.append(reason)
                return "corr-1"

        pre, dec = _Scaler(), _Scaler()
        calm = PoolRebalancer(
            _Disagg(), pre, dec, policy=RebalancePolicy(vote_ticks=3),
            clock=lambda: 0.0, burn_monitor=_StubAlert(False),
        )
        calm.tick()
        assert calm.moves == 0  # hysteresis holds at one vote
        hot = PoolRebalancer(
            _Disagg(), pre, dec, policy=RebalancePolicy(vote_ticks=3),
            clock=lambda: 0.0, burn_monitor=_StubAlert(True),
        )
        decision = hot.tick()
        assert hot.moves == 1  # burn alert: act on the first vote
        assert decision["burn_alert"] is True
        assert pre.reasons == ["ttft_to_decode"]


class TestEndpointsAndBundles:
    def _populate_fleet(self):
        FLEET.ingest("w1", {
            "instance": "w1", "mono": 1.0,
            "journal": [{"component": "serve", "event": "admit",
                         "ts_s": 1.0, "correlation": "req-1"}],
            "spans": [{"trace_id": "req-1", "span_id": "w1.s1",
                       "parent_id": "", "name": "hop.decode",
                       "t0": 1.0, "t1": 2.0}],
            "metrics": "tpu_serve_x_total 5",
        })

    def test_fleet_endpoints_and_federated_metrics(self):
        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        self._populate_fleet()
        srv = DiagnosticsServer(port=0)
        srv.start()
        base = f"http://127.0.0.1:{srv.port}"
        try:
            jd = json.loads(urllib.request.urlopen(
                base + "/debug/fleet-journal?instance=w1").read())
            assert jd["instances"] == ["w1"]
            assert jd["events"][-1]["event"] == "admit"
            td = json.loads(urllib.request.urlopen(
                base + "/debug/fleet-traces?trace_id=req-1").read())
            assert td["traces"][0]["instances"] == ["w1"]
            metrics = urllib.request.urlopen(base + "/metrics").read().decode()
        finally:
            srv.stop()
        parsed = parse_prom_text(metrics)
        # Local registry renders label-free; the worker's copy rides the
        # SAME scrape under its instance label.
        assert parsed["tpu_obs_instances"][()] == 1.0
        assert parsed["tpu_serve_x_total"][(("instance", "w1"),)] == 5.0

    def test_plain_metrics_when_fleet_is_empty(self):
        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        srv = DiagnosticsServer(port=0)
        srv.start()
        try:
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics").read().decode()
        finally:
            srv.stop()
        assert text == REGISTRY.render()

    def test_diag_bundle_fleet_mode(self):
        import diag_bundle

        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        self._populate_fleet()
        srv = DiagnosticsServer(port=0)
        srv.start()
        try:
            bundle, answered = diag_bundle.build_bundle(
                f"http://127.0.0.1:{srv.port}", fleet=True)
        finally:
            srv.stop()
        assert answered == len(diag_bundle.ENDPOINTS) + len(
            diag_bundle.FLEET_ENDPOINTS)
        assert bundle["kind"] == "tpu-dra-fleet-diag-bundle"
        assert bundle["fleet_journal"]["instances"] == ["w1"]
        assert bundle["fleet_traces"]["instances"] == ["w1"]
        assert 'instance="w1"' in bundle["metrics"]

    def test_mp_harness_death_report_carries_fleet_telemetry(self, tmp_path):
        import os

        from tests.mp_harness import SupervisedWorker, wait_ready

        self._populate_fleet()
        env = dict(os.environ)
        crasher = SupervisedWorker(
            "crasher",
            [sys.executable, "-c",
             "import sys; sys.stderr.write('pre-ready boom\\n'); sys.exit(7)"],
            env,
        )
        with pytest.raises(AssertionError) as exc:
            wait_ready([crasher], lambda: False, timeout=30,
                       bundle_dir=tmp_path)
        msg = str(exc.value)
        assert "before its ready handshake" in msg
        assert "pre-ready boom" in msg  # stderr tail ALWAYS attached
        bundle_path = msg.split("diag bundle: ")[1].split(" ---")[0].strip()
        bundle = json.loads(open(bundle_path).read())
        assert bundle["workers"]["crasher"]["returncode"] == 7
        assert "pre-ready boom" in bundle["workers"]["crasher"]["stderr_tail"]
        # The surviving fleet's federated snapshots ride the death report.
        assert bundle["fleet_telemetry"]["instances"]["w1"]["metrics"]

    def test_wait_ready_returns_probe_value(self):
        from tests.mp_harness import wait_ready

        assert wait_ready([], lambda: "link", timeout=1) == "link"


class TestInProcessFederation:
    def test_poolworker_ships_and_fleet_ingests_with_skew(self):
        """LoopbackConn federation rig: a PoolWorker with a -5s-skewed
        clock and a private trace ring ships TELEM every pump; the
        supervisor's RemotePool drains it into FLEET with the PING/PONG
        offset estimate, so the federated view lands under the worker's
        instance label with a recovered clock offset."""
        from k8s_dra_driver_tpu.models import transport as T
        from k8s_dra_driver_tpu.models import workload
        from k8s_dra_driver_tpu.models.fleet import FleetRouter

        clock = workload.SimClock()
        sink = workload.SimSink()
        import time as _time

        skew = lambda: _time.monotonic() - 5.0  # noqa: E731
        a, b = T.LoopbackConn.pair()
        worker = T.PoolWorker(
            b, FleetRouter([workload.SimEngine(clock=clock, sink=sink)]),
            role="decode", name="obs-w", clock=skew,
            telem_interval_s=0.0, traces=TraceBuffer(),
        )
        link = T.PeerLink("obs-w", a, heartbeat_interval_s=0.0)
        pool = T.RemotePool(link, peer_pump=worker.pump_once)
        for _ in range(20):
            pool.tick()
        assert "obs-w" in FLEET.stats()["instances"]
        assert worker.shipper.shipped_frames > 0
        assert link.clock_offset_s is not None
        assert link.clock_offset_s == pytest.approx(-5.0, abs=0.5)
        assert 'instance="obs-w"' in FLEET.render_federated()
        # CONTROL telem_flush forces a stack-bearing snapshot through.
        link.send_json(T.CONTROL, {"op": "telem_flush"})
        for _ in range(5):
            pool.tick()
        assert FLEET.bundle_doc()["instances"]["obs-w"]["stacks"]
