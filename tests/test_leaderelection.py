"""Lease-based leader-election tests with a deterministic clock."""

import threading

from k8s_dra_driver_tpu.controller.leaderelection import (
    LeaderElectionConfig,
    LeaderElector,
)
from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer


class FakeClock:
    def __init__(self):
        self.now = 1_000_000.0

    def __call__(self):
        return self.now


def elector(server, identity, clock, duration=15.0):
    return LeaderElector(
        server,
        LeaderElectionConfig(identity=identity, lease_duration_s=duration),
        clock=clock,
    )


class TestLeaderElector:
    def test_first_candidate_acquires(self):
        server = InMemoryAPIServer()
        clock = FakeClock()
        a = elector(server, "a", clock)
        assert a.tick() is True
        lease = server.get("Lease", "tpu-dra-controller", "tpu-dra-driver")
        assert lease.spec.holder_identity == "a"

    def test_standby_blocked_until_expiry(self):
        server = InMemoryAPIServer()
        clock = FakeClock()
        a = elector(server, "a", clock)
        b = elector(server, "b", clock)
        assert a.tick() and not b.tick()
        clock.now += 10  # within lease duration
        assert b.tick() is False
        clock.now += 6  # renew_time + 15 < now: expired (a crashed)
        assert b.tick() is True
        lease = server.get("Lease", "tpu-dra-controller", "tpu-dra-driver")
        assert lease.spec.holder_identity == "b"
        assert lease.spec.lease_transitions == 1

    def test_renewal_keeps_leadership(self):
        server = InMemoryAPIServer()
        clock = FakeClock()
        a = elector(server, "a", clock)
        b = elector(server, "b", clock)
        a.tick()
        for _ in range(5):
            clock.now += 10
            assert a.tick() is True  # renews before expiry
            assert b.tick() is False

    def test_clean_release_hands_over_immediately(self):
        server = InMemoryAPIServer()
        clock = FakeClock()
        a = elector(server, "a", clock)
        b = elector(server, "b", clock)
        a.tick()
        a.release()
        assert b.tick() is True

    def test_handover_keeps_published_slices(self):
        # Leadership moves A -> B; A's step-down must not delete the slices
        # B just published (shared owner label).
        from k8s_dra_driver_tpu.controller.slice_manager import SliceManager
        from tests.test_controller import add_node, membership_slices

        server = InMemoryAPIServer()
        add_node(server, "h0", domain="d", host_id=0)
        mgr_a = SliceManager(server)
        mgr_a.start()
        assert len(membership_slices(server)) == 1
        # B takes over and republishes before A steps down (the racy order)
        mgr_b = SliceManager(server)
        mgr_b.start()
        mgr_a.stop(delete_owned=False)  # leadership loss, not shutdown
        assert len(membership_slices(server)) == 1
        mgr_b.stop()  # process shutdown deletes

    def test_transient_api_error_does_not_kill_run_loop(self):
        server = InMemoryAPIServer()
        clock = FakeClock()
        a = elector(server, "a", clock, duration=5.0)
        calls = {"n": 0}
        real_get = server.get

        def flaky_get(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise RuntimeError("transient apiserver error")
            return real_get(*args, **kwargs)

        server.get = flaky_get
        events = []
        stop = threading.Event()
        ticks = {"n": 0}

        def sleeper(_):
            ticks["n"] += 1
            if ticks["n"] >= 3:
                stop.set()

        a.run(
            on_started_leading=lambda: events.append("start"),
            on_stopped_leading=lambda: events.append("stop"),
            stop=stop,
            sleeper=sleeper,
        )
        # first tick errored (survived), later tick acquired
        assert events == ["start", "stop"]

    def test_run_loop_transitions(self):
        server = InMemoryAPIServer()
        clock = FakeClock()
        a = elector(server, "a", clock, duration=5.0)
        events = []
        stop = threading.Event()
        ticks = {"n": 0}

        def sleeper(_):
            ticks["n"] += 1
            if ticks["n"] >= 3:
                stop.set()

        a.run(
            on_started_leading=lambda: events.append("start"),
            on_stopped_leading=lambda: events.append("stop"),
            stop=stop,
            sleeper=sleeper,
        )
        assert events == ["start", "stop"]  # led, then released on shutdown
        lease = server.get("Lease", "tpu-dra-controller", "tpu-dra-driver")
        assert lease.spec.holder_identity == ""  # released
