"""tools/xplane_summary.py against a REAL jax.profiler capture: the
train-MFU profiling workflow must work end-to-end before the chip run
depends on it."""

import pytest


@pytest.fixture(scope="module")
def capture_dir(tmp_path_factory):
    import jax

    from k8s_dra_driver_tpu.models import burnin

    cfg = burnin.TINY
    fns = burnin.build_train_step(cfg)
    p, o = fns.init(jax.random.PRNGKey(0))
    t = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=2, seq=16)
    p, o, loss = fns.step(p, o, t)  # compile outside the capture
    d = tmp_path_factory.mktemp("prof")
    with jax.profiler.trace(str(d)):
        p, o, loss = fns.step(p, o, t)
        float(loss)
    return str(d)


def _proto_available() -> bool:
    import importlib.util

    try:
        return importlib.util.find_spec(
            "tensorflow.tsl.profiler.protobuf.xplane_pb2"
        ) is not None
    except ModuleNotFoundError:  # no tensorflow at all
        return False


@pytest.mark.skipif(not _proto_available(), reason="xplane proto unavailable")
class TestSummarize:
    def test_summarizes_real_capture(self, capture_dir):
        from tools.xplane_summary import summarize

        s = summarize(capture_dir, plane_filter="CPU", top=5)
        assert s["total_ms"] > 0
        assert s["buckets"]  # at least one bucket with time
        assert 0 < len(s["top_ops"]) <= 5
        assert abs(sum(b["pct"] for b in s["buckets"].values()) - 100) < 1e-6

    def test_unknown_plane_lists_what_exists(self, capture_dir):
        from tools.xplane_summary import summarize

        with pytest.raises(ValueError, match="planes present"):
            summarize(capture_dir, plane_filter="no-such-plane")

    def test_missing_dir_fails_loud(self, tmp_path):
        from tools.xplane_summary import load_xspaces

        with pytest.raises(FileNotFoundError):
            load_xspaces(str(tmp_path / "empty"))
