"""REAL multi-host SERVING: the continuous-batching engine DP-sharded over
two OS processes via jax.distributed.

The multi-controller pattern a v5e pod fleet runs: every process executes
the SAME scheduler loop in lockstep (submit order, steps, retirements),
the slot axis shards over the global mesh, and host readbacks allgather.
Built on the same fake-cluster → CDI-env → consumer.attach() bootstrap as
tests/test_multiprocess.py (shared harness: tests/mp_harness.py) —
nothing below the k8s layer is mocked; the rendezvous, the global mesh,
and the sharded step program are the real thing (CPU devices standing in
for chips)."""

from k8s_dra_driver_tpu.controller.slice_manager import SliceManager
from k8s_dra_driver_tpu.e2e.harness import make_cluster
from tests.mp_harness import run_two_process_workers

# Deterministic request mix every controller replays identically.
REQS = "[([5, 9, 2], 6), ([11, 3], 8), ([7, 7, 7, 1], 5), ([2], 7)]"

WORKER = r"""
import json
from k8s_dra_driver_tpu import consumer

ctx = consumer.attach()  # real jax.distributed.initialize over TCP
import jax
import numpy as np
from jax.sharding import Mesh

from k8s_dra_driver_tpu.models import burnin
from k8s_dra_driver_tpu.models.serve import ServeEngine

cfg = burnin.ModelConfig(
    vocab_size=61, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=32
)
params = burnin.init_params(jax.random.PRNGKey(0), cfg)  # same on all hosts
mesh = Mesh(np.array(jax.devices()), ("data",))  # 2 hosts x 2 devices
eng = ServeEngine(
    params=params, cfg=cfg, n_slots=4, prompt_bucket=8,
    mesh=mesh, slot_axis="data",
)
pending = list(REQS)
streams = {}
for _ in range(500):
    while pending:
        prompt, max_tokens = pending[0]
        try:
            eng.submit(prompt, max_tokens)
            pending.pop(0)
        except RuntimeError:
            break
    stepped = eng.step()
    for c in eng.completions():
        streams[c.request_id] = c.generated
    if not pending and stepped == 0 and eng.free_slots() == eng.n_slots:
        break
print(json.dumps({
    "worker": ctx.worker_id,
    "process_count": jax.process_count(),
    "global_devices": len(jax.devices()),
    "streams": {str(k): v for k, v in streams.items()},
}))
""".replace("REQS", REQS)


PAGED_WORKER = r"""
import json
from k8s_dra_driver_tpu import consumer

ctx = consumer.attach()  # real jax.distributed.initialize over TCP
import jax
import numpy as np
from jax.sharding import Mesh

from k8s_dra_driver_tpu.models import burnin, lora
from k8s_dra_driver_tpu.models.paged import PagedServeEngine

cfg = burnin.ModelConfig(
    vocab_size=61, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=32
)
params = burnin.init_params(jax.random.PRNGKey(0), cfg)  # same on all hosts
lcfg = lora.LoraConfig(rank=2, alpha=4.0)
bank = lora.stack_adapters(
    cfg, lcfg,
    [lora.init_adapters(jax.random.PRNGKey(7 + i), cfg, lcfg) for i in range(2)],
)
mesh = Mesh(np.array(jax.devices()), ("data",))  # 2 hosts x 2 devices
eng = PagedServeEngine(
    params=params, cfg=cfg, n_slots=4, n_blocks=32, block_size=4,
    prompt_bucket=8, attn_impl="xla", spec_gamma=2, adapter_bank=bank,
    mesh=mesh, slot_axis="data",
)
pending = list(REQS)
streams = {}
for _ in range(500):
    while pending:
        prompt, max_tokens, adapter = pending[0]
        try:
            eng.submit(prompt, max_tokens, adapter=adapter)
            pending.pop(0)
        except RuntimeError:
            break
    stepped = eng.step()
    for c in eng.completions():
        streams[c.request_id] = c.generated
    if not pending and stepped == 0 and eng.free_slots() == eng.n_slots:
        break
print(json.dumps({
    "worker": ctx.worker_id,
    "process_count": jax.process_count(),
    "global_devices": len(jax.devices()),
    "streams": {str(k): v for k, v in streams.items()},
}))
"""

# paged mix exercises per-request adapters on top of speculative rounds
PAGED_REQS = "[([5, 9, 2], 6, 0), ([11, 3], 8, 1), ([7, 7, 7, 1], 5, 2), ([2], 7, 0)]"


MULTISLICE_WORKER = r"""
import json
from k8s_dra_driver_tpu import consumer

ctx = consumer.attach()  # real jax.distributed.initialize over TCP
import jax
import numpy as np

from k8s_dra_driver_tpu.models import burnin
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_multislice_mesh

cfg = burnin.ModelConfig(
    vocab_size=61, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=32
)
params = burnin.init_params(jax.random.PRNGKey(0), cfg)  # same on all hosts
# 2 slices x data=2 over the 2-process global mesh: the slice axis spans
# the PROCESS boundary — the DCN seam of a real multislice pod set.
mesh = build_multislice_mesh(jax.devices(), 2, MeshShape(data=2))
eng = ServeEngine(
    params=params, cfg=cfg, n_slots=4, prompt_bucket=8,
    mesh=mesh, slot_axis=("slice", "data"),
)
pending = list(REQS)
streams = {}
for _ in range(500):
    while pending:
        prompt, max_tokens = pending[0]
        try:
            eng.submit(prompt, max_tokens)
            pending.pop(0)
        except RuntimeError:
            break
    stepped = eng.step()
    for c in eng.completions():
        streams[c.request_id] = c.generated
    if not pending and stepped == 0 and eng.free_slots() == eng.n_slots:
        break
print(json.dumps({
    "worker": ctx.worker_id,
    "process_count": jax.process_count(),
    "slice_axis": int(mesh.shape["slice"]),
    "streams": {str(k): v for k, v in streams.items()},
}))
""".replace("REQS", REQS)


def test_two_process_multislice_serving_bit_equal(tmp_path):
    """MULTISLICE serving across REAL processes: the slice axis spans the
    process boundary (each OS process = one slice, the DCN seam), slots
    shard over ('slice', 'data') tuple axes, and streams bit-equal the
    single-process single-slice engine."""
    cluster = make_cluster(
        hosts=2, topology="v5e-16", work_dir=str(tmp_path),
        slice_domain="mp-multislice",
    )
    manager = SliceManager(cluster.server)
    manager.start()
    try:
        outs = run_two_process_workers(cluster, tmp_path, MULTISLICE_WORKER)
        assert sorted(o["worker"] for o in outs) == [0, 1]
        for o in outs:
            assert o["process_count"] == 2
            assert o["slice_axis"] == 2
        assert outs[0]["streams"] == outs[1]["streams"]
        assert sorted(outs[0]["streams"]) == ["0", "1", "2", "3"]

        import jax

        from k8s_dra_driver_tpu.models import burnin
        from k8s_dra_driver_tpu.models.serve import ServeEngine

        cfg = burnin.ModelConfig(
            vocab_size=61, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=32
        )
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        ref = ServeEngine(params=params, cfg=cfg, n_slots=4, prompt_bucket=8)
        for prompt, max_tokens in [([5, 9, 2], 6), ([11, 3], 8),
                                   ([7, 7, 7, 1], 5), ([2], 7)]:
            ref.submit(prompt, max_tokens)
        ref.run_until_drained()
        want = {str(c.request_id): c.generated for c in ref.completions()}
        assert outs[0]["streams"] == want
    finally:
        manager.stop()


def test_two_process_dp_sharded_paged_engine_bit_equal(tmp_path):
    """The PRODUCTION serving shape across REAL processes: paged pool +
    speculative rounds + per-request LoRA, slot/pool axes sharded over a
    2-process global mesh — streams bit-equal the single-process engine."""
    cluster = make_cluster(
        hosts=2, topology="v5e-16", work_dir=str(tmp_path), slice_domain="mp-paged"
    )
    manager = SliceManager(cluster.server)
    manager.start()
    try:
        outs = run_two_process_workers(
            cluster, tmp_path, PAGED_WORKER.replace("REQS", PAGED_REQS)
        )
        assert sorted(o["worker"] for o in outs) == [0, 1]
        for o in outs:
            assert o["process_count"] == 2
            assert o["global_devices"] == 4
        assert outs[0]["streams"] == outs[1]["streams"]
        assert sorted(outs[0]["streams"]) == ["0", "1", "2", "3"]

        # ...and they are the SAME tokens the single-process engine serves
        import jax

        from k8s_dra_driver_tpu.models import burnin, lora
        from k8s_dra_driver_tpu.models.paged import PagedServeEngine

        cfg = burnin.ModelConfig(
            vocab_size=61, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=32
        )
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        lcfg = lora.LoraConfig(rank=2, alpha=4.0)
        bank = lora.stack_adapters(
            cfg, lcfg,
            [lora.init_adapters(jax.random.PRNGKey(7 + i), cfg, lcfg)
             for i in range(2)],
        )
        ref = PagedServeEngine(
            params=params, cfg=cfg, n_slots=4, n_blocks=32, block_size=4,
            prompt_bucket=8, attn_impl="xla", spec_gamma=2, adapter_bank=bank,
        )
        for prompt, max_tokens, adapter in [
            ([5, 9, 2], 6, 0), ([11, 3], 8, 1), ([7, 7, 7, 1], 5, 2),
            ([2], 7, 0),
        ]:
            ref.submit(prompt, max_tokens, adapter=adapter)
        ref.run_until_drained()
        want = {str(c.request_id): c.generated for c in ref.completions()}
        assert outs[0]["streams"] == want
    finally:
        manager.stop()


def test_two_process_dp_sharded_engine_serves_identical_streams(tmp_path):
    cluster = make_cluster(
        hosts=2, topology="v5e-16", work_dir=str(tmp_path), slice_domain="mp-serve"
    )
    manager = SliceManager(cluster.server)
    manager.start()
    try:
        outs = run_two_process_workers(cluster, tmp_path, WORKER)
        assert sorted(o["worker"] for o in outs) == [0, 1]
        for o in outs:
            assert o["process_count"] == 2
            assert o["global_devices"] == 4
        # every controller saw the same four completed streams
        assert outs[0]["streams"] == outs[1]["streams"]
        assert sorted(outs[0]["streams"]) == ["0", "1", "2", "3"]

        # ...and they are the SAME tokens a single-process engine serves
        import jax

        from k8s_dra_driver_tpu.models import burnin
        from k8s_dra_driver_tpu.models.serve import ServeEngine

        cfg = burnin.ModelConfig(
            vocab_size=61, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=32
        )
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        ref = ServeEngine(params=params, cfg=cfg, n_slots=4, prompt_bucket=8)
        for prompt, max_tokens in [([5, 9, 2], 6), ([11, 3], 8),
                                   ([7, 7, 7, 1], 5), ([2], 7)]:
            ref.submit(prompt, max_tokens)
        ref.run_until_drained()
        want = {str(c.request_id): c.generated for c in ref.completions()}
        assert outs[0]["streams"] == want
    finally:
        manager.stop()
