"""Gang allocation property tests (PR 15 satellite).

The gang contract under test, from ``Allocator.allocate_gang``:

* **All-or-nothing** — a gang either commits every member or leaves the
  store EXACTLY as it found it, including under an injected 409 storm
  that breaks commits mid-gang (the unwind path).
* **No leaked reservations** — after any unwound gang, every device
  marker the gang touched is free again: the index's consumed set and
  the store agree with a world where the gang never happened.
* **Determinism** — identical inventories and claims produce identical
  plans (device-for-device), seed-independent of dict/set iteration.
"""

import pytest

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.e2e.harness import (
    SUBSLICE_CLASS,
    install_device_classes,
    simple_claim,
)
from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import ResourceClaim
from k8s_dra_driver_tpu.kube.resourceslice_controller import (
    DriverResources,
    Pool,
    ResourceSliceController,
    Slice,
)
from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices
from k8s_dra_driver_tpu.scheduler.allocator import (
    AllocationError,
    Allocator,
    GangConflictError,
    GangMember,
)
from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, parse_prom_text


def publish_host(server, node, spec="v5e-16", host_id=0, pool=None):
    """One v5e-16 host block (a 2x2: four chips, subslices up to 2x2) in
    its own pool on ``node`` — co-locating several blocks per node gives
    gangs same-node headroom."""
    pool = pool or node
    topo = enumerate_topology(env={
        "TPUINFO_FAKE_TOPOLOGY": spec,
        "TPUINFO_FAKE_HOST_ID": str(host_id),
    })
    devices = AllocatableDevices.from_topology(topo).get_devices()
    ctrl = ResourceSliceController(server, DRIVER_NAME, pool)
    ctrl.update(DriverResources(pools={
        pool: Pool(slices=[Slice(devices=devices)], node_name=node),
    }))


def build_cluster(n_nodes=3, blocks=4, injector=None):
    server = InMemoryAPIServer(fault_injector=injector)
    install_device_classes(server)
    for i in range(n_nodes):
        for b in range(blocks):
            publish_host(
                server, f"node-{i}", host_id=b, pool=f"node-{i}-b{b}",
            )
    return server, Allocator(server)


def subslice_claim(server, name, chips=4):
    return server.create(simple_claim(
        name,
        device_class=SUBSLICE_CLASS,
        selectors=(
            f"device.attributes['{DRIVER_NAME}'].chipCount == {chips}",
        ),
    ))


def gang_of(server, tag, nodes, chips=4):
    return [
        GangMember(
            claim=subslice_claim(server, f"{tag}-{i}", chips=chips),
            node_name=node,
        )
        for i, node in enumerate(nodes)
    ]


def allocated_names(server):
    return {
        c.metadata.name
        for c in server.list(ResourceClaim.KIND)
        if c.status.allocation is not None
    }


def consumed_markers(allocator, n_nodes=3):
    taken = set()
    for i in range(n_nodes):
        view = allocator.view(f"node-{i}")
        taken |= set(view.used_markers)
    return taken


class TestGangCommit:
    def test_commits_every_member(self):
        server, alloc = build_cluster()
        members = gang_of(server, "g", ["node-0", "node-1", "node-2"])
        out = alloc.allocate_gang(members)
        assert len(out) == 3
        assert allocated_names(server) == {"g-0", "g-1", "g-2"}
        counts = parse_prom_text(REGISTRY.render())["dra_gang_plans_total"]
        assert counts[(("outcome", "committed"),)] == 1.0

    def test_same_node_members_get_disjoint_devices(self):
        server, alloc = build_cluster(n_nodes=1, blocks=1)
        members = gang_of(server, "g", ["node-0", "node-0"], chips=2)
        out = alloc.allocate_gang(members)
        picks = [
            (r.pool, r.device)
            for c in out for r in c.status.allocation.devices.results
        ]
        assert len(picks) == len(set(picks)) == 2
        # Both 2-chip subslices of the lone 2x2 block are now taken, so
        # the covering 4-chip subslice must be unplaceable.
        extra = gang_of(server, "x", ["node-0"], chips=4)
        with pytest.raises(AllocationError):
            alloc.allocate_gang(extra)

    def test_empty_gang_is_loud(self):
        _, alloc = build_cluster(n_nodes=1)
        with pytest.raises(AllocationError, match="empty"):
            alloc.allocate_gang([])


class TestAllOrNothing:
    def test_infeasible_member_writes_nothing(self):
        server, alloc = build_cluster(n_nodes=2)
        # Three 8-chip members on two 16-chip nodes plus one on a node
        # that doesn't exist: the gang must abort before ANY write.
        members = gang_of(
            server, "g", ["node-0", "node-1", "node-no-such"], chips=8
        )
        with pytest.raises(AllocationError):
            alloc.allocate_gang(members)
        assert allocated_names(server) == set()
        assert consumed_markers(alloc, 2) == set()
        counts = parse_prom_text(REGISTRY.render())["dra_gang_plans_total"]
        assert counts.get((("outcome", "infeasible"),)) == 1.0
        assert (("outcome", "committed"),) not in counts

    def test_atomic_under_conflict_storm_no_leaked_reservations(self):
        """The property run: gangs attempted under a seeded 409/500 storm
        either commit whole or unwind whole; when the storm clears, the
        store and the index match a world containing exactly the
        committed gangs — and after deallocating those, nothing at all."""
        inj = FaultInjector(seed=11)
        server, alloc = build_cluster(n_nodes=3, injector=inj)
        inj.arm(FaultProfile(
            name="storm-409", conflict_rate=0.30,
            verbs=("PUT",), kinds=(ResourceClaim.KIND,),
        ))
        inj.arm(FaultProfile(
            name="storm-500", error_rate=0.10, error_code=500,
            verbs=("PUT",), kinds=(ResourceClaim.KIND,),
        ))
        committed = []
        for g in range(12):
            members = gang_of(
                server, f"g{g}", ["node-0", "node-1", "node-2"], chips=4
            )
            try:
                alloc.allocate_gang(members)
                committed.append(f"g{g}")
            except AllocationError:
                # Whatever broke it, nothing of THIS gang may survive.
                assert not any(
                    n.startswith(f"g{g}-") for n in allocated_names(server)
                )
        inj.disarm(None)
        # Exactly the committed gangs' members hold allocations.
        expect = {f"{g}-{i}" for g in committed for i in range(3)}
        assert allocated_names(server) == expect
        events = [e["event"] for e in JOURNAL.tail(limit=5000)]
        assert "gang.unwound" in events, \
            "storm must exercise the mid-gang unwind path"
        # Deallocate every committed gang: zero markers must remain.
        for name in sorted(expect):
            alloc.deallocate(server.get(ResourceClaim.KIND, name, "default"))
        assert consumed_markers(alloc, 3) == set()
        assert allocated_names(server) == set()

    def test_unwind_exhaustion_is_loud(self):
        """A storm the unwind can't outlast raises and journals the leak
        instead of silently abandoning the reservation."""
        inj = FaultInjector(seed=5)
        server, alloc = build_cluster(n_nodes=1, injector=inj)
        alloc.GANG_UNWIND_ATTEMPTS = 3
        members = gang_of(server, "g", ["node-0", "node-0"], chips=4)
        # Make the SECOND member's commit conflict genuinely (its held
        # copy goes stale when the server-side object advances)...
        server.update(server.get(ResourceClaim.KIND, "g-1", "default"))
        # ...and jam every refetch so the unwind cannot converge.
        inj.arm(FaultProfile(
            name="jam", error_rate=1.0, error_code=500,
            verbs=("GET",), kinds=(ResourceClaim.KIND,),
        ))
        with pytest.raises(AllocationError, match="unwind"):
            alloc.allocate_gang(members)
        events = [e["event"] for e in JOURNAL.tail(limit=200)]
        assert "gang.unwind_leak" in events


class TestDeterminism:
    def _run(self):
        server, alloc = build_cluster(n_nodes=3)
        out = alloc.allocate_gang(
            gang_of(server, "g", ["node-0", "node-1", "node-0"], chips=4)
        )
        picks = tuple(
            (r.pool, r.device)
            for c in out for r in c.status.allocation.devices.results
        )
        plans = alloc.plan_gang(
            gang_of(server, "h", ["node-1", "node-2"], chips=2)
        )
        planned = tuple(
            c.key for _, p in plans for _, c in p.chosen
        )
        return picks, planned

    def test_identical_worlds_plan_identically(self):
        assert self._run() == self._run()


class TestGangConflictError:
    def test_mid_gang_conflict_is_typed_and_carries_unwound_names(self):
        """A stale member mid-commit raises GangConflictError (an
        AllocationError, so existing catches still work) naming exactly
        the siblings that were rolled back, in commit order — no caller
        ever needs to string-match the message again."""
        server, alloc = build_cluster(n_nodes=2)
        members = gang_of(server, "g", ["node-0", "node-0", "node-1"])
        # The THIRD member's held copy goes stale: the first two commit,
        # then the gang must unwind both.
        server.update(server.get(ResourceClaim.KIND, "g-2", "default"))
        with pytest.raises(GangConflictError) as err:
            alloc.allocate_gang(members)
        assert isinstance(err.value, AllocationError)
        assert err.value.unwound == ("g-0", "g-1")
        assert allocated_names(server) == set(), "unwind must balance the store"
        unwound = [
            e["attrs"]["claim"]
            for e in JOURNAL.tail(limit=200)
            if e["event"] == "gang.unwound"
        ]
        assert unwound == ["g-1", "g-0"], "unwind must run in reverse order"


class TestConcurrentGangUnwind:
    def test_overlapping_gangs_commit_exactly_once(self):
        """Two scheduler loops race overlapping gangs (they share the
        claim ``x``, committed last) against one store: claim-level CAS
        picks exactly one winner, the loser unwinds its committed
        sibling in reverse, and the store ends balanced — the winner's
        claims allocated on disjoint devices, the loser's claim and
        nothing else rolled back."""
        import threading

        # Injected PUT latency (GIL-releasing sleep at the commit seam)
        # guarantees the two commit sequences genuinely interleave
        # instead of racing GIL scheduling luck.
        inj = FaultInjector(seed=3)
        inj.arm(FaultProfile(
            name="slow-put", latency_s=0.01,
            verbs=("PUT",), kinds=(ResourceClaim.KIND,),
        ))
        server, _ = build_cluster(n_nodes=2, injector=inj)
        shared = subslice_claim(server, "x")
        gangs = {
            "a": [
                GangMember(claim=subslice_claim(server, "a-0"), node_name="node-0"),
                GangMember(claim=shared, node_name="node-1"),
            ],
            "b": [
                GangMember(claim=subslice_claim(server, "b-0"), node_name="node-0"),
                GangMember(
                    claim=server.get(ResourceClaim.KIND, "x", "default"),
                    node_name="node-1",
                ),
            ],
        }
        results: dict = {}
        barrier = threading.Barrier(2)

        def race(tag):
            alloc = Allocator(server)
            try:
                barrier.wait()
                results[tag] = ("won", alloc.allocate_gang(gangs[tag]))
            except GangConflictError as exc:
                results[tag] = ("lost", exc)
            finally:
                alloc.close()

        threads = [
            threading.Thread(target=race, args=(t,)) for t in ("a", "b")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

        outcomes = sorted(kind for kind, _ in results.values())
        assert outcomes == ["lost", "won"], f"exactly one winner: {results}"
        winner = next(t for t, (k, _) in results.items() if k == "won")
        loser = "b" if winner == "a" else "a"
        assert allocated_names(server) == {f"{winner}-0", "x"}
        loss = results[loser][1]
        assert loss.unwound == (f"{loser}-0",)
        # The winner's two members must sit on genuinely disjoint devices.
        winner_claims = results[winner][1]
        picks = [
            (c.metadata.name, r.pool, r.device)
            for c in winner_claims
            for r in c.status.allocation.devices.results
        ]
        assert len({(p, d) for _, p, d in picks}) == len(picks)
