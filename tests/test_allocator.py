"""Structured-parameter allocator tests.

Covers the scheduler semantics the driver's published geometry relies on
(SURVEY.md §3.5), including the central property: overlapping subslices are
never co-allocated (the memorySlice%d analog)."""

import pytest

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.kube.objects import (
    CELDeviceSelector,
    DeviceClaim,
    DeviceClass,
    DeviceClassSpec,
    DeviceConstraint,
    DeviceRequest,
    DeviceSelector,
    ObjectMeta,
    ResourceClaim,
    ResourceClaimSpec,
    ResourceSlice,
)
from k8s_dra_driver_tpu.kube.resourceslice_controller import (
    DriverResources,
    Pool,
    ResourceSliceController,
    Slice,
)
from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices
from k8s_dra_driver_tpu.scheduler.allocator import AllocationError, Allocator
from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology

TPU_CLASS = "tpu.google.com"
SUBSLICE_CLASS = "subslice.tpu.google.com"


def sel(expr: str) -> DeviceSelector:
    return DeviceSelector(cel=CELDeviceSelector(expression=expr))


def install_classes(server):
    server.create(
        DeviceClass(
            metadata=ObjectMeta(name=TPU_CLASS),
            spec=DeviceClassSpec(
                selectors=[
                    sel(
                        f"device.driver == '{DRIVER_NAME}' && "
                        f"device.attributes['{DRIVER_NAME}'].type == 'tpu'"
                    )
                ]
            ),
        )
    )
    server.create(
        DeviceClass(
            metadata=ObjectMeta(name=SUBSLICE_CLASS),
            spec=DeviceClassSpec(
                selectors=[
                    sel(
                        f"device.driver == '{DRIVER_NAME}' && "
                        f"device.attributes['{DRIVER_NAME}'].type == 'subslice'"
                    )
                ]
            ),
        )
    )


def publish_host(server, spec="v5e-16", host_id=0, node="host0", pool=None):
    """Publish one TPU host's inventory.  ``pool`` lets tests co-locate
    several host-blocks' pools on one k8s node (device names collide across
    pools otherwise)."""
    pool = pool or node
    topo = enumerate_topology(
        env={"TPUINFO_FAKE_TOPOLOGY": spec, "TPUINFO_FAKE_HOST_ID": str(host_id)}
    )
    devices = AllocatableDevices.from_topology(topo).get_devices()
    ctrl = ResourceSliceController(server, DRIVER_NAME, pool)
    ctrl.update(
        DriverResources(pools={pool: Pool(slices=[Slice(devices=devices)], node_name=node)})
    )
    return topo


def make_claim(server, name, requests, constraints=None):
    claim = ResourceClaim(
        metadata=ObjectMeta(name=name, namespace="default"),
        spec=ResourceClaimSpec(
            devices=DeviceClaim(requests=requests, constraints=constraints or [])
        ),
    )
    return server.create(claim)


@pytest.fixture
def cluster(api_server):
    install_classes(api_server)
    publish_host(api_server)
    return api_server


class TestBasicAllocation:
    def test_single_chip(self, cluster):
        claim = make_claim(
            cluster, "c1", [DeviceRequest(name="tpu", device_class_name=TPU_CLASS)]
        )
        updated = Allocator(cluster).allocate(claim, node_name="host0")
        results = updated.status.allocation.devices.results
        assert len(results) == 1
        assert results[0].device.startswith("tpu-")
        assert updated.status.allocation.node_selector.matches(
            {"kubernetes.io/hostname": "host0"}
        )

    def test_exact_count(self, cluster):
        claim = make_claim(
            cluster,
            "c2",
            [DeviceRequest(name="tpus", device_class_name=TPU_CLASS, count=4)],
        )
        updated = Allocator(cluster).allocate(claim, node_name="host0")
        assert len(updated.status.allocation.devices.results) == 4

    def test_insufficient_devices(self, cluster):
        claim = make_claim(
            cluster,
            "c3",
            [DeviceRequest(name="tpus", device_class_name=TPU_CLASS, count=5)],
        )
        with pytest.raises(AllocationError):
            Allocator(cluster).allocate(claim, node_name="host0")

    def test_wrong_node_sees_nothing(self, cluster):
        claim = make_claim(
            cluster, "c4", [DeviceRequest(name="tpu", device_class_name=TPU_CLASS)]
        )
        with pytest.raises(AllocationError):
            Allocator(cluster).allocate(claim, node_name="other-host")

    def test_allocation_mode_all(self, cluster):
        claim = make_claim(
            cluster,
            "c5",
            [DeviceRequest(name="all", device_class_name=TPU_CLASS, allocation_mode="All")],
        )
        updated = Allocator(cluster).allocate(claim, node_name="host0")
        assert len(updated.status.allocation.devices.results) == 4

    def test_idempotent(self, cluster):
        claim = make_claim(
            cluster, "c6", [DeviceRequest(name="tpu", device_class_name=TPU_CLASS)]
        )
        a = Allocator(cluster)
        first = a.allocate(claim, node_name="host0")
        again = a.allocate(first, node_name="host0")
        assert again.status.allocation.devices.results == first.status.allocation.devices.results


class TestSelectors:
    def test_request_level_cel(self, cluster):
        claim = make_claim(
            cluster,
            "c1",
            [
                DeviceRequest(
                    name="tpu",
                    device_class_name=TPU_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].index in [2, 3]")],
                )
            ],
        )
        updated = Allocator(cluster).allocate(claim, node_name="host0")
        assert updated.status.allocation.devices.results[0].device in ("tpu-2", "tpu-3")

    def test_shape_selector(self, cluster):
        claim = make_claim(
            cluster,
            "c2",
            [
                DeviceRequest(
                    name="slice",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x2'")],
                )
            ],
        )
        updated = Allocator(cluster).allocate(claim, node_name="host0")
        assert updated.status.allocation.devices.results[0].device == "tpu-slice-2x2-0-0"

    def test_capacity_quantity_selector(self, cluster):
        # hbm >= quantity('48Gi'): only the 2x2 subslice (64Gi) qualifies.
        claim = make_claim(
            cluster,
            "cap",
            [
                DeviceRequest(
                    name="big",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[
                        sel(
                            f"device.capacity['{DRIVER_NAME}'].hbm >= quantity('48Gi')"
                        )
                    ],
                )
            ],
        )
        updated = Allocator(cluster).allocate(claim, node_name="host0")
        assert updated.status.allocation.devices.results[0].device == "tpu-slice-2x2-0-0"

    def test_bad_quantity_in_selector_is_nonmatch(self, cluster):
        claim = make_claim(
            cluster,
            "badq",
            [
                DeviceRequest(
                    name="t",
                    device_class_name=TPU_CLASS,
                    selectors=[
                        sel(f"device.capacity['{DRIVER_NAME}'].hbm >= quantity('banana')")
                    ],
                )
            ],
        )
        with pytest.raises(AllocationError):
            Allocator(cluster).allocate(claim, node_name="host0")

    def test_erroring_selector_is_nonmatch(self, cluster):
        claim = make_claim(
            cluster,
            "c3",
            [
                DeviceRequest(
                    name="tpu",
                    device_class_name=TPU_CLASS,
                    selectors=[sel("device.attributes['missing.domain'].x == 1")],
                )
            ],
        )
        with pytest.raises(AllocationError):
            Allocator(cluster).allocate(claim, node_name="host0")


class TestOverlapExclusion:
    def test_subslice_excludes_chip(self, cluster):
        a = Allocator(cluster)
        slice_claim = make_claim(
            cluster,
            "slice",
            [
                DeviceRequest(
                    name="s",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x2'")],
                )
            ],
        )
        a.allocate(cluster.get("ResourceClaim", "slice", "default"), node_name="host0")
        # The 2x2 subslice covers all 4 chips: any chip claim must now fail.
        chip_claim = make_claim(
            cluster, "chip", [DeviceRequest(name="t", device_class_name=TPU_CLASS)]
        )
        with pytest.raises(AllocationError):
            a.allocate(chip_claim, node_name="host0")

    def test_chip_excludes_covering_subslice_only(self, cluster):
        a = Allocator(cluster)
        chip0 = make_claim(
            cluster,
            "chip0",
            [
                DeviceRequest(
                    name="t",
                    device_class_name=TPU_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].index == 0")],
                )
            ],
        )
        a.allocate(chip0, node_name="host0")
        # 1x2 at origin (1,0) covers chips 1,3 (column x=1) — still free.
        ok = make_claim(
            cluster,
            "free-slice",
            [
                DeviceRequest(
                    name="s",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[
                        sel(
                            f"device.attributes['{DRIVER_NAME}'].shape == '1x2' && "
                            f"device.attributes['{DRIVER_NAME}'].originX == 1"
                        )
                    ],
                )
            ],
        )
        updated = a.allocate(ok, node_name="host0")
        assert updated.status.allocation is not None
        # But the covering 2x2 must fail.
        bad = make_claim(
            cluster,
            "covering",
            [
                DeviceRequest(
                    name="s",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x2'")],
                )
            ],
        )
        with pytest.raises(AllocationError):
            a.allocate(bad, node_name="host0")

    def test_disjoint_subslices_coexist(self, cluster):
        a = Allocator(cluster)
        for origin in (0, 1):
            claim = make_claim(
                cluster,
                f"s{origin}",
                [
                    DeviceRequest(
                        name="s",
                        device_class_name=SUBSLICE_CLASS,
                        selectors=[
                            sel(
                                f"device.attributes['{DRIVER_NAME}'].shape == '1x2' && "
                                f"device.attributes['{DRIVER_NAME}'].originX == {origin}"
                            )
                        ],
                    )
                ],
            )
            assert a.allocate(claim, node_name="host0").status.allocation


class TestConstraints:
    def test_match_attribute_same_host(self, api_server):
        install_classes(api_server)
        publish_host(api_server, host_id=0, node="host0", pool="block0")
        publish_host(api_server, host_id=1, node="host0", pool="block1")
        claim = make_claim(
            api_server,
            "pair",
            [
                DeviceRequest(name="a", device_class_name=TPU_CLASS, count=2),
                DeviceRequest(name="b", device_class_name=TPU_CLASS, count=2),
            ],
            constraints=[
                DeviceConstraint(requests=[], match_attribute=f"{DRIVER_NAME}/hostId")
            ],
        )
        updated = Allocator(api_server).allocate(claim, node_name="host0")
        slices = api_server.list("ResourceSlice")
        host_ids = set()
        for r in updated.status.allocation.devices.results:
            for s in slices:
                if s.spec.pool.name == r.pool:
                    for d in s.spec.devices:
                        if d.name == r.device:
                            host_ids.add(d.basic.attributes["hostId"].value)
        assert len(host_ids) == 1

    def test_independent_constraints_same_attribute_not_coupled(self, api_server):
        # Two constraints on the same attribute but disjoint request sets are
        # independent: a may land on host block 0 and b on block 1.  Coupling
        # them (one shared attr_value) would make 3+3 chips unsatisfiable.
        install_classes(api_server)
        publish_host(api_server, host_id=0, node="host0", pool="block0")
        publish_host(api_server, host_id=1, node="host0", pool="block1")
        claim = make_claim(
            api_server,
            "indep",
            [
                DeviceRequest(name="a", device_class_name=TPU_CLASS, count=3),
                DeviceRequest(name="b", device_class_name=TPU_CLASS, count=3),
            ],
            constraints=[
                DeviceConstraint(requests=["a"], match_attribute=f"{DRIVER_NAME}/hostId"),
                DeviceConstraint(requests=["b"], match_attribute=f"{DRIVER_NAME}/hostId"),
            ],
        )
        updated = Allocator(api_server).allocate(claim, node_name="host0")
        assert len(updated.status.allocation.devices.results) == 6

    def test_match_attribute_unsatisfiable(self, api_server):
        install_classes(api_server)
        publish_host(api_server, host_id=0, node="host0", pool="block0")
        publish_host(api_server, host_id=1, node="host0", pool="block1")
        # 5 chips same hostId is impossible (4 per host block)
        claim = make_claim(
            api_server,
            "five",
            [DeviceRequest(name="a", device_class_name=TPU_CLASS, count=5)],
            constraints=[
                DeviceConstraint(requests=["a"], match_attribute=f"{DRIVER_NAME}/hostId")
            ],
        )
        with pytest.raises(AllocationError):
            Allocator(api_server).allocate(claim, node_name="host0")


class TestAdminAccess:
    def test_admin_sees_allocated_devices_without_consuming(self, cluster):
        a = Allocator(cluster)
        # Exhaust all 4 chips with a normal claim.
        normal = make_claim(
            cluster, "all", [DeviceRequest(name="t", device_class_name=TPU_CLASS, count=4)]
        )
        a.allocate(normal, node_name="host0")
        # A monitoring claim with adminAccess still allocates...
        admin = make_claim(
            cluster,
            "monitor",
            [
                DeviceRequest(
                    name="mon",
                    device_class_name=TPU_CLASS,
                    admin_access=True,
                    allocation_mode="All",
                )
            ],
        )
        granted = a.allocate(admin, node_name="host0")
        results = granted.status.allocation.devices.results
        assert len(results) == 4
        assert all(r.admin_access for r in results)
        # ...and does not block further normal claims beyond the real usage.
        another = make_claim(
            cluster, "late", [DeviceRequest(name="t", device_class_name=TPU_CLASS)]
        )
        with pytest.raises(AllocationError):  # chips truly exhausted by "all"
            a.allocate(another, node_name="host0")

    def test_admin_zero_match_all_is_loud(self, cluster):
        a = Allocator(cluster)
        admin = make_claim(
            cluster,
            "typo",
            [
                DeviceRequest(
                    name="mon",
                    device_class_name=TPU_CLASS,
                    admin_access=True,
                    allocation_mode="All",
                    selectors=[sel("device.attributes['missing.domain'].x == 1")],
                )
            ],
        )
        with pytest.raises(AllocationError, match="0 device"):
            a.allocate(admin, node_name="host0")

    def test_constraint_over_admin_request_rejected(self, cluster):
        a = Allocator(cluster)
        claim = make_claim(
            cluster,
            "bad",
            [
                DeviceRequest(name="mon", device_class_name=TPU_CLASS, admin_access=True),
                DeviceRequest(name="w", device_class_name=TPU_CLASS),
            ],
            constraints=[
                DeviceConstraint(
                    requests=["mon", "w"], match_attribute=f"{DRIVER_NAME}/hostId"
                )
            ],
        )
        with pytest.raises(AllocationError, match="adminAccess"):
            a.allocate(claim, node_name="host0")

    def test_admin_results_do_not_mark_devices_used(self, cluster):
        a = Allocator(cluster)
        admin = make_claim(
            cluster,
            "monitor",
            [DeviceRequest(name="mon", device_class_name=TPU_CLASS, admin_access=True)],
        )
        a.allocate(admin, node_name="host0")
        # Normal allocation of every chip still succeeds afterwards.
        normal = make_claim(
            cluster, "all", [DeviceRequest(name="t", device_class_name=TPU_CLASS, count=4)]
        )
        granted = a.allocate(normal, node_name="host0")
        assert len(granted.status.allocation.devices.results) == 4


class TestBacktracking:
    def test_all_or_nothing_forces_disjoint_choice(self, cluster):
        # Request both a 2x1 and a 2x2... impossible (2x2 is the whole block
        # minus nothing; 2x1 overlaps it) → whole claim fails, nothing leaks.
        claim = make_claim(
            cluster,
            "both",
            [
                DeviceRequest(
                    name="a",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x2'")],
                ),
                DeviceRequest(
                    name="b",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x1'")],
                ),
            ],
        )
        with pytest.raises(AllocationError):
            Allocator(cluster).allocate(claim, node_name="host0")
        fresh = cluster.get("ResourceClaim", "both", "default")
        assert fresh.status.allocation is None

    def test_two_disjoint_slices_found_by_search(self, cluster):
        # Two 1x2 requests: the only non-overlapping assignment is the two
        # distinct columns; the search must find it.
        claim = make_claim(
            cluster,
            "cols",
            [
                DeviceRequest(
                    name="a",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '1x2'")],
                ),
                DeviceRequest(
                    name="b",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '1x2'")],
                ),
            ],
        )
        updated = Allocator(cluster).allocate(claim, node_name="host0")
        devices = {r.device for r in updated.status.allocation.devices.results}
        assert devices == {"tpu-slice-1x2-0-0", "tpu-slice-1x2-1-0"}


class TestBestFitScoring:
    """Placement scoring: smallest-fit shapes, fragmentation-minimizing chip
    choice (the bin-packing concern MIG operators handle out-of-band)."""

    @pytest.fixture
    def wide_host(self, api_server):
        # v5e-8 = one host, 2x4 chip block: two disjoint 2x2 quadrants.
        install_classes(api_server)
        publish_host(api_server, spec="v5e-8")
        return api_server

    def chip_req(self, name):
        return DeviceRequest(name=name, device_class_name=TPU_CLASS)

    def test_smallest_matching_subslice_wins(self, wide_host):
        # chipCount >= 2 matches 2x1/1x2 (2), 2x2 (4), wider shapes — the
        # 2-chip shape must be chosen, conserving the rest.
        claim = make_claim(
            wide_host,
            "smallest",
            [
                DeviceRequest(
                    name="s",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[
                        sel(f"device.attributes['{DRIVER_NAME}'].chipCount >= 2")
                    ],
                )
            ],
        )
        allocated = Allocator(wide_host).allocate(claim, node_name="host0")
        device = allocated.status.allocation.devices.results[0].device
        slices = wide_host.list(ResourceSlice.KIND)
        dev = [d for s in slices for d in s.spec.devices if d.name == device][0]
        assert dev.basic.attributes["chipCount"].value == 2

    def test_chip_claims_pack_into_broken_quadrant(self, wide_host):
        # First chip breaks one 2x2 quadrant; the second must land in the
        # SAME quadrant so the other 2x2 stays allocatable.
        alloc = Allocator(wide_host)
        c1 = alloc.allocate(
            make_claim(wide_host, "c1", [self.chip_req("t")]), node_name="host0"
        )
        first = c1.status.allocation.devices.results[0].device
        c2 = alloc.allocate(
            make_claim(wide_host, "c2", [self.chip_req("t")]), node_name="host0"
        )
        second = c2.status.allocation.devices.results[0].device
        # local index = x + 2*y on the 2x4 block: quadrant A = {0,1,2,3}
        quadrant = lambda name: int(name.split("-")[1]) // 4  # noqa: E731
        assert quadrant(first) == quadrant(second), (first, second)
        # and a whole 2x2 subslice claim still fits afterwards
        c3 = alloc.allocate(
            make_claim(
                wide_host,
                "c3",
                [
                    DeviceRequest(
                        name="s",
                        device_class_name=SUBSLICE_CLASS,
                        selectors=[
                            sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x2'")
                        ],
                    )
                ],
            ),
            node_name="host0",
        )
        assert c3.status.allocation is not None

    def test_determinism(self, wide_host):
        # Same cluster state -> same placement (scores tie-break by name).
        a1 = Allocator(wide_host).allocate(
            make_claim(wide_host, "d1", [self.chip_req("t")]), node_name="host0"
        )
        chosen = a1.status.allocation.devices.results[0].device
        Allocator(wide_host).deallocate(a1)
        a2 = Allocator(wide_host).allocate(
            make_claim(wide_host, "d2", [self.chip_req("t")]), node_name="host0"
        )
        assert a2.status.allocation.devices.results[0].device == chosen
