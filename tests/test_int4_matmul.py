"""Fused int4 dequant-dot kernel (ops/int4_matmul.py): the opt-in
throughput path for weight-only int4.  Interpret mode on CPU; the bench's
decode_int4 block A/Bs it on the real chip (TPU_INT4_KERNEL=1)."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_dra_driver_tpu.models.quant import Quantized4Matrix
from k8s_dra_driver_tpu.ops import int4_matmul as i4


def _qm(k=256, n=256, gs=64, dtype=jnp.float32, seed=0):
    w = jax.random.normal(jax.random.PRNGKey(seed), (k, n), jnp.float32)
    return Quantized4Matrix.quantize(w, group_size=gs, dtype=dtype)


class TestKernel:
    def test_matches_dequant_dot_f32(self):
        qm = _qm()
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 256), jnp.float32)
        want = x @ qm.dequant()
        got = i4.int4_matmul(x, qm, block_n=128, block_k=128, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_matches_dequant_dot_bf16(self):
        qm = _qm(dtype=jnp.bfloat16)
        x = jax.random.normal(
            jax.random.PRNGKey(2), (16, 256), jnp.float32
        ).astype(jnp.bfloat16)
        want = (x @ qm.dequant()).astype(jnp.float32)
        got = i4.int4_matmul(
            x, qm, block_n=128, block_k=128, interpret=True
        ).astype(jnp.float32)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-2, atol=2e-2
        )

    def test_single_k_tile_is_exact_order(self):
        """With ONE K tile the kernel's accumulation order equals the
        plain dot's — results must be bit-identical, pinning that the
        unpack chain itself introduces no drift."""
        qm = _qm(k=128, n=128)
        x = jax.random.normal(jax.random.PRNGKey(3), (16, 128), jnp.float32)
        want = x @ qm.dequant()
        got = i4.int4_matmul(x, qm, block_n=128, block_k=128, interpret=True)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_leading_shape_and_row_padding(self):
        """[B, S, K] inputs reshape through; a 2-row decode batch rides
        the sublane padding and comes back unpadded."""
        qm = _qm(k=128, n=128)
        x = jax.random.normal(jax.random.PRNGKey(4), (2, 3, 128), jnp.float32)
        want = x @ qm.dequant()
        got = i4.int4_matmul(x, qm, block_n=128, block_k=128, interpret=True)
        assert got.shape == (2, 3, 128)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_multi_tile_grid(self):
        """K and N both larger than one block: the grid accumulates K
        tiles and writes independent N tiles."""
        qm = _qm(k=512, n=384, gs=64)
        x = jax.random.normal(jax.random.PRNGKey(5), (16, 512), jnp.float32)
        want = x @ qm.dequant()
        got = i4.int4_matmul(x, qm, block_n=128, block_k=128, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )


class TestFit:
    def test_fits_standard_shapes(self):
        assert i4.fits(_qm(k=512, n=2048))
        assert i4.fits(_qm(k=2048, n=512))

    def test_unfittable_narrow_n(self):
        assert not i4.fits(_qm(k=128, n=64))  # N below one lane tile

    def test_block_clamp_to_group_multiple(self):
        bk, bn = i4._fit_blocks(k=192, n=256, group_size=64,
                                block_n=256, block_k=512)
        assert bk in (64, 192) and 192 % bk == 0 and bk % 64 == 0
        assert bn in (128, 256) and 256 % bn == 0

    def test_matmul_last_seam_gated_off_by_default(self, monkeypatch):
        """The kernel opt-in must not leak into default quantization —
        the engine bit-exactness contract depends on the XLA path."""
        from k8s_dra_driver_tpu.models import burnin, quant

        cfg = burnin.ModelConfig(
            vocab_size=61, d_model=64, n_heads=4, n_layers=1, d_ff=128,
            max_seq=16,
        )
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        monkeypatch.delenv("TPU_INT4_KERNEL", raising=False)
        q = quant.quantize_blocks(params, bits=4)
        assert not q["blocks"][0]["qkv"].kernel
        monkeypatch.setenv("TPU_INT4_KERNEL", "1")
        q = quant.quantize_blocks(params, bits=4)
        assert q["blocks"][0]["qkv"].kernel
        q = quant.quantize_blocks(params, bits=4, kernel=False)
        assert not q["blocks"][0]["qkv"].kernel

    def test_kernel_flag_changes_pytree_aux(self):
        """kernel=True must change the treedef (jit cache key) — flipping
        the flag retraces instead of reusing the other path's program."""
        qm_off = _qm(k=128, n=128)
        qm_on = Quantized4Matrix(
            qm_off.packed, qm_off.scale, qm_off.group_size, qm_off.dtype,
            kernel=True,
        )
        t_off = jax.tree_util.tree_structure(qm_off)
        t_on = jax.tree_util.tree_structure(qm_on)
        assert t_off != t_on
