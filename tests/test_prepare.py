"""DeviceState Prepare/Unprepare tests: CDI specs, checkpointing, sharing
managers, config precedence, and compensable rollback."""

import json

import pytest

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.api import API_VERSION
from k8s_dra_driver_tpu.kube.objects import (
    Deployment,
    DeviceClaimConfiguration,
    DeviceRequest,
    OpaqueDeviceConfiguration,
)
from k8s_dra_driver_tpu.plugin.device_state import (
    DeviceState,
    DeviceStateConfig,
    PrepareError,
)
from k8s_dra_driver_tpu.plugin.sharing import SharingError
from tests.test_allocator import (
    SUBSLICE_CLASS,
    TPU_CLASS,
    install_classes,
    make_claim,
    publish_host,
    sel,
)


def daemon_controller(server):
    """Simulates the kubelet/deployment controller: marks topology-daemon
    Deployments ready as soon as they appear."""

    def on_event(event):
        dep = event.object
        if event.type in ("ADDED",) and not (dep.status or {}).get("readyReplicas"):
            dep.status = {"readyReplicas": 1}
            server.update(dep)

    return server.watch(Deployment.KIND, on_event)


@pytest.fixture
def cluster(api_server):
    install_classes(api_server)
    publish_host(api_server)
    return api_server


@pytest.fixture
def state(cluster, tmp_path):
    return DeviceState(
        cluster,
        DeviceStateConfig(
            node_name="host0",
            cdi_root=str(tmp_path / "cdi"),
            checkpoint_path=str(tmp_path / "checkpoint.json"),
            topology_env={"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"},
            daemon_backoff_initial=0.001,
            daemon_backoff_steps=2,
        ),
    )


def allocate(cluster, name, requests, config=None):
    from k8s_dra_driver_tpu.scheduler.allocator import Allocator

    claim = make_claim(cluster, name, requests)
    if config:
        claim.spec.devices.config = config
        claim = cluster.update(claim)
    return Allocator(cluster).allocate(claim, node_name="host0")


def opaque(parameters, requests=()):
    return DeviceClaimConfiguration(
        requests=list(requests),
        opaque=OpaqueDeviceConfiguration(driver=DRIVER_NAME, parameters=parameters),
    )


class TestExclusivePrepare:
    def test_single_chip(self, cluster, state, tmp_path):
        claim = allocate(cluster, "c1", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])
        devices = state.prepare(claim)
        assert len(devices) == 1
        d = devices[0]
        assert d["pool_name"] == "host0"
        assert d["device_name"].startswith("tpu-")
        assert len(d["cdi_device_ids"]) == 2
        assert d["cdi_device_ids"][0].startswith("k8s.tpu.google.com/tpu=")
        spec_path = tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json"
        spec = json.loads(spec_path.read_text())
        env = dict(e.split("=", 1) for e in spec["devices"][0]["containerEdits"]["env"])
        assert env["TPU_VISIBLE_DEVICES"] in {"0", "1", "2", "3"}

    def test_base_spec_has_all_devices(self, state, tmp_path):
        base = json.loads((tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-base.json").read_text())
        names = {d["name"] for d in base["devices"]}
        assert {"tpu-0", "tpu-slice-2x2-0-0"} <= names
        assert base["kind"] == "k8s.tpu.google.com/tpu"
        # chips carry their device node
        chip = [d for d in base["devices"] if d["name"] == "tpu-0"][0]
        assert chip["containerEdits"]["deviceNodes"] == [{"path": "/dev/accel0"}]

    def test_subslice_bounds_env(self, cluster, state, tmp_path):
        claim = allocate(
            cluster,
            "c2",
            [
                DeviceRequest(
                    name="s",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x2'")],
                )
            ],
        )
        state.prepare(claim)
        spec = json.loads(
            (tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json").read_text()
        )
        env = dict(e.split("=", 1) for e in spec["devices"][0]["containerEdits"]["env"])
        assert env["TPU_VISIBLE_DEVICES"] == "0,1,2,3"
        assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,2,1"
        assert env["TPU_PROCESS_BOUNDS"] == "1,1,1"

    def test_idempotent_and_checkpoint_restore(self, cluster, state, tmp_path):
        claim = allocate(cluster, "c3", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])
        first = state.prepare(claim)
        assert state.prepare(claim) == first

        # a fresh DeviceState (plugin restart) restores from checkpoint
        restarted = DeviceState(
            cluster,
            DeviceStateConfig(
                node_name="host0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "checkpoint.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
            ),
        )
        assert restarted.prepare(claim) == first
        assert restarted.prepared_claim_uids() == [claim.metadata.uid]

    def test_unprepare_removes_state(self, cluster, state, tmp_path):
        claim = allocate(cluster, "c4", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])
        state.prepare(claim)
        state.unprepare(claim.metadata.uid)
        assert state.prepared_claim_uids() == []
        assert not (
            tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json"
        ).exists()
        state.unprepare(claim.metadata.uid)  # idempotent

    def test_prepare_unallocated_claim_fails(self, cluster, state):
        claim = make_claim(cluster, "c5", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])
        with pytest.raises(PrepareError, match="no allocation"):
            state.prepare(claim)


class TestSharingConfigs:
    def test_time_slicing_from_claim_config(self, cluster, state, tmp_path):
        claim = allocate(
            cluster,
            "ts",
            [DeviceRequest(name="t", device_class_name=TPU_CLASS)],
            config=[
                opaque(
                    {
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {
                            "strategy": "TimeSlicing",
                            "timeSlicingConfig": {"interval": "Long"},
                        },
                    }
                )
            ],
        )
        state.prepare(claim)
        spec = json.loads(
            (tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json").read_text()
        )
        env = dict(e.split("=", 1) for e in spec["devices"][0]["containerEdits"]["env"])
        assert env["TPU_SHARING_STRATEGY"] == "time-slicing"
        assert env["TPU_QUEUE_QUANTUM_MS"] == "20"

    def test_quantum_table_maps_four_intervals_to_four_distinct_quanta(self):
        # sharing.go:34-39 gives the four named intervals four distinct
        # timeslice values; round 1 shipped Default==Medium by typo.
        from k8s_dra_driver_tpu.api.sharing import TimeSliceInterval
        from k8s_dra_driver_tpu.plugin.sharing import _QUANTUM_MS

        quanta = [_QUANTUM_MS[i.level()] for i in TimeSliceInterval]
        assert len(quanta) == 4
        assert len(set(quanta)) == 4, f"named intervals share a quantum: {quanta}"

    def test_spatial_partition_spawns_daemon(self, cluster, state):
        watch = daemon_controller(cluster)
        claim = allocate(
            cluster,
            "sp",
            [DeviceRequest(name="t", device_class_name=TPU_CLASS, count=2)],
            config=[
                opaque(
                    {
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {
                            "strategy": "SpatialPartition",
                            "spatialPartitionConfig": {"defaultHbmLimit": "4Gi"},
                        },
                    }
                )
            ],
        )
        state.prepare(claim)
        daemons = cluster.list(Deployment.KIND, namespace="tpu-dra-driver")
        assert len(daemons) == 1
        assert daemons[0].metadata.name.startswith("tpu-topology-daemon-")
        # teardown deletes the daemon
        state.unprepare(claim.metadata.uid)
        assert cluster.list(Deployment.KIND, namespace="tpu-dra-driver") == []
        watch.stop()

    def test_spatial_partition_divides_chips_disjointly(self, cluster, state, tmp_path):
        """The MPS-division analog (sharing.go:346-366): a multi-container
        claim over 4 chips must hand each consumer a DISJOINT env slot in a
        process grid derived from real chip coordinates — not the same
        'all four chips' view (round-1 weakness #3)."""
        watch = daemon_controller(cluster)
        claim = allocate(
            cluster,
            "sp-div",
            [DeviceRequest(name="t", device_class_name=TPU_CLASS, count=4)],
            config=[
                opaque(
                    {
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {
                            "strategy": "SpatialPartition",
                            "spatialPartitionConfig": {"defaultHbmLimit": "4Gi"},
                        },
                    }
                )
            ],
        )
        state.prepare(claim)
        spec = json.loads(
            (tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json").read_text()
        )
        assert len(spec["devices"]) == 4
        envs = [
            dict(e.split("=", 1) for e in d["containerEdits"]["env"])
            for d in spec["devices"]
        ]
        # v5e-16 host block is 2x2: the process grid must reflect the real
        # coordinates, each consumer seeing exactly one chip of it.
        visible = [e["TPU_VISIBLE_DEVICES"] for e in envs]
        assert sorted(visible) == ["0", "1", "2", "3"]  # disjoint singletons
        coords = {e["TPU_PROCESS_COORD"] for e in envs}
        assert coords == {"0,0,0", "1,0,0", "0,1,0", "1,1,0"}
        for e in envs:
            assert e["TPU_PROCESS_BOUNDS"] == "2,2,1"
            assert e["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
            assert e["TPU_HBM_LIMIT_MIB"] == "4096"
            assert e["TPU_SHARING_STRATEGY"] == "spatial-partition"
        # the daemon Deployment carries the matching partition table
        daemons = cluster.list(Deployment.KIND, namespace="tpu-dra-driver")
        env_list = daemons[0].spec["template"]["spec"]["containers"][0]["env"]
        env_map = {e["name"]: e["value"] for e in env_list}
        assert env_map["TPU_PARTITION_SPEC"] == "2,2,1"
        table = json.loads(env_map["TPU_PARTITIONS"])
        assert [p["index"] for p in table] == [0, 1, 2, 3]
        assert sorted(p["visible_devices"] for p in table) == ["0", "1", "2", "3"]
        # checkpoint round-trips the division (plugin restart keeps it)
        restarted = DeviceState(
            cluster,
            DeviceStateConfig(
                node_name="host0",
                cdi_root=str(state.config.cdi_root),
                checkpoint_path=str(state.config.checkpoint_path),
                topology_env=state.config.topology_env,
            ),
        )
        group = restarted.prepared[claim.metadata.uid].groups[0]
        assert len(group.config_state.per_device_env) == 4
        state.unprepare(claim.metadata.uid)
        watch.stop()

    def test_time_slicing_env_names_host_daemon_socket(self, cluster, state, tmp_path):
        """TimeSlicing's motor is the host-mode daemon sidecar: consumers
        must be handed its socket (round-1 weakness: quantum env had no
        consumer)."""
        claim = allocate(
            cluster,
            "ts-sock",
            [DeviceRequest(name="t", device_class_name=TPU_CLASS)],
            config=[
                opaque(
                    {
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {"strategy": "TimeSlicing"},
                    }
                )
            ],
        )
        state.prepare(claim)
        spec = json.loads(
            (tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json").read_text()
        )
        env = dict(e.split("=", 1) for e in spec["devices"][0]["containerEdits"]["env"])
        assert env["TPU_TOPOLOGY_DAEMON_SOCKET"].endswith("/host.sock")
        # the socket dir must actually be bind-mounted into the consumer —
        # env naming a path that doesn't exist in the container is dead wiring
        mounts = spec["devices"][0]["containerEdits"]["mounts"]
        assert any(m["containerPath"] == "/run/tpu-topology" for m in mounts)

    def test_spatial_partition_rollback_on_unready_daemon(self, cluster, state, tmp_path):
        # No daemon controller -> readiness never arrives -> prepare fails and
        # compensable undo removes the daemon Deployment; nothing checkpointed.
        claim = allocate(
            cluster,
            "sp-fail",
            [DeviceRequest(name="t", device_class_name=TPU_CLASS)],
            config=[
                opaque(
                    {
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {"strategy": "SpatialPartition"},
                    }
                )
            ],
        )
        with pytest.raises(SharingError, match="did not become ready"):
            state.prepare(claim)
        assert cluster.list(Deployment.KIND, namespace="tpu-dra-driver") == []
        assert state.prepared_claim_uids() == []
        assert not (
            tmp_path / "cdi" / f"k8s.{DRIVER_NAME}-claim-{claim.metadata.uid}.json"
        ).exists()

    def test_class_config_overridden_by_claim_config(self, cluster, state):
        # Simulate a class-level TimeSlicing default overridden by the
        # claim's Exclusive config: reverse-precedence scan must pick the
        # claim's (device_state.go:225-259).
        claim = allocate(cluster, "prec", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])
        from k8s_dra_driver_tpu.kube.objects import DeviceAllocationConfiguration

        claim.status.allocation.devices.config = [
            DeviceAllocationConfiguration(
                source="FromClass",
                opaque=OpaqueDeviceConfiguration(
                    driver=DRIVER_NAME,
                    parameters={
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {"strategy": "TimeSlicing"},
                    },
                ),
            ),
            DeviceAllocationConfiguration(
                source="FromClaim",
                requests=["t"],
                opaque=OpaqueDeviceConfiguration(
                    driver=DRIVER_NAME,
                    parameters={
                        "apiVersion": API_VERSION,
                        "kind": "TpuConfig",
                        "sharing": {"strategy": "Exclusive"},
                    },
                ),
            ),
        ]
        claim = cluster.update(claim)
        state.prepare(claim)
        group = state.prepared[claim.metadata.uid].groups[0]
        assert group.config_state.strategy == "Exclusive"

    def test_config_kind_device_mismatch(self, cluster, state):
        claim = allocate(
            cluster,
            "mismatch",
            [
                DeviceRequest(
                    name="s",
                    device_class_name=SUBSLICE_CLASS,
                    selectors=[sel(f"device.attributes['{DRIVER_NAME}'].shape == '2x2'")],
                )
            ],
            config=[
                opaque(
                    {"apiVersion": API_VERSION, "kind": "TpuConfig"},
                    requests=["s"],
                )
            ],
        )
        with pytest.raises(PrepareError, match="cannot apply"):
            state.prepare(claim)

    def test_foreign_driver_config_ignored(self, cluster, state):
        claim = allocate(cluster, "foreign", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])
        from k8s_dra_driver_tpu.kube.objects import DeviceAllocationConfiguration

        claim.status.allocation.devices.config = [
            DeviceAllocationConfiguration(
                source="FromClaim",
                opaque=OpaqueDeviceConfiguration(
                    driver="gpu.nvidia.com", parameters={"kind": "GpuConfig"}
                ),
            )
        ]
        claim = cluster.update(claim)
        state.prepare(claim)  # must not try to decode the foreign config
        assert state.prepared[claim.metadata.uid].groups[0].config_state.strategy == "Exclusive"


class TestCheckpointFailureRecovery:
    def test_prepare_checkpoint_write_failure_is_not_stale_success(
        self, cluster, state, monkeypatch
    ):
        claim = allocate(cluster, "cpfail", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])

        def boom(_):
            raise OSError("disk full")

        monkeypatch.setattr(state._checkpoint, "write", boom)
        with pytest.raises(OSError):
            state.prepare(claim)
        # the idempotence fast-path must NOT now report success
        assert state.prepared_claim_uids() == []
        monkeypatch.undo()
        devices = state.prepare(claim)  # retry succeeds for real
        assert devices and state.prepared_claim_uids() == [claim.metadata.uid]

    def test_unprepare_checkpoint_write_failure_keeps_entry_for_retry(
        self, cluster, state, monkeypatch
    ):
        claim = allocate(cluster, "upfail", [DeviceRequest(name="t", device_class_name=TPU_CLASS)])
        state.prepare(claim)

        def boom(_):
            raise OSError("disk full")

        monkeypatch.setattr(state._checkpoint, "write", boom)
        with pytest.raises(OSError):
            state.unprepare(claim.metadata.uid)
        assert state.prepared_claim_uids() == [claim.metadata.uid]
        monkeypatch.undo()
        state.unprepare(claim.metadata.uid)  # retry completes
        assert state.prepared_claim_uids() == []


class TestCheckpointIntegrity:
    def test_corrupt_checkpoint_detected(self, tmp_path):
        from k8s_dra_driver_tpu.plugin.checkpoint import CheckpointFile, CorruptCheckpoint

        cp = CheckpointFile(tmp_path / "checkpoint.json")
        cp.write({"uid1": {"uid": "uid1"}})
        assert cp.read() == {"uid1": {"uid": "uid1"}}
        raw = (tmp_path / "checkpoint.json").read_text().replace("uid1", "uid2")
        (tmp_path / "checkpoint.json").write_text(raw)
        with pytest.raises(CorruptCheckpoint, match="checksum"):
            cp.read()

    def test_v1_checkpoint_migrates_on_write(self, tmp_path):
        """Upgrade path: a round-1/2 (v1) file reads transparently — same
        claims, checksum still enforced — and the next write upgrades the
        schema in place, stamping the writer version."""
        import hashlib
        import json

        from k8s_dra_driver_tpu.plugin.checkpoint import CheckpointFile
        from k8s_dra_driver_tpu.version import __version__

        claims = {"uid1": {"uid": "uid1"}}
        payload = json.dumps(claims, sort_keys=True)
        v1 = {
            "version": "v1",
            "checksum": hashlib.sha256(payload.encode()).hexdigest(),
            "preparedClaims": claims,
        }
        path = tmp_path / "checkpoint.json"
        path.write_text(json.dumps(v1))
        cp = CheckpointFile(path)
        assert cp.read() == claims
        assert cp.writer_version == ""  # v1 predates the field
        cp.write(claims)
        doc = json.loads(path.read_text())
        assert doc["version"] == "v2"
        assert doc["writerVersion"] == __version__
        assert cp.read() == claims
        assert cp.writer_version == __version__

    def test_v1_checksum_still_enforced(self, tmp_path):
        import hashlib
        import json

        from k8s_dra_driver_tpu.plugin.checkpoint import CheckpointFile, CorruptCheckpoint

        v1 = {
            "version": "v1",
            "checksum": hashlib.sha256(b"{}").hexdigest(),
            "preparedClaims": {"uid9": {}},  # does not match the checksum
        }
        path = tmp_path / "checkpoint.json"
        path.write_text(json.dumps(v1))
        with pytest.raises(CorruptCheckpoint, match="checksum"):
            CheckpointFile(path).read()

    def test_future_version_fails_loudly(self, tmp_path):
        """Downgrade safety: a v3 file written by a newer build must refuse
        to load, not silently drop fields the newer schema depends on."""
        import json

        from k8s_dra_driver_tpu.plugin.checkpoint import CheckpointFile, CorruptCheckpoint

        path = tmp_path / "checkpoint.json"
        path.write_text(json.dumps({"version": "v3", "preparedClaims": {}}))
        with pytest.raises(CorruptCheckpoint, match="unknown checkpoint version 'v3'"):
            CheckpointFile(path).read()
