"""PagedServeEngine: stream parity with the dense engine, pool accounting,
stall/backpressure, wedge detection."""

import jax
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, paged
from k8s_dra_driver_tpu.models.serve import ServeEngine

# max_seq a multiple of block_size so both engines mask the same key width
CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128
)
BS = 16


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _prompts(n, rng=7):
    r = np.random.RandomState(rng)
    return [r.randint(0, CFG.vocab_size, size=r.randint(3, 12)).tolist() for _ in range(n)]


def _streams(engine, reqs, max_steps=10_000):
    """FIFO queue in front of the engine: submit as capacity frees.
    Request ids are assigned in submit order (FIFO in both engines), so
    stream dicts are comparable across engines by id."""
    pending = list(reqs)
    out = {}
    for _ in range(max_steps):
        while pending:
            prompt, max_tokens, temp, seed = pending[0]
            try:
                engine.submit(prompt, max_tokens, temperature=temp, seed=seed)
                pending.pop(0)
            except RuntimeError:
                break
        stepped = engine.step()
        for c in engine.completions():
            out[c.request_id] = c.generated
        if not pending and stepped == 0 and engine.free_slots() == engine.n_slots:
            return out
    raise RuntimeError("queue did not drain")


class TestParityWithDense:
    def test_greedy_streams_identical(self, params):
        reqs = [(p, 12, 0.0, i) for i, p in enumerate(_prompts(5))]
        dense = ServeEngine(params=params, cfg=CFG, n_slots=3, prompt_bucket=16)
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=3, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        assert _streams(dense, reqs) == _streams(pag, reqs)

    def test_sampled_streams_identical(self, params):
        reqs = [(p, 8, 0.8, 100 + i) for i, p in enumerate(_prompts(4, rng=11))]
        dense = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=16)
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        assert _streams(dense, reqs) == _streams(pag, reqs)

    def test_eos_retires_early(self, params):
        # find the greedy continuation's 3rd token and use it as eos
        dense = ServeEngine(params=params, cfg=CFG, n_slots=1, prompt_bucket=16)
        prompt = _prompts(1)[0]
        dense.submit(prompt, 10)
        dense.run_until_drained()
        stream = dense.completions()[0].generated
        eos = stream[2]
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla", eos_id=eos,
        )
        pag.submit(prompt, 10)
        pag.run_until_drained()
        want = stream[: stream.index(eos) + 1]  # first eos occurrence wins
        assert pag.completions()[0].generated == want


class TestPreemption:
    """preempt_on_stall: a pool too small for the resident set evicts the
    youngest request (recompute-style) instead of wedging, and the
    re-admitted stream continues bit-exactly."""

    # two 6-token prompts, long generations: both slots outgrow a 7-block
    # pool (bs=4) mid-flight — one request alone needs 7 blocks to finish,
    # so the only way through is evicting the other and resuming it after
    REQS = [([1, 2, 3, 4, 5, 6], 20), ([7, 8, 9, 10, 11, 12], 20)]

    def _run(self, params, *, n_blocks, preempt, temperature=0.0, **kw):
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=n_blocks,
            block_size=4, prompt_bucket=32, preempt_on_stall=preempt, **kw,
        )
        for prompt, mt in self.REQS:
            eng.submit(prompt, mt, temperature=temperature, seed=11)
        eng.run_until_drained()
        out = {c.request_id: c.generated for c in eng.completions()}
        return eng, out

    def test_streams_survive_preemption(self):
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        _, want = self._run(params, n_blocks=40, preempt=False)  # roomy pool
        eng, got = self._run(params, n_blocks=8, preempt=True)   # starved
        assert eng.preempted_count > 0  # the scenario actually preempted
        assert got == want

    def test_sampled_streams_survive_preemption(self):
        """Temperature > 0: the parked base key + fold-by-position step
        keys must reproduce the identical sampled continuation."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        _, want = self._run(params, n_blocks=40, preempt=False, temperature=0.8)
        eng, got = self._run(params, n_blocks=8, preempt=True, temperature=0.8)
        assert eng.preempted_count > 0
        assert got == want

    def test_submit_cannot_starve_parked_requests(self):
        """New submissions are refused while requests sit parked — parked
        work holds no reservation, so without priority an eager caller
        would re-fill every freed slot forever."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=8, block_size=4,
            prompt_bucket=32, preempt_on_stall=True,
        )
        for prompt, mt in self.REQS:
            eng.submit(prompt, mt)
        # step until a preemption happens
        for _ in range(200):
            eng.step()
            if eng.preempted_count:
                break
        assert eng.preempted_count == 1
        # pool still too tight to re-admit: a new submit must be refused
        # in favor of the parked request
        with pytest.raises(RuntimeError, match="preempted requests pending"):
            eng.submit([40, 41, 42], 2)
        eng.run_until_drained()
        out = {c.request_id: len(c.generated) for c in eng.completions()}
        assert out == {0: 20, 1: 20}  # both originals completed in full

    def test_priority_picks_the_victim(self):
        """Eviction targets the LOWEST-priority resumable request — the
        plain youngest-first rule only breaks ties inside a tier.  Here
        the younger request outranks the older one, so the old rule's
        victim (the youngest) must survive."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=8, block_size=4,
            prompt_bucket=32, preempt_on_stall=True,
        )
        eng.submit(self.REQS[0][0], 20, priority=0)   # request 0: low
        eng.submit(self.REQS[1][0], 20, priority=5)   # request 1: high
        for _ in range(200):
            eng.step()
            if eng.preempted_count:
                break
        assert eng.preempted_count == 1
        assert eng._preempted[0]["st"].request_id == 0  # low prio parked
        eng.run_until_drained()
        out = {c.request_id: len(c.generated) for c in eng.completions()}
        assert out == {0: 20, 1: 20}  # parked request still completes fully

    def test_priority_orders_stalls_not_tokens(self):
        """Under a tight pool, block growth serves high priority first —
        but the streams stay bit-identical to an unpressured run."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        prios = [0, 5, 1, 3]
        reqs = [([10 + i, 20 + i, 30 + i], 12) for i in range(4)]

        def run(n_blocks):
            eng = paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=2, n_blocks=n_blocks,
                block_size=4, prompt_bucket=32, preempt_on_stall=True,
            )
            pending = list(zip(reqs, prios))
            out = {}
            for _ in range(500):
                while pending:
                    (prompt, mt), pr = pending[0]
                    try:
                        eng.submit(prompt, mt, priority=pr)
                        pending.pop(0)
                    except RuntimeError:
                        break
                stepped = eng.step()
                for c in eng.completions():
                    out[c.request_id] = c.generated
                if (not pending and stepped == 0
                        and eng.free_slots() == eng.n_slots
                        and not eng._preempted):
                    return out
            raise RuntimeError("did not drain")

        assert run(n_blocks=64) == run(n_blocks=9)

    def test_readmission_drains_high_priority_first(self):
        """Multiple parked requests re-admit priority-first (FIFO within a
        tier), not in park order."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=3, n_blocks=10, block_size=4,
            prompt_bucket=32, preempt_on_stall=True,
        )
        eng.submit([1, 2, 3, 4, 5, 6], 20, priority=2)
        eng.submit([7, 8, 9, 10, 11, 12], 20, priority=0)
        eng.submit([13, 14, 15, 16, 17, 18], 20, priority=1)
        for _ in range(400):
            eng.step()
            if len(eng._preempted) >= 2:
                break
        prios = [r["priority"] for r in eng._preempted]
        assert prios == sorted(prios, reverse=True)  # high first in queue
        eng.run_until_drained()
        assert {c.request_id for c in eng.completions()} == {0, 1, 2}

    def test_disabled_still_wedges(self):
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        with pytest.raises(RuntimeError, match="wedged"):
            self._run(params, n_blocks=8, preempt=False)

    def test_unpreemptable_when_grown_past_bucket_wedges(self):
        """Requests grown beyond prompt_bucket cannot re-prefill in one
        pass; with every resident unpreemptable the wedge error stands."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=8, block_size=4,
            prompt_bucket=8, preempt_on_stall=True,
        )
        for prompt, mt in self.REQS:
            eng.submit(prompt, mt, temperature=0.0)
        with pytest.raises(RuntimeError, match="wedged"):
            eng.run_until_drained()


class TestTpuBlockSizeGuard:
    def test_unaligned_block_size_fails_at_construction(self, params, monkeypatch):
        """On a TPU backend the kernel path's DMA needs lane-tile-exact
        blocks; the engine must say so at construction, not deep inside
        the first submit()'s trace."""
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        with pytest.raises(ValueError, match="128"):
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=1, n_blocks=9, block_size=16
            )
        # explicit xla fallback keeps small blocks usable
        paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=9, block_size=16,
            attn_impl="xla",
        )


class TestPoolAccounting:
    def test_blocks_freed_on_retirement(self, params):
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=20, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        before = pag.free_blocks
        for p in _prompts(4):
            pag.submit(p, 6)
            pag.run_until_drained()
        assert pag.free_blocks == before
        assert np.all(np.asarray(pag._table) == paged.NULL_BLOCK)

    def test_capacity_is_tokens_not_slots(self, params):
        """Pool of 9 usable blocks (144 tokens) serves 4 requests whose
        dense reservation would be 4 x 128 = 512 token rows."""
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=4, n_blocks=10, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        reqs = [(p, 10, 0.0, i) for i, p in enumerate(_prompts(4))]
        dense = ServeEngine(params=params, cfg=CFG, n_slots=4, prompt_bucket=16)
        assert _streams(pag, reqs) == _streams(dense, reqs)

    def test_admission_rejects_on_empty_pool(self, params):
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=4, n_blocks=3, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        pag.submit(list(range(10)), 30)  # holds 1 block, grows later
        with pytest.raises(RuntimeError, match="no free blocks"):
            pag.submit(list(range(16)), 4)  # needs 2 blocks, 1 free

    def test_stall_and_resume(self, params):
        """When the pool momentarily empties, growing slots stall (not
        overrun) and resume after a retirement frees blocks — streams still
        exactly match the dense engine's."""
        # 3 usable blocks.  A (10+20 toks, 2 blocks) grabs the third block
        # at its step 6; B (5+40 toks, 3 blocks) hits its first boundary at
        # step 11 with the pool empty -> stalls until A retires at step 19.
        reqs = [
            (list(np.arange(10) % CFG.vocab_size), 20, 0.0, 0),
            (list((np.arange(5) + 17) % CFG.vocab_size), 40, 0.0, 1),
        ]
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=4, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        dense = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=16)
        assert _streams(pag, reqs) == _streams(dense, reqs)
        assert pag.stalled_steps > 0

    def test_wedge_detected(self, params):
        """A single resident request that outgrows the whole pool cannot
        make progress — the engine says so instead of spinning."""
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=2, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        pag.submit(list(range(10)), 60)  # needs 5 blocks eventually, has 1
        with pytest.raises(RuntimeError, match="wedged"):
            pag.run_until_drained()

    def test_prefix_cache_streams_identical(self, params):
        """Block-level prefix sharing changes residency and admission
        compute, never tokens: same streams with caching on and off."""
        sys_prefix = list(np.arange(32) % CFG.vocab_size)  # 2 full blocks
        reqs = [
            (sys_prefix + [5, 7, 9], 10, 0.0, 0),
            (sys_prefix + [11, 2], 10, 0.0, 1),
            (sys_prefix + [3], 8, 0.9, 2),
        ]
        off = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=30, block_size=BS,
            prompt_bucket=48, attn_impl="xla",
        )
        on = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=30, block_size=BS,
            prompt_bucket=48, attn_impl="xla", prefix_cache_blocks=4,
        )
        assert _streams(off, reqs) == _streams(on, reqs)
        assert on.prefix_hits > 0 and on.prefix_misses > 0

    def test_prefix_blocks_shared_not_recomputed(self, params):
        """Two live requests with a common 2-block prefix consume the
        prefix blocks ONCE; the store keeps them after both retire."""
        sys_prefix = list(np.arange(32) % CFG.vocab_size)
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=30, block_size=BS,
            prompt_bucket=48, attn_impl="xla", prefix_cache_blocks=4,
        )
        before = eng.free_blocks
        eng.submit(sys_prefix + [1, 2, 3], 40)
        used_first = before - eng.free_blocks
        eng.submit(sys_prefix + [4, 5], 40)
        used_second = (before - eng.free_blocks) - used_first
        # second request shares the 2 prefix blocks: only its own suffix +
        # growth blocks are newly drawn
        assert used_second == used_first - 2
        shared_id = eng._prefix_store[tuple(sys_prefix[:BS])]
        assert eng._alloc.refcount(shared_id) == 3  # store + both slots
        eng.run_until_drained()
        # slots retired: store still holds one ref per cached block
        assert eng._alloc.refcount(shared_id) == 1
        assert eng.free_blocks == before - len(eng._prefix_store)

    def test_prefix_store_lru_eviction_frees_blocks(self, params):
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=30, block_size=BS,
            prompt_bucket=48, attn_impl="xla", prefix_cache_blocks=2,
        )
        baseline = eng.free_blocks
        for seed in range(4):  # distinct full-block prefixes (plen > bs:
            # the block holding plen-1 is never stored, so a storable
            # block needs at least bs+1 prompt tokens)
            prompt = list((np.arange(20) + 7 * seed) % CFG.vocab_size)
            eng.submit(prompt, 2)
            eng.run_until_drained()
        assert len(eng._prefix_store) == 2  # LRU capped
        assert eng.free_blocks == baseline - 2  # evicted entries freed

    def test_chunked_prefill_streams_identical(self, params):
        """Chunked admission changes WHEN prefill compute runs, never the
        tokens: same streams with chunking on and off (greedy + sampled)."""
        reqs = [(p, 10, t, i) for i, (p, t) in enumerate(
            zip(_prompts(5, rng=13), [0.0, 0.8, 0.0, 1.1, 0.0])
        )]
        plain = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=48, attn_impl="xla",
        )
        chunked = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=48, attn_impl="xla", prefill_chunk_blocks=1,
        )
        assert _streams(plain, reqs) == _streams(chunked, reqs)

    def test_decode_interleaves_with_admission(self, params):
        """The Sarathi property: resident requests keep generating while a
        prompt admits chunk by chunk — no head-of-line blocking."""
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=48, attn_impl="xla", prefill_chunk_blocks=1,
        )
        eng.submit(_prompts(1)[0], 30)
        while eng._admitting:  # admit A fully
            eng.step()
        a_slot = next(i for i, s in enumerate(eng._slots) if s is not None)
        eng.submit(list(np.arange(40) % CFG.vocab_size), 5)  # 3-chunk admission
        assert eng._admitting
        before = len(eng._slots[a_slot].tokens)
        eng.step()
        assert eng._admitting  # B still admitting...
        assert len(eng._slots[a_slot].tokens) == before + 1  # ...A advanced

    def test_chunked_composes_with_prefix_cache(self, params):
        """Shared prefix blocks count as already-done chunks: the second
        admission needs fewer steps AND produces identical tokens."""
        sys_prefix = list(np.arange(32) % CFG.vocab_size)  # 2 full blocks
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=40, block_size=BS,
            prompt_bucket=48, attn_impl="xla", prefill_chunk_blocks=1,
            prefix_cache_blocks=4,
        )

        def admit_steps(prompt):
            eng.submit(prompt, 6)
            n = 0
            while eng._admitting:
                eng.step()
                n += 1
            eng.run_until_drained()
            return n, eng.completions()[0].generated

        n1, gen1 = admit_steps(sys_prefix + [5, 7])
        n2, gen2 = admit_steps(sys_prefix + [5, 7])
        assert n2 < n1  # 2 of 3 chunks came from the store
        assert gen1 == gen2

    def test_spec_streams_identical_to_plain_paged(self, params):
        """Speculative rounds over the PAGED cache: same tokens as the
        plain paged engine (and hence the dense one)."""
        reqs = [(p, 12, 0.0, i) for i, p in enumerate(_prompts(4, rng=21))]
        plain = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        spec = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla", spec_gamma=3,
        )
        assert _streams(plain, reqs) == _streams(spec, reqs)

    def test_spec_composes_with_prefix_and_chunked(self, params):
        sys_prefix = list(np.arange(32) % CFG.vocab_size)
        # the 4th request admits after a retirement, when the store is
        # populated (concurrent admissions can't hit a store that fills at
        # activation)
        reqs = [(sys_prefix + [5, 7], 10, 0.0, 0), (sys_prefix + [9], 10, 0.0, 1),
                ([3, 1], 12, 0.0, 2), (sys_prefix + [12], 8, 0.0, 3)]
        plain = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=48, attn_impl="xla",
        )
        fancy = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=48, attn_impl="xla", spec_gamma=2,
            prefix_cache_blocks=4, prefill_chunk_blocks=1,
        )
        assert _streams(plain, reqs) == _streams(fancy, reqs)
        assert fancy.prefix_hits > 0

    def test_spec_full_acceptance_grows_blocks(self, params):
        """Self-draft with target weights: gamma+1 tokens per round across
        block boundaries, pool fully returned after drain."""
        gamma = 3
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=20, block_size=4,
            prompt_bucket=16, attn_impl="xla", spec_gamma=gamma,
            draft_params=params,
        )
        before = eng.free_blocks
        eng.submit(_prompts(1)[0], 21)
        rounds = 0
        while eng.free_slots() < eng.n_slots:
            eng.step()
            rounds += 1
        assert rounds == -(-(21 - 1) // (gamma + 1))
        assert eng.free_blocks == before
        plain = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=20, block_size=4,
            prompt_bucket=16, attn_impl="xla",
        )
        plain.submit(_prompts(1)[0], 21)
        plain.run_until_drained()
        assert (
            eng.completions()[0].generated == plain.completions()[0].generated
        )

    def test_spec_with_int4_draft(self, params):
        """The int4 self-draft through the paged spec engine: quantization
        error moves acceptance only — streams stay identical."""
        from k8s_dra_driver_tpu.models.quant import quantize_blocks

        reqs = [(p, 8, 0.0, i) for i, p in enumerate(_prompts(2, rng=41))]
        plain = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        spec4 = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla", spec_gamma=2,
            draft_params=quantize_blocks(params, bits=4),
        )
        assert _streams(plain, reqs) == _streams(spec4, reqs)

    def test_spec_kernel_interpret_path(self, params):
        reqs = [(p, 6, 0.0, i) for i, p in enumerate(_prompts(2, rng=31))]
        plain = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        spec = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="kernel", interpret=True, spec_gamma=2,
        )
        assert _streams(plain, reqs) == _streams(spec, reqs)

    def test_spec_validation(self, params):
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=20, block_size=BS,
            prompt_bucket=16, attn_impl="xla", spec_gamma=4,
        )
        with pytest.raises(ValueError, match="temperature"):
            eng.submit([1, 2, 3], 4, temperature=0.5)
        with pytest.raises(ValueError, match="slack"):
            eng.submit([1, 2, 3], CFG.max_seq - 3)

    def test_metrics_land_in_registry(self, params):
        """The paged backend feeds the SAME serving counters as the dense
        engine (observability parity) plus the pool-free gauge."""
        from k8s_dra_driver_tpu.utils.metrics import REGISTRY

        def sample():
            out = {}
            for line in REGISTRY.render().splitlines():
                if line.startswith("tpu_serve_") and " " in line:
                    name, val = line.rsplit(" ", 1)
                    out[name] = float(val)
            return out

        before = sample()
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=20, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        for p in _prompts(2):
            pag.submit(p, 4)
        pag.run_until_drained()
        after = sample()

        def delta(name):
            return after.get(name, 0) - before.get(name, 0)

        assert delta("tpu_serve_requests_total") == 2
        assert delta("tpu_serve_completions_total") == 2
        assert delta("tpu_serve_tokens_total") == 8
        assert after["tpu_serve_kv_pool_free_blocks"] == 19  # all returned

    def test_validation(self, params):
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=1, n_blocks=20, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        with pytest.raises(ValueError, match="empty"):
            pag.submit([], 4)
        with pytest.raises(ValueError, match="exceeds bucket"):
            pag.submit(list(range(17)), 4)
        with pytest.raises(ValueError, match="max_seq"):
            pag.submit(list(range(10)), 1000)
