"""Chaos suite: the driver stack under injected API-server faults.

The proof side of the robustness subsystem: utils/faults.py makes the API
server misbehave (error storms, conflict storms, dropped connections, watch
outages) and these tests assert the retry/breaker layer (utils/retry.py)
converges — zero lost claims, consistent checkpoints, healed ResourceSlices
— with the retries observable on metrics and in the journal.

Every test draws faults from a seeded RNG: a failure replays from its seed.
Runs in `make chaos` (<10s).
"""

import time

import pytest

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.e2e.harness import install_device_classes, simple_claim
from k8s_dra_driver_tpu.e2e.mock_api import MockKubeAPI
from k8s_dra_driver_tpu.kube.fakeserver import APIError, InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import Device, Node, ObjectMeta
from k8s_dra_driver_tpu.kube.resourceslice_controller import (
    DriverResources,
    Pool,
    ResourceSliceController,
    Slice,
    SliceSyncError,
)
from k8s_dra_driver_tpu.kube.restclient import KubeClientConfig, RESTClient
from k8s_dra_driver_tpu.plugin.driver import ClaimRef, Driver, DriverConfig
from k8s_dra_driver_tpu.scheduler.allocator import Allocator
from k8s_dra_driver_tpu.scheduler.index import AllocationIndex
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY
from k8s_dra_driver_tpu.utils.retry import (
    Backoff,
    CircuitOpenError,
    RetryPolicy,
)

FAKE_TOPOLOGY = {"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"}


@pytest.fixture
def chaos():
    """A mock API whose in-memory store and HTTP facade share one armed
    (initially silent) fault injector."""
    inj = FaultInjector(seed=1234)
    api = MockKubeAPI(server=InMemoryAPIServer(fault_injector=inj)).start()
    yield api, inj
    inj.disarm()
    api.stop()


def fast_client(api, **kw):
    """RESTClient tuned for test time: millisecond backoffs, short watch
    read timeout, quick breaker cooldown."""
    defaults = dict(
        retry_policy=RetryPolicy(max_attempts=6, base_delay_s=0.002, max_delay_s=0.02),
        watch_policy=RetryPolicy(
            max_attempts=0, base_delay_s=0.01, max_delay_s=0.05, jitter=0.0
        ),
        watch_read_timeout_s=2.0,
        breaker_threshold=12,
        breaker_reset_s=0.05,
    )
    defaults.update(kw)
    return RESTClient(
        KubeClientConfig(server=api.url, qps=100000, burst=100000), **defaults
    )


def until_ok(fn, attempts=40):
    """Caller-level reconcile loop (the kubelet/scheduler retry the whole
    operation; declarative state makes replay safe)."""
    bo = Backoff(
        RetryPolicy(
            max_attempts=0, base_delay_s=0.005, max_delay_s=0.05,
            multiplier=1.5, jitter=0.0,
        )
    )
    last = None
    for _ in range(attempts):
        try:
            return fn()
        except Exception as exc:
            last = exc
            bo.sleep()
    raise AssertionError(f"did not converge after {attempts} attempts: {last!r}")


def wait_until(pred, timeout=5.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return pred()


class TestErrorStorm:
    def test_full_lifecycle_converges_at_30_percent_errors(self, chaos, tmp_path):
        """The acceptance scenario: allocate→prepare→unprepare for a batch
        of claims with every API verb failing 30% of the time, plus one
        forced watch outage and a slice republish mid-storm.  Zero lost
        claims, empty prepared set at the end, checkpoint consistent, the
        watch-backed index reconverged, and the retries that healed it
        all visible on the metrics."""
        api, inj = chaos
        install_device_classes(api.server)
        client = fast_client(api)
        cp_path = str(tmp_path / "cp.json")
        driver = Driver(
            client,
            DriverConfig(
                node_name="chaos-host",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=cp_path,
                topology_env=FAKE_TOPOLOGY,
            ),
        )
        assert api.server.list("ResourceSlice")  # published while healthy
        index = AllocationIndex(client, live=True)
        device_names = lambda: {  # noqa: E731
            c.device.name for c in index.snapshot("chaos-host", {}).candidates
        }
        assert wait_until(device_names)
        baseline_devices = device_names()

        inj.arm(FaultProfile(name="storm", error_rate=0.3))
        uids: dict[str, str] = {}
        for i in range(4):  # v5e-16 single host = 4 chips = 4 one-chip claims
            name = f"claim-{i}"
            until_ok(lambda n=name: client.create(simple_claim(n)))
            allocated = until_ok(
                lambda n=name: Allocator(client).allocate(
                    client.get("ResourceClaim", n, "default"),
                    node_name="chaos-host",
                )
            )
            uids[name] = allocated.metadata.uid

            def prepare(n=name, uid=allocated.metadata.uid):
                res = driver.node_prepare_resources(
                    [ClaimRef(uid=uid, name=n, namespace="default")]
                )
                if res[uid].error:
                    raise RuntimeError(res[uid].error)

            until_ok(prepare)

        assert set(driver.state.prepared) == set(uids.values())

        for name, uid in uids.items():

            def unprepare(n=name, uid=uid):
                res = driver.node_unprepare_resources(
                    [ClaimRef(uid=uid, name=n, namespace="default")]
                )
                if res[uid].error:
                    raise RuntimeError(res[uid].error)

            until_ok(unprepare)

        # the forced watch outage, still mid-storm: every stream dies, a
        # slice republish happens in the gap (degrading, never raising),
        # and the watch-backed index reconverges on the full inventory
        for sw in list(api.server._watches):
            sw.stop()
        assert wait_until(driver.publish_resources)
        assert wait_until(lambda: device_names() == baseline_devices)
        index.close()
        inj.disarm()

        # zero lost claims: every claim still allocated exactly once
        for name in uids:
            claim = api.server.get("ResourceClaim", name, "default")
            assert claim.status.allocation is not None
        # clean teardown + checkpoint consistency: a fresh driver restored
        # from the same checkpoint agrees nothing is prepared
        assert driver.state.prepared == {}
        restored = Driver(
            api.server,
            DriverConfig(
                node_name="chaos-host",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=cp_path,
                topology_env=FAKE_TOPOLOGY,
                publish=False,
            ),
        )
        assert restored.state.prepared == {}
        # the storm really happened, and the retry layer healed it
        assert inj.total_injected() > 0
        retries = REGISTRY.counter("dra_api_retries_total")
        assert sum(retries._values.values()) > 0
        retry_events = [
            e for e in JOURNAL.tail(limit=10000, component="retry")
            if e["event"] == "call.retry"
        ]
        assert retry_events


class TestConflictStorm:
    def _device(self, name):
        return Device(name=name)

    def test_reconciler_heals_409_storm(self):
        """Injected PUT conflicts on ResourceSlice are healed by the
        re-get-and-replay loop; bounded by the profile's limit so
        convergence is deterministic."""
        inj = FaultInjector(seed=5)
        server = InMemoryAPIServer(fault_injector=inj)
        ctrl = ResourceSliceController(server, DRIVER_NAME, "host-a")
        ctrl.update(
            DriverResources(
                pools={"p": Pool(slices=[Slice(devices=[self._device("d0")])],
                                 node_name="n0")}
            )
        )
        inj.arm(
            FaultProfile(
                name="conflicts", conflict_rate=1.0,
                verbs=("PUT",), kinds=("ResourceSlice",), limit=3,
            )
        )
        ctrl.update(
            DriverResources(
                pools={"p": Pool(
                    slices=[Slice(devices=[self._device("d0"), self._device("d1")])],
                    node_name="n0",
                )}
            )
        )
        slices = server.list("ResourceSlice")
        assert len(slices) == 1
        assert [d.name for d in slices[0].spec.devices] == ["d0", "d1"]
        assert REGISTRY.counter("dra_slice_sync_retries_total").value() > 0
        conflict_events = [
            e for e in JOURNAL.tail(component="resourceslices")
            if e["event"] == "slice.conflict_retry"
        ]
        assert conflict_events

    def test_partial_reconcile_continues_then_heals(self):
        """One sick slice must not park the whole pass: the failure is
        recorded, every other op still applies, and the summary error is
        retryable — the next pass converges."""
        inj = FaultInjector(seed=2)
        server = InMemoryAPIServer(fault_injector=inj)
        ctrl = ResourceSliceController(server, DRIVER_NAME, "host-b")
        inj.arm(
            FaultProfile(
                name="one-shot", error_rate=1.0,
                verbs=("POST",), kinds=("ResourceSlice",), limit=1,
            )
        )
        resources = DriverResources(
            pools={
                "a": Pool(slices=[Slice(devices=[self._device("a0")])], node_name="n1"),
                "b": Pool(slices=[Slice(devices=[self._device("b0")])], node_name="n2"),
            }
        )
        with pytest.raises(SliceSyncError) as ei:
            ctrl.update(resources)
        assert len(ei.value.failures) == 1
        assert ei.value.code == 503  # retryable classification
        assert len(server.list("ResourceSlice")) == 1  # the pass continued
        ctrl.update(resources)  # next debounce heals the remainder
        assert len(server.list("ResourceSlice")) == 2
        assert REGISTRY.counter("dra_slice_sync_errors_total").value(op="apply") == 1


class TestCircuitBreaker:
    def test_opens_fails_fast_and_recovers(self, chaos):
        api, inj = chaos
        api.server.create(Node(metadata=ObjectMeta(name="n1")))
        client = fast_client(
            api,
            retry_policy=RetryPolicy(
                max_attempts=1, base_delay_s=0.001, max_delay_s=0.005
            ),
            breaker_threshold=3,
            breaker_reset_s=0.05,
        )
        inj.arm(FaultProfile(name="down", error_rate=1.0, kinds=("Node",)))
        for _ in range(3):
            with pytest.raises(APIError):
                client.get("Node", "n1")
        # open: requests short-circuit without reaching the server
        before = inj.total_injected()
        with pytest.raises(CircuitOpenError):
            client.get("Node", "n1")
        assert inj.total_injected() == before
        gauge = REGISTRY.gauge("dra_circuit_state")
        assert gauge.value(endpoint="nodes") == 2  # open
        # outage ends; after the cooldown the half-open probe closes it
        inj.disarm()
        time.sleep(0.06)
        assert client.get("Node", "n1").metadata.name == "n1"
        assert gauge.value(endpoint="nodes") == 0  # closed
        transitions = REGISTRY.counter("dra_circuit_transitions_total")
        assert transitions.value(endpoint="nodes", to="open") == 1
        assert transitions.value(endpoint="nodes", to="closed") == 1


class TestWatchOutage:
    """Scheduler index convergence across watch outages, over real HTTP."""

    def _rig(self, api, tmp_path):
        install_device_classes(api.server)
        Driver(  # publishes chaos-host's slices straight to the store
            api.server,
            DriverConfig(
                node_name="chaos-host",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env=FAKE_TOPOLOGY,
            ),
        )
        client = fast_client(api)
        index = AllocationIndex(client, live=True)
        assert wait_until(lambda: self._names(index))
        return client, index

    def _names(self, index):
        return {c.device.name for c in index.snapshot("chaos-host", {}).candidates}

    def _drop_streams(self, api):
        for sw in list(api.server._watches):
            sw.stop()

    def _extra_slice(self, api, name="extra"):
        from k8s_dra_driver_tpu.kube import objects

        src = api.server.list("ResourceSlice")[0]
        s = objects.deepcopy(src)
        s.metadata.name = name
        s.metadata.uid = ""
        s.metadata.resource_version = ""
        s.spec.pool.name = f"{name}-pool"
        s.spec.devices = [Device(name=f"{name}-dev")]
        return s

    def test_410_on_connect_recovers_through_relist(self, chaos, tmp_path):
        api, inj = chaos
        client, index = self._rig(api, tmp_path)
        baseline = self._names(index)
        # outage: streams die and the next connects answer 410 Gone
        inj.arm(FaultProfile(name="gone", watch_gone=3))
        self._drop_streams(api)
        api.server.create(self._extra_slice(api))  # mutation during outage
        assert wait_until(lambda: "extra-dev" in self._names(index))
        assert baseline <= self._names(index)
        index.close()

    def test_error_frame_mid_stream_recovers(self, chaos, tmp_path):
        api, inj = chaos
        client, index = self._rig(api, tmp_path)
        # scoped to the slice stream: frames shared across all watches can
        # all land on claim/class streams, leaving the asserted slice
        # reconnect counter at zero
        inj.arm(
            FaultProfile(name="frames", watch_error_frames=3,
                         kinds=("ResourceSlice",))
        )
        # frames are injected into the LIVE streams within one poll tick
        assert wait_until(lambda: inj.stats().get("watch_error_frames", 0) >= 1)
        api.server.create(self._extra_slice(api, name="after"))
        assert wait_until(lambda: "after-dev" in self._names(index))
        assert REGISTRY.counter("dra_watch_reconnects_total").value(
            kind="ResourceSlice"
        ) >= 1
        index.close()

    def test_relist_synthesizes_deleted_during_outage(self, chaos, tmp_path):
        """Objects deleted while the watch is down never produce DELETED
        events; the recovery relist must synthesize them or the scheduler
        keeps placing onto vanished devices."""
        api, inj = chaos
        client, index = self._rig(api, tmp_path)
        victim = api.server.list("ResourceSlice")[0].metadata.name
        # A clean stream end reconnects with no delay, so a single 410 can
        # force its relist before the delete below lands.  Arm enough 410s
        # (scoped to the slice watch) that relists keep firing past it.
        inj.arm(FaultProfile(name="gone", watch_gone=12, kinds=("ResourceSlice",)))
        self._drop_streams(api)
        api.server.delete("ResourceSlice", victim)  # vanishes in the gap
        assert wait_until(lambda: self._names(index) == set())
        index.close()


class TestDroppedConnections:
    def test_crud_heals_through_truncated_responses(self, chaos):
        """30% of responses cut mid-body (client sees IncompleteRead) plus
        1ms injected latency: the transport retry layer heals every verb
        with no caller-visible failures."""
        api, inj = chaos
        client = fast_client(api)
        inj.arm(
            FaultProfile(
                name="flaky-net", drop_rate=0.3, latency_s=0.001, limit=40,
            )
        )
        for i in range(10):
            until_ok(
                lambda i=i: client.create(
                    Node(metadata=ObjectMeta(name=f"n{i}", labels={"i": str(i)}))
                )
            )
        assert len(client.list("Node")) == 10
        for i in range(10):
            def touch(i=i):
                n = client.get("Node", f"n{i}")
                n.metadata.labels["touched"] = "1"
                client.update(n)

            until_ok(touch)
        for i in range(10):
            until_ok(lambda i=i: client.delete("Node", f"n{i}"))
        assert client.list("Node") == []
        assert inj.stats().get("drop", 0) > 0
        assert (
            REGISTRY.counter("dra_faults_injected_total").value(
                profile="flaky-net", fault="drop"
            )
            > 0
        )
