"""Randomized equivalence + allocator invariants for the paged stack.

Two layers of assurance beyond the targeted tests:

* a hypothesis STATE MACHINE drives BlockAllocator through arbitrary
  alloc/share/free interleavings against a reference refcount model —
  the free list and refcounts can never drift (the property the prefix
  store and every engine lean on);
* a seeded CHURN harness pushes one randomized request mix through the
  dense engine, the plain paged engine, and the paged engine with EVERY
  feature on (prefix sharing + chunked admission + speculative rounds —
  plus a starved-pool variant with recompute preemption armed) — token
  streams must be identical across all four.  SURVEY.md §4.5: invest in
  the testing the reference never built.
"""

import jax
import numpy as np
import pytest
from hypothesis import settings
from hypothesis import strategies as st
from hypothesis.stateful import RuleBasedStateMachine, invariant, precondition, rule

from k8s_dra_driver_tpu.models import burnin, paged
from k8s_dra_driver_tpu.models.serve import ServeEngine

CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128
)
BS = 16


class AllocatorMachine(RuleBasedStateMachine):
    """BlockAllocator vs a dict-of-refcounts reference model."""

    def __init__(self):
        super().__init__()
        self.n_blocks = 12
        self.alloc = paged.BlockAllocator(self.n_blocks)
        self.refs: dict[int, int] = {}  # block id -> model refcount

    @rule(n=st.integers(min_value=1, max_value=4))
    def allocate(self, n):
        free_before = self.alloc.free_blocks
        if n > free_before:
            with pytest.raises(paged.OutOfBlocks):
                self.alloc.alloc(n)
            return
        ids = self.alloc.alloc(n)
        assert len(set(ids)) == n
        for i in ids:
            assert i not in self.refs, "allocator handed out a held block"
            assert 0 < i < self.n_blocks
            self.refs[i] = 1

    @precondition(lambda self: self.refs)
    @rule(data=st.data())
    def share(self, data):
        i = data.draw(st.sampled_from(sorted(self.refs)))
        self.alloc.share(i)
        self.refs[i] += 1

    @precondition(lambda self: self.refs)
    @rule(data=st.data())
    def free_one(self, data):
        i = data.draw(st.sampled_from(sorted(self.refs)))
        self.alloc.free([i])
        self.refs[i] -= 1
        if self.refs[i] == 0:
            del self.refs[i]

    @rule()
    def free_unheld_is_loud(self):
        unheld = [
            i for i in range(1, self.n_blocks) if i not in self.refs
        ]
        if unheld:
            with pytest.raises(ValueError, match="double free"):
                self.alloc.free([unheld[0]])

    @invariant()
    def conservation(self):
        # every usable block is either free or held, never both/neither
        assert self.alloc.free_blocks + len(self.refs) == self.n_blocks - 1
        for i, n in self.refs.items():
            assert self.alloc.refcount(i) == n

    @invariant()
    def null_block_never_leaves(self):
        assert paged.NULL_BLOCK not in self.refs


TestAllocatorStateMachine = AllocatorMachine.TestCase
TestAllocatorStateMachine.settings = settings(max_examples=40, deadline=None)


class TestEngineChurn:
    def test_randomized_mix_identical_across_engines(self):
        """One seeded workload (shared prefixes, ragged lengths, ragged
        max_tokens) through three engine configurations — identical
        streams.  Greedy throughout (speculation's contract)."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG)
        r = np.random.RandomState(42)
        shared = list(r.randint(0, CFG.vocab_size, size=32))  # 2 full blocks
        reqs = []
        for i in range(14):
            if r.rand() < 0.5:
                prompt = shared + list(
                    r.randint(0, CFG.vocab_size, size=r.randint(1, 8))
                )
            else:
                prompt = list(r.randint(0, CFG.vocab_size, size=r.randint(2, 40)))
            reqs.append((prompt, int(r.randint(1, 20))))

        def drain(eng):
            pending = list(reqs)
            out = {}
            for _ in range(20_000):
                while pending:
                    prompt, max_tokens = pending[0]
                    try:
                        eng.submit(prompt, max_tokens)
                        pending.pop(0)
                    except RuntimeError:
                        break
                stepped = eng.step()
                for c in eng.completions():
                    out[c.request_id] = c.generated
                if (
                    not pending and stepped == 0
                    and not getattr(eng, "_admitting", None)
                    and eng.free_slots() == eng.n_slots
                ):
                    return out
            raise RuntimeError("churn did not drain")

        dense = drain(ServeEngine(params=params, cfg=CFG, n_slots=3, prompt_bucket=48))
        plain = drain(
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=3, n_blocks=60, block_size=BS,
                prompt_bucket=48, attn_impl="xla",
            )
        )
        fancy = drain(
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=3, n_blocks=60, block_size=BS,
                prompt_bucket=48, attn_impl="xla", prefix_cache_blocks=6,
                prefill_chunk_blocks=1, spec_gamma=2,
            )
        )
        # a STARVED pool with every feature on: prefix sharing + chunked
        # admission + speculation + preemption interacting under pressure
        # (pool deliberately below the resident set's worst-case demand)
        starved_eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=3, n_blocks=16, block_size=BS,
            prompt_bucket=48, attn_impl="xla", prefix_cache_blocks=6,
            prefill_chunk_blocks=1, spec_gamma=2, preempt_on_stall=True,
        )
        starved = drain(starved_eng)
        assert dense == plain == fancy == starved
