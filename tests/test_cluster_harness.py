"""Multi-node kind harness: generated cluster config + per-node fake knobs.

The reference's multi-node story needs nvkind + params masking
(values.yaml:41-48); ours is label-driven.  These tests run
create-cluster.sh against a stub `kind` binary that captures the generated
config, and pin the plugin's label-fallback knob resolution."""

import os
import stat
import subprocess
from pathlib import Path

import pytest
import yaml

from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
from k8s_dra_driver_tpu.kube.objects import Node, ObjectMeta
from k8s_dra_driver_tpu.plugin.main import resolve_topology_env

REPO = Path(__file__).parent.parent


class TestCreateClusterScript:
    def generate_config(self, tmp_path, env):
        """Run create-cluster.sh with a stub `kind` that captures stdin."""
        captured = tmp_path / "config.yaml"
        stub = tmp_path / "kind"
        stub.write_text(f"#!/bin/sh\ncat > {captured}\n")
        stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
        subprocess.run(
            [str(REPO / "demo/clusters/kind/create-cluster.sh")],
            env={
                **os.environ,
                "PATH": f"{tmp_path}:{os.environ['PATH']}",
                **env,
            },
            check=True,
            capture_output=True,
        )
        return yaml.safe_load(captured.read_text())

    def test_generates_n_labeled_workers(self, tmp_path):
        cfg = self.generate_config(
            tmp_path, {"NUM_WORKERS": "4", "FAKE_TOPOLOGY": "v5e-16"}
        )
        assert cfg["kind"] == "Cluster"
        assert cfg["featureGates"]["DynamicResourceAllocation"] is True
        roles = [n["role"] for n in cfg["nodes"]]
        assert roles == ["control-plane"] + ["worker"] * 4
        for i, worker in enumerate(cfg["nodes"][1:]):
            labels = worker["labels"]
            assert labels["tpu.google.com/fake-topology"] == "v5e-16"
            assert labels["tpu.google.com/fake-host-id"] == str(i)
            assert labels["tpu.google.com/slice-domain"] == "v5e-16-demo"
            assert labels["tpu.google.com/slice-host-id"] == str(i)
        # CDI must be enabled for kubelet->containerd device injection
        assert "enable_cdi = true" in cfg["containerdConfigPatches"][0]

    def generate_split_config(self, tmp_path, env):
        captured = tmp_path / "config.yaml"
        stub = tmp_path / "kind"
        stub.write_text(f"#!/bin/sh\ncat > {captured}\n")
        stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
        subprocess.run(
            [str(REPO / "demo/clusters/kind/create-split-host-cluster.sh")],
            env={**os.environ, "PATH": f"{tmp_path}:{os.environ['PATH']}", **env},
            check=True, capture_output=True,
        )
        return yaml.safe_load(captured.read_text())

    def test_split_host_variant_generates_disjoint_masks(self, tmp_path):
        """The nvkind analog: N workers impersonate ONE host with
        complementary '.'-separated visible-chips labels, and the masks
        exactly tile the host's chips with no overlap."""
        cfg = self.generate_split_config(
            tmp_path, {"NUM_SPLITS": "2", "FAKE_TOPOLOGY": "v5e-8",
                       "CHIPS_PER_HOST": "4"}
        )
        workers = [n for n in cfg["nodes"] if n["role"] == "worker"]
        assert len(workers) == 2
        seen: list[int] = []
        for w in workers:
            labels = w["labels"]
            assert labels["tpu.google.com/fake-topology"] == "v5e-8"
            assert labels["tpu.google.com/fake-host-id"] == "0"  # SAME host
            mask = [int(p) for p in labels["tpu.google.com/visible-chips"].split(".")]
            assert mask  # never an empty mask (would fail plugin startup)
            seen += mask
        assert sorted(seen) == [0, 1, 2, 3]  # disjoint and complete

    def test_split_host_rejects_undividable_splits(self, tmp_path):
        with pytest.raises(subprocess.CalledProcessError):
            self.generate_split_config(
                tmp_path, {"NUM_SPLITS": "3", "CHIPS_PER_HOST": "4"}
            )

    def test_install_script_exists_and_parses(self):
        for script in (
            "scripts/common.sh",
            "scripts/build-driver-image.sh",
            "scripts/load-driver-image-into-kind.sh",
            "scripts/install-dra-driver.sh",
            "scripts/delete-cluster.sh",
            "create-cluster.sh",
        ):
            path = REPO / "demo/clusters/kind" / script
            assert path.exists(), script
            assert os.access(path, os.X_OK) or script.endswith("common.sh"), script
            subprocess.run(["bash", "-n", str(path)], check=True)

    def test_gke_script_family_exists_and_parses(self):
        for script in (
            "common.sh",
            "create-cluster.sh",
            "label-slice-nodes.sh",
            "install-dra-driver.sh",
            "delete-cluster.sh",
        ):
            path = REPO / "demo/clusters/gke/scripts" / script
            assert path.exists(), script
            assert os.access(path, os.X_OK) or script == "common.sh", script
            subprocess.run(["bash", "-n", str(path)], check=True)


class TestFakeKnobResolution:
    def make_node(self, server, labels):
        return server.create(
            Node(metadata=ObjectMeta(name="worker-1", labels=labels))
        )

    def test_explicit_flags_win(self):
        server = InMemoryAPIServer()
        self.make_node(server, {"tpu.google.com/fake-topology": "v5e-32"})
        env = resolve_topology_env(server, "worker-1", "v4-8", "3")
        assert env == {"TPUINFO_FAKE_TOPOLOGY": "v4-8", "TPUINFO_FAKE_HOST_ID": "3"}

    def test_labels_fill_missing_knobs(self):
        server = InMemoryAPIServer()
        self.make_node(
            server,
            {
                "tpu.google.com/fake-topology": "v5e-16",
                "tpu.google.com/fake-host-id": "2",
            },
        )
        env = resolve_topology_env(server, "worker-1", "", "")
        assert env == {"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "2"}

    def test_no_knobs_no_labels_is_real_hardware_mode(self):
        server = InMemoryAPIServer()
        self.make_node(server, {})
        assert resolve_topology_env(server, "worker-1", "", "") == {}

    def test_unreadable_node_defaults_host_zero(self):
        server = InMemoryAPIServer()  # node object absent entirely
        env = resolve_topology_env(server, "worker-1", "v5e-16", "")
        assert env == {"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"}
