"""Per-request LoRA serving (S-LoRA shape): one base model + a stacked
adapter bank, each request applying its own fine-tune inside the shared
engine step.  Contracts: the identity adapter changes nothing, a banked
adapter reproduces the MERGED model's stream, fine-tunes never leak
across slots or through the prefix cache."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, lora
from k8s_dra_driver_tpu.models.serve import ServeEngine

CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128
)
LORA = lora.LoraConfig(rank=4, alpha=8.0)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _trained_adapter(seed: int) -> dict:
    """An adapter with NONZERO B (init gives B=0 = identity), scaled small
    enough to stay in-distribution but large enough that streams visibly
    diverge from the base."""
    ad = lora.init_adapters(jax.random.PRNGKey(seed), CFG, LORA)
    for li, blk in enumerate(ad["blocks"]):
        for name, ab in blk.items():
            # deterministic per-(layer, name) fold: hash() is randomized
            # per process and would make the adapters flaky across runs
            tag = li * 1000 + sum(ord(c) for c in name)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), tag)
            ab["b"] = 0.3 * jax.random.normal(key, ab["b"].shape, jnp.float32)
    return ad


@pytest.fixture(scope="module")
def bank(params):
    return lora.stack_adapters(CFG, LORA, [_trained_adapter(1), _trained_adapter(2)])


def _drain(eng, reqs):
    out = {}
    for prompt, max_tokens, adapter in reqs:
        eng.submit(prompt, max_tokens, adapter=adapter)
    eng.run_until_drained()
    for c in eng.completions():
        out[c.request_id] = c.generated
    return out


PROMPTS = [[3, 14, 15, 9, 2], [6, 53, 58, 9], [7, 1, 8, 2, 8, 1]]


class TestAdapterServing:
    def test_identity_adapter_streams_identical(self, params, bank):
        plain = ServeEngine(params=params, cfg=CFG, n_slots=3, prompt_bucket=16)
        banked = ServeEngine(
            params=params, cfg=CFG, n_slots=3, prompt_bucket=16,
            adapter_bank=bank,
        )
        reqs = [(p, 10, 0) for p in PROMPTS]
        assert _drain(plain, reqs) == _drain(banked, reqs)

    def test_mixed_batch_logits_match_merged_models(self, params, bank):
        """A 3-row batch with ids [0, 1, 2] produces (per row) the logits
        of the corresponding MERGED model, to fp tolerance — the separate
        low-rank delta and the weight merge are the same math in different
        accumulation order, so logits agree to bf16 noise while token
        streams may legitimately flip on near-ties."""
        from k8s_dra_driver_tpu.models import decode

        prompts = jnp.asarray(
            [[3, 14, 15, 9, 2], [6, 53, 58, 9, 1], [7, 1, 8, 2, 8]], jnp.int32
        )
        ids = jnp.asarray([0, 1, 2], jnp.int32)
        _, logits = decode.prefill(
            params, prompts, CFG, max_seq=32, adapters=(bank, ids)
        )
        models = [
            params,
            lora.merge(params, _trained_adapter(1), LORA),
            lora.merge(params, _trained_adapter(2), LORA),
        ]
        for i, model in enumerate(models):
            _, solo = decode.prefill(model, prompts[i : i + 1], CFG, max_seq=32)
            np.testing.assert_allclose(
                np.asarray(logits[i]), np.asarray(solo[0]), atol=0.1,
                err_msg=f"row {i} diverged from its merged model",
            )
        # rows 1/2 are genuinely different models from row 0
        assert float(jnp.abs(logits[1] - logits[0]).max()) > 1.0

    def test_mixed_adapters_bind_per_request(self, params, bank):
        """No cross-slot leakage, proven EXACTLY: permuting the bank and
        the submitted ids together is the same math in the same batch
        positions, so streams must be bit-identical — any row reading a
        neighbor's adapter breaks the correspondence."""
        bank_swapped = lora.stack_adapters(
            CFG, LORA, [_trained_adapter(2), _trained_adapter(1)]
        )
        reqs = [(PROMPTS[0], 9, 1), (PROMPTS[1], 9, 2), (PROMPTS[2], 9, 0)]
        got = _drain(
            ServeEngine(
                params=params, cfg=CFG, n_slots=3, prompt_bucket=16,
                adapter_bank=bank,
            ),
            reqs,
        )
        swapped_reqs = [(PROMPTS[0], 9, 2), (PROMPTS[1], 9, 1), (PROMPTS[2], 9, 0)]
        want = _drain(
            ServeEngine(
                params=params, cfg=CFG, n_slots=3, prompt_bucket=16,
                adapter_bank=bank_swapped,
            ),
            swapped_reqs,
        )
        assert got == want
        # and the three streams are pairwise distinct (adapters bite)
        streams = list(got.values())
        assert streams[0] != streams[2] and streams[1] != streams[2]

    def test_prefix_cache_keys_by_adapter(self, params, bank):
        """Two fine-tunes sharing a prompt prefix must NOT share cached
        prefix k/v — the store keys by adapter."""
        shared = [11, 12, 13, 14, 15, 16, 17, 18]  # > prefix_bucket
        eng = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16,
            prefix_bucket=4, adapter_bank=bank,
        )
        r1 = _drain(eng, [(shared + [20], 8, 1)])
        r2 = _drain(eng, [(shared + [20], 8, 2)])
        assert eng.prefix_hits == 0  # different adapters: no cross-hit
        # same adapter again: NOW it hits, stream unchanged
        r1b = _drain(eng, [(shared + [20], 8, 1)])
        assert eng.prefix_hits == 1
        assert list(r1.values())[0] == list(r1b.values())[0]
        # and the two fine-tunes produced different streams
        assert list(r1.values())[0] != list(r2.values())[0]

    def test_validation(self, params, bank):
        with pytest.raises(ValueError, match="no adapter_bank"):
            ServeEngine(params=params, cfg=CFG, n_slots=1, prompt_bucket=16).submit(
                [1, 2], 2, adapter=1
            )
        eng = ServeEngine(
            params=params, cfg=CFG, n_slots=1, prompt_bucket=16,
            adapter_bank=bank,
        )
        with pytest.raises(ValueError, match="out of range"):
            eng.submit([1, 2], 2, adapter=3)

    def test_speculative_compose(self, params, bank):
        """Speculation composes with adapters: the verify pass applies the
        request's adapter while the draft stays the base model — streams
        bit-equal the non-speculative banked engine (the any-draft
        contract, per adapter)."""
        reqs = [(PROMPTS[0], 10, 1), (PROMPTS[1], 10, 2), (PROMPTS[2], 10, 0)]
        plain = _drain(
            ServeEngine(
                params=params, cfg=CFG, n_slots=3, prompt_bucket=16,
                adapter_bank=bank,
            ),
            reqs,
        )
        spec = _drain(
            ServeEngine(
                params=params, cfg=CFG, n_slots=3, prompt_bucket=16,
                adapter_bank=bank, spec_gamma=3,
            ),
            reqs,
        )
        assert plain == spec

    def test_bank_layer_mismatch_rejected(self, params):
        ad = _trained_adapter(1)
        ad["blocks"] = ad["blocks"][:1]
        with pytest.raises(ValueError, match="layers"):
            lora.stack_adapters(CFG, LORA, [ad])


class TestPagedAdapterServing:
    """The same per-request-adapter contracts over the PAGED engine — and
    the interactions paging adds: block-level prefix sharing keyed by
    adapter, and preemption parking/restoring the adapter id."""

    def _engine(self, params, bank, **kw):
        from k8s_dra_driver_tpu.models import paged

        return paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=3, n_blocks=40, block_size=8,
            prompt_bucket=16, attn_impl="xla", adapter_bank=bank, **kw,
        )

    def test_identity_adapter_streams_identical(self, params, bank):
        from k8s_dra_driver_tpu.models import paged

        plain = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=3, n_blocks=40, block_size=8,
            prompt_bucket=16, attn_impl="xla",
        )
        reqs = [(p, 10, 0) for p in PROMPTS]
        assert _drain(plain, reqs) == _drain(self._engine(params, bank), reqs)

    def test_mixed_adapters_bind_per_request(self, params, bank):
        bank_swapped = lora.stack_adapters(
            CFG, LORA, [_trained_adapter(2), _trained_adapter(1)]
        )
        got = _drain(
            self._engine(params, bank),
            [(PROMPTS[0], 9, 1), (PROMPTS[1], 9, 2), (PROMPTS[2], 9, 0)],
        )
        want = _drain(
            self._engine(params, bank_swapped),
            [(PROMPTS[0], 9, 2), (PROMPTS[1], 9, 1), (PROMPTS[2], 9, 0)],
        )
        assert got == want
        streams = list(got.values())
        assert streams[0] != streams[2] and streams[1] != streams[2]

    def test_block_prefix_store_keys_by_adapter(self, params, bank):
        shared = list(range(20, 36))  # 2 full 8-token blocks
        eng = self._engine(params, bank, prefix_cache_blocks=6)
        r1 = _drain(eng, [(shared[:12] + [40], 8, 1)])
        hits_after_first = eng.prefix_hits
        r2 = _drain(eng, [(shared[:12] + [40], 8, 2)])
        assert eng.prefix_hits == hits_after_first  # no cross-adapter hit
        r1b = _drain(eng, [(shared[:12] + [40], 8, 1)])
        assert eng.prefix_hits > hits_after_first  # same adapter DOES hit
        assert list(r1.values())[0] == list(r1b.values())[0]
        assert list(r1.values())[0] != list(r2.values())[0]

    def test_paged_speculative_compose(self, params, bank):
        from k8s_dra_driver_tpu.models import paged

        reqs = [(PROMPTS[0], 10, 1), (PROMPTS[1], 10, 2), (PROMPTS[2], 10, 0)]
        plain = _drain(self._engine(params, bank), reqs)
        spec = _drain(self._engine(params, bank, spec_gamma=3), reqs)
        assert plain == spec

    def test_preemption_restores_adapter(self, params, bank):
        """A preempted adapted request resumes with ITS adapter: streams
        under a starved pool equal the roomy-pool run, per adapter."""
        from k8s_dra_driver_tpu.models import paged

        reqs = [([1, 2, 3, 4, 5, 6], 14, 1), ([7, 8, 9, 10, 11, 12], 14, 2)]

        def run(n_blocks, preempt):
            eng = paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=2, n_blocks=n_blocks,
                block_size=4, prompt_bucket=32, attn_impl="xla",
                adapter_bank=bank, preempt_on_stall=preempt,
            )
            out = _drain(eng, reqs)
            return eng, out

        _, want = run(40, False)
        eng, got = run(7, True)
        assert eng.preempted_count > 0
        assert got == want
