"""Metrics registry + diagnostics endpoint + driver instrumentation tests."""

import urllib.request

from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
from k8s_dra_driver_tpu.plugin.driver import ClaimRef, Driver, DriverConfig
from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer
from k8s_dra_driver_tpu.utils.metrics import Registry


class TestRegistry:
    def test_counter_and_labels(self):
        r = Registry()
        c = r.counter("errors_total", "errors")
        c.inc(op="prepare")
        c.inc(op="prepare")
        c.inc(op="unprepare")
        assert c.value(op="prepare") == 2
        text = r.render()
        assert 'errors_total{op="prepare"} 2.0' in text
        assert "# TYPE errors_total counter" in text

    def test_histogram_quantile_and_render(self):
        r = Registry()
        h = r.histogram("latency_seconds", "lat", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5):
            h.observe(v)
        assert h.quantile(0.5) == 0.01  # 2 of 4 in first bucket
        assert h.quantile(0.99) == 1.0
        text = r.render()
        assert 'latency_seconds_bucket{le="0.01"} 2' in text
        assert 'latency_seconds_bucket{le="+Inf"} 4' in text
        assert "latency_seconds_count 4" in text

    def test_gauge(self):
        r = Registry()
        g = r.gauge("devices", "devices")
        g.set(9, node="h0")
        assert 'devices{node="h0"} 9' in r.render()

    def test_same_name_returns_same_metric(self):
        r = Registry()
        assert r.counter("x_total") is r.counter("x_total")

    def test_label_escaping_round_trips(self):
        r = Registry()
        c = r.counter("escapes_total", "hostile label values")
        hostile = 'quote:" backslash:\\ newline:\nend'
        c.inc(claim=hostile)
        text = r.render()
        line = next(
            ln for ln in text.splitlines() if ln.startswith("escapes_total{")
        )
        # Exposition lines are newline-delimited: a raw newline in a label
        # would split the sample in two.
        assert "\n" not in line
        assert 'claim="quote:\\" backslash:\\\\ newline:\\nend"' in line
        # Round trip: unescaping the rendered value recovers the original.
        rendered = line.split('claim="', 1)[1].rsplit('"', 1)[0]
        unescaped = (
            rendered.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")
        )
        assert unescaped == hostile

    def test_reset_keeps_metric_objects_but_zeroes_values(self):
        r = Registry()
        c = r.counter("resets_total", "reset test")
        g = r.gauge("reset_level", "reset test")
        h = r.histogram("reset_seconds", "reset test")
        c.inc()
        g.set(7)
        h.observe(0.2)
        r.reset()
        # Same objects (modules bind metrics at import time)...
        assert r.counter("resets_total") is c
        # ...but every recorded value is gone.
        assert c.value() == 0
        assert g.value() == 0
        assert h.count() == 0
        c.inc()
        assert "resets_total 1.0" in r.render()


class TestDiagnosticsServer:
    def test_endpoints(self):
        r = Registry()
        r.counter("hits_total", "endpoint test hits").inc()
        srv = DiagnosticsServer(port=0, registry=r, state_provider=lambda: {"ok": True})
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert "hits_total 1.0" in metrics
            assert urllib.request.urlopen(f"{base}/healthz").read() == b"ok"
            state = urllib.request.urlopen(f"{base}/debug/state").read().decode()
            assert '"ok": true' in state
            try:
                urllib.request.urlopen(f"{base}/nope")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            srv.stop()


class TestDriverInstrumentation:
    def test_prepare_latency_recorded(self, tmp_path):
        from k8s_dra_driver_tpu.utils.metrics import REGISTRY

        cluster = make_cluster(hosts=1, work_dir=str(tmp_path))
        driver = Driver(
            cluster.server,
            DriverConfig(
                node_name="tpu-host-0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
                publish=False,
            ),
        )
        # Absolute asserts: the autouse REGISTRY.reset() fixture
        # (tests/conftest.py) guarantees a clean slate per test — no
        # before/after deltas against whatever earlier tests left behind.
        h = REGISTRY.histogram("dra_node_prepare_seconds")
        claim = cluster.server.create(simple_claim("m1"))
        allocated = cluster.allocator.allocate(claim, node_name="tpu-host-0")
        driver.node_prepare_resources(
            [ClaimRef(uid=allocated.metadata.uid, name="m1", namespace="default")]
        )
        assert h.count() == 1

        errs = REGISTRY.counter("dra_claim_errors_total")
        driver.node_prepare_resources(
            [ClaimRef(uid="x", name="ghost", namespace="default")]
        )
        assert errs.value(op="prepare") == 1
