"""Metrics registry + diagnostics endpoint + driver instrumentation tests."""

import urllib.request

from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
from k8s_dra_driver_tpu.plugin.driver import ClaimRef, Driver, DriverConfig
from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer
from k8s_dra_driver_tpu.utils.metrics import Registry


class TestRegistry:
    def test_counter_and_labels(self):
        r = Registry()
        c = r.counter("errors_total", "errors")
        c.inc(op="prepare")
        c.inc(op="prepare")
        c.inc(op="unprepare")
        assert c.value(op="prepare") == 2
        text = r.render()
        assert 'errors_total{op="prepare"} 2.0' in text
        assert "# TYPE errors_total counter" in text

    def test_histogram_quantile_and_render(self):
        r = Registry()
        h = r.histogram("latency_seconds", "lat", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.005, 0.05, 0.5):
            h.observe(v)
        assert h.quantile(0.5) == 0.01  # 2 of 4 in first bucket
        assert h.quantile(0.99) == 1.0
        text = r.render()
        assert 'latency_seconds_bucket{le="0.01"} 2' in text
        assert 'latency_seconds_bucket{le="+Inf"} 4' in text
        assert "latency_seconds_count 4" in text

    def test_gauge(self):
        r = Registry()
        g = r.gauge("devices", "devices")
        g.set(9, node="h0")
        assert 'devices{node="h0"} 9' in r.render()

    def test_same_name_returns_same_metric(self):
        r = Registry()
        assert r.counter("x") is r.counter("x")


class TestDiagnosticsServer:
    def test_endpoints(self):
        r = Registry()
        r.counter("hits_total", "").inc()
        srv = DiagnosticsServer(port=0, registry=r, state_provider=lambda: {"ok": True})
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            metrics = urllib.request.urlopen(f"{base}/metrics").read().decode()
            assert "hits_total 1.0" in metrics
            assert urllib.request.urlopen(f"{base}/healthz").read() == b"ok"
            state = urllib.request.urlopen(f"{base}/debug/state").read().decode()
            assert '"ok": true' in state
            try:
                urllib.request.urlopen(f"{base}/nope")
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            srv.stop()


class TestDriverInstrumentation:
    def test_prepare_latency_recorded(self, tmp_path):
        from k8s_dra_driver_tpu.utils.metrics import REGISTRY

        cluster = make_cluster(hosts=1, work_dir=str(tmp_path))
        driver = Driver(
            cluster.server,
            DriverConfig(
                node_name="tpu-host-0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
                publish=False,
            ),
        )
        h = REGISTRY.histogram("dra_node_prepare_seconds")
        before = h.count()
        claim = cluster.server.create(simple_claim("m1"))
        allocated = cluster.allocator.allocate(claim, node_name="tpu-host-0")
        driver.node_prepare_resources(
            [ClaimRef(uid=allocated.metadata.uid, name="m1", namespace="default")]
        )
        assert h.count() == before + 1

        errs = REGISTRY.counter("dra_claim_errors_total")
        before_err = errs.value(op="prepare")
        driver.node_prepare_resources(
            [ClaimRef(uid="x", name="ghost", namespace="default")]
        )
        assert errs.value(op="prepare") == before_err + 1
