"""Integration tests: the quickstart demo specs run against the closed loop.

The functional north star (BASELINE.md): the tpu-test{1,2,3} clones run JAX
containers with every chip bound via DRA; tpu-test4/5/6 exercise subslice
geometry, mixed sharing configs and CEL selection; slice-test1 runs the
multi-host membership flow.  The reference can only verify these manually on
a kind cluster with real GPUs (SURVEY.md §4.3) — here they are pytest."""

from pathlib import Path

import pytest

from k8s_dra_driver_tpu.controller.slice_manager import SliceManager
from k8s_dra_driver_tpu.e2e.harness import make_cluster
from k8s_dra_driver_tpu.e2e.spec_runner import SpecError, apply_spec

SPECS = Path(__file__).parent.parent / "demo" / "specs" / "quickstart"


@pytest.fixture
def cluster(tmp_path):
    return make_cluster(hosts=1, topology="v5e-16", work_dir=str(tmp_path))


class TestQuickstart:
    def test_tpu_test1_distinct_chips(self, cluster):
        pods = apply_spec(cluster, SPECS / "tpu-test1.yaml")
        assert len(pods) == 2
        chips = {p.devices[0]["device_name"] for p in pods}
        assert len(chips) == 2  # distinct devices
        for p in pods:
            assert p.env["TPU_VISIBLE_DEVICES"] in {"0", "1", "2", "3"}

    def test_tpu_test2_containers_share_one_claim(self, cluster):
        pods = apply_spec(cluster, SPECS / "tpu-test2.yaml")
        assert len(pods) == 1
        assert len(pods[0].devices) == 1  # one chip, both containers see it

    def test_tpu_test3_pods_share_global_claim(self, cluster):
        pods = apply_spec(cluster, SPECS / "tpu-test3.yaml")
        assert len(pods) == 2
        assert pods[0].devices == pods[1].devices  # same underlying chip
        assert pods[0].node == pods[1].node  # pinned by the shared allocation

    def test_tpu_test4_subslices_same_host(self, cluster):
        pods = apply_spec(cluster, SPECS / "tpu-test4.yaml")
        (pod,) = pods
        names = {d["device_name"] for d in pod.devices}
        assert names == {"tpu-slice-1x2-0-0", "tpu-slice-1x2-1-0"}

    def test_tpu_test5_mixed_sharing_configs(self, cluster):
        pods = apply_spec(cluster, SPECS / "tpu-test5.yaml")
        (pod,) = pods
        assert len(pod.devices) == 2
        # Both strategies visible in the merged env; the spatial partition
        # spawned a topology daemon.
        assert pod.env["TPU_QUEUE_QUANTUM_MS"] == "20"  # TimeSlicing Long
        assert pod.env["TPU_CORE_FRACTION"] == "50"
        daemons = cluster.server.list("Deployment", namespace="tpu-dra-driver")
        assert len(daemons) == 1

    def test_tpu_test6_cel_selection(self, cluster):
        pods = apply_spec(cluster, SPECS / "tpu-test6.yaml")
        assert pods[0].devices[0]["device_name"] in {"tpu-0", "tpu-1"}

    def test_tpu_test_sharing_spatial_partition(self, cluster):
        pods = apply_spec(cluster, SPECS / "tpu-test-sharing.yaml")
        (pod,) = pods
        assert pod.env["TPU_SHARING_STRATEGY"] == "spatial-partition"
        assert pod.env["TPU_CORE_FRACTION"] == "50"
        daemons = cluster.server.list("Deployment", namespace="tpu-dra-driver")
        assert len(daemons) == 1

    def test_shared_claim_lifecycle(self, cluster):
        # gpu-test3 semantics: the claim stays allocated while ANY consumer
        # pod lives; the last deletion frees the chip.
        apply_spec(cluster, SPECS / "tpu-test3.yaml")
        claim = cluster.server.get("ResourceClaim", "shared-tpu", "tpu-test3")
        assert len(claim.status.reserved_for) == 2
        cluster.delete_pod("pod0", "tpu-test3")
        claim = cluster.server.get("ResourceClaim", "shared-tpu", "tpu-test3")
        assert claim.status.allocation is not None  # pod1 still consuming
        assert len(claim.status.reserved_for) == 1
        cluster.delete_pod("pod1", "tpu-test3")
        claim = cluster.server.get("ResourceClaim", "shared-tpu", "tpu-test3")
        assert claim.status.allocation is None
        node = cluster.nodes["tpu-host-0"]
        assert node.state.prepared_claim_uids() == []

    def test_deallocate_refused_while_reserved(self, cluster):
        from k8s_dra_driver_tpu.scheduler.allocator import AllocationError

        apply_spec(cluster, SPECS / "tpu-test3.yaml")
        claim = cluster.server.get("ResourceClaim", "shared-tpu", "tpu-test3")
        with pytest.raises(AllocationError, match="still reserved"):
            cluster.allocator.deallocate(claim)

    def test_whole_inventory_exhaustion_is_clean(self, cluster):
        apply_spec(cluster, SPECS / "tpu-test6.yaml")  # one of chips 0/1
        apply_spec(cluster, SPECS / "tpu-test3.yaml")  # one more
        apply_spec(cluster, SPECS / "tpu-test1.yaml")  # remaining two
        # Fifth chip does not exist: next spec must fail with a clear error.
        with pytest.raises(SpecError, match="unschedulable"):
            apply_spec(cluster, SPECS / "tpu-test2.yaml")


class TestSliceTest1:
    def test_multihost_membership_flow(self, tmp_path):
        cluster = make_cluster(
            hosts=4, topology="v5e-16", work_dir=str(tmp_path), slice_domain="v5e-16-demo"
        )
        manager = SliceManager(cluster.server)
        manager.start()
        pods = apply_spec(cluster, SPECS / "slice-test1.yaml")
        assert len(pods) == 4
        assert len({p.node for p in pods}) == 4  # anti-affinity honored
        worker_envs = sorted(p.env.get("JAX_COORDINATOR_PORT") for p in pods)
        assert worker_envs == ["8476"] * 4
        # every pod got a 2x2 subslice (4 chips) + a membership seat
        for p in pods:
            kinds = sorted(d["device_name"] for d in p.devices)
            assert any(k.startswith("tpu-slice-2x2") for k in kinds)
            assert any(k.startswith("membership-") for k in kinds)
        # distinct seats
        seats = {
            d["device_name"] for p in pods for d in p.devices
            if d["device_name"].startswith("membership-")
        }
        assert len(seats) == 4
        # consumer side of the same env: every pod resolves a distinct
        # worker identity with a common coordinator — what
        # `python -m k8s_dra_driver_tpu.consumer` does at container start.
        from k8s_dra_driver_tpu import consumer

        worker_ids = set()
        coordinators = set()
        for p in pods:
            ctx = consumer.attach(environ=p.env, init_distributed=False)
            assert ctx.multi_host and ctx.host_count == 4
            assert len(ctx.visible_devices) == 4  # the 2x2 block's chips
            worker_ids.add(ctx.worker_id)
            coordinators.add(ctx.coordinator_address)
        assert worker_ids == {0, 1, 2, 3}
        assert len(coordinators) == 1 and next(iter(coordinators)).endswith(":8476")
        manager.stop()
