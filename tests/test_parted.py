"""tpu-parted: out-of-band subslice-layout partitioning (mig-parted analog).

Covers the config contract, the apply CLI, the plugin-side publication
filter, and the LIVE re-shape through DeviceState.refresh() — the dynamic
repartitioning path the reference carries only as commented-out code
(nvlib.go:560-669)."""

import json
from pathlib import Path

import pytest
import yaml

from k8s_dra_driver_tpu.plugin import parted

REPO = Path(__file__).parent.parent
DEMO_CONFIG = REPO / "demo" / "specs" / "quickstart" / "tpu-parted-config.yaml"


class TestConfigContract:
    def test_demo_config_parses(self):
        layouts = parted.parse_config(yaml.safe_load(DEMO_CONFIG.read_text()))
        assert {"all-shapes", "whole-host-only", "half-balanced", "chips-only"} <= set(
            layouts
        )

    @pytest.mark.parametrize(
        "doc",
        [
            {"version": "v2", "subslice-configs": {"a": [{"hosts": "all", "shapes": "all"}]}},
            {"version": "v1"},
            {"version": "v1", "subslice-configs": {}},
            {"version": "v1", "subslice-configs": {"a": []}},
            {"version": "v1", "subslice-configs": {"a": [{"hosts": "some", "shapes": "all"}]}},
            {"version": "v1", "subslice-configs": {"a": [{"hosts": "all", "shapes": 5}]}},
        ],
    )
    def test_invalid_configs_rejected(self, doc):
        with pytest.raises(parted.PartedError):
            parted.parse_config(doc)

    def test_per_host_resolution_first_match_wins(self):
        entries = [
            {"hosts": [0, 1], "shapes": ["2x2"]},
            {"hosts": "all", "shapes": []},
        ]
        assert parted.resolve_layout("l", entries, 0).allows("2x2")
        assert not parted.resolve_layout("l", entries, 0).allows("2x1")
        assert not parted.resolve_layout("l", entries, 3).allows("2x2")

    def test_unmatched_host_keeps_all_shapes(self):
        entries = [{"hosts": [7], "shapes": []}]
        assert parted.resolve_layout("l", entries, 0).allows("2x2")


class TestApplyCLI:
    def test_apply_and_export_roundtrip(self, tmp_path, capsys):
        state = tmp_path / "state.json"
        rc = parted.main(
            ["apply", "-f", str(DEMO_CONFIG), "-c", "whole-host-only",
             f"--state-path={state}"]
        )
        assert rc == 0
        doc = json.loads(state.read_text())
        assert doc["layout"] == "whole-host-only"
        rc = parted.main(["export", f"--state-path={state}"])
        assert rc == 0
        assert "whole-host-only" in capsys.readouterr().out

    def test_apply_unknown_layout_fails(self, tmp_path):
        with pytest.raises(parted.PartedError, match="no layout"):
            parted.apply_config(str(DEMO_CONFIG), "nope", str(tmp_path / "s.json"))

    def test_missing_state_means_all_shapes(self, tmp_path):
        layout = parted.load_applied_layout(tmp_path / "absent.json", 0)
        assert layout.allows("2x2") and layout.allows("1x2")


class TestPluginPublication:
    def make_state(self, tmp_path, layout_name):
        state = tmp_path / "tpu-parted-state.json"
        parted.apply_config(str(DEMO_CONFIG), layout_name, str(state))
        return state

    def device_state(self, server, tmp_path, state_path):
        from k8s_dra_driver_tpu.plugin.device_state import (
            DeviceState,
            DeviceStateConfig,
        )

        return DeviceState(
            server,
            DeviceStateConfig(
                node_name="host0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "cp.json"),
                topology_env={
                    "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                    "TPUINFO_FAKE_HOST_ID": "0",
                },
                parted_state_path=str(state_path),
            ),
        )

    def shapes_published(self, state):
        return {
            d.subslice.subslice.shape_name(d.subslice.topology.ndims)
            for d in state.allocatable
            if d.subslice is not None
        }

    def test_layout_filters_published_subslices(self, api_server, tmp_path):
        state_path = self.make_state(tmp_path, "whole-host-only")
        ds = self.device_state(api_server, tmp_path, state_path)
        assert self.shapes_published(ds) == {"2x2"}
        # chips always publish
        assert any(d.chip is not None for d in ds.allocatable)

    def test_chips_only_layout(self, api_server, tmp_path):
        state_path = self.make_state(tmp_path, "chips-only")
        ds = self.device_state(api_server, tmp_path, state_path)
        assert self.shapes_published(ds) == set()

    def test_live_reshape_via_refresh(self, api_server, tmp_path):
        """Re-apply a different layout and the refresh sweep republishes —
        dynamic repartitioning without a plugin restart."""
        state_path = self.make_state(tmp_path, "all-shapes")
        ds = self.device_state(api_server, tmp_path, state_path)
        assert "2x1" in self.shapes_published(ds)
        parted.apply_config(str(DEMO_CONFIG), "whole-host-only", str(state_path))
        assert ds.refresh() is True
        assert self.shapes_published(ds) == {"2x2"}
        assert ds.refresh() is False  # stable until the next change

    def test_corrupt_state_publishes_everything(self, api_server, tmp_path):
        state_path = tmp_path / "tpu-parted-state.json"
        state_path.write_text("{not json")
        ds = self.device_state(api_server, tmp_path, state_path)
        assert "2x2" in self.shapes_published(ds)

    def test_half_balanced_differs_per_host(self, api_server, tmp_path):
        state_path = self.make_state(tmp_path, "half-balanced")
        from k8s_dra_driver_tpu.plugin.device_state import (
            DeviceState,
            DeviceStateConfig,
        )

        def for_host(hid):
            return DeviceState(
                api_server,
                DeviceStateConfig(
                    node_name=f"host{hid}",
                    cdi_root=str(tmp_path / f"cdi{hid}"),
                    checkpoint_path=str(tmp_path / f"cp{hid}.json"),
                    topology_env={
                        "TPUINFO_FAKE_TOPOLOGY": "v5e-16",
                        "TPUINFO_FAKE_HOST_ID": str(hid),
                    },
                    parted_state_path=str(state_path),
                ),
            )

        assert self.shapes_published(for_host(0)) == {"2x2"}
        assert self.shapes_published(for_host(2)) == {"2x1", "1x2"}
