"""Shared harness for REAL multi-process tests.

One implementation of the supervised-subprocess flow used by
tests/test_multiprocess.py (training collective), tests/
test_multiprocess_serve.py (DP-sharded serving) and tests/
test_transport_chaos.py (KV transport workers): spawn children, POLL
them all, and fail fast with evidence when any child dies early.

The failure mode this exists to kill: worker A crashes on startup while
worker B blocks inside ``jax.distributed.initialize`` (or a transport
dial loop) for its FULL init timeout — the test then reports a timeout
on B instead of A's actual traceback.  :func:`supervise` watches every
child concurrently; the first non-zero exit (or the deadline) reaps the
siblings and raises with the dead worker's stderr tail AND a watchdog
diag bundle (thread stacks, journal tail, metrics) for the supervisor
side."""

import json
import socket
import subprocess
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
SPECS = REPO_ROOT / "demo" / "specs" / "quickstart"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class SupervisedWorker:
    """One child process under supervision.

    Holds the Popen plus the collected stdout/stderr once the child is
    reaped — :func:`supervise` owns the lifecycle; tests only read
    ``out`` / ``err`` / ``returncode`` afterwards."""

    def __init__(self, name: str, argv: list, env: dict):
        self.name = name
        self.proc = subprocess.Popen(
            argv, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        self.out = ""
        self.err = ""
        self.collected = False

    @property
    def pid(self) -> int:
        return self.proc.pid

    @property
    def returncode(self):
        return self.proc.returncode

    def poll(self):
        return self.proc.poll()

    def collect(self, timeout: float = 10.0) -> None:
        """Reap the child's pipes (idempotent)."""
        if self.collected:
            return
        try:
            self.out, self.err = self.proc.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.out, self.err = self.proc.communicate()
        self.collected = True

    def kill(self) -> None:
        if self.proc.poll() is None:
            self.proc.kill()
        self.collect()

    def last_json(self) -> dict:
        """The worker-result convention: parse the last stdout line."""
        return json.loads(self.out.strip().splitlines()[-1])

    def stderr_tail(self, n: int = 3000) -> str:
        return self.err[-n:]


def _fail(workers, culprit: SupervisedWorker, why: str, bundle_dir) -> None:
    """Reap every sibling, dump a diag bundle, raise with the evidence."""
    from k8s_dra_driver_tpu.utils.watchdog import dump_diag_bundle

    for w in workers:
        w.kill()
    extra = {
        "workers": {
            w.name: {
                "pid": w.pid,
                "returncode": w.returncode,
                "stderr_tail": w.stderr_tail(),
            }
            for w in workers
        },
    }
    # When the observability plane is live in this supervisor, the death
    # report also carries every federated worker's last journal/metrics/
    # stacks snapshot — the SURVIVORS' view of the crash, not just the
    # corpse's stderr.  Guarded on sys.modules so harness users that
    # never load models/ pay nothing.
    obs = sys.modules.get("k8s_dra_driver_tpu.models.obs_plane")
    if obs is not None:
        extra["fleet_telemetry"] = obs.FLEET.bundle_doc()
    bundle = dump_diag_bundle(
        str(bundle_dir), reason=f"mp-harness: {why}",
        correlation=f"worker-{culprit.name}",
        extra=extra,
    )
    raise AssertionError(
        f"{why}\n"
        f"--- worker {culprit.name!r} (pid {culprit.pid}, "
        f"rc={culprit.returncode}) stderr tail ---\n"
        f"{culprit.stderr_tail()}\n"
        f"--- diag bundle: {bundle} ---"
    )


def wait_ready(workers: list, is_ready, timeout: float, bundle_dir="/tmp",
               poll_s: float = 0.02):
    """Block until ``is_ready()`` returns truthy, watching every worker
    for early death the whole time.

    The failure mode this kills: a worker crashes during startup while
    the test blocks inside a ready-side call (``hub.link_for``, a dial
    loop) for ITS full timeout — the eventual error says "timeout", not
    why the worker died.  A worker that dies before the handshake fails
    the wait immediately with its stderr tail attached (via
    :func:`_fail`'s evidence bundle), ALWAYS — there is no JSON result
    line to parse from a corpse.  Returns ``is_ready()``'s truthy value
    so readiness probes can hand back a link/handle."""
    deadline = time.monotonic() + timeout
    while True:
        val = is_ready()
        if val:
            return val
        for w in workers:
            rc = w.poll()
            if rc is not None:
                w.collect()
                _fail(
                    workers, w,
                    f"worker {w.name!r} died rc={rc} before its ready "
                    f"handshake",
                    bundle_dir,
                )
        if time.monotonic() > deadline:
            _fail(
                workers, workers[0],
                f"ready handshake still pending at the {timeout}s deadline",
                bundle_dir,
            )
        time.sleep(poll_s)


def supervise(workers: list, timeout: float, bundle_dir="/tmp") -> None:
    """Watch every worker until ALL exit 0.

    The first worker to die non-zero fails the run immediately — its
    siblings are killed rather than left to block out their own timeouts
    — and the raised AssertionError carries the dead worker's stderr
    tail plus a supervisor-side diag bundle path.  The deadline is
    enforced the same way, attributing the failure to the slowest
    still-running worker."""
    deadline = time.monotonic() + timeout
    alive = list(workers)
    while alive:
        for w in list(alive):
            rc = w.poll()
            if rc is None:
                continue
            w.collect()
            alive.remove(w)
            if rc != 0:
                _fail(
                    workers, w,
                    f"worker {w.name!r} exited rc={rc} with "
                    f"{len(alive)} sibling(s) still running",
                    bundle_dir,
                )
        if alive and time.monotonic() > deadline:
            _fail(
                workers, alive[0],
                f"worker {alive[0].name!r} still running at the "
                f"{timeout}s harness deadline",
                bundle_dir,
            )
        if alive:
            time.sleep(0.05)


def run_two_process_workers(cluster, tmp_path, worker_src: str,
                            n_devices: int = 2, timeout: int = 300):
    """Apply slice-test1 scaled to 2 hosts, hand each pod's CDI env to a
    separate python process running ``worker_src``, and return the parsed
    last-line JSON of each worker.  Supervision is poll-based
    (:func:`supervise`): a worker failing EARLY fails the test with its
    own stderr, instead of its sibling blocking in
    ``jax.distributed.initialize`` for the full init timeout."""
    from k8s_dra_driver_tpu.e2e.dryrun import force_cpu_env
    from k8s_dra_driver_tpu.e2e.spec_runner import apply_spec

    spec = (SPECS / "slice-test1.yaml").read_text().replace(
        "replicas: 4", "replicas: 2"
    )
    spec_path = tmp_path / "slice-test1-2host.yaml"
    spec_path.write_text(spec)
    pods = apply_spec(cluster, spec_path)
    assert len(pods) == 2

    port = free_port()
    workers = []
    for idx, pod in enumerate(pods):
        env = dict(pod.env)
        # the seat wired tpu-host-0:8476; re-point at this test's real TCP
        # port on localhost (the cluster DNS name cannot resolve here)
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        force_cpu_env(env, n_devices=n_devices)
        env["PYTHONPATH"] = str(REPO_ROOT)
        workers.append(SupervisedWorker(
            f"host-{idx}", [sys.executable, "-c", worker_src], env,
        ))
    try:
        supervise(workers, timeout, bundle_dir=tmp_path)
    finally:
        for w in workers:
            w.kill()
    return [w.last_json() for w in workers]
