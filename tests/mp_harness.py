"""Shared harness for REAL two-process jax.distributed tests.

One implementation of the fake-cluster → slice-test1 → CDI-env →
subprocess-worker flow (coordinator re-pointing, CPU forcing, orphan
cleanup), used by tests/test_multiprocess.py (training collective) and
tests/test_multiprocess_serve.py (DP-sharded serving)."""

import json
import socket
import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
SPECS = REPO_ROOT / "demo" / "specs" / "quickstart"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_two_process_workers(cluster, tmp_path, worker_src: str,
                            n_devices: int = 2, timeout: int = 300):
    """Apply slice-test1 scaled to 2 hosts, hand each pod's CDI env to a
    separate python process running ``worker_src``, and return the parsed
    last-line JSON of each worker.  A failing worker never orphans its
    sibling (the survivor would block in jax.distributed.initialize for
    its full init timeout)."""
    from k8s_dra_driver_tpu.e2e.dryrun import force_cpu_env
    from k8s_dra_driver_tpu.e2e.spec_runner import apply_spec

    spec = (SPECS / "slice-test1.yaml").read_text().replace(
        "replicas: 4", "replicas: 2"
    )
    spec_path = tmp_path / "slice-test1-2host.yaml"
    spec_path.write_text(spec)
    pods = apply_spec(cluster, spec_path)
    assert len(pods) == 2

    port = free_port()
    children = []
    for pod in pods:
        env = dict(pod.env)
        # the seat wired tpu-host-0:8476; re-point at this test's real TCP
        # port on localhost (the cluster DNS name cannot resolve here)
        env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
        force_cpu_env(env, n_devices=n_devices)
        env["PYTHONPATH"] = str(REPO_ROOT)
        children.append(
            subprocess.Popen(
                [sys.executable, "-c", worker_src],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for child in children:
            out, err = child.communicate(timeout=timeout)
            assert child.returncode == 0, f"worker failed:\n{err[-3000:]}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for c in children:
            if c.poll() is None:
                c.kill()
                c.wait()
    return outs
