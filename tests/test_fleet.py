"""Fleet router unit suite: the Engine protocol conformance matrix and the
router's three responsibilities exercised WITHOUT injected faults.

* Engine protocol (models/fleet.py): both engine kinds satisfy it — not
  just structurally (runtime_checkable only proves member presence) but
  by signature (submit/restore/pump parameter surfaces), by Completion
  status vocabulary (serve.TERMINAL_STATUSES), and by stats() field set
  (telemetry.EngineStats) — so a replica kind cannot drift out of
  interchangeability silently.
* Health-gated routing: least-loaded placement, prefix/LoRA affinity
  stickiness, suspect/breaker gating.
* Live migration: planned drain() continues every stream bit-equally on
  the surviving replica under ONE journal correlation, parks overflow,
  and balances the source's accounting.
* Fleet admission: bounded front-door queue with typed sheds carrying a
  fleet-wide retry-after, and per-request admission deadline budgets.

Fault-injected variants (crash/wedge/stale storms) live in
tests/test_fleet_chaos.py (`make chaos-fleet`).
"""

import dataclasses
import inspect

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, fleet, lora, paged, serve
from k8s_dra_driver_tpu.models.fleet import (
    DRAINED,
    EVACUATING,
    HEALTHY,
    ID_STRIDE,
    SUSPECT,
    Engine,
    FleetPolicy,
    FleetRouter,
    debug_fleet_doc,
)
from k8s_dra_driver_tpu.models.serve import Completion, ServeEngine, ShedError
from k8s_dra_driver_tpu.models.telemetry import EngineStats
from k8s_dra_driver_tpu.utils.journal import JOURNAL

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 33)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


REQS = [
    {"prompt": [7, 8, 9], "max_tokens": 6, "seed": 5},
    {"prompt": [3, 4], "max_tokens": 6, "temperature": 0.7, "seed": 9},
    {"prompt": [11, 12, 13, 14], "max_tokens": 6, "seed": 21},
    {"prompt": [1, 2], "max_tokens": 5, "seed": 33},
    {"prompt": [21, 22, 23], "max_tokens": 5, "seed": 44},
]


def _by_prompt(completions):
    """prompt-tuple -> generated-tuple: replica-minted ids differ between a
    fleet run and a single-engine reference, prompts don't."""
    return {
        tuple(c.tokens[: len(c.tokens) - len(c.generated)]): tuple(c.generated)
        for c in completions
        if c.status == "ok"
    }


class TestEngineProtocol:
    """The conformance matrix: every replica kind against the formal
    Engine contract."""

    def test_both_engine_kinds_satisfy_protocol(self, params):
        for eng in (_dense(params), _paged(params)):
            assert isinstance(eng, Engine)

    def test_plain_object_is_rejected_with_missing_members(self, params):
        with pytest.raises(TypeError, match="Engine"):
            FleetRouter([object()])

    @pytest.mark.parametrize("make", [_dense, _paged], ids=["dense", "paged"])
    def test_submit_signature_surface(self, params, make):
        sig = inspect.signature(make(params).submit)
        names = set(sig.parameters)
        # The shared admission surface every router placement relies on.
        assert {
            "prompt", "max_tokens", "temperature", "seed", "adapter",
            "deadline", "queued_at",
        } <= names
        # Everything beyond (prompt, max_tokens) must stay optional, so the
        # router can route a minimal request to ANY replica kind.
        for name, p in sig.parameters.items():
            if name in ("prompt", "max_tokens"):
                continue
            assert p.default is not inspect.Parameter.empty, (
                f"submit({name}=...) has no default: replica kinds are no "
                f"longer interchangeable for minimal requests"
            )

    def test_paged_extends_dense_submit_surface(self, params):
        dense_names = set(inspect.signature(_dense(params).submit).parameters)
        paged_names = set(inspect.signature(_paged(params).submit).parameters)
        assert dense_names <= paged_names
        assert "priority" in paged_names - dense_names

    @pytest.mark.parametrize("make", [_dense, _paged], ids=["dense", "paged"])
    def test_restore_and_pump_signatures(self, params, make):
        eng = make(params)
        restore = inspect.signature(eng.restore)
        assert restore.parameters["merge"].default is False
        pump = inspect.signature(eng.pump)
        assert pump.parameters["queue_limit"].default is None
        assert pump.parameters["max_steps"].default == 100_000

    def test_completion_status_vocabulary(self):
        assert serve.TERMINAL_STATUSES == {
            "ok", "deadline_exceeded", "cancelled", "quarantined", "shed",
            "error",
        }
        assert Completion(request_id=0, tokens=[1], generated=[]).status == "ok"

    @pytest.mark.parametrize("make", [_dense, _paged], ids=["dense", "paged"])
    def test_stats_returns_engine_stats_contract(self, params, make):
        st = make(params).stats()
        assert isinstance(st, EngineStats)
        fields = {f.name for f in dataclasses.fields(EngineStats)}
        # The load-signal fields the router's health verdicts and placement
        # scoring read; dropping one breaks fleets, not just dashboards.
        assert {
            "n_slots", "resident_slots", "admitting", "preempted",
            "free_blocks", "quarantined", "bursts", "last_step_s",
            "uptime_s", "heartbeat_age_s",
        } <= fields
        assert st.heartbeat_age_s >= 0.0


class TestMembership:
    def test_replicas_get_disjoint_id_ranges(self, params):
        router = FleetRouter([_dense(params), _dense(params), _paged(params)])
        for i, rep in enumerate(router.replicas):
            assert rep.engine._next_id == i * ID_STRIDE
        rids = [
            router.submit([5 + i, 6 + i], max_tokens=2) for i in range(3)
        ]
        strides = {rid // ID_STRIDE for rid in rids}
        assert len(rids) == len(set(rids))
        assert len(strides) == 3  # least-loaded spread one per replica

    def test_duplicate_replica_name_rejected(self, params):
        router = FleetRouter([("a", _dense(params))])
        with pytest.raises(ValueError, match="duplicate"):
            router.add_replica(_dense(params), name="a")


class TestRouting:
    def test_least_loaded_spread(self, params):
        router = FleetRouter([_dense(params), _dense(params)])
        owners = [
            router._owner[router.submit([9 + i, 1], max_tokens=2)].name
            for i in range(4)
        ]
        # free-slot scoring alternates: r0 (tie, lowest index), then r1...
        assert owners == ["r0", "r1", "r0", "r1"]

    def test_prefix_affinity_beats_one_slot_imbalance(self, params):
        router = FleetRouter([_dense(params), _dense(params)])
        warm = list(range(1, 9))  # affinity_prefix-long prompt
        rid = router.submit(warm, max_tokens=2)
        assert router._owner[rid].name == "r0"
        # r0 now one slot busier, so pure least-loaded would pick r1 —
        # the warm prefix cache must out-score a single-slot imbalance.
        rid2 = router.submit(list(warm), max_tokens=2)
        assert router._owner[rid2].name == "r0"
        # ...but a different prefix has no bonus and goes least-loaded.
        rid3 = router.submit([31, 32], max_tokens=2)
        assert router._owner[rid3].name == "r1"

    def test_adapter_affinity_sticks(self, params):
        cfg_lora = lora.LoraConfig(rank=2, alpha=4.0)
        bank = lora.stack_adapters(CFG, cfg_lora, [
            lora.init_adapters(jax.random.PRNGKey(s), CFG, cfg_lora)
            for s in (1, 2)
        ])
        router = FleetRouter([_dense(params, adapter_bank=bank),
                              _dense(params, adapter_bank=bank)])
        rid = router.submit([5, 6], max_tokens=2, adapter=1)
        home = router._owner[rid].name
        rid2 = router.submit([41, 42], max_tokens=2, adapter=1)
        assert router._owner[rid2].name == home

    def test_affinity_history_is_bounded(self, params):
        router = FleetRouter(
            [_dense(params)], policy=FleetPolicy(max_affinity_entries=4)
        )
        for i in range(10):
            router._remember(router._prefix_home, ("k", i), "r0")
        assert len(router._prefix_home) == 4
        assert ("k", 9) in router._prefix_home  # newest kept, oldest evicted

    def test_suspect_replica_takes_no_admissions(self, params):
        router = FleetRouter([_dense(params), _dense(params)])
        router.replicas[0].state = SUSPECT
        for i in range(3):
            rid = router.submit([7 + i, 8], max_tokens=2)
            assert router._owner[rid].name == "r1"

    def test_open_breaker_gates_admission(self, params):
        router = FleetRouter([_dense(params), _dense(params)])
        router.replicas[0].breaker.trip()
        rid = router.submit([7, 8], max_tokens=2)
        assert router._owner[rid].name == "r1"

    def test_submit_raises_when_fleet_is_full(self, params):
        router = FleetRouter([_dense(params, n_slots=1)])
        router.submit([5, 6], max_tokens=4)
        with pytest.raises(RuntimeError):
            router.submit([7, 8], max_tokens=4)

    def test_cancel_routes_to_owning_replica(self, params):
        router = FleetRouter([_dense(params), _dense(params)])
        rid = router.submit([5, 6, 7], max_tokens=10)
        router.replicas[0].engine.step()
        assert router.cancel(rid) is True
        assert router.cancel(rid) is False  # already retired
        assert router.cancel(999_999_999) is False  # never admitted
        (c,) = router.completions()
        assert c.status == "cancelled" and c.request_id == rid


class TestFleetPump:
    def test_pump_matches_single_engine_bit_equal(self, params):
        reference = _by_prompt(_dense(params).pump([dict(r) for r in REQS]))
        router = FleetRouter([_dense(params), _paged(params)])
        out = router.pump([dict(r) for r in REQS])
        assert len(out) == len(REQS)
        assert _by_prompt(out) == reference

    def test_fleet_shed_is_typed_with_fleet_retry_after(self, params):
        from k8s_dra_driver_tpu.utils.metrics import REGISTRY

        router = FleetRouter([_dense(params)])
        out = router.pump(
            [{"prompt": [i + 1, i + 2], "max_tokens": 3} for i in range(6)],
            queue_limit=0,
        )
        shed = [c for c in out if c.status == "shed"]
        served = [c for c in out if c.status == "ok"]
        assert len(served) == 3 and len(shed) == 3
        assert all(c.request_id == -1 for c in shed)
        assert isinstance(router.last_shed, ShedError)
        assert router.last_shed.retry_after_s > 0
        assert router.shed_count == 3
        assert REGISTRY.counter("tpu_fleet_shed_total").value() == 3

    def test_shed_rejects_newest_keeps_fifo(self, params):
        router = FleetRouter([_dense(params)])
        prompts = [[10 + i, 20 + i] for i in range(6)]
        out = router.pump(
            [{"prompt": p, "max_tokens": 3} for p in prompts], queue_limit=0
        )
        shed_prompts = sorted(tuple(c.tokens) for c in out if c.status == "shed")
        assert shed_prompts == sorted(tuple(p) for p in prompts[3:])

    def test_admission_deadline_budget_sheds_stale_waiters(self, params):
        router = FleetRouter([_dense(params)])
        reqs = [{"prompt": [i + 1, i + 2], "max_tokens": 3} for i in range(3)]
        reqs += [
            {"prompt": [51, 52], "max_tokens": 3, "admission_deadline_s": 0.0},
            {"prompt": [61, 62], "max_tokens": 3, "admission_deadline_s": 0.0},
        ]
        out = router.pump(reqs)
        assert sum(c.status == "ok" for c in out) == 3
        shed = [c for c in out if c.status == "shed"]
        assert sorted(tuple(c.tokens) for c in shed) == [(51, 52), (61, 62)]
        assert "deadline" in (shed[0].error or "")

    def test_fleet_retry_after_divides_by_live_replicas(self, params):
        # Same depth and step latency, twice the live replicas -> half the
        # retry-after hint: the fleet drains its queue in parallel.
        def hint(n_replicas):
            router = FleetRouter([_dense(params) for _ in range(n_replicas)])
            for rep in router.replicas:
                rep.last_stats = dataclasses.replace(
                    rep.engine.stats(), last_step_s=0.1
                )
            router._fleet_shed({"prompt": [1, 2]}, depth=10, why="test")
            return router.last_shed.retry_after_s

        assert hint(1) == pytest.approx(1.0)
        assert hint(2) == pytest.approx(0.5)

    def test_retry_after_counts_fresh_replicas_without_stats(self, params):
        # A just-added healthy replica has last_stats=None until its first
        # health tick, but it WILL absorb queue drain — the retry-after
        # denominator must count it (regression: the old denominator only
        # counted replicas with a cached stats snapshot).
        router = FleetRouter([_dense(params), _dense(params)])
        router.replicas[0].last_stats = dataclasses.replace(
            router.replicas[0].engine.stats(), last_step_s=0.1
        )
        assert router.replicas[1].last_stats is None
        router._fleet_shed({"prompt": [1, 2]}, depth=10, why="test")
        assert router.last_shed.retry_after_s == pytest.approx(0.5)

    def test_retry_after_excludes_draining_replicas(self, params):
        # An evacuating replica takes no admissions, so it cannot help
        # drain the queue — the hint must not be diluted by it.
        router = FleetRouter([_dense(params), _dense(params)])
        for rep in router.replicas:
            rep.last_stats = dataclasses.replace(
                rep.engine.stats(), last_step_s=0.1
            )
        router.replicas[1].state = EVACUATING
        router._fleet_shed({"prompt": [1, 2]}, depth=10, why="test")
        assert router.last_shed.retry_after_s == pytest.approx(1.0)

    def test_admittable_replicas_gates_state_and_breaker(self, params):
        router = FleetRouter([_dense(params) for _ in range(3)])
        assert len(router.admittable_replicas()) == 3  # fresh = admittable
        router.replicas[0].state = SUSPECT
        router.replicas[1].breaker.trip()
        assert [r.name for r in router.admittable_replicas()] == [
            router.replicas[2].name
        ]


class TestDrainMigration:
    def _mid_flight_router(self, params, second):
        """Two streams decoding on r0 for three steps, r1 idle."""
        router = FleetRouter([_dense(params)])
        router.submit([5, 6, 7], max_tokens=10, temperature=0.7, seed=3)
        router.submit([9, 1], max_tokens=10, seed=11)
        for _ in range(3):
            router.replicas[0].engine.step()
        router.add_replica(second, name="r1")
        return router

    def _reference(self, params):
        return _by_prompt(_dense(params).pump([
            {"prompt": [5, 6, 7], "max_tokens": 10, "temperature": 0.7, "seed": 3},
            {"prompt": [9, 1], "max_tokens": 10, "seed": 11},
        ]))

    @pytest.mark.parametrize("second", ["dense", "paged"])
    def test_drain_continues_streams_bit_equal(self, params, second):
        make = _dense if second == "dense" else _paged
        router = self._mid_flight_router(params, make(params))
        moved = router.drain("r0", reason="scale_down")
        assert len(moved) == 2
        assert router.replica("r0").state == DRAINED
        assert router.replica("r0").engine.free_slots() == 3
        out = router.pump([])
        assert _by_prompt(out) == self._reference(params)
        # ownership moved with the streams
        assert not router._owner

    def test_drain_journals_one_correlation_span(self, params):
        router = self._mid_flight_router(params, _dense(params))
        JOURNAL.clear()
        router.drain("r0")
        events = JOURNAL.tail(limit=100, component="fleet")
        corrs = {e["correlation"] for e in events if e["event"].startswith(("replica.", "evac."))}
        assert len(corrs) == 1, f"expected ONE evacuation correlation, got {corrs}"
        kinds = [e["event"] for e in events]
        for expected in (
            "replica.suspect", "replica.evacuating", "evac.snapshot",
            "evac.restore", "replica.drained", "evac.resumed",
        ):
            assert expected in kinds, f"missing {expected} in {kinds}"

    def test_drain_parks_overflow_until_capacity_frees(self, params):
        # Target has 1 slot for 2 evacuated streams: one restores now, one
        # parks at the router and resumes when the slot frees mid-pump.
        router = FleetRouter([_dense(params)])
        router.submit([5, 6, 7], max_tokens=10, temperature=0.7, seed=3)
        router.submit([9, 1], max_tokens=10, seed=11)
        for _ in range(3):
            router.replicas[0].engine.step()
        router.add_replica(_dense(params, n_slots=1), name="r1")
        moved = router.drain("r0")
        assert len(moved) == 1 and len(router._parked) == 1
        out = router.pump([])
        assert _by_prompt(out) == self._reference(params)
        assert not router._parked

    def test_restore_refusal_parks_instead_of_raising(self, params):
        # Regression (disagg PR): a decode replica that is ITSELF draining
        # can refuse restore(merge=True) ("needs an idle engine") under a
        # race.  The router used to let that RuntimeError escape — the
        # whole evacuation batch was lost.  Now the refused entries go
        # back to the parking lot and retry on the next tick.
        class RefusesOnce(ServeEngine):
            refusals = 0

            def restore(self, snap, merge=False):
                # refuse the first REAL batch (add_replica's id-stride
                # alignment restore carries no requests — let it through)
                if merge and snap["requests"] and RefusesOnce.refusals == 0:
                    RefusesOnce.refusals += 1
                    raise RuntimeError(
                        "restore(merge=True) needs an idle engine"
                    )
                return super().restore(snap, merge=merge)

        router = self._mid_flight_router(
            params,
            RefusesOnce(params=params, cfg=CFG, n_slots=3, prompt_bucket=16),
        )
        JOURNAL.clear()
        moved = router.drain("r0", reason="scale_down")
        assert moved == [] and len(router._parked) == 2
        kinds = [e["event"] for e in JOURNAL.tail(limit=100, component="fleet")]
        assert "evac.restore_refused" in kinds
        assert "evac.parked" in kinds
        out = router.pump([])
        assert RefusesOnce.refusals == 1
        assert _by_prompt(out) == self._reference(params)
        assert not router._parked and not router._owner

    def test_drain_with_no_survivors_parks_everything(self, params):
        router = FleetRouter([_dense(params)])
        router.submit([5, 6, 7], max_tokens=10, seed=3)
        moved = router.drain("r0")
        assert moved == [] and len(router._parked) == 1
        # a fleet with zero live replicas and parked work is wedged, loudly
        with pytest.raises(RuntimeError, match="every replica drained"):
            router.pump([])

    def test_drained_replica_is_reusable_after_readd(self, params):
        router = FleetRouter([_dense(params), _dense(params)])
        router.drain("r0", reason="rebalance")
        assert router.replica("r0").state == DRAINED
        out = router.pump([{"prompt": [4, 5], "max_tokens": 3}])
        assert [c.status for c in out] == ["ok"]
        assert router._owner == {}


class TestObservability:
    def test_stats_doc_shape(self, params):
        router = FleetRouter([_dense(params), _paged(params)])
        router.pump([dict(r) for r in REQS[:2]])
        doc = router.stats()
        assert doc["queue_depth"] == 0 and doc["parked"] == 0
        assert [r["name"] for r in doc["replicas"]] == ["r0", "r1"]
        for r in doc["replicas"]:
            assert r["state"] == HEALTHY
            assert r["breaker"] == "closed"
            assert r["stats"]["n_slots"] == 3

    def test_debug_fleet_doc_lists_live_routers(self, params):
        router = FleetRouter([_dense(params)])
        doc = debug_fleet_doc()
        seqs = [f["router_seq"] for f in doc["fleets"]]
        assert router.seq in seqs

    def test_debug_fleet_endpoint_serves_router_state(self, params):
        import json
        import urllib.request

        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        router = FleetRouter([_dense(params)])
        srv = DiagnosticsServer(port=0)
        srv.start()
        try:
            doc = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/fleet").read())
        finally:
            srv.stop()
        fleets = {f["router_seq"]: f for f in doc["fleets"]}
        mine = fleets[router.seq]
        assert mine["replicas"][0]["state"] == HEALTHY
        assert "queue_depth" in mine
