"""Request-lifecycle telemetry (models/telemetry.py).

Four layers of the PR's contract:

* SLO math against a FAKE clock: queue-wait / TTFT / TPOT / e2e derive
  exactly from the timeline anchors, bursts amortize K tokens per
  timestamp pair, mid-burst retirees flush before their status stamps,
  and migration merges two engines' halves into one contiguous timeline;
* real-engine integration across {dense, paged} x {greedy, spec, LoRA}
  plus the failure statuses (shed, deadline, quarantine): every pumped
  request's trace is complete, its journal correlation resolves, and the
  SLO histograms populate under the right ``status=`` label;
* the /debug/serve contract: per-engine EngineStats + by-request-id
  timeline over live HTTP, and the wedge bundle embedding;
* scrape hygiene: the telemetry metrics pass the lint checks, and the
  Prometheus text round-trip (render -> parse) is exact — including the
  single ``le="+Inf"`` line and float-sum precision.
"""

import json
import sys
import urllib.request
from pathlib import Path

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, lora, paged
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.models.telemetry import EngineTelemetry, debug_serve_doc
from k8s_dra_driver_tpu.utils.faults import FaultInjector
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, Histogram, parse_prom_text

REPO = Path(__file__).parent.parent

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)
LORA = lora.LoraConfig(rank=2, alpha=4.0)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def bank(params):
    ads = [lora.init_adapters(jax.random.PRNGKey(s), CFG, LORA) for s in (1, 2)]
    return lora.stack_adapters(CFG, LORA, ads)


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 33)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


def _scrape():
    return parse_prom_text(REGISTRY.render())


def _status_key(status):
    return (("status", status),)


# ---------------------------------------------------------------------------
# fake-clock unit layer: no jax, no engine — pure timeline math
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


class _HostA:
    """Weakref-able engine stand-in (the real engines are dataclasses with
    __eq__, which is why telemetry holds THEM by weakref, not a set)."""

    n_slots = 4
    sync_interval = 8
    host_syncs = 0

    def free_slots(self):
        return 4


class _HostB(_HostA):
    pass


class TestFakeClockSloMath:
    def test_ok_timeline_derives_every_slo(self):
        clk = FakeClock(100.0)
        tel = EngineTelemetry(_HostA(), clock=clk)
        clk.t = 100.5
        tel.on_admit(7, prompt_len=3, max_tokens=9,
                     submitted_at=tel.now(), queued_at=100.0)
        clk.t = 101.0
        tel.burst_begin(4, step_no=1)
        tel.on_commit(7, 4)
        clk.t = 101.8
        tel.burst_end(occupancy=2)
        clk.t = 102.5
        tel.on_retire(7, "ok", 5)

        tr = tel.trace(7)
        assert tr["status"] == "ok" and tr["generated"] == 5
        assert tr["queue_wait_s"] == pytest.approx(0.5)
        assert tr["ttft_s"] == pytest.approx(0.5)      # arrival -> activation
        assert tr["e2e_s"] == pytest.approx(2.5)
        # 4 burst tokens over retired-first_token: (102.5-100.5)/(5-1)
        assert tr["tpot_s"] == pytest.approx(0.5)
        # the burst record carries the amortized pair, not per-token stamps
        (burst,) = tr["bursts"]
        assert burst["tokens"] == 4 and burst["t0"] == 101.0 and burst["t1"] == 101.8

        doc = _scrape()
        ok = _status_key("ok")
        assert doc["tpu_serve_ttft_seconds_count"][ok] == 1
        assert doc["tpu_serve_ttft_seconds_sum"][ok] == pytest.approx(0.5)
        assert doc["tpu_serve_queue_wait_seconds_sum"][ok] == pytest.approx(0.5)
        assert doc["tpu_serve_e2e_seconds_sum"][ok] == pytest.approx(2.5)
        assert doc["tpu_serve_tpot_seconds_sum"][ok] == pytest.approx(0.5)
        assert doc["tpu_serve_burst_committed_tokens_count"][()] == 1
        assert doc["tpu_serve_batch_occupancy"][()] == 2

    def test_direct_submit_has_zero_queue_wait(self):
        clk = FakeClock(5.0)
        tel = EngineTelemetry(_HostA(), clock=clk)
        tel.on_admit(1, prompt_len=2, max_tokens=4, submitted_at=5.0)
        clk.t = 6.0
        tel.on_retire(1, "ok", 1)
        tr = tel.trace(1)
        assert tr["queued_at"] == tr["submitted_at"] == 5.0
        assert tr["queue_wait_s"] == 0.0

    def test_chunked_admission_stamps_ttft_at_final_chunk(self):
        clk = FakeClock(10.0)
        tel = EngineTelemetry(_HostA(), clock=clk)
        tel.on_admit(1, prompt_len=32, max_tokens=4, submitted_at=10.0,
                     queued_at=9.0, activated=False)
        clk.t = 10.2
        tel.on_admission_chunk(1)
        clk.t = 10.4
        tel.on_admission_chunk(1)
        clk.t = 10.6
        tel.on_activate(1)
        tr = tel.trace(1)
        assert tr["admission_chunks"] == 2
        assert tr["admitted_at"] == tr["first_token_at"] == 10.6
        assert tr["ttft_s"] == pytest.approx(1.6)
        assert tr["generated"] == 1  # activation committed the first token

    def test_single_token_request_has_no_tpot(self):
        clk = FakeClock(0.0)
        tel = EngineTelemetry(_HostA(), clock=clk)
        tel.on_admit(1, prompt_len=2, max_tokens=1, submitted_at=0.0)
        clk.t = 1.0
        tel.on_retire(1, "ok", 1)
        assert tel.trace(1)["tpot_s"] is None
        # nothing observed into the TPOT histogram at all
        assert "tpu_serve_tpot_seconds_count" not in _scrape()

    def test_shed_observes_queue_wait_under_shed_status(self):
        clk = FakeClock(50.0)
        tel = EngineTelemetry(_HostA(), clock=clk)
        clk.t = 51.5
        tel.on_shed(queued_at=50.0)
        doc = _scrape()
        assert doc["tpu_serve_queue_wait_seconds_sum"][_status_key("shed")] == (
            pytest.approx(1.5)
        )
        assert tel.stats().statuses == {"shed": 1}

    def test_mid_burst_retiree_flushes_before_status(self):
        clk = FakeClock(0.0)
        tel = EngineTelemetry(_HostA(), clock=clk)
        tel.on_admit(3, prompt_len=2, max_tokens=8, submitted_at=0.0)
        clk.t = 1.0
        tel.burst_begin(8)
        tel.on_commit(3, 2)
        clk.t = 1.5
        tel.on_retire(3, "deadline_exceeded", 3)
        tr = tel.trace(3)
        assert tr["status"] == "deadline_exceeded" and tr["generated"] == 3
        assert len(tr["bursts"]) == 1 and tr["bursts"][0]["tokens"] == 2
        # the replay at burst close must not re-attribute the flushed rid
        clk.t = 2.0
        tel.burst_end(occupancy=0)
        tr = tel.trace(3)
        assert tr["generated"] == 3 and len(tr["bursts"]) == 1

    def test_disabled_telemetry_is_inert(self):
        tel = EngineTelemetry(_HostA(), enabled=False, clock=FakeClock())
        assert tel.now() is None
        tel.on_admit(1, prompt_len=2, max_tokens=4)
        tel.burst_begin(4)
        tel.on_commit(1, 4)
        tel.burst_end(1)
        tel.on_retire(1, "ok", 5)
        assert tel.trace(1) is None
        assert "tpu_serve_ttft_seconds_count" not in _scrape()

    def test_migration_merges_one_contiguous_timeline(self):
        clk = FakeClock(5.0)
        tel_a = EngineTelemetry(_HostA(), clock=clk)
        tel_a.on_admit(2, prompt_len=2, max_tokens=8,
                       submitted_at=5.0, queued_at=4.0)
        clk.t = 6.0
        tel_a.burst_begin(4)
        tel_a.on_commit(2, 4)
        clk.t = 6.5
        tel_a.burst_end(1)

        # the trace rides the drain snapshot as plain JSON
        doc = json.loads(json.dumps(tel_a.export_trace(2)))
        tel_b = EngineTelemetry(_HostB(), clock=clk)
        clk.t = 7.0
        tel_b.import_trace(2, doc)
        tel_b.on_restore(2, resumed_at=7)
        clk.t = 8.0
        tel_b.on_retire(2, "ok", 0)  # 0: keep the accumulated count

        tr = tel_b.trace(2)
        assert tr["migrations"] == 1
        assert tr["engines"] == ["_HostA", "_HostB"]
        # original anchors survive the hop: TTFT/e2e span BOTH engines
        assert tr["queued_at"] == 4.0 and tr["submitted_at"] == 5.0
        assert tr["ttft_s"] == pytest.approx(1.0)
        assert tr["e2e_s"] == pytest.approx(4.0)
        assert tr["generated"] == 5
        names = [e["event"] for e in tr["events"]]
        assert "migrate_in" in names and "restore" in names


# ---------------------------------------------------------------------------
# real-engine integration
# ---------------------------------------------------------------------------

FEATURES = {
    "greedy": dict(kw={}),
    "spec": dict(kw=dict(spec_gamma=2)),
    "lora": dict(kw="bank"),
}
REQS = [
    {"prompt": [5, 6, 7], "max_tokens": 8},
    {"prompt": [9, 1], "max_tokens": 8},
]


def _engine(params, bank, kind, feature, **extra):
    kw = FEATURES[feature]["kw"]
    kw = dict(adapter_bank=bank) if kw == "bank" else dict(kw)
    kw.update(extra)
    return _dense(params, **kw) if kind == "dense" else _paged(params, **kw)


class TestEngineTimelines:
    @pytest.mark.parametrize("feature", sorted(FEATURES))
    @pytest.mark.parametrize("kind", ["dense", "paged"])
    def test_pumped_timeline_is_complete(self, params, bank, kind, feature):
        eng = _engine(params, bank, kind, feature, sync_interval=4)
        reqs = [dict(r) for r in REQS]
        if feature == "lora":
            for i, r in enumerate(reqs):
                r["adapter"] = i + 1
        done = eng.pump(reqs)
        assert len(done) == len(reqs)
        for c in done:
            tr = eng.telemetry.trace(c.request_id)
            assert tr is not None and tr["status"] == "ok"
            # anchors exist and are ordered; first token == activation
            assert (tr["queued_at"] <= tr["submitted_at"]
                    <= tr["admitted_at"] <= tr["retired_at"])
            assert tr["first_token_at"] == tr["admitted_at"]
            assert tr["generated"] == len(c.generated)
            # K tokens per timestamp pair: every generated token after the
            # first is attributed to exactly one burst record
            assert sum(b["tokens"] for b in tr["bursts"]) == tr["generated"] - 1
            assert tr["ttft_s"] >= 0 and tr["e2e_s"] >= tr["ttft_s"]
            assert tr["tpot_s"] is not None  # >= 2 tokens generated
            # the journal correlation resolves the same retirement
            events = JOURNAL.tail(correlation=f"req-{c.request_id}")
            assert any(e["event"] == "request.timeline" for e in events)

        doc = _scrape()
        assert doc["tpu_serve_ttft_seconds_count"][_status_key("ok")] == len(done)
        stats = eng.stats()
        assert stats.completed == len(done) and stats.in_flight == 0
        assert stats.statuses == {"ok": len(done)}
        assert stats.bursts > 0 and stats.tokens_generated > 0
        assert stats.ttft_p50_s >= 0 and stats.tpot_p50_s > 0

    def test_shed_and_deadline_statuses(self, params):
        eng = _dense(params, n_slots=1)
        done = eng.pump(
            [
                {"prompt": [1, 2, 3], "max_tokens": 10, "deadline": 2},
                {"prompt": [4, 5], "max_tokens": 4},
                {"prompt": [6, 7], "max_tokens": 4},
            ],
            queue_limit=0,
        )
        by_status = {}
        for c in done:
            by_status.setdefault(c.status, []).append(c)
        assert len(by_status["deadline_exceeded"]) == 1
        assert len(by_status["shed"]) == 2
        dl = by_status["deadline_exceeded"][0]
        tr = eng.telemetry.trace(dl.request_id)
        assert tr["status"] == "deadline_exceeded"
        doc = _scrape()
        assert doc["tpu_serve_ttft_seconds_count"][
            _status_key("deadline_exceeded")] == 1
        assert doc["tpu_serve_queue_wait_seconds_count"][_status_key("shed")] == 2
        stats = eng.stats()
        assert stats.statuses["deadline_exceeded"] == 1
        assert stats.statuses["shed"] == 2

    def test_quarantine_status_reaches_histograms(self, params, bank):
        eng = _paged(
            params,
            adapter_bank=bank,
            fault_injector=FaultInjector.from_env(
                "nan_logits_rate=1.0,slots=0,steps=2"
            ),
        )
        done = eng.pump([
            {"prompt": [5, 6, 7], "max_tokens": 8, "adapter": 1},
            {"prompt": [9, 1], "max_tokens": 8, "adapter": 2},
        ])
        quarantined = [c for c in done if c.status == "quarantined"]
        assert quarantined
        for c in quarantined:
            tr = eng.telemetry.trace(c.request_id)
            assert tr["status"] == "quarantined"
        doc = _scrape()
        assert doc["tpu_serve_e2e_seconds_count"][
            _status_key("quarantined")] == len(quarantined)
        assert eng.stats().statuses["quarantined"] == len(quarantined)

    def test_cross_engine_restore_keeps_one_timeline(self, params):
        src = _paged(params, sync_interval=2)
        for r in REQS:
            src.submit(**dict(r))
        src.step()
        snap = json.loads(json.dumps(src.snapshot_active()))
        dst = _dense(params)
        rids = sorted(dst.restore(snap))
        assert rids == [0, 1]
        dst.run_until_drained()
        for rid in rids:
            tr = dst.telemetry.trace(rid)
            assert tr["status"] == "ok"
            assert tr["migrations"] == 1
            assert tr["engines"] == ["PagedServeEngine", "ServeEngine"]
            assert any(e["event"] == "migrate_in" for e in tr["events"])
            # the pre-migration anchors and bursts survived: one timeline
            assert tr["queued_at"] is not None and tr["admitted_at"] is not None
            assert tr["retired_at"] >= tr["admitted_at"]
            assert tr["generated"] >= 2 and tr["bursts"]
            # by-id lookup resolves to the request's NEW home
            doc = debug_serve_doc(request_id=rid)
            assert doc["engine"] == "ServeEngine"
            assert doc["trace"]["migrations"] == 1


# ---------------------------------------------------------------------------
# the /debug/serve contract
# ---------------------------------------------------------------------------

class TestDebugServe:
    def test_http_endpoint_serves_stats_and_timeline(self, params):
        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        eng = _dense(params)
        done = eng.pump([([1, 2, 3], 4)])
        rid = done[0].request_id
        srv = DiagnosticsServer(port=0, bind_host="127.0.0.1")
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            doc = json.loads(urllib.request.urlopen(f"{base}/debug/serve").read())
            ours = [e for e in doc["engines"]
                    if e["engine_seq"] == eng.telemetry.engine_seq]
            assert ours and ours[0]["completed"] == 1
            assert ours[0]["statuses"] == {"ok": 1}
            assert any(
                s["request_id"] == rid and s["status"] == "ok"
                for s in doc["recent_traces"]
            )
            one = json.loads(urllib.request.urlopen(
                f"{base}/debug/serve?request_id={rid}").read())
            tr = one["trace"]
            assert tr["status"] == "ok"
            assert tr["retired_at"] >= tr["admitted_at"]
        finally:
            srv.stop()

    def test_wedge_bundle_embeds_stats_and_traces(
        self, params, tmp_path, monkeypatch
    ):
        from k8s_dra_driver_tpu.utils.watchdog import WATCHDOG

        monkeypatch.setattr(WATCHDOG, "_bundle_dir", str(tmp_path))
        eng = _dense(params, sync_interval=4)
        eng.submit([1, 2, 3], max_tokens=60)
        with pytest.raises(RuntimeError, match="diag bundle"):
            eng.run_until_drained(max_steps=2)
        bundles = sorted(
            p for p in tmp_path.glob("*.json") if "drain-snapshot" not in p.name
        )
        state = json.loads(bundles[-1].read_text())["state"]
        assert state["engine_stats"]["engine"] == "ServeEngine"
        assert state["engine_stats"]["in_flight"] == 1
        assert state["recent_traces"], "wedged request's trace missing"
        assert state["recent_traces"][0]["status"] == "in-flight"


# ---------------------------------------------------------------------------
# scrape hygiene & the text-format round-trip
# ---------------------------------------------------------------------------

class TestScrapeHygiene:
    def _lint(self):
        sys.path.insert(0, str(REPO / "tools"))
        try:
            import lint
        finally:
            sys.path.pop(0)
        return lint

    def test_telemetry_metrics_pass_lint(self):
        lint = self._lint()
        path = REPO / "k8s_dra_driver_tpu" / "models" / "telemetry.py"
        assert lint.check_file(path) == []

    def test_metric_docs_contract_holds(self):
        lint = self._lint()
        models = sorted((REPO / "k8s_dra_driver_tpu" / "models").glob("*.py"))
        arch = (REPO / "ARCHITECTURE.md").read_text()
        assert lint.check_metric_docs(models, arch) == []

    def test_explicit_inf_bucket_renders_one_inf_line(self):
        h = Histogram("rt_seconds", "roundtrip", buckets=(0.1, 1, float("inf")))
        h.observe(0.05, status="ok")
        h.observe(9.0, status="ok")
        text = "\n".join(h.render()) + "\n"
        assert text.count('le="+Inf"') == 1
        assert 'le="inf"' not in text
        # finite-bound rendering unchanged: int bound 1 stays le="1"
        assert 'le="1"' in text and 'le="1.0"' not in text

    def test_render_parse_roundtrip_is_exact(self):
        h = Histogram("rt_seconds", "roundtrip",
                      buckets=(0.005, 0.1, 1, float("inf")))
        values = (0.1 + 0.2, 1e-9, 3.5)  # 0.30000000000000004: repr territory
        for v in values:
            h.observe(v, status="ok")
        doc = parse_prom_text("\n".join(h.render()) + "\n")
        ok = _status_key("ok")
        total = 0.0
        for v in values:
            total += v
        assert doc["rt_seconds_sum"][ok] == total  # exact, not approx
        assert doc["rt_seconds_count"][ok] == 3
        assert doc["rt_seconds_bucket"][
            tuple(sorted((("status", "ok"), ("le", "+Inf"))))] == 3

    def test_registry_scrape_roundtrip_after_real_traffic(self, params):
        eng = _dense(params)
        eng.pump([([1, 2, 3], 6)])
        text = REGISTRY.render()
        doc = parse_prom_text(text)
        # every _count in the scrape re-parses to the value the histogram
        # reports through its API — the two views cannot drift
        ttft = REGISTRY.histogram("tpu_serve_ttft_seconds")
        assert doc["tpu_serve_ttft_seconds_count"][_status_key("ok")] == (
            ttft.count(status="ok")
        )


class TestFleetSignals:
    """The fleet half of the load-signal contract (PR 7): the heartbeat
    field the router's wedge verdict reads, and the tpu_fleet_* metric
    inventory asserted through the exact render -> parse round-trip."""

    def test_heartbeat_age_tracks_observable_progress(self):
        clk = FakeClock(100.0)
        tel = EngineTelemetry(_HostA(), clock=clk)
        clk.t = 130.0  # idle engine: age grows from construction
        assert tel.stats().heartbeat_age_s == pytest.approx(30.0)
        tel.on_admit(1, prompt_len=2, max_tokens=8, submitted_at=130.0)
        assert tel.stats().heartbeat_age_s == pytest.approx(0.0)
        clk.t = 131.0
        tel.burst_begin(4)
        tel.on_commit(1, 4)
        clk.t = 131.5
        tel.burst_end(occupancy=1)  # burst boundary stamps the beat
        assert tel.stats().heartbeat_age_s == pytest.approx(0.0)
        clk.t = 140.0  # no progress since: the age is the stall evidence
        assert tel.stats().heartbeat_age_s == pytest.approx(8.5)
        tel.on_retire(1, "ok", 4)
        assert tel.stats().heartbeat_age_s == pytest.approx(0.0)

    def test_fleet_metrics_render_parse_roundtrip(self, params):
        from k8s_dra_driver_tpu.models.fleet import FleetRouter

        router = FleetRouter([_dense(params), _dense(params)])
        out = router.pump(
            [{"prompt": [i + 1, i + 2], "max_tokens": 3} for i in range(8)],
            queue_limit=0,
        )
        sheds = sum(c.status == "shed" for c in out)
        assert sheds > 0
        router.drain("r0", reason="scale_down")
        doc = parse_prom_text(REGISTRY.render())
        states = doc["tpu_fleet_replicas"]
        assert states[(("state", "healthy"),)] == 1
        assert states[(("state", "drained"),)] == 1
        assert states[(("state", "suspect"),)] == 0
        assert states[(("state", "evacuating"),)] == 0
        assert doc["tpu_fleet_evacuations_total"][
            (("reason", "scale_down"),)
        ] == 1
        assert doc["tpu_fleet_shed_total"][()] == sheds
        assert doc["tpu_fleet_queue_depth"][()] == 0
