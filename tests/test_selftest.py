"""Runtime self-test (tpuinfo/selftest.py) + the driver's health overlay.

The probe itself runs on whatever backend the suite has (forced CPU) — its
job in tests is contract shape; the compute path is exercised for real by
`tpu-ctl selftest` on hardware.  The driver integration is fully testable:
stubbed probe reports become `selftest-failed` health overlays on the
published inventory, and recovery clears them."""

import pytest

from k8s_dra_driver_tpu.e2e.harness import make_cluster
from k8s_dra_driver_tpu.plugin.driver import Driver, DriverConfig
from k8s_dra_driver_tpu.tpuinfo import selftest


class TestProbe:
    def test_inprocess_passes_on_cpu(self):
        report = selftest.run_inprocess(size=32)
        assert report["ok"] is True
        assert report["devices"]
        for dev in report["devices"]:
            assert dev["ok"] is True
            assert dev["latency_ms"] >= 0

    def test_subprocess_roundtrip(self):
        report = selftest.run_selftest(timeout_s=120, size=32)
        assert report["ok"] is True
        assert report["devices"]

    def test_timeout_is_a_result_not_a_hang(self):
        report = selftest.run_selftest(timeout_s=0.01, size=32)
        assert report["ok"] is False
        assert "timed out" in report["error"]

    def test_cli_human_output(self, capsys):
        rc = selftest.main(["--size", "32"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "device 0: OK" in out

    def test_cli_json_single_line(self, capsys):
        import json

        rc = selftest.main(["--size", "32", "--json"])
        assert rc == 0
        report = json.loads(capsys.readouterr().out.strip())
        assert report["ok"] is True


def _fake_env():
    return {"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"}


@pytest.fixture
def rig(tmp_path):
    cluster = make_cluster(hosts=1, work_dir=str(tmp_path / "w"))
    driver = Driver(
        cluster.server,
        DriverConfig(
            node_name="tpu-host-0",
            cdi_root=str(tmp_path / "cdi"),
            checkpoint_path=str(tmp_path / "cp.json"),
            topology_env=_fake_env(),
            selftest_interval_s=0.0001,  # due on every sweep
        ),
    )
    return cluster, driver


class _FakeRun:
    def __init__(self, report):
        self._report = report
        self.cancelled = False

    def alive(self):
        return False

    def cancel(self):
        self.cancelled = True

    def result(self):
        if self.cancelled:
            return {"ok": False, "platform": None, "devices": [],
                    "cancelled": True, "error": "selftest cancelled"}
        return self._report


def _stub_report(monkeypatch, report, calls=None):
    def fake_start_selftest(timeout_s):
        if calls is not None:
            calls.append(timeout_s)
        return _FakeRun(report)

    monkeypatch.setattr(selftest, "start_selftest", fake_start_selftest)


def _chip_health(cluster):
    devs = {}
    for s in cluster.server.list("ResourceSlice"):
        if s.spec.pool.name != "tpu-host-0":
            continue
        for d in s.spec.devices:
            attrs = d.basic.attributes
            if attrs["type"].string == "tpu":
                reason = attrs["healthReason"].value if "healthReason" in attrs else ""
                devs[d.name] = (attrs["healthy"].value, reason)
    return devs


class TestDriverOverlay:
    def test_whole_run_failure_fences_the_node(self, rig, monkeypatch):
        cluster, driver = rig
        _stub_report(monkeypatch, {"ok": False, "platform": None, "devices": [],
                                   "error": "selftest timed out after 30s"})
        assert driver.refresh_inventory() is True
        health = _chip_health(cluster)
        assert len(health) == 4
        assert all(h == (False, "selftest-failed") for h in health.values())

    def test_single_device_failure_fences_one_chip(self, rig, monkeypatch):
        cluster, driver = rig
        devices = [{"id": i, "platform": "tpu", "ok": i != 2} for i in range(4)]
        _stub_report(monkeypatch, {"ok": False, "platform": "tpu", "devices": devices})
        assert driver.refresh_inventory() is True
        health = _chip_health(cluster)
        bad = {name for name, (ok, _) in health.items() if not ok}
        assert bad == {"tpu-2"}
        assert health["tpu-2"][1] == "selftest-failed"

    def test_count_mismatch_fences_the_node_not_a_guess(self, rig, monkeypatch):
        cluster, driver = rig
        devices = [{"id": 0, "platform": "tpu", "ok": False}]  # 1 device, 4 chips
        _stub_report(monkeypatch, {"ok": False, "platform": "tpu", "devices": devices})
        driver.refresh_inventory()
        health = _chip_health(cluster)
        assert all(not ok for ok, _ in health.values())

    def test_all_ok_count_mismatch_still_fences(self, rig, monkeypatch):
        # 3 passing devices against 4 published chips: a chip the runtime
        # cannot even see is the strongest failure signal — must fence.
        cluster, driver = rig
        devices = [{"id": i, "platform": "tpu", "ok": True} for i in range(3)]
        _stub_report(monkeypatch, {"ok": True, "platform": "tpu", "devices": devices})
        driver.refresh_inventory()
        assert all(not ok for ok, _ in _chip_health(cluster).values())

    def test_busy_node_skips_the_probe(self, rig, monkeypatch):
        # libtpu is process-exclusive: probing under a running workload
        # would fail spuriously AND disturb it — idle nodes only.
        cluster, driver = rig
        calls = []
        _stub_report(monkeypatch, {"ok": True, "platform": "tpu", "devices": []}, calls)
        driver.state.prepared["some-claim-uid"] = object()
        try:
            driver.refresh_inventory()
        finally:
            del driver.state.prepared["some-claim-uid"]
        assert calls == []

    def test_recovery_clears_the_overlay(self, rig, monkeypatch):
        cluster, driver = rig
        _stub_report(monkeypatch, {"ok": False, "platform": None, "devices": [],
                                   "error": "boom"})
        driver.refresh_inventory()
        assert all(not ok for ok, _ in _chip_health(cluster).values())
        driver._last_selftest = 0.0
        _stub_report(monkeypatch, {
            "ok": True, "platform": "tpu",
            "devices": [{"id": i, "platform": "tpu", "ok": True} for i in range(4)],
        })
        assert driver.refresh_inventory() is True
        assert all(ok for ok, _ in _chip_health(cluster).values())

    def test_non_tpu_platform_says_nothing(self, rig, monkeypatch):
        cluster, driver = rig
        _stub_report(monkeypatch, {
            "ok": True, "platform": "cpu",
            "devices": [{"id": 0, "platform": "cpu", "ok": True}],
        })
        assert driver.refresh_inventory() is False  # no overlay, no change
        assert all(ok for ok, _ in _chip_health(cluster).values())

    def test_interval_gates_probe_frequency(self, rig, monkeypatch):
        cluster, driver = rig
        driver.config.selftest_interval_s = 3600.0
        calls = []
        _stub_report(monkeypatch, {
            "ok": True, "platform": "tpu",
            "devices": [{"id": i, "platform": "tpu", "ok": True} for i in range(4)],
        }, calls)
        driver.refresh_inventory()
        driver.refresh_inventory()
        driver.refresh_inventory()
        assert len(calls) == 1  # once per hour, not per sweep

    def test_prepare_cancels_inflight_probe(self, rig):
        # A workload arriving mid-probe must kill the probe (libtpu is
        # process-exclusive) — and the cancelled report must fence nothing.
        cluster, driver = rig
        run = _FakeRun({"ok": False, "platform": None, "devices": [],
                        "error": "would-have-fenced"})
        driver._selftest_run = run
        driver.node_prepare_resources([])  # empty batch still sweeps the cancel
        assert run.cancelled is True
        driver._selftest_report = run.result()
        driver._fold_selftest_report()
        assert all(ok for ok, _ in _chip_health(cluster).values())

    def test_report_folded_while_busy_discards_init_failures(self, rig, monkeypatch):
        # busy is recomputed at FOLD time: a claim prepared while the probe
        # ran explains an init failure (exclusive access), so no fencing.
        cluster, driver = rig
        driver._selftest_report = {"ok": False, "platform": None, "devices": [],
                                   "error": "backend init failed: device busy"}
        driver.state.prepared["uid"] = object()
        try:
            driver._fold_selftest_report()
        finally:
            del driver.state.prepared["uid"]
        assert all(ok for ok, _ in _chip_health(cluster).values())

    def test_disabled_by_default(self, tmp_path, monkeypatch):
        cluster = make_cluster(hosts=1, work_dir=str(tmp_path / "w2"))
        calls = []
        _stub_report(monkeypatch, {"ok": True, "platform": "tpu", "devices": []}, calls)
        driver = Driver(
            cluster.server,
            DriverConfig(
                node_name="tpu-host-0",
                cdi_root=str(tmp_path / "cdi2"),
                checkpoint_path=str(tmp_path / "cp2.json"),
                topology_env=_fake_env(),
            ),
        )
        driver.refresh_inventory()
        assert calls == []
