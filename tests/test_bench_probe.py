"""bench._wait_for_backend — the bounded retry that keeps one tunnel
outage from voiding a round's data plane.  The real probe is a subprocess
(tools/tunnel_probe.py); here it is monkeypatched so the schedule logic is
testable without a device link."""

import bench


def _patch(monkeypatch, results, sleeps):
    """probe() pops from ``results``; time.sleep records into ``sleeps``."""
    import tools.tunnel_probe as tp

    def fake_probe(timeout_s=90.0, quiet=False):
        return results.pop(0) if results else False

    monkeypatch.setattr(tp, "probe", fake_probe)
    monkeypatch.setattr(
        bench.time, "sleep", lambda s: sleeps.append(s)
    )


class TestWaitForBackend:
    def test_zero_budget_disables_entirely(self, monkeypatch):
        sleeps = []
        _patch(monkeypatch, [True], sleeps)  # would succeed if probed
        out = bench._wait_for_backend(0)
        assert out == {"ok": False, "attempts": 0, "waited_s": 0.0}
        assert sleeps == []

    def test_immediate_success_needs_one_attempt(self, monkeypatch):
        sleeps = []
        _patch(monkeypatch, [True], sleeps)
        out = bench._wait_for_backend(900)
        assert out["ok"] and out["attempts"] == 1
        assert sleeps == []  # first attempt has no preceding delay

    def test_backoff_then_recovery(self, monkeypatch):
        sleeps = []
        _patch(monkeypatch, [False, False, True], sleeps)
        out = bench._wait_for_backend(900)
        assert out["ok"] and out["attempts"] == 3
        assert sleeps == [30, 60]  # the documented backoff prefix

    def test_every_sleep_is_followed_by_a_probe(self, monkeypatch):
        """A recovered backend must never be reported down because the
        budget expired during a sleep — the last act is always a probe
        (the review finding that reshaped this loop)."""
        probes = []
        import tools.tunnel_probe as tp

        def fake_probe(timeout_s=90.0, quiet=False):
            probes.append(timeout_s)
            return False

        sleeps = []
        monkeypatch.setattr(tp, "probe", fake_probe)
        monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
        out = bench._wait_for_backend(100)
        assert not out["ok"]
        # one probe per loop iteration that slept (plus the first)
        assert len(probes) == len(sleeps) + 1
