"""bench._wait_for_backend — the bounded retry that keeps one tunnel
outage from voiding a round's data plane.  The real probe is a subprocess
(tools/tunnel_probe.py); here it is monkeypatched so the schedule logic is
testable without a device link."""

import bench


def _patch(monkeypatch, results, sleeps):
    """probe() pops from ``results``; time.sleep records into ``sleeps``."""
    import tools.tunnel_probe as tp

    def fake_probe(timeout_s=90.0, quiet=False):
        return results.pop(0) if results else False

    monkeypatch.setattr(tp, "probe", fake_probe)
    monkeypatch.setattr(bench, "_BACKEND_PROBE", None)  # fresh verdict cache
    monkeypatch.setattr(
        bench.time, "sleep", lambda s: sleeps.append(s)
    )


class TestWaitForBackend:
    def test_zero_budget_disables_entirely(self, monkeypatch):
        sleeps = []
        _patch(monkeypatch, [True], sleeps)  # would succeed if probed
        out = bench._wait_for_backend(0)
        assert out == {"ok": False, "attempts": 0, "waited_s": 0.0}
        assert sleeps == []

    def test_immediate_success_needs_one_attempt(self, monkeypatch):
        sleeps = []
        _patch(monkeypatch, [True], sleeps)
        out = bench._wait_for_backend(900)
        assert out["ok"] and out["attempts"] == 1
        assert sleeps == []  # first attempt has no preceding delay

    def test_backoff_then_recovery(self, monkeypatch):
        sleeps = []
        _patch(monkeypatch, [False, False, True], sleeps)
        out = bench._wait_for_backend(900)
        assert out["ok"] and out["attempts"] == 3
        assert sleeps == [30, 60]  # the documented backoff prefix

    def test_every_sleep_is_followed_by_a_probe(self, monkeypatch):
        """A recovered backend must never be reported down because the
        budget expired during a sleep — the last act is always a probe
        (the review finding that reshaped this loop)."""
        probes = []
        import tools.tunnel_probe as tp

        def fake_probe(timeout_s=90.0, quiet=False):
            probes.append(timeout_s)
            return False

        sleeps = []
        monkeypatch.setattr(tp, "probe", fake_probe)
        monkeypatch.setattr(bench, "_BACKEND_PROBE", None)
        monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
        out = bench._wait_for_backend(100)
        assert not out["ok"]
        # one probe per loop iteration that slept (plus the first)
        assert len(probes) == len(sleeps) + 1

    def test_verdict_caches_process_wide(self, monkeypatch):
        """The retry schedule runs ONCE per process: a second caller gets
        the cached verdict without re-probing (the per-scenario re-probe
        was burning the whole degraded-body budget on retries)."""
        sleeps = []
        _patch(monkeypatch, [False, True], sleeps)
        first = bench._wait_for_backend(900)
        assert first["ok"] and first["attempts"] == 2
        # the fake probe's results list is exhausted — any re-probe would
        # now return False and flip the verdict
        second = bench._wait_for_backend(900)
        assert second == first
        assert sleeps == [30]  # only the first call's backoff

    def test_disabled_wait_never_caches(self, monkeypatch):
        sleeps = []
        _patch(monkeypatch, [True], sleeps)
        out = bench._wait_for_backend(0)
        assert out["attempts"] == 0
        assert bench._BACKEND_PROBE is None  # no verdict to cache
        assert bench._wait_for_backend(900)["ok"]  # real probe still runs

    def test_failed_verdict_attaches_probe_error(self, monkeypatch):
        import tools.tunnel_probe as tp

        def fake_probe(timeout_s=90.0, quiet=False):
            tp.LAST_ERROR = "rc=1: RuntimeError: tunnel dead"
            return False

        monkeypatch.setattr(tp, "probe", fake_probe)
        monkeypatch.setattr(tp, "LAST_ERROR", "", raising=False)
        monkeypatch.setattr(bench, "_BACKEND_PROBE", None)
        monkeypatch.setattr(bench.time, "sleep", lambda s: None)
        out = bench._wait_for_backend(50)
        assert not out["ok"]
        assert out["last_error"] == "rc=1: RuntimeError: tunnel dead"


class TestDegradedDataPlane:
    """Probe-failed fallback: the artifact must record a real (reduced,
    CPU-pinned) data-plane number with the ``degraded`` marker instead of
    an error blob — the old 900s probe wait overran the 240s backend-down
    budget by itself."""

    def test_guard_dispatches_reduced_body(self, monkeypatch):
        calls = []

        def fake_degraded(sink=None):
            out = sink if sink is not None else {}
            out["serving_throughput"] = {"speedup": 1.9}
            calls.append("degraded")
            return out

        monkeypatch.setattr(bench, "_data_plane_degraded", fake_degraded)
        monkeypatch.setattr(
            bench, "run_data_plane", lambda sink=None: calls.append("full")
        )
        out = bench._run_data_plane_guarded(timeout_s=30, degraded=True)
        assert calls == ["degraded"]
        assert out["serving_throughput"]["speedup"] == 1.9

    def test_guard_healthy_path_unchanged(self, monkeypatch):
        calls = []
        monkeypatch.setattr(
            bench, "_data_plane_degraded",
            lambda sink=None: calls.append("degraded"),
        )

        def fake_full(sink=None):
            (sink if sink is not None else {})["matmul_tflops"] = 1.0
            calls.append("full")

        monkeypatch.setattr(bench, "run_data_plane", fake_full)
        out = bench._run_data_plane_guarded(timeout_s=30, degraded=False)
        assert calls == ["full"]
        assert out["matmul_tflops"] == 1.0

    def test_probe_budget_stays_under_degraded_body_budget(self):
        import os

        retry = float(os.environ.get("BENCH_BACKEND_RETRY_S", "120"))
        body = float(os.environ.get("BENCH_DATA_PLANE_TIMEOUT_S_DOWN", "240"))
        assert retry < body, (
            "the backend probe budget must cost less than the degraded "
            "data-plane body it gates, or the artifact times out again"
        )
