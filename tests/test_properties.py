"""Property-based tests for the subtle invariants (SURVEY.md §7 hard part #3:
"topology math for overlap capacities is easy to get subtly wrong").

Hypothesis generates topologies/claims; the properties assert the safety
invariants the whole scheduling scheme rests on.
"""

import itertools

from hypothesis import given, settings
from hypothesis import strategies as st

from k8s_dra_driver_tpu.api import HbmLimits
from k8s_dra_driver_tpu.kube.quantity import format_bytes, parse
from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices
from k8s_dra_driver_tpu.plugin.geometry import enumerate_subslices
from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology

# Standard fake topologies + a couple of explicit odd ones.
TOPOLOGIES = [
    "v5e-1", "v5e-4", "v5e-8", "v5e-16", "v5e-32", "v5e-256",
    "v4-4", "v4-8", "v4-16", "v4-64",
    "v5e-6x1", "v5e-2x3", "v4-2x2x3",
]


def topo(spec, host_id=0):
    return enumerate_topology(
        env={"TPUINFO_FAKE_TOPOLOGY": spec, "TPUINFO_FAKE_HOST_ID": str(host_id)}
    )


@st.composite
def host_topologies(draw):
    spec = draw(st.sampled_from(TOPOLOGIES))
    t = topo(spec)
    host_id = draw(st.integers(0, t.host_count - 1))
    return topo(spec, host_id)


class TestGeometryProperties:
    @settings(max_examples=40, deadline=None)
    @given(host_topologies())
    def test_overlap_markers_iff_shared_chip(self, t):
        """Two published devices share a chip marker iff they share a chip —
        the invariant that makes counter exclusion equal physical safety."""
        devices = AllocatableDevices.from_topology(t)
        chips = {}
        markers = {}
        for name, d in devices.devices.items():
            if d.chip is not None:
                chips[name] = {d.chip.local_pos}
            else:
                chips[name] = set(d.subslice.subslice.chip_indices)
            markers[name] = {
                c for c in d.get_device().basic.capacity if c.startswith("chip")
            }
        for a, b in itertools.combinations(devices.devices, 2):
            assert bool(chips[a] & chips[b]) == bool(markers[a] & markers[b]), (a, b)

    @settings(max_examples=40, deadline=None)
    @given(host_topologies())
    def test_subslices_within_block_and_contiguous(self, t):
        hb = t.host_bounds
        n = hb[0] * hb[1] * hb[2]
        for s in enumerate_subslices(t):
            assert all(0 <= i < n for i in s.chip_indices)
            assert len(set(s.chip_indices)) == s.chip_count
            # contiguity: covered coords form an axis-aligned box
            coords = sorted(
                (i % hb[0], (i // hb[0]) % hb[1], i // (hb[0] * hb[1]))
                for i in s.chip_indices
            )
            xs, ys, zs = zip(*coords)
            assert (max(xs) - min(xs) + 1) * (max(ys) - min(ys) + 1) * (
                max(zs) - min(zs) + 1
            ) == s.chip_count

    @settings(max_examples=40, deadline=None)
    @given(host_topologies())
    def test_same_shape_placements_partition_block(self, t):
        subs = enumerate_subslices(t)
        for shape in {s.shape for s in subs}:
            covered = [i for s in subs if s.shape == shape for i in s.chip_indices]
            assert len(covered) == len(set(covered)), shape  # disjoint

    @settings(max_examples=40, deadline=None)
    @given(host_topologies())
    def test_hbm_capacity_sums(self, t):
        devices = AllocatableDevices.from_topology(t)
        per_chip = t.chips[0].hbm_bytes
        for d in devices:
            cap = parse(d.get_device().basic.capacity["hbm"])
            expected = per_chip * (
                1 if d.chip is not None else d.subslice.subslice.chip_count
            )
            assert cap == expected


class TestAllocatorSafetyProperty:
    @settings(max_examples=25, deadline=None)
    @given(
        st.sampled_from(["v5e-8", "v5e-16", "v4-8"]),
        st.lists(
            st.tuples(
                st.sampled_from(["chip", "1x2", "2x1", "2x2", "2x4", "any-slice"]),
                st.integers(1, 2),
            ),
            min_size=1,
            max_size=8,
        ),
    )
    def test_no_chip_ever_double_booked(self, spec, requests):
        """Whatever mix of chip/subslice claims is thrown at the allocator,
        the union of physically covered chips across granted claims never
        overlaps — the MIG memorySlice guarantee, generalized."""
        from k8s_dra_driver_tpu import DRIVER_NAME
        from k8s_dra_driver_tpu.e2e.harness import (
            SUBSLICE_CLASS,
            TPU_CLASS,
            cel_selector,
            install_device_classes,
        )
        from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer
        from k8s_dra_driver_tpu.kube.objects import (
            DeviceClaim,
            DeviceRequest,
            ObjectMeta,
            ResourceClaim,
            ResourceClaimSpec,
        )
        from k8s_dra_driver_tpu.kube.resourceslice_controller import (
            DriverResources,
            Pool,
            ResourceSliceController,
            Slice,
        )
        from k8s_dra_driver_tpu.scheduler.allocator import AllocationError, Allocator

        t = topo(spec)
        server = InMemoryAPIServer()
        install_device_classes(server)
        devices = AllocatableDevices.from_topology(t)
        ResourceSliceController(server, DRIVER_NAME, "n").update(
            DriverResources(
                pools={"n": Pool(slices=[Slice(devices=devices.get_devices())], node_name="n")}
            )
        )
        allocator = Allocator(server)

        chips_of = {
            name: (
                {d.chip.local_pos} if d.chip is not None
                else set(d.subslice.subslice.chip_indices)
            )
            for name, d in devices.devices.items()
        }
        used: set = set()
        for i, (kind, count) in enumerate(requests):
            if kind == "chip":
                req = DeviceRequest(name="r", device_class_name=TPU_CLASS, count=count)
            elif kind == "any-slice":
                req = DeviceRequest(name="r", device_class_name=SUBSLICE_CLASS, count=count)
            else:
                req = DeviceRequest(
                    name="r",
                    device_class_name=SUBSLICE_CLASS,
                    count=count,
                    selectors=[
                        cel_selector(
                            f"device.attributes['{DRIVER_NAME}'].shape == '{kind}'"
                        )
                    ],
                )
            claim = server.create(
                ResourceClaim(
                    metadata=ObjectMeta(name=f"c{i}", namespace="d"),
                    spec=ResourceClaimSpec(devices=DeviceClaim(requests=[req])),
                )
            )
            try:
                granted = allocator.allocate(claim, node_name="n")
            except AllocationError:
                continue  # rejection is always safe
            for r in granted.status.allocation.devices.results:
                covered = chips_of[r.device]
                assert not (covered & used), (
                    f"chip double-booked: {r.device} overlaps {used}"
                )
                used |= covered


class TestQuantityProperties:
    @settings(max_examples=200, deadline=None)
    @given(st.integers(0, 2**62))
    def test_format_parse_roundtrip(self, n):
        assert parse(format_bytes(n)) == n


class TestHbmLimitProperties:
    @settings(max_examples=100, deadline=None)
    @given(
        st.lists(st.integers(1, 64), min_size=1, max_size=6, unique=True),
        st.integers(1, 1024),
        st.booleans(),
    )
    def test_wildcard_never_overrides_explicit(self, indices, gib, wildcard_first):
        # Both insertion orders: explicit keys must win either way.
        uuids = [f"u{i}" for i in indices]
        explicit = {uuids[0]: f"{gib}Gi"}
        limits = (
            HbmLimits({"*": "1Gi", **explicit})
            if wildcard_first
            else HbmLimits({**explicit, "*": "1Gi"})
        )
        out = limits.normalize(uuids)
        assert out[uuids[0]] == f"{gib * 1024}Mi"
        for u in uuids[1:]:
            assert out[u] == "1024Mi"


class TestPartitionPlanProperties:
    """plan_partitions (the MPS-division analog) must always produce
    disjoint, in-bounds consumer slots, for ANY subset of a host's chips
    the scheduler may have picked."""

    @given(
        spec=st.sampled_from(["v5e-16", "v5e-8", "v4-16"]),
        data=st.data(),
    )
    @settings(max_examples=40, deadline=None)
    def test_slots_disjoint_and_in_bounds(self, spec, data):
        from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevice, TpuChipInfo
        from k8s_dra_driver_tpu.plugin.sharing import plan_partitions

        topo = enumerate_topology(
            env={"TPUINFO_FAKE_TOPOLOGY": spec, "TPUINFO_FAKE_HOST_ID": "0"}
        )
        n_chips = len(topo.chips)
        positions = data.draw(
            st.lists(
                st.integers(min_value=0, max_value=n_chips - 1),
                min_size=1, max_size=n_chips, unique=True,
            )
        )
        devices = [
            AllocatableDevice(chip=TpuChipInfo(topo.chips[p], topo, local_pos=p))
            for p in positions
        ]
        plan = plan_partitions(devices, {})

        assert len(plan.per_device_env) == len(devices)
        # disjoint single-chip visibility
        visible = [env["TPU_VISIBLE_DEVICES"] for env in plan.per_device_env.values()]
        assert len(set(visible)) == len(visible)
        # coords distinct and within the advertised process grid
        bounds = tuple(int(x) for x in plan.process_bounds.split(","))
        coords = set()
        for env in plan.per_device_env.values():
            coord = tuple(int(x) for x in env["TPU_PROCESS_COORD"].split(","))
            assert all(0 <= c < b for c, b in zip(coord, bounds)), (coord, bounds)
            coords.add(coord)
            assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,1,1"
        assert len(coords) == len(devices)
        # grid is either the exact region box (volume == n) or the linear
        # fallback (n,1,1)
        volume = bounds[0] * bounds[1] * bounds[2]
        assert volume == len(devices) or bounds == (len(devices), 1, 1)
        # the daemon table mirrors the env slots
        assert [p["index"] for p in plan.partitions] == list(range(len(devices)))
        assert sorted(p["visible_devices"] for p in plan.partitions) == sorted(visible)
