"""tools/perf_smoke.py wired into the test gate: the hot-path perf budgets
(CEL evals memoized per inventory version, pool snapshots rebuilt only on
change, one checkpoint write per prepare/unprepare batch) are enforced on
every run, so a future PR cannot silently reintroduce
O(claims x devices x selectors) work or per-claim fsyncs."""

import sys
from pathlib import Path

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))

import perf_smoke  # noqa: E402


def test_hot_path_stays_within_perf_budgets():
    stats = perf_smoke.check()
    # check() raises PerfBudgetError on any busted ceiling; pin the headline
    # invariants here too so the test is self-describing.
    assert stats["cel_evals"] <= stats["cel_eval_ceiling"]
    assert stats["index_misses"] <= stats["index_miss_ceiling"]
    # Group commit: a BATCH_SIZE-claim call costs ONE durable write each
    # way, not one per claim.
    assert stats["batched_checkpoint_writes"] == 2 * stats["batch_rounds"]


def test_pipelined_decode_stays_within_perf_budgets():
    stats = perf_smoke.check_pipelined_decode()
    assert stats["requests"] == 8
    assert stats["elapsed_s"] <= stats["budget_s"]
    # The pipelined loop's reason to exist: host syncs amortize over
    # sync_interval-token bursts instead of one readback per token.
    assert stats["host_syncs"] <= stats["host_sync_ceiling"]
    assert stats["host_syncs"] < stats["generated_tokens"] / 4


def test_telemetry_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_telemetry_overhead()
    assert stats["requests"] == 8
    # The telemetry layer's hard invariant: lifecycle timing piggybacks on
    # burst-boundary readbacks the engine already pays for — the
    # instrumented pump syncs EXACTLY as often as its telemetry-off twin.
    assert stats["host_syncs_on"] == stats["host_syncs_off"]


def test_shed_fastpath_stays_within_perf_budgets():
    stats = perf_smoke.check_shed_fastpath()
    assert stats["served"] == 3 and stats["sheds"] == 5
    # Shedding's contract: typed rejection without ANY device dispatch —
    # the overloaded pump pays exactly the twin's host syncs.
    assert stats["host_syncs"] == stats["twin_host_syncs"]
    assert stats["elapsed_s"] <= stats["budget_s"]


def test_router_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_router_overhead()
    assert stats["requests_routed"] == 8
    # The fleet router's contract: placement is a host-side decision over
    # stats() snapshots — a 1-replica fleet dispatches EXACTLY the device
    # work of the bare engine (zero routing-added syncs).
    assert stats["host_syncs_routed"] == stats["host_syncs_bare"]


def test_handoff_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_handoff_overhead()
    assert stats["requests_disagg"] == 8
    # The disaggregation contract: the 1-prefill/1-decode pair pays at
    # most the unified engine's host syncs PLUS one KV-capture sync per
    # request (= one transfer per request), and every transfer delivers
    # on a fault-free channel.
    assert stats["host_syncs_disagg"] <= stats["host_sync_ceiling"]
    assert stats["transfers_ok"] == stats["requests_disagg"]


def test_transport_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_transport_overhead()
    assert stats["requests_wired"] == 8
    # The transport's contract: the real wire is host work (numpy + crc32
    # over already-captured bytes, frame/deque bookkeeping) — a loopback
    # TransportChannel pays EXACTLY the in-process channel's host syncs,
    # and every payload physically crosses and decodes at the far end.
    assert stats["host_syncs_wired"] == stats["host_syncs_inproc"]
    assert stats["transfers_ok"] == stats["requests_wired"]
    assert stats["frames_decoded"] == stats["requests_wired"]


def test_plan_scale_stays_within_perf_budgets():
    stats = perf_smoke.check_plan_scale()
    # Cluster-scale placement's contract: plan() against a 1k-node
    # inventory is index-backed dict work — latency stays flat in pool
    # count — and the churn slice accounts every claim exactly once.
    assert stats["plan_samples"] >= 100
    assert stats["plan_p90_ms"] <= stats["plan_p90_ceiling_ms"]
    assert stats["audit_failures"] == 0 and stats["leaked_claims"] == 0


def test_contention_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_contention_overhead()
    # The conflict-aware allocator's contract: with one scheduler and no
    # storm, every avoidance lever (tie shuffling, shard routing,
    # per-attempt refetch, backoff bookkeeping) is free — same plan()
    # ceilings as the naive-path churn slice, zero conflicts.
    assert stats["n_schedulers"] == 1
    assert stats["plan_samples"] >= 100
    assert stats["conflicts_total"] == 0
    assert stats["plan_p50_ms"] <= stats["plan_p50_ceiling_ms"]
    assert stats["plan_p90_ms"] <= stats["plan_p90_ceiling_ms"]


def test_obs_plane_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_obs_plane_overhead()
    assert stats["requests_shipped"] == 8
    # The observability plane's contract: a telemetry tick is cursor
    # exports + a registry render over host-resident rings — the router
    # with a force-every-tick shipper attached pays EXACTLY the bare
    # router's host syncs, every TELEM frame fits the 48 KiB ceiling,
    # and the snapshots really land in the fleet merger.
    assert stats["host_syncs_shipped"] == stats["host_syncs_bare"]
    assert stats["telem_frames"] > 0
    assert stats["telem_max_frame_bytes"] <= stats["telem_budget_bytes"]
    assert stats["instances_federated"] == ["perf-w"]


def test_autoscaler_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_autoscaler_overhead()
    assert stats["requests_scaled"] == 8
    # The autoscaler's contract: the control loop is host-side arithmetic
    # over stats() snapshots the router already collects — a 1-replica
    # fleet under a pinned (min==max==1) autoscaler pays EXACTLY the bare
    # fleet's host syncs and never touches the engine factory.
    assert stats["host_syncs_scaled"] == stats["host_syncs_bare"]
    assert stats["autoscaler_actions"] == 0
    assert stats["autoscaler_ticks"] > 0


def test_quantized_decode_stays_within_perf_budgets():
    stats = perf_smoke.check_quantized_decode()
    assert stats["requests"] == 4
    # The quantized pool's host-axis contract: dequant is fused into the
    # attention operand load on-device, so the int8-KV engine pays
    # EXACTLY the float pool's host syncs for the same workload.
    assert stats["host_syncs_int8"] == stats["host_syncs_float"]
    # And the reason the feature exists: >= 1.9x reservable blocks at an
    # equal HBM budget — the capacity the KV-demand ledger admits on.
    assert stats["capacity_ratio"] >= stats["capacity_ratio_floor"]


def test_ondevice_sampling_stays_within_perf_budgets():
    stats = perf_smoke.check_ondevice_sampling()
    assert stats["sync_interval"] == 32
    # On-device sampling's contract: sampling + stop masks live inside
    # the scanned burst and the trace planes ride ONE stacked array, so a
    # sync_interval=32 burst is 1 dispatch + 1 readback on BOTH engines.
    assert stats["dense_dispatches"] == 1 and stats["dense_readbacks"] == 1
    assert stats["paged_dispatches"] == 1 and stats["paged_readbacks"] == 1


def test_prefix_fleet_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_prefix_fleet_overhead()
    assert stats["requests_tiered"] == 8
    # The fleet prefix tier's contract: index publish and admission-time
    # lookup are host-side dict/digest work riding hooks the engine
    # already fires — the tier-attached fleet on all-miss traffic pays
    # EXACTLY the bare fleet's host syncs, entries really landed in the
    # index, and the miss-path prepare() stays under its p50 ceiling.
    assert stats["host_syncs_tiered"] == stats["host_syncs_bare"]
    assert stats["published_total"] > 0
    assert stats["lookup_p50_s"] <= stats["lookup_p50_ceiling_s"]


def test_prefix_gossip_overhead_stays_within_perf_budgets():
    stats = perf_smoke.check_prefix_gossip_overhead()
    assert stats["requests_gossiped"] == 8
    # The gossip plane's contract: PREFIXPUB/PREFIXWDL publishing is
    # host-side dict/json work riding hooks and cadence the worker pump
    # already pays for — a gossip-attached engine dispatches EXACTLY the
    # bare engine's device work, every shipped frame fits the TELEM-style
    # byte budget, and a publish storm sheds the shallow tail (accounted)
    # without ever losing an event.
    assert stats["host_syncs_gossiped"] == stats["host_syncs_bare"]
    assert stats["shipped_frames"] > 0
    assert stats["max_frame_bytes"] <= stats["budget_bytes"]
    assert stats["storm_shed_total"] > 0
    assert stats["storm_max_frame_bytes"] <= 2048
