"""Visible-chips masking — the nvkind params-masking analog (reference
values.yaml:41-48 / kubeletplugin.yaml:58-67): several kind workers on one
host each publish a disjoint share of its chips."""

import pytest

from k8s_dra_driver_tpu.plugin.device_state import (
    DeviceState,
    DeviceStateConfig,
    _parse_visible_chips,
)
from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices
from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology

V5E16_HOST = {"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"}


class TestParse:
    def test_empty_means_all(self):
        assert _parse_visible_chips("", 4) is None

    def test_comma_and_dot_forms(self):
        # '.' is the node-label form (label values cannot carry commas)
        assert _parse_visible_chips("0,2", 4) == {0, 2}
        assert _parse_visible_chips("0.2", 4) == {0, 2}

    def test_out_of_range_is_loud(self):
        with pytest.raises(ValueError, match="out of range"):
            _parse_visible_chips("0,7", 4)

    def test_garbage_is_loud(self):
        with pytest.raises(ValueError, match="invalid visible-chips"):
            _parse_visible_chips("0,x", 4)

    @pytest.mark.parametrize("spec", [".", ",", " ,"])
    def test_nonempty_spec_naming_no_chips_is_loud(self, spec):
        """A templating bug like '.' must not silently mean 'publish ALL'
        — that re-creates the double-booking the mask prevents."""
        with pytest.raises(ValueError, match="names no chip positions"):
            _parse_visible_chips(spec, 4)


class TestInventoryMasking:
    def topology(self):
        return enumerate_topology(env=V5E16_HOST)  # 4 local chips (2x2)

    def test_masked_chips_not_published(self):
        inv = AllocatableDevices.from_topology(self.topology(), visible={0, 1})
        chip_names = [d.chip.name for d in inv if d.chip is not None]
        assert sorted(chip_names) == ["tpu-0", "tpu-1"]

    def test_local_positions_preserved(self):
        """Masking must not renumber: chip markers / CDI paths follow the
        TRUE local index."""
        inv = AllocatableDevices.from_topology(self.topology(), visible={2, 3})
        names = sorted(d.chip.name for d in inv if d.chip is not None)
        assert names == ["tpu-2", "tpu-3"]

    def test_subslice_needs_every_member_visible(self):
        topo = self.topology()
        full = AllocatableDevices.from_topology(topo)
        sub_names = {d.subslice.name for d in full if d.subslice is not None}
        assert sub_names  # the host block publishes subslices at all
        # half the host visible: the 2x2 (whole-host) subslice must vanish;
        # a 2x1/1x2 shape fully inside {0,1} may survive
        masked = AllocatableDevices.from_topology(topo, visible={0, 1})
        for d in masked:
            if d.subslice is not None:
                assert set(d.subslice.subslice.chip_indices) <= {0, 1}

    def test_disjoint_shares_have_disjoint_uuids(self):
        """Two plugins on one (fake) host with complementary masks publish
        disjoint devices — the nvkind per-worker-subset property."""
        topo = self.topology()
        a = AllocatableDevices.from_topology(topo, visible={0, 1})
        b = AllocatableDevices.from_topology(topo, visible={2, 3})
        ua = {u for d in a for u in d.uuids()}
        ub = {u for d in b for u in d.uuids()}
        assert ua and ub and not (ua & ub)


class TestDeviceStateWiring:
    def test_state_publishes_masked_inventory(self, api_server, tmp_path):
        state = DeviceState(
            api_server,
            DeviceStateConfig(
                node_name="host0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "checkpoint.json"),
                topology_env=dict(V5E16_HOST),
                visible_chips="0,1",
            ),
        )
        names = sorted(state.allocatable.devices)
        assert "tpu-0" in names and "tpu-1" in names
        assert "tpu-2" not in names and "tpu-3" not in names

    def test_mask_survives_refresh(self, api_server, tmp_path):
        state = DeviceState(
            api_server,
            DeviceStateConfig(
                node_name="host0",
                cdi_root=str(tmp_path / "cdi"),
                checkpoint_path=str(tmp_path / "checkpoint.json"),
                topology_env=dict(V5E16_HOST),
                visible_chips="0.1",
            ),
        )
        # force a re-enumeration: the overlay makes the topology differ so
        # refresh() rebuilds allocatable — the mask must be re-applied
        state._health_overlay[0] = "test"
        assert state.refresh()
        names = sorted(state.allocatable.devices)
        assert "tpu-2" not in names and "tpu-3" not in names

    def test_bad_mask_fails_startup(self, api_server, tmp_path):
        with pytest.raises(ValueError, match="out of range"):
            DeviceState(
                api_server,
                DeviceStateConfig(
                    node_name="host0",
                    cdi_root=str(tmp_path / "cdi"),
                    checkpoint_path=str(tmp_path / "checkpoint.json"),
                    topology_env=dict(V5E16_HOST),
                    visible_chips="0,9",
                ),
            )
