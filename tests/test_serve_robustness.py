"""Drain/restore and early-retire robustness across the serving matrix.

tests/test_serve_chaos.py proves the four SLO properties under injected
faults; this suite pins the REST of the robustness contract:

* drain -> restore bit-equality across {dense, paged} x {greedy, sampled,
  spec, LoRA, prefix-cache}: a mid-flight snapshot restored into a fresh
  engine finishes every stream exactly as an uninterrupted engine would;
* quarantine-replay bit-equality composes with per-request LoRA;
* block-leak checks on EVERY early-retire path the robustness layer added
  (deadline, cancel resident, cancel parked, quarantine, unrestorable);
* the scrape/hygiene contract for the four new serving metrics.
"""

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, lora, paged, serve
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.utils.faults import FaultInjector
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)
LORA = lora.LoraConfig(rank=2, alpha=4.0)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def bank(params):
    def trained(seed):
        ad = lora.init_adapters(jax.random.PRNGKey(seed), CFG, LORA)
        for li, blk in enumerate(ad["blocks"]):
            for name, ab in blk.items():
                tag = li * 1000 + sum(ord(c) for c in name)
                key = jax.random.fold_in(jax.random.PRNGKey(seed), tag)
                ab["b"] = 0.3 * jax.random.normal(
                    key, ab["b"].shape, jax.numpy.float32
                )
        return ad

    return lora.stack_adapters(CFG, LORA, [trained(1), trained(2)])


# The restore matrix: every composing serving feature, each with requests
# exercising it.  ``kw``/``paged_kw`` extend the engine config; ``reqs``
# are submit kwargs (ids assign in submit order).
FEATURES = {
    "greedy": dict(
        kw={}, paged_kw={},
        reqs=[
            {"prompt": [5, 6, 7], "max_tokens": 8},
            {"prompt": [9, 1], "max_tokens": 8},
        ],
    ),
    "sampled": dict(
        kw={}, paged_kw={},
        reqs=[
            {"prompt": [5, 6, 7], "max_tokens": 8, "temperature": 0.7, "seed": 3},
            {"prompt": [9, 1], "max_tokens": 8, "temperature": 1.1, "seed": 11},
        ],
    ),
    "spec": dict(
        kw=dict(spec_gamma=2), paged_kw=dict(spec_gamma=2),
        reqs=[
            {"prompt": [5, 6, 7], "max_tokens": 8},
            {"prompt": [9, 1], "max_tokens": 8},
        ],
    ),
    "lora": dict(
        kw="bank", paged_kw="bank",
        reqs=[
            {"prompt": [5, 6, 7], "max_tokens": 8, "adapter": 1},
            {"prompt": [9, 1], "max_tokens": 8, "adapter": 2},
        ],
    ),
    "prefix": dict(
        kw=dict(prefix_bucket=4), paged_kw=dict(prefix_cache_blocks=4),
        # shared 4-token prefix: the second admission hits the store
        reqs=[
            {"prompt": [5, 6, 7, 8, 1], "max_tokens": 8},
            {"prompt": [5, 6, 7, 8, 2], "max_tokens": 8},
        ],
    ),
}


def _engine(params, bank, kind, feature, **extra):
    spec = FEATURES[feature]
    kw = spec["kw" if kind == "dense" else "paged_kw"]
    kw = dict(adapter_bank=bank) if kw == "bank" else dict(kw)
    kw.update(extra)
    if kind == "dense":
        kw.setdefault("n_slots", 3)
        kw.setdefault("prompt_bucket", 16)
        return ServeEngine(params=params, cfg=CFG, **kw)
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 33)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


class TestRestoreMatrix:
    @pytest.mark.parametrize("feature", sorted(FEATURES))
    @pytest.mark.parametrize("kind", ["dense", "paged"])
    def test_mid_flight_restore_bit_equal(self, params, bank, kind, feature):
        reqs = FEATURES[feature]["reqs"]
        ref = _engine(params, bank, kind, feature)
        expected = {
            c.request_id: tuple(c.tokens) for c in ref.pump([dict(r) for r in reqs])
        }
        eng = _engine(params, bank, kind, feature)
        for r in reqs:
            eng.submit(**dict(r))
        # 2 steps keeps every request mid-flight even under spec_gamma=2
        # (up to gamma+1 commits per step)
        for _ in range(2):
            eng.step()
        snap = eng.snapshot_active()
        assert snap["requests"], "nothing in flight to snapshot"
        fresh = _engine(params, bank, kind, feature)
        restored = fresh.restore(snap)
        assert sorted(restored) == sorted(r["request_id"] for r in snap["requests"])
        fresh.run_until_drained()
        got = {c.request_id: tuple(c.tokens) for c in fresh.completions()}
        # requests that finished BEFORE the snapshot drained on the old
        # engine; everything in the snapshot must finish bit-equal
        for rid, stream in got.items():
            assert stream == expected[rid], (feature, kind, rid)
        assert set(got) == {r["request_id"] for r in snap["requests"]}

    def test_snapshot_is_json_round_trippable(self, params, bank):
        import json

        eng = _engine(params, bank, "paged", "sampled")
        for r in FEATURES["sampled"]["reqs"]:
            eng.submit(**dict(r))
        eng.step()
        snap = json.loads(json.dumps(eng.snapshot_active()))
        fresh = _engine(params, bank, "paged", "sampled")
        assert sorted(fresh.restore(snap)) == [0, 1]
        fresh.run_until_drained()
        assert len(fresh.completions()) == 2

    @pytest.mark.parametrize("kind", ["dense", "paged"])
    def test_terminal_snapshot_entry_rejected_typed(self, params, bank, kind):
        # Regression: a snapshot entry that already carries a terminal
        # status (e.g. a Completion-shaped dict that leaked into a
        # hand-assembled snapshot) used to die with a KeyError on the
        # missing sampler fields MID-restore, after slots had mutated.
        # Now it's a typed SnapshotRestoreError raised before ANY
        # mutation — restoring a finished stream would duplicate its
        # delivery.
        from k8s_dra_driver_tpu.models.serve import SnapshotRestoreError

        eng = _engine(params, bank, kind, "greedy")
        snap = {
            "engine": type(eng).__name__,
            "next_id": 8,
            "requests": [
                {
                    "request_id": 3, "tokens": [5, 6, 7, 8], "prompt_len": 2,
                    "max_tokens": 4, "deadline": None, "temperature": 0.0,
                    "key": [0, 0], "adapter": 0, "priority": 0,
                },
                # terminal entry, Completion-shaped: no sampler fields at all
                {"request_id": 7, "tokens": [1, 2, 3], "prompt_len": 2,
                 "status": "ok"},
            ],
        }
        for merge in (False, True):
            with pytest.raises(SnapshotRestoreError) as exc:
                eng.restore(dict(snap), merge=merge)
            assert exc.value.request_id == 7
            assert exc.value.status == "ok"
            assert "duplicate" in str(exc.value)
        # rejected before any mutation: no slots claimed, no ids burned,
        # no completions minted — the good entry did NOT partially restore
        assert eng.free_slots() == eng.n_slots
        assert eng.completions() == []
        assert eng._next_id == 0
        if kind == "paged":
            assert not eng._preempted and not eng._admitting

    @pytest.mark.parametrize(
        "status", sorted(serve.TERMINAL_STATUSES)
    )
    def test_every_terminal_status_is_unrestorable(self, params, bank, status):
        eng = _engine(params, bank, "dense", "greedy")
        snap = {"engine": "x", "next_id": 1, "requests": [
            {"request_id": 0, "tokens": [1, 2], "prompt_len": 1,
             "status": status},
        ]}
        with pytest.raises(serve.SnapshotRestoreError):
            eng.restore(snap)


class TestQuarantineComposition:
    def test_lora_survivor_bit_equal_under_quarantine(self, params, bank):
        reqs = FEATURES["lora"]["reqs"]
        ref = _engine(params, bank, "paged", "lora")
        expected = {
            c.request_id: tuple(c.tokens) for c in ref.pump([dict(r) for r in reqs])
        }
        eng = _engine(
            params, bank, "paged", "lora",
            fault_injector=FaultInjector.from_env(
                "nan_logits_rate=1.0,slots=0,steps=2"
            ),
        )
        out = {c.request_id: c for c in eng.pump([dict(r) for r in reqs])}
        assert out[0].status == "quarantined"
        assert out[1].status == "ok"
        assert tuple(out[1].tokens) == expected[1]


class TestBlockLeaks:
    """free_blocks must return to the post-init baseline after EVERY
    early-retire path — a leaked block is permanent capacity loss in a
    long-lived pool."""

    def _baseline(self, eng):
        return eng.n_blocks - eng._axis_size  # each shard's null block

    def test_deadline_path(self, params, bank):
        eng = _engine(params, bank, "paged", "greedy")
        eng.pump([{"prompt": [1, 2, 3], "max_tokens": 10, "deadline": 2}])
        assert eng.free_blocks == self._baseline(eng)
        assert eng.free_slots() == eng.n_slots

    def test_cancel_resident_path(self, params, bank):
        eng = _engine(params, bank, "paged", "greedy")
        rid = eng.submit([1, 2, 3], max_tokens=10)
        eng.step()
        assert eng.cancel(rid)
        assert eng.free_blocks == self._baseline(eng)

    def test_cancel_parked_path(self, params, bank):
        # Preempt a request under a tight pool, then cancel it while
        # parked: it holds no blocks, and the cancel must not double-free.
        # prompt_bucket must stay ABOVE the stall point: a victim grown
        # past one-pass re-prefill is not resumable and cannot be evicted
        eng = _engine(
            params, bank, "paged", "greedy", n_blocks=9, block_size=4,
            n_slots=2, prompt_bucket=32, preempt_on_stall=True,
        )
        eng.submit([1, 2, 3], max_tokens=20)
        eng.submit([4, 5, 6], max_tokens=20)
        # 8 usable blocks vs 2 x 6-block streams: growth MUST stall
        # before either request finishes (23 tokens each)
        for _ in range(40):
            eng.step()
            if eng._preempted:
                break
        assert eng._preempted, "pool never forced a preemption"
        parked = eng._preempted[0]["st"].request_id
        assert eng.cancel(parked)
        (c,) = [x for x in eng.completions() if x.status == "cancelled"]
        assert c.request_id == parked
        eng.run_until_drained()
        assert eng.free_blocks == self._baseline(eng)

    def test_quarantine_path(self, params, bank):
        eng = _engine(
            params, bank, "paged", "greedy",
            fault_injector=FaultInjector.from_env(
                "step_raise_rate=1.0,slots=1,steps=2"
            ),
        )
        eng.pump([
            {"prompt": [1, 2], "max_tokens": 6},
            {"prompt": [3, 4], "max_tokens": 6},
        ])
        assert eng.quarantined == [1]
        assert eng.free_blocks == self._baseline(eng)

    def test_unrestorable_path_touches_no_blocks(self, params, bank):
        eng = _engine(params, bank, "paged", "greedy")
        snap = {
            "engine": "PagedServeEngine",
            "next_id": 1,
            "requests": [{
                "request_id": 0,
                "tokens": list(range(40)),  # > prompt_bucket: unrestorable
                "prompt_len": 4, "max_tokens": 50, "deadline": None,
                "temperature": 0.0, "key": [0, 0], "adapter": 0,
                "priority": 0,
            }],
        }
        assert eng.restore(snap) == []
        (c,) = eng.completions()
        assert c.status == "error" and "unrestorable" in c.error
        assert eng.free_blocks == self._baseline(eng)


class TestRobustnessMetrics:
    def test_scrape_exposes_slo_metrics(self, params, bank):
        eng = _engine(params, bank, "dense", "greedy")
        eng.pump(
            [
                {"prompt": [i + 1, i + 2], "max_tokens": 4,
                 **({"deadline": 2} if i == 0 else {})}
                for i in range(6)
            ],
            queue_limit=1,
        )
        qeng = _engine(
            params, bank, "paged", "greedy",
            fault_injector=FaultInjector.from_env(
                "nan_logits_rate=1.0,slots=0,steps=2"
            ),
        )
        qeng.pump([{"prompt": [1, 2], "max_tokens": 6},
                   {"prompt": [3, 4], "max_tokens": 6}])
        assert REGISTRY.counter("tpu_serve_shed_total").value() >= 1
        assert REGISTRY.counter("tpu_serve_deadline_exceeded_total").value() == 1
        assert REGISTRY.counter("tpu_serve_quarantine_total").value(
            kind="nan_logits"
        ) == 1
        assert REGISTRY.gauge("tpu_serve_queue_depth").value() == 0
        text = REGISTRY.render()
        for name, kind in (
            ("tpu_serve_shed_total", "counter"),
            ("tpu_serve_deadline_exceeded_total", "counter"),
            ("tpu_serve_quarantine_total", "counter"),
            ("tpu_serve_queue_depth", "gauge"),
        ):
            assert f"# TYPE {name} {kind}" in text
            assert f"# HELP {name} " in text
        # hygiene: counters end _total, the gauge must not
        assert "tpu_serve_queue_depth_total" not in text


class TestKVDtypeRestore:
    """kv_dtype axis of the restore matrix: a bf16 pool restores bit-equal
    to an uninterrupted DENSE bf16 engine (paged == dense holds across the
    snapshot boundary); int8/int4 pools restore bit-equal to an
    uninterrupted same-dtype engine (include_kv carries raw block bytes +
    per-block scales, so the continuation is deterministic, not
    re-quantized-approximate); a cross-dtype restore falls back to
    re-prefill without losing the stream; quantized greedy streams stay
    within bounded divergence of the float reference."""

    REQS = [
        {"prompt": [5, 6, 7], "max_tokens": 8, "temperature": 0.8, "seed": 3},
        {"prompt": [9, 1], "max_tokens": 8, "temperature": 1.1, "seed": 11},
    ]

    def _paged(self, params, **kw):
        kw.setdefault("n_slots", 3)
        kw.setdefault("n_blocks", 33)
        kw.setdefault("block_size", 4)
        kw.setdefault("prompt_bucket", 16)
        kw.setdefault("attn_impl", "xla")
        return paged.PagedServeEngine(params=params, cfg=CFG, **kw)

    def _snapshot_restore(self, make):
        """Submit, run 2 mid-flight steps, snapshot WITH KV payloads,
        restore into a fresh engine, drain."""
        eng = make()
        for r in self.REQS:
            eng.submit(**dict(r))
        for _ in range(2):
            eng.step()
        snap = eng.snapshot_active(include_kv=True)
        assert snap["requests"], "nothing in flight to snapshot"
        fresh = make()
        restored = fresh.restore(snap)
        assert sorted(restored) == sorted(
            r["request_id"] for r in snap["requests"]
        )
        fresh.run_until_drained()
        return {c.request_id: tuple(c.tokens) for c in fresh.completions()}

    def test_bf16_pool_restores_bit_equal_to_dense(self, params):
        ref = ServeEngine(
            params=params, cfg=CFG, n_slots=3, prompt_bucket=16,
            cache_dtype="bfloat16",
        )
        expected = {
            c.request_id: tuple(c.tokens)
            for c in ref.pump([dict(r) for r in self.REQS])
        }
        got = self._snapshot_restore(
            lambda: self._paged(params, cache_dtype="bfloat16")
        )
        assert set(got) == set(expected)
        for rid, stream in got.items():
            assert stream == expected[rid], rid

    @pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
    def test_quantized_restore_bit_equal_to_unified(self, params, kv_dtype):
        ref = self._paged(params, kv_dtype=kv_dtype)
        expected = {
            c.request_id: tuple(c.tokens)
            for c in ref.pump([dict(r) for r in self.REQS])
        }
        got = self._snapshot_restore(
            lambda: self._paged(params, kv_dtype=kv_dtype)
        )
        assert set(got) == set(expected)
        for rid, stream in got.items():
            assert stream == expected[rid], (kv_dtype, rid)

    def test_cross_dtype_restore_falls_back_not_lost(self, params):
        """int8 snapshot into a float pool: the geometry gate refuses the
        inject (typed 'incompatible' fallback), the stream re-prefills
        from its token history and still finishes every request."""
        eng = self._paged(params, kv_dtype="int8")
        for r in self.REQS:
            eng.submit(**dict(r))
        for _ in range(2):
            eng.step()
        snap = eng.snapshot_active(include_kv=True)
        assert all(r.get("kv") is not None for r in snap["requests"])
        incompat0 = serve._M_DISAGG_FALLBACK.value(reason="incompatible")
        fresh = self._paged(params)  # float pool
        restored = fresh.restore(snap)
        assert sorted(restored) == sorted(
            r["request_id"] for r in snap["requests"]
        )
        assert serve._M_DISAGG_FALLBACK.value(
            reason="incompatible"
        ) == incompat0 + len(snap["requests"])
        fresh.run_until_drained()
        got = {c.request_id: c for c in fresh.completions()}
        assert set(got) == {r["request_id"] for r in snap["requests"]}
        for c in got.values():
            assert c.status == "ok"
            assert len(c.generated) == 8

    @pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
    def test_quantized_greedy_divergence_bounded(self, params, kv_dtype):
        """Lossy KV may drift from the float stream, but on this tiny
        model most greedy tokens must still agree."""
        reqs = [
            {"prompt": [5, 6, 7], "max_tokens": 8},
            {"prompt": [9, 1], "max_tokens": 8},
        ]
        ref = {
            c.request_id: tuple(c.generated)
            for c in self._paged(params).pump([dict(r) for r in reqs])
        }
        got = {
            c.request_id: tuple(c.generated)
            for c in self._paged(params, kv_dtype=kv_dtype).pump(
                [dict(r) for r in reqs]
            )
        }
        assert set(got) == set(ref)
        agree = sum(
            t1 == t2
            for rid in got
            for t1, t2 in zip(got[rid], ref[rid])
        )
        total = sum(len(g) for g in got.values())
        assert agree / total >= 0.5, (kv_dtype, agree, total, got, ref)
