"""Config API tests.

Mirrors (behaviorally, not textually) the reference's only unit test file —
the table-driven MPS limit-normalization test (sharing_test.go:28-160) — and
extends coverage to the strict decoder and validation, per SURVEY.md §4's
"do better" mandate.
"""

import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

from k8s_dra_driver_tpu.api import (
    API_VERSION,
    Decoder,
    DecodeError,
    ErrInvalidDeviceSelector,
    ErrInvalidLimit,
    HbmLimits,
    SharingStrategy,
    SpatialPartitionConfig,
    TimeSliceInterval,
    TpuConfig,
    TpuSharing,
    default_tpu_config,
)
from k8s_dra_driver_tpu.api.tpuconfig import SliceMembershipConfig, SubsliceConfig

UUIDS = ["tpu-aaaa", "tpu-bbbb", "tpu-cccc"]


class TestHbmLimitNormalize:
    @pytest.mark.parametrize(
        "limits,expected",
        [
            ({}, {}),
            ({"*": "4Gi"}, {u: "4096Mi" for u in UUIDS}),
            ({"0": "1Gi"}, {"tpu-aaaa": "1024Mi"}),
            ({"2": "2048Mi"}, {"tpu-cccc": "2048Mi"}),
            ({"tpu-bbbb": "512Mi"}, {"tpu-bbbb": "512Mi"}),
            # explicit key beats wildcard regardless of iteration order
            (
                {"*": "1Gi", "tpu-aaaa": "2Gi"},
                {"tpu-aaaa": "2048Mi", "tpu-bbbb": "1024Mi", "tpu-cccc": "1024Mi"},
            ),
            (
                {"tpu-aaaa": "2Gi", "*": "1Gi"},
                {"tpu-aaaa": "2048Mi", "tpu-bbbb": "1024Mi", "tpu-cccc": "1024Mi"},
            ),
            # decimal suffixes convert to binary-MiB strings (floored)
            ({"1": "1500M"}, {"tpu-bbbb": "1430Mi"}),
            ({"0": "1Mi"}, {"tpu-aaaa": "1Mi"}),
        ],
    )
    def test_normalize(self, limits, expected):
        assert HbmLimits(limits).normalize(UUIDS) == expected

    @pytest.mark.parametrize(
        "limits,err",
        [
            ({"3": "1Gi"}, ErrInvalidDeviceSelector),  # index out of range
            ({"tpu-zzzz": "1Gi"}, ErrInvalidDeviceSelector),  # unknown uuid
            ({"-1": "1Gi"}, ErrInvalidDeviceSelector),
            ({"0": "512Ki"}, ErrInvalidLimit),  # below 1Mi floor
            ({"0": "banana"}, ErrInvalidLimit),
            ({"0": ""}, ErrInvalidLimit),
        ],
    )
    def test_errors(self, limits, err):
        with pytest.raises(err):
            HbmLimits(limits).normalize(UUIDS)


class TestSharingValidation:
    def test_default_config_is_exclusive(self):
        cfg = default_tpu_config()
        assert cfg.sharing.strategy == SharingStrategy.EXCLUSIVE
        cfg.validate()

    def test_timeslicing_normalize_fills_interval(self):
        s = TpuSharing(strategy=SharingStrategy.TIME_SLICING)
        s.normalize()
        assert s.time_slicing_config.interval == TimeSliceInterval.DEFAULT
        assert s.get_time_slicing_config().interval.level() == 0
        s.validate()

    def test_mutually_exclusive_configs(self):
        s = TpuSharing(
            strategy=SharingStrategy.EXCLUSIVE,
            spatial_partition_config=SpatialPartitionConfig(),
        )
        with pytest.raises(ValueError, match="spatialPartitionConfig"):
            s.validate()

    def test_get_config_respects_strategy(self):
        s = TpuSharing(strategy=SharingStrategy.TIME_SLICING)
        s.normalize()
        assert s.get_spatial_partition_config() is None

    def test_core_fraction_range(self):
        c = SpatialPartitionConfig(default_core_fraction=0)
        with pytest.raises(ValueError, match="defaultCoreFraction"):
            c.validate()
        c = SpatialPartitionConfig(default_core_fraction=101)
        with pytest.raises(ValueError):
            c.validate()

    def test_spatial_normalize_propagates_default_limit(self):
        c = SpatialPartitionConfig(default_hbm_limit="2Gi")
        c.normalize()
        assert c.normalized_limits(UUIDS) == {u: "2048Mi" for u in UUIDS}

    def test_subslice_rejects_spatial_partition(self):
        cfg = SubsliceConfig(sharing=TpuSharing(strategy=SharingStrategy.SPATIAL_PARTITION))
        cfg.normalize()
        with pytest.raises(ValueError, match="already a spatial partition"):
            cfg.validate()

    def test_slice_membership_defaults_and_validation(self):
        cfg = SliceMembershipConfig()
        cfg.normalize()
        assert cfg.coordinator_port == 8476
        cfg.validate()
        cfg = SliceMembershipConfig(extra_env={"lower": "x"})
        cfg.normalize()
        with pytest.raises(ValueError, match="UPPER_SNAKE"):
            cfg.validate()


class TestDecoder:
    def decode(self, body):
        return Decoder().decode(body)

    def test_decode_full_tpu_config(self):
        cfg = self.decode(
            {
                "apiVersion": API_VERSION,
                "kind": "TpuConfig",
                "sharing": {
                    "strategy": "SpatialPartition",
                    "spatialPartitionConfig": {
                        "defaultCoreFraction": 50,
                        "perDeviceHbmLimit": {"0": "4Gi"},
                    },
                },
            }
        )
        assert isinstance(cfg, TpuConfig)
        cfg.normalize()
        cfg.validate()
        sp = cfg.sharing.get_spatial_partition_config()
        assert sp.default_core_fraction == 50
        assert sp.normalized_limits(UUIDS) == {"tpu-aaaa": "4096Mi"}

    def test_rejects_wrong_api_version(self):
        with pytest.raises(DecodeError, match="apiVersion"):
            self.decode({"apiVersion": "nvidia.com/v1", "kind": "TpuConfig"})

    def test_rejects_unknown_kind(self):
        with pytest.raises(DecodeError, match="unknown kind"):
            self.decode({"apiVersion": API_VERSION, "kind": "GpuConfig"})

    def test_strict_unknown_field(self):
        with pytest.raises(DecodeError, match="unknown field 'sharingg'"):
            self.decode({"apiVersion": API_VERSION, "kind": "TpuConfig", "sharingg": {}})

    def test_strict_nested_unknown_field(self):
        with pytest.raises(DecodeError, match="TpuConfig.sharing: unknown field"):
            self.decode(
                {"apiVersion": API_VERSION, "kind": "TpuConfig", "sharing": {"strat": "x"}}
            )

    def test_strict_bad_enum(self):
        with pytest.raises(DecodeError, match="strategy"):
            self.decode(
                {"apiVersion": API_VERSION, "kind": "TpuConfig", "sharing": {"strategy": "MPS"}}
            )

    def test_strict_type_mismatch(self):
        with pytest.raises(DecodeError, match="expected int"):
            self.decode(
                {
                    "apiVersion": API_VERSION,
                    "kind": "SliceMembershipConfig",
                    "coordinatorPort": "8476",
                }
            )


class TestFuzzDecoderOnlyDecodeError:
    """The decoder parses USER-authored opaque parameters at Prepare time;
    its contract is typed failure (DecodeError) for any malformed input.
    The gRPC fan-out contains failures per claim either way, but a raw
    TypeError/KeyError would surface as an opaque internal error instead
    of the actionable message the reference's strict decoder produces."""

    json_values = st.recursive(
        st.one_of(
            st.none(),
            st.booleans(),
            st.integers(min_value=-(2**31), max_value=2**31),
            st.text(max_size=12),
        ),
        lambda inner: st.one_of(
            st.lists(inner, max_size=4),
            st.dictionaries(st.text(max_size=10), inner, max_size=4),
        ),
        max_leaves=8,
    )

    @settings(max_examples=200, deadline=None)
    @given(data=json_values)
    def test_arbitrary_json(self, data):
        try:
            Decoder().decode(data)
        except DecodeError:
            pass

    @settings(max_examples=150, deadline=None)
    @given(body=json_values)
    def test_wellformed_envelope_garbage_body(self, body):
        """A valid kind/apiVersion envelope with arbitrary spec inside."""
        doc = {"apiVersion": API_VERSION, "kind": "TpuConfig", "sharing": body}
        try:
            Decoder().decode(doc)
        except DecodeError:
            pass
