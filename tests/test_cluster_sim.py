"""The ``make sim-cluster`` chaos suite (PR 15 acceptance gate).

Drives the REAL ``AllocationIndex`` + ``plan()``/``plan_gang()`` through
seeded synthetic-cluster churn and pins the invariants the simulator
exists to check:

* **Exactly-once accounting** — every submitted claim ends in exactly
  one of bound/infeasible/failed; relist audits against the store find
  zero mismatches; nothing leaks at drain.
* **Gang atomicity under storms** — a 409/500 storm breaks commits
  mid-gang; every broken gang unwinds whole (the audit would catch a
  half-committed gang as a ledger mismatch).
* **Determinism** — one seed, one report, bit-for-bit (minus wall time).
* **Scale** — a 10k-pool build stays correct and plan() latency stays
  sub-millisecond-ish (the hard p90 budget lives in
  ``tools/perf_smoke.py check_plan_scale``).

Budget: the whole file is tier-1 and must stay well under 30s CPU.
"""

import json

from k8s_dra_driver_tpu.scheduler.cluster_sim import (
    SimConfig,
    default_storms,
    run_sim,
)


def _accounts_exactly_once(r):
    assert r.submitted == r.bound + r.infeasible + r.failed, (
        f"claim accounting leak: submitted={r.submitted} != "
        f"bound={r.bound} + infeasible={r.infeasible} + failed={r.failed}"
    )
    assert r.gangs_submitted == r.gangs_committed + r.gangs_infeasible
    assert r.audit_failures == 0, "relist audit found ledger/store mismatch"
    assert r.leaked_claims == 0, "claims survived the drain"


class TestChurnUnderStorms:
    def test_1k_chaos_run_accounts_every_claim(self):
        r = run_sim(SimConfig(
            seed=42, n_nodes=300, duration_s=300.0, arrival_rate=3.0,
            storms=default_storms(), audit_interval_s=30.0,
        ))
        _accounts_exactly_once(r)
        assert r.audits >= 9
        assert r.bound > 500, "churn must actually bind claims"
        assert r.released == r.bound, "every bound claim must release"
        # The storm must break commits mid-gang AND every break must
        # converge: unwound gangs retried to commit or counted
        # infeasible, never half-committed (the audit above is the
        # half-commit detector).
        assert r.gangs_unwound > 0, "storm never exercised the unwind path"
        assert r.gangs_committed > 0
        assert r.plan_samples > 1000
        assert 0.0 < r.packing_efficiency <= 1.0
        assert 0.0 < r.utilization_mean < 1.0

    def test_same_seed_same_report(self):
        cfg = dict(
            seed=11, n_nodes=120, duration_s=150.0, arrival_rate=3.0,
            storms=default_storms(), audit_interval_s=30.0,
        )
        a = json.loads(run_sim(SimConfig(**cfg)).to_json())
        b = json.loads(run_sim(SimConfig(**cfg)).to_json())
        for doc in (a, b):
            for key in ("wall_s", "plan_p50_ms", "plan_p90_ms"):
                doc.pop(key)  # wall-clock measurements may jitter
        assert a == b

    def test_different_seed_different_trace(self):
        base = dict(n_nodes=120, duration_s=150.0, arrival_rate=3.0)
        a = run_sim(SimConfig(seed=1, **base))
        b = run_sim(SimConfig(seed=2, **base))
        assert (a.submitted, a.bound) != (b.submitted, b.bound)


class TestScale:
    def test_10k_pools_zero_misaccounting(self):
        r = run_sim(SimConfig(
            seed=7, n_nodes=10_000, duration_s=30.0, arrival_rate=3.0,
            fanout=4, audit_interval_s=15.0,
        ))
        _accounts_exactly_once(r)
        assert r.n_nodes == 10_000
        assert r.bound > 50
        assert r.plan_samples > 100
        assert r.plan_p90_ms > 0.0
