"""MoE models (cfg.n_experts — the Mixtral family shape) through the FULL
decode/serving stack: deterministic top-k routing means every bit-equality
contract the dense model carries extends to MoE unchanged — sequential
greedy == dense engine == paged engine, speculation, quantized self-draft,
mesh sharding, training."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, decode, paged
from k8s_dra_driver_tpu.models.serve import ServeEngine

CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_seq=128, rope=True, n_experts=4, moe_top_k=2,
)
DENSE_CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_seq=128, rope=True,
)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _prompts(n, rng=7):
    r = np.random.RandomState(rng)
    return [
        r.randint(0, CFG.vocab_size, size=r.randint(3, 12)).tolist()
        for _ in range(n)
    ]


def _streams(engine, reqs, max_steps=10_000):
    pending = list(reqs)
    out = {}
    for _ in range(max_steps):
        while pending:
            prompt, max_tokens = pending[0]
            try:
                engine.submit(prompt, max_tokens)
                pending.pop(0)
            except RuntimeError:
                break
        stepped = engine.step()
        for c in engine.completions():
            out[c.request_id] = c.generated
        if (
            not pending
            and stepped == 0
            and engine.free_slots() == engine.n_slots
            and not getattr(engine, "_preempted", None)
        ):
            return out
    raise RuntimeError("queue did not drain")


class TestMoEModel:
    def test_params_carry_experts_not_dense_mlp(self, params):
        blk = params["blocks"][0]
        assert blk["expert_up"].shape == (4, 64, 128)
        assert blk["expert_down"].shape == (4, 128, 64)
        assert blk["router"].shape == (64, 4)
        assert "mlp_up" not in blk and "mlp_down" not in blk

    def test_routing_is_actually_sparse_and_varied(self, params):
        """Different tokens pick different experts (the router is not
        degenerate) and gates are a distribution over top_k."""
        x = jax.random.normal(jax.random.PRNGKey(3), (32, CFG.d_model))
        p = params["blocks"][0]
        scores = x @ p["router"]
        _, idx = jax.lax.top_k(scores, CFG.moe_top_k)
        assert len(np.unique(np.asarray(idx))) > 1
        out = burnin._moe_mlp(x.astype(CFG.dtype), p, CFG.moe_top_k)
        assert out.shape == x.shape
        assert bool(jnp.isfinite(out.astype(jnp.float32)).all())

    def test_moe_output_differs_from_dense(self):
        """The flag actually changes the function (not a silent no-op)."""
        moe_p = burnin.init_params(jax.random.PRNGKey(0), CFG)
        dense_p = burnin.init_params(jax.random.PRNGKey(0), DENSE_CFG)
        toks = burnin.sample_tokens(jax.random.PRNGKey(1), CFG, batch=2, seq=16)
        lm = burnin.forward(moe_p, toks, CFG)
        ld = burnin.forward(dense_p, toks, DENSE_CFG)
        assert not np.allclose(np.asarray(lm), np.asarray(ld))

    def test_loss_decreases_under_training(self):
        fns = burnin.build_train_step(CFG, lr=1e-2)
        p, o = fns.init(jax.random.PRNGKey(0))
        toks = burnin.sample_tokens(jax.random.PRNGKey(1), CFG, batch=4, seq=32)
        first = None
        for _ in range(5):
            p, o, loss = fns.step(p, o, toks)
            first = first if first is not None else float(loss)
        assert float(loss) < first

    def test_sharded_train_matches_single_device(self):
        """TP shards the expert FF dims over the model axis (the psum on
        the sharded contraction mirrors the dense pair's)."""
        from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh

        mesh = build_mesh(
            jax.devices("cpu")[:4], MeshShape(data=2, model=2)
        )
        # vocab divisible by the model axis (embed is vocab-sharded)
        cfg = burnin.ModelConfig(
            vocab_size=96, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
            d_ff=128, max_seq=128, rope=True, n_experts=4, moe_top_k=2,
        )
        toks = burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=4, seq=32)
        single = burnin.build_train_step(cfg)
        p1, o1 = single.init(jax.random.PRNGKey(0))
        _, _, loss1 = single.step(p1, o1, toks)
        sharded = burnin.build_train_step(cfg, mesh=mesh)
        p2, o2 = sharded.init(jax.random.PRNGKey(0))
        _, _, loss2 = sharded.step(p2, o2, toks)
        np.testing.assert_allclose(
            float(loss1), float(loss2), rtol=2e-2
        )

    def test_pipeline_refuses_moe_loudly(self, params):
        from k8s_dra_driver_tpu.models import pp_burnin

        with pytest.raises(ValueError, match="pipeline.*MoE|MoE"):
            pp_burnin.pp_params_from_dense(params, CFG)

    def test_lora_targets_validated_for_moe(self):
        from k8s_dra_driver_tpu.models import lora

        with pytest.raises(ValueError, match="MoE"):
            lora.init_adapters(
                jax.random.PRNGKey(0), CFG, lora.LoraConfig(rank=2)
            )
        # attention-only targets work
        ad = lora.init_adapters(
            jax.random.PRNGKey(0), CFG,
            lora.LoraConfig(rank=2, targets=("qkv", "attn_out")),
        )
        assert set(ad["blocks"][0]) == {"qkv", "attn_out"}


class TestMoEServing:
    def test_dense_and_paged_engines_bit_equal(self, params):
        reqs = [(p, 10) for p in _prompts(5)]
        dense = ServeEngine(params=params, cfg=CFG, n_slots=3, prompt_bucket=16)
        pag = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=3, n_blocks=40, block_size=16,
            prompt_bucket=16, attn_impl="xla",
        )
        assert _streams(dense, reqs) == _streams(pag, reqs)

    def test_engine_matches_sequential_greedy(self, params):
        prompt = _prompts(1)[0]
        eng = ServeEngine(params=params, cfg=CFG, n_slots=1, prompt_bucket=16)
        eng.submit(prompt, 12)
        eng.run_until_drained()
        got = eng.completions()[0].generated
        want = decode.greedy_decode(
            params, jnp.asarray([prompt], jnp.int32), 12, cfg=CFG,
            batch_prefill=True,
        )
        assert got == np.asarray(want)[0, len(prompt):].tolist()

    def test_speculative_int8_self_draft_bit_equal(self, params):
        """quantize_blocks touches only the attention matmuls under MoE
        (experts stay full-precision) — the any-draft contract holds."""
        reqs = [(p, 10) for p in _prompts(4, rng=11)]
        plain = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=16)
        spec = ServeEngine(
            params=params, cfg=CFG, n_slots=2, prompt_bucket=16, spec_gamma=3
        )
        assert _streams(plain, reqs) == _streams(spec, reqs)

    def test_moe_with_attention_lora_adapters(self, params):
        """Per-request LoRA composes with MoE: adapters target the
        attention matmuls (the MLP is expert-owned), the identity adapter
        changes nothing, and a trained adapter diverges the stream."""
        from k8s_dra_driver_tpu.models import lora

        lcfg = lora.LoraConfig(rank=2, alpha=8.0, targets=("qkv", "attn_out"))
        ad = lora.init_adapters(jax.random.PRNGKey(5), CFG, lcfg)
        for li, blk in enumerate(ad["blocks"]):
            for name, w in blk.items():
                key = jax.random.fold_in(jax.random.PRNGKey(5), li * 10 + len(name))
                w["b"] = 0.3 * jax.random.normal(key, w["b"].shape, jnp.float32)
        bank = lora.stack_adapters(CFG, lcfg, [ad])
        prompt = _prompts(1)[0]

        def run(adapter):
            eng = ServeEngine(
                params=params, cfg=CFG, n_slots=1, prompt_bucket=16,
                adapter_bank=bank,
            )
            eng.submit(prompt, 10, adapter=adapter)
            eng.run_until_drained()
            return eng.completions()[0].generated

        base = ServeEngine(params=params, cfg=CFG, n_slots=1, prompt_bucket=16)
        base.submit(prompt, 10)
        base.run_until_drained()
        plain = base.completions()[0].generated
        assert run(0) == plain        # identity adapter = the base model
        assert run(1) != plain        # the fine-tune actually applies

    def test_sharded_paged_moe_bit_equal(self, params):
        from jax.sharding import Mesh

        reqs = [(p, 8) for p in _prompts(4, rng=3)]
        kw = dict(
            params=params, cfg=CFG, n_slots=4, n_blocks=64, block_size=16,
            prompt_bucket=16, attn_impl="xla",
        )
        ref = paged.PagedServeEngine(**kw)
        shd = paged.PagedServeEngine(
            **kw, mesh=Mesh(np.array(jax.devices("cpu")[:4]), ("data",)),
            slot_axis="data",
        )
        assert _streams(shd, reqs) == _streams(ref, reqs)
