"""Remote-worker scale-up: the autoscaler's flagged transport path.

The satellite contract (PR 15): ``FleetAutoscaler`` can spawn a
transport-worker-backed replica — :class:`RemoteWorkerEngine` over the
same ``PoolWorker`` protocol loop ``worker_main`` drives — behind the
``DRA_REMOTE_WORKERS`` flag, and the chaos suite proves a scale-up
registers one and serves through it:

* Flag selection is loud: set-without-wiring raises, unset stays local.
* Scale-up under spawn faults: the first attempt fails and backs off
  (nothing half-registers), the retry lands a RemoteWorkerEngine whose
  request ids come from the fleet-reserved stride (the worker reseeds).
* Worker death mid-stream: the stall detectors evacuate the replica and
  its retained KV-less entries finish on the survivors — zero loss,
  no double delivery.
"""

from __future__ import annotations

import pytest

from k8s_dra_driver_tpu.models import fleet, workload as W
from k8s_dra_driver_tpu.models.autoscaler import (
    ENV_REMOTE_WORKERS,
    AutoscalerPolicy,
    FleetAutoscaler,
    select_engine_factory,
)
from k8s_dra_driver_tpu.models.fleet import ID_STRIDE, Engine
from k8s_dra_driver_tpu.models.transport import (
    RemoteWorkerEngine,
    make_remote_engine_factory,
)
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.journal import JOURNAL


def _remote_factory(clock, *, n_slots=4):
    """In-process worker rig: each spawn hosts a fresh single-SimEngine
    FleetRouter behind a loopback PoolWorker (the worker_main loop,
    minus the process)."""
    return make_remote_engine_factory(
        worker_factory=lambda: fleet.FleetRouter(
            [W.SimEngine(clock=clock, n_slots=n_slots, n_blocks=512)],
            clock=clock,
        ),
        n_slots=n_slots,
        clock=clock,
    )


def _drive(clock, router, until, *, dt=0.1, max_ticks=500):
    """Advance sim time and pump the fleet until ``until()`` or budget."""
    out = []
    for _ in range(max_ticks):
        if until():
            return out
        clock.advance(dt)
        router.tick()
        out.extend(router.completions())
    raise AssertionError(f"fleet did not converge in {max_ticks} ticks")


class TestFlagSelection:
    def test_unset_selects_local(self):
        local, remote = object(), object()
        assert select_engine_factory(local, remote, environ={}) is local

    def test_set_selects_remote(self):
        local, remote = object(), object()
        env = {ENV_REMOTE_WORKERS: "1"}
        assert select_engine_factory(local, remote, environ=env) is remote

    def test_set_without_remote_factory_raises(self):
        with pytest.raises(ValueError, match=ENV_REMOTE_WORKERS):
            select_engine_factory(object(), None,
                                  environ={ENV_REMOTE_WORKERS: "true"})

    def test_factory_needs_exactly_one_rig(self):
        with pytest.raises(ValueError, match="exactly one"):
            make_remote_engine_factory()


class TestRemoteEngineProtocol:
    def test_satisfies_engine_protocol_and_serves(self):
        clock = W.SimClock()
        engine = _remote_factory(clock)()
        assert isinstance(engine, Engine)
        out = engine.pump([([1, 2, 3], 8), ([4, 5], 4)])
        assert sorted(len(c.generated) for c in out) == [4, 8]
        assert all(c.status == "ok" for c in out)
        assert engine.free_slots() == engine.n_slots

    def test_reseed_forwards_id_stride_to_worker(self):
        clock = W.SimClock()
        engine = _remote_factory(clock)()
        base = 7 * ID_STRIDE
        engine.restore(
            {"engine": "RemoteWorkerEngine", "next_id": base, "requests": []},
            merge=True,
        )
        rid = engine.submit([1, 2], max_tokens=4)
        assert rid >= base

    def test_cancel_round_trips_a_cancelled_completion(self):
        clock = W.SimClock()
        engine = _remote_factory(clock)()
        rid = engine.submit([1, 2, 3], max_tokens=64)
        assert engine.cancel(rid) is True
        clock.advance(0.1)
        engine.step_burst()
        (c,) = engine.completions()
        assert c.request_id == rid and c.status == "cancelled"
        assert engine.free_slots() == engine.n_slots


class TestRemoteScaleUp:
    def _build(self, *, injector=None):
        clock = W.SimClock()
        router = fleet.FleetRouter(
            [W.SimEngine(clock=clock, n_slots=4, n_blocks=512)],
            clock=clock,
            fault_injector=injector,
        )
        local = lambda: W.SimEngine(clock=clock, n_slots=4)  # noqa: E731
        factory = select_engine_factory(
            local, _remote_factory(clock),
            environ={ENV_REMOTE_WORKERS: "1"},
        )
        asc = FleetAutoscaler(
            router, engine_factory=factory,
            policy=AutoscalerPolicy(
                min_replicas=1, max_replicas=3, up_ticks=2,
                cooldown_s=1.0, spawn_backoff_s=2.0,
            ),
            clock=clock,
        )
        return clock, router, asc

    def test_scale_up_registers_remote_worker_under_spawn_faults(self):
        inj = FaultInjector(seed=3)
        inj.arm(FaultProfile(name="boom", spawn_fail_rate=1.0, limit=1))
        clock, router, asc = self._build(injector=inj)
        for i in range(4):
            router.submit([1, i + 2], max_tokens=32)

        asc.tick()  # streak 1
        clock.advance(0.5)
        asc.tick()  # streak 2 -> act, but the spawn fault eats it
        assert asc.spawn_failures == 1
        assert len(router.replicas) == 1  # nothing half-registered

        clock.advance(5.0)  # past spawn backoff + cooldown
        asc.tick()
        clock.advance(0.5)
        asc.tick()
        remotes = [
            r for r in router.replicas
            if isinstance(r.engine, RemoteWorkerEngine)
        ]
        assert len(remotes) == 1, "retry must register the remote replica"

        # The fleet serves THROUGH the worker: saturate the local replica
        # so placement must pick the remote one, then ride a completion
        # back across the protocol with a fleet-stride request id.
        rid = router.submit([9, 9, 9], max_tokens=8)
        assert rid >= ID_STRIDE  # the worker reseeded onto its stride
        assert remotes[0].engine.free_slots() < remotes[0].engine.n_slots
        done = _drive(clock, router, lambda: router.idle())
        assert rid in {c.request_id for c in done}
        events = [e["event"] for e in JOURNAL.tail(limit=200)]
        assert "scale_up.spawn_failed" in events
        assert "scale_up.resumed" in events

    def test_worker_death_evacuates_retained_streams(self):
        clock, router, asc = self._build()
        for i in range(4):
            router.submit([1, i + 2], max_tokens=32)
        asc.tick()
        clock.advance(1.5)
        asc.tick()
        (remote,) = [
            r for r in router.replicas
            if isinstance(r.engine, RemoteWorkerEngine)
        ]
        rid = router.submit([5, 6, 7], max_tokens=64)
        assert rid in remote.engine._resident

        # Kill the worker mid-stream: sever its side of the loopback pair.
        worker = remote.engine.peer_pump.__self__
        worker.conn.close()
        worker.dead = True

        # Drain the local replicas' head start, then let the detectors
        # catch the frozen remote and evacuate its retained entry.
        done = _drive(clock, router, lambda: router.idle(), max_ticks=2000)
        assert rid in {c.request_id for c in done}, "stream must survive"
        assert sum(1 for c in done if c.request_id == rid) == 1, \
            "no double delivery"
        assert remote.state in (fleet.DRAINED, fleet.EVACUATING, "suspect")
