"""Tests for the C++ libtpuinfo shim through its Python binding."""

import json

import pytest

from k8s_dra_driver_tpu.tpuinfo import binding
from k8s_dra_driver_tpu.tpuinfo.binding import TpuInfoError, enumerate_topology


def fake(spec: str, host_id: int = 0):
    return enumerate_topology(
        env={"TPUINFO_FAKE_TOPOLOGY": spec, "TPUINFO_FAKE_HOST_ID": str(host_id)}
    )


class TestFakeTopologies:
    def test_v5e_16_is_2d_multihost(self):
        t = fake("v5e-16")
        assert (t.generation, t.topology, t.ndims) == ("v5e", "4x4", 2)
        assert t.dims == (4, 4, 1)
        assert t.host_bounds == (2, 2, 1)
        assert t.chips_per_host == 4 and t.host_count == 4
        assert len(t.chips) == 4
        assert t.wrap == (False, False, False)  # v5e is a mesh, no torus links
        assert [c.device_path for c in t.chips] == [f"/dev/accel{i}" for i in range(4)]

    def test_v5e_8_single_host(self):
        t = fake("v5e-8")
        assert t.topology == "2x4"
        assert t.host_count == 1 and t.chips_per_host == 8
        assert len(t.chips) == 8

    def test_v4_16_is_3d(self):
        t = fake("v4-16")
        assert (t.topology, t.ndims) == ("2x2x4", 3)
        assert t.host_count == 4
        assert t.wrap == (False, False, True)  # dim 4 wraps on 3D torus gens
        assert all(c.cores == 2 for c in t.chips)
        assert all(c.hbm_bytes == 32 << 30 for c in t.chips)

    def test_explicit_topology_spec(self):
        t = fake("v4-2x2x2")
        assert t.topology == "2x2x2" and t.total_chips == 8

    def test_host_coords_partition_the_mesh(self):
        # Collect every host's chips; together they must tile the 4x4 mesh
        # exactly once.
        seen = set()
        for host in range(4):
            t = fake("v5e-16", host_id=host)
            for c in t.chips:
                assert c.coords not in seen, "chip coordinate double-assigned"
                seen.add(c.coords)
        assert seen == {(x, y, 0) for x in range(4) for y in range(4)}

    def test_uuids_are_stable_and_unique(self):
        a = fake("v5e-16", host_id=1)
        b = fake("v5e-16", host_id=1)
        assert [c.uuid for c in a.chips] == [c.uuid for c in b.chips]
        uuids = set()
        for host in range(4):
            uuids.update(c.uuid for c in fake("v5e-16", host_id=host).chips)
        assert len(uuids) == 16

    def test_worker_hostnames(self):
        t = fake("v5e-32")
        assert t.host_count == 8
        assert len(t.worker_hostnames) == 8
        assert t.worker_hostnames[3] == "tpu-host-3"

    @pytest.mark.parametrize("spec", ["v5e-3", "v7x-8", "banana", "v5e-", "v4-0x2x2"])
    def test_invalid_specs_error(self, spec):
        with pytest.raises(TpuInfoError):
            fake(spec)

    def test_non_tileable_multihost_topology_errors_cleanly(self):
        # 12x1 exceeds the single-host limit but does not tile into 2x2 host
        # blocks: must be a clean error, not a SIGFPE in host-coord math.
        with pytest.raises(TpuInfoError, match="does not tile"):
            fake("v5e-12x1")

    def test_odd_single_host_topology_works(self):
        t = fake("v5e-6x1")
        assert t.host_count == 1 and t.chips_per_host == 6
        assert len(t.chips) == 6

    def test_host_id_out_of_range(self):
        with pytest.raises(TpuInfoError, match="out of range"):
            fake("v5e-16", host_id=4)


class TestBinding:
    def test_version(self):
        assert binding.library_version() == "0.1.0"

    def test_json_is_parseable_raw(self):
        # The ABI contract: a single JSON doc crosses the boundary.
        import ctypes

        lib = binding.load()
        out = ctypes.c_char_p()
        import os

        os.environ["TPUINFO_FAKE_TOPOLOGY"] = "v5e-4"
        try:
            rc = lib.tpuinfo_enumerate(ctypes.byref(out))
            data = json.loads(ctypes.string_at(out).decode())
            lib.tpuinfo_free(out)
        finally:
            os.environ.pop("TPUINFO_FAKE_TOPOLOGY", None)
        assert rc == 0
        assert data["mode"] == "fake"
        assert {c["index"] for c in data["chips"]} == {0, 1, 2, 3}
