"""Paged KV cache: kernel/gather numerics, allocator invariants, decode
parity (pallas kernel in interpret mode on CPU — same policy as
test_flash_attention)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, decode, paged
from k8s_dra_driver_tpu.models.decode import _masked_attention
from k8s_dra_driver_tpu.ops import paged_attention

CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128
)
CFG_GQA = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_kv_heads=2, n_layers=2,
    d_ff=128, max_seq=128, rope=True,
)


class _Case:
    """Sequence-major k/v FIRST, pool built FROM them: the oracle attends
    the original contiguous arrays with an independent code path, so a bug
    replicated in the gather implementation cannot cancel out (a previous
    oracle copied paged_attention_xla line for line and was vacuous)."""

    def __init__(self, rng, *, b, hq, hkv, d, bs, max_blocks, dtype=jnp.float32,
                 table_perm=None):
        ks = jax.random.split(rng, 4)
        self.q = jax.random.normal(ks[0], (b, hq, d), jnp.float32)
        seq = bs * max_blocks
        self.k_seq = jax.random.normal(ks[1], (b, seq, hkv, d), jnp.float32).astype(dtype)
        self.v_seq = jax.random.normal(ks[2], (b, seq, hkv, d), jnp.float32).astype(dtype)
        self.lengths = jax.random.randint(ks[3], (b,), 1, seq + 1)
        # table: row r's i-th logical block lives at pool id table[r, i]
        table = 1 + np.arange(b * max_blocks, dtype=np.int32).reshape(b, max_blocks)
        if table_perm is not None:
            table = table_perm(table)
        self.table = jnp.asarray(table)
        n_pool = 1 + b * max_blocks
        k_pool = np.zeros((n_pool, hkv, d, bs), np.float32)
        v_pool = np.zeros((n_pool, hkv, d, bs), np.float32)
        for r in range(b):
            for i in range(max_blocks):
                blk = int(table[r, i])
                # [bs, hkv, d] -> head-major transposed [hkv, d, bs]
                k_pool[blk] = np.asarray(
                    self.k_seq[r, i * bs : (i + 1) * bs], np.float32
                ).transpose(1, 2, 0)
                v_pool[blk] = np.asarray(
                    self.v_seq[r, i * bs : (i + 1) * bs], np.float32
                ).transpose(1, 2, 0)
        self.k_pool = jnp.asarray(k_pool).astype(dtype)
        self.v_pool = jnp.asarray(v_pool).astype(dtype)

    def oracle(self):
        """Dense attention over the ORIGINAL sequence-major arrays."""
        mask = (
            jnp.arange(self.k_seq.shape[1])[None, :] < self.lengths[:, None]
        )[:, None, None]
        return _masked_attention(self.q[:, None], self.k_seq, self.v_seq, mask)[:, 0]


class TestKernelNumerics:
    @pytest.mark.parametrize("hq,hkv", [(4, 4), (4, 2), (8, 1)])
    def test_kernel_matches_dense(self, hq, hkv):
        c = _Case(
            jax.random.PRNGKey(0), b=3, hq=hq, hkv=hkv, d=64, bs=16, max_blocks=4
        )
        got = paged_attention.paged_decode_attention(
            c.q, c.k_pool, c.v_pool, c.table, c.lengths, interpret=True
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(c.oracle()), atol=2e-5)

    def test_xla_gather_matches_dense(self):
        c = _Case(
            jax.random.PRNGKey(1), b=4, hq=4, hkv=2, d=32, bs=8, max_blocks=3
        )
        got = paged_attention.paged_attention_xla(
            c.q, c.k_pool, c.v_pool, c.table, c.lengths
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(c.oracle()), atol=2e-5)

    def test_bf16_pool(self):
        c = _Case(
            jax.random.PRNGKey(2), b=2, hq=4, hkv=2, d=64, bs=16, max_blocks=2,
            dtype=jnp.bfloat16,
        )
        got = paged_attention.paged_decode_attention(
            c.q, c.k_pool, c.v_pool, c.table, c.lengths, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(c.oracle(), np.float32),
            atol=3e-2,
        )

    def test_single_key(self):
        """length=1: only the first key of the first block attends."""
        c = _Case(jax.random.PRNGKey(3), b=2, hq=2, hkv=2, d=32, bs=8, max_blocks=2)
        c.lengths = jnp.ones((2,), jnp.int32)
        got = paged_attention.paged_decode_attention(
            c.q, c.k_pool, c.v_pool, c.table, c.lengths, interpret=True
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(c.oracle()), atol=2e-5)

    def test_scrambled_table(self):
        """Block ids in arbitrary pool order — the table, not pool layout,
        defines key order."""
        rng = jax.random.PRNGKey(4)

        def scramble(table):
            perm = np.asarray(jax.random.permutation(rng, table.ravel()))
            return perm.reshape(table.shape)

        c = _Case(
            rng, b=2, hq=4, hkv=4, d=32, bs=8, max_blocks=4, table_perm=scramble
        )
        got = paged_attention.paged_decode_attention(
            c.q, c.k_pool, c.v_pool, c.table, c.lengths, interpret=True
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(c.oracle()), atol=2e-5)

    def test_bad_head_ratio_raises(self):
        q = jnp.zeros((1, 3, 8))
        kp = vp = jnp.zeros((2, 2, 4, 8))
        with pytest.raises(ValueError, match="multiple of kv heads"):
            paged_attention.paged_decode_attention(
                q, kp, vp, jnp.zeros((1, 1), jnp.int32), jnp.ones((1,), jnp.int32),
                interpret=True,
            )


class TestWindowAttention:
    @pytest.mark.parametrize("nq", [2, 4])
    @pytest.mark.parametrize("impl", ["kernel", "xla"])
    def test_window_matches_dense_causal(self, nq, impl):
        """Window query j attends keys <= pos + j — checked against dense
        attention over the sequence-major oracle arrays."""
        c = _Case(
            jax.random.PRNGKey(11), b=3, hq=4, hkv=2, d=32, bs=8, max_blocks=4
        )
        # frontier per row such that pos + nq - 1 stays in range
        pos = jnp.minimum(c.lengths - 1, 8 * 4 - nq)
        q = jax.random.normal(jax.random.PRNGKey(12), (3, nq, 4, 32), jnp.float32)
        if impl == "kernel":
            got = paged_attention.paged_window_attention(
                q, c.k_pool, c.v_pool, c.table, pos, interpret=True
            )
        else:
            got = paged_attention.paged_window_attention_xla(
                q, c.k_pool, c.v_pool, c.table, pos
            )
        k_pos = jnp.arange(c.k_seq.shape[1])
        qpos = pos[:, None] + jnp.arange(nq)[None, :]
        mask = (k_pos[None, None, :] <= qpos[:, :, None])[:, None]
        want = _masked_attention(q, c.k_seq, c.v_seq, mask)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)

    def test_nq1_equals_decode_attention(self):
        c = _Case(jax.random.PRNGKey(13), b=2, hq=4, hkv=4, d=32, bs=8, max_blocks=2)
        got = paged_attention.paged_window_attention(
            c.q[:, None], c.k_pool, c.v_pool, c.table, c.lengths - 1,
            interpret=True,
        )[:, 0]
        want = paged_attention.paged_decode_attention(
            c.q, c.k_pool, c.v_pool, c.table, c.lengths, interpret=True
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


class TestAppendAttention:
    """The FUSED append+attend kernel: every edge the engine relies on,
    checked directly against scatter-then-attend with the XLA oracle."""

    def _setup(self, *, b=3, nq=1, hq=4, hkv=2, d=32, bs=8, mb=4, layers=2,
               pos=None, seed=21):
        ks = jax.random.split(jax.random.PRNGKey(seed), 5)
        n_pool = 1 + b * mb
        k5 = jax.random.normal(ks[0], (layers, n_pool, hkv, d, bs), jnp.float32)
        v5 = jax.random.normal(ks[1], (layers, n_pool, hkv, d, bs), jnp.float32)
        table = jnp.asarray(
            1 + np.arange(b * mb, dtype=np.int32).reshape(b, mb)
        )
        q = jax.random.normal(ks[2], (b, nq, hq, d), jnp.float32)
        new_k = jax.random.normal(ks[3], (b, nq, hkv, d), jnp.float32)
        new_v = jax.random.normal(ks[4], (b, nq, hkv, d), jnp.float32)
        if pos is None:
            pos = jnp.asarray([0, bs - 1, bs * mb - nq][:b], jnp.int32)
        return k5, v5, table, q, new_k, new_v, jnp.asarray(pos, jnp.int32)

    def _oracle(self, k5, v5, table, q, new_k, new_v, pos, li, wmask=None):
        """Scatter the window into layer ``li`` with plain indexing, then
        run the gather-based reference attention."""
        b, nq = q.shape[:2]
        bs = k5.shape[4]
        rows = jnp.arange(b)
        positions = pos[:, None] + jnp.arange(nq)[None, :]
        ids = table[rows[:, None], positions // bs]
        offs = positions % bs
        if wmask is not None:
            ids = jnp.where(wmask[:, None], ids, 0)
        kk = k5.at[li, ids, :, :, offs].set(new_k)
        vv = v5.at[li, ids, :, :, offs].set(new_v)
        out = paged_attention.paged_window_attention_xla(
            q, kk[li], vv[li], table, pos
        )
        return out, kk, vv

    @pytest.mark.parametrize("nq,pos", [
        (1, [0, 7, 31]),       # fresh block start / block end / table end
        (5, [0, 6, 27]),       # windows crossing block boundaries
    ])
    def test_matches_scatter_then_attend(self, nq, pos):
        k5, v5, table, q, nk, nv, pos = self._setup(nq=nq, pos=pos)
        out, ko, vo = paged_attention.paged_append_attention(
            q, nk, nv, k5, v5, table, pos, 1, interpret=True
        )
        want, kw, vw = self._oracle(k5, v5, table, q, nk, nv, pos, 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)
        np.testing.assert_allclose(np.asarray(ko), np.asarray(kw), atol=0)
        np.testing.assert_allclose(np.asarray(vo), np.asarray(vw), atol=0)

    def test_window_crossing_superblock_boundary(self):
        """pages_per_step=1 forces one block per grid step, so a window
        spanning two blocks is blended and flushed by TWO different steps."""
        k5, v5, table, q, nk, nv, pos = self._setup(nq=4, pos=[6, 14, 22])
        out, ko, vo = paged_attention.paged_append_attention(
            q, nk, nv, k5, v5, table, pos, 0, pages_per_step=1, interpret=True
        )
        want, kw, vw = self._oracle(k5, v5, table, q, nk, nv, pos, 0)
        np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=2e-5)
        np.testing.assert_allclose(np.asarray(ko), np.asarray(kw), atol=0)

    def test_write_mask_gates_pool_writes_only(self):
        """Masked rows attend (output still defined) but write NOTHING —
        the engine's stale-table safety for inactive slots."""
        k5, v5, table, q, nk, nv, pos = self._setup()
        wmask = jnp.asarray([True, False, True])
        out, ko, vo = paged_attention.paged_append_attention(
            q, nk, nv, k5, v5, table, pos, 0, write_mask=wmask, interpret=True
        )
        # row 1's blocks are bit-identical to the input pool
        row1_blocks = np.asarray(table[1])
        np.testing.assert_array_equal(
            np.asarray(ko[0, row1_blocks]), np.asarray(k5[0, row1_blocks])
        )
        np.testing.assert_array_equal(
            np.asarray(vo[0, row1_blocks]), np.asarray(v5[0, row1_blocks])
        )
        # unmasked rows' writes landed
        _, kw, _ = self._oracle(k5, v5, table, q, nk, nv, pos, 0, wmask=wmask)
        row0_blocks = np.asarray(table[0])
        np.testing.assert_array_equal(
            np.asarray(ko[0, row0_blocks]), np.asarray(kw[0, row0_blocks])
        )

    def test_only_target_layer_written(self):
        k5, v5, table, q, nk, nv, pos = self._setup(layers=3)
        _, ko, vo = paged_attention.paged_append_attention(
            q, nk, nv, k5, v5, table, pos, 2, interpret=True
        )
        np.testing.assert_array_equal(np.asarray(ko[0]), np.asarray(k5[0]))
        np.testing.assert_array_equal(np.asarray(ko[1]), np.asarray(k5[1]))
        assert np.any(np.asarray(ko[2]) != np.asarray(k5[2]))

    def test_untouched_blocks_preserved(self):
        """Blocks before the frontier (incl. potentially SHARED prefix
        blocks) are never flushed — only the page(s) holding the appended
        positions change."""
        k5, v5, table, q, nk, nv, pos = self._setup(
            nq=1, pos=[17, 17, 17], mb=4, bs=8
        )
        _, ko, _ = paged_attention.paged_append_attention(
            q, nk, nv, k5, v5, table, pos, 0, interpret=True
        )
        frontier = {int(table[r, 17 // 8]) for r in range(3)}
        for blk in range(k5.shape[1]):
            if blk not in frontier:
                np.testing.assert_array_equal(
                    np.asarray(ko[0, blk]), np.asarray(k5[0, blk]),
                    err_msg=f"block {blk} was touched",
                )

    def test_window_larger_than_block_rejected(self):
        k5, v5, table, q, nk, nv, pos = self._setup(nq=1)
        big = jnp.zeros((3, 9, 4, 32))
        bigkv = jnp.zeros((3, 9, 2, 32))
        with pytest.raises(ValueError, match="at most two blocks"):
            paged_attention.paged_append_attention(
                big, bigkv, bigkv, k5, v5, table, pos, 0, interpret=True
            )


class TestAllocator:
    def test_lifo_and_exhaustion(self):
        a = paged.BlockAllocator(5)  # usable: 1..4
        assert a.alloc(2) == [1, 2]
        assert a.free_blocks == 2
        with pytest.raises(paged.OutOfBlocks):
            a.alloc(3)
        a.free([1])
        assert a.alloc(1) == [1]  # hottest block reused first

    def test_null_block_never_allocated(self):
        a = paged.BlockAllocator(4)
        assert paged.NULL_BLOCK not in a.alloc(3)

    def test_double_free_and_range(self):
        a = paged.BlockAllocator(4)
        ids = a.alloc(1)
        a.free(ids)
        with pytest.raises(ValueError, match="double free"):
            a.free(ids)
        with pytest.raises(ValueError, match="out of range"):
            a.free([0])

    def test_blocks_needed(self):
        assert paged.blocks_needed(1, 16) == 1
        assert paged.blocks_needed(16, 16) == 1
        assert paged.blocks_needed(17, 16) == 2


class TestPagedDecode:
    @pytest.mark.parametrize("cfg", [CFG, CFG_GQA], ids=["mha", "gqa+rope"])
    def test_greedy_parity_with_dense(self, cfg):
        """Token-exact vs the dense batched-prefill greedy decode."""
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (3, 7), 0, cfg.vocab_size)
        want = decode.greedy_decode(params, prompt, 20, cfg, batch_prefill=True)
        got = paged.paged_greedy_decode(
            params, prompt, 20, cfg, block_size=8, attn_impl="xla"
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_greedy_parity_kernel(self):
        """Same contract through the pallas kernel (interpret mode)."""
        params = burnin.init_params(jax.random.PRNGKey(0), CFG_GQA)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 5), 0, CFG_GQA.vocab_size)
        want = decode.greedy_decode(params, prompt, 8, CFG_GQA, batch_prefill=True)
        got = paged.paged_greedy_decode(
            params, prompt, 8, CFG_GQA, block_size=8,
            attn_impl="kernel", interpret=True,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_block_boundary_crossing(self):
        """Generation crosses several block boundaries (bs=4, 18 tokens)."""
        params = burnin.init_params(jax.random.PRNGKey(2), CFG)
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 3), 0, CFG.vocab_size)
        want = decode.greedy_decode(params, prompt, 15, CFG, batch_prefill=True)
        got = paged.paged_greedy_decode(
            params, prompt, 15, CFG, block_size=4, attn_impl="xla"
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_inactive_rows_write_null_block(self):
        """A retired slot whose stale table points at a REASSIGNED block
        must not clobber the new owner's keys (write-after-free guard)."""
        cfg = CFG
        params = burnin.init_params(jax.random.PRNGKey(0), cfg)
        cache = paged.init_paged_cache(cfg, n_blocks=3, block_size=4)
        # both rows' tables point at the SAME block 1: row 1 is inactive
        # (its slot was freed; block 1 reassigned to row 0)
        table = jnp.asarray([[1, 2], [1, 2]], jnp.int32)
        token = jnp.asarray([5, 9], jnp.int32)
        pos = jnp.asarray([0, 0], jnp.int32)
        active = jnp.asarray([True, False])
        _, cache2 = paged.paged_decode_step(
            params, cache, table, token, pos, cfg=cfg, active=active
        )
        # row 0's write must be exactly what a solo active write produces
        _, solo = paged.paged_decode_step(
            params, cache, table[:1], token[:1], pos[:1], cfg=cfg,
            active=jnp.asarray([True]),
        )
        np.testing.assert_allclose(
            np.asarray(cache2.k[:, 1]), np.asarray(solo.k[:, 1]), atol=0
        )
        # the inactive row's key landed in the null block, nowhere else
        assert np.any(np.asarray(cache2.k[:, paged.NULL_BLOCK]) != 0)
        np.testing.assert_array_equal(np.asarray(cache2.k[:, 2]), 0)

    def test_prefill_fills_only_owned_blocks(self):
        cfg = CFG
        params = burnin.init_params(jax.random.PRNGKey(1), cfg)
        cache = paged.init_paged_cache(cfg, n_blocks=6, block_size=4)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 6), 0, cfg.vocab_size)
        table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
        cache2, logits = paged.paged_prefill(params, prompt, cache, table, cfg=cfg)
        assert logits.shape == (2, cfg.vocab_size)
        # blocks 1..4 written, block 5 and the null block untouched
        for blk in (1, 2, 3, 4):
            assert np.any(np.asarray(cache2.k[:, blk]) != 0)
        np.testing.assert_array_equal(np.asarray(cache2.k[:, 5]), 0)
        np.testing.assert_array_equal(np.asarray(cache2.k[:, 0]), 0)


class TestGQAWindowAttention:
    """The GQA-aware gather path must be indistinguishable from the
    reference gather path — bit-equal, not allclose — or bench's
    ``bit_equal`` honesty field and the engine's xla branch are lying."""

    def _window(self, seed, *, dtype=jnp.float32, nq=1, hq=8, hkv=2):
        c = _Case(
            jax.random.PRNGKey(seed), b=3, hq=hq, hkv=hkv, d=32, bs=8,
            max_blocks=4, dtype=dtype,
        )
        pos = jnp.minimum(c.lengths - 1, 8 * 4 - nq)
        q = jax.random.normal(
            jax.random.PRNGKey(seed + 1), (3, nq, hq, 32), jnp.float32
        ).astype(dtype).astype(jnp.float32)
        return c, q, pos

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
    @pytest.mark.parametrize("nq", [1, 4])
    def test_bit_equal_to_reference(self, dtype, nq):
        c, q, pos = self._window(31, dtype=dtype, nq=nq)
        ref = paged_attention.paged_window_attention_xla(
            q, c.k_pool, c.v_pool, c.table, pos
        )
        got = paged_attention.paged_window_attention_xla_gqa(
            q, c.k_pool, c.v_pool, c.table, pos
        )
        # bit-equality: same dtype, zero tolerance
        assert got.dtype == ref.dtype
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))

    def test_mha_degenerates_cleanly(self):
        """hq == hkv (groups = 1): the reference's _masked_attention takes
        its ungrouped-einsum branch here, a different contraction order, so
        the contract is allclose — bit-equality only holds where the engine
        actually routes MHA configs (the grouped branch both sides)."""
        c, q, pos = self._window(37, hq=4, hkv=4)
        ref = paged_attention.paged_window_attention_xla(
            q, c.k_pool, c.v_pool, c.table, pos
        )
        got = paged_attention.paged_window_attention_xla_gqa(
            q, c.k_pool, c.v_pool, c.table, pos
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)

    def test_ragged_groups_rejected(self):
        c, q, pos = self._window(41, hq=8, hkv=2)
        # 6 query heads over 2 kv heads is fine; over 4 it is ragged
        kp = jnp.concatenate([c.k_pool, c.k_pool], axis=1)
        vp = jnp.concatenate([c.v_pool, c.v_pool], axis=1)
        with pytest.raises(ValueError, match="multiple of kv heads"):
            paged_attention.paged_window_attention_xla_gqa(
                q[:, :, :6], kp, vp, c.table, pos
            )

    @pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
    def test_quantized_scales_match_explicit_dequant(self, kv_dtype):
        """Passing k_scale/v_scale must equal quantize -> dequant by hand
        -> reference path: the fused operand-load dequant changes WHERE the
        multiply happens, never the value."""
        from k8s_dra_driver_tpu.models import quant

        c, q, pos = self._window(43)
        # int4 comes back already packed [..., hd, bs//2] uint8
        kq, ksc = quant.quantize_kv_blocks(c.k_pool, kv_dtype)
        vq, vsc = quant.quantize_kv_blocks(c.v_pool, kv_dtype)
        got = paged_attention.paged_window_attention_xla_gqa(
            q, kq, vq, c.table, pos, k_scale=ksc, v_scale=vsc
        )
        want = paged_attention.paged_window_attention_xla_gqa(
            q,
            quant.dequant_kv_blocks(kq, ksc),
            quant.dequant_kv_blocks(vq, vsc),
            c.table, pos,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # bounded divergence vs the unquantized truth (sanity, not bit)
        ref = paged_attention.paged_window_attention_xla(
            q, c.k_pool, c.v_pool, c.table, pos
        )
        atol = 0.05 if kv_dtype == "int8" else 0.5
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=atol)


class TestKernelBlockSizeGuard:
    """check_kernel_block_size is the callable form of the TPU DMA lane
    invariant — it must fire on CPU, where the runtime kernel guards stay
    silent, so sweep configs can't claim TPU validity they don't have."""

    @pytest.mark.parametrize("bs", [128, 256, 512])
    def test_accepts_lane_multiples(self, bs):
        paged_attention.check_kernel_block_size(bs)

    @pytest.mark.parametrize("bs", [4, 16, 100, 127, 129])
    def test_rejects_non_multiples_on_cpu(self, bs):
        assert jax.default_backend() == "cpu"
        with pytest.raises(ValueError, match="block_size % 128"):
            paged_attention.check_kernel_block_size(bs)
