"""Weight-only int8 serving quantization (models/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_dra_driver_tpu.models import burnin, decode
from k8s_dra_driver_tpu.models.quant import (
    QuantizedMatrix,
    mat,
    quantize_blocks,
    quantized_bytes,
)

CFG = burnin.ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=64
)


def _params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


class TestQuantizedMatrix:
    def test_roundtrip_error_is_small(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (128, 64), jnp.float32)
        qm = QuantizedMatrix.quantize(w)
        err = jnp.abs(qm.dequant().astype(jnp.float32) - w)
        # symmetric per-column int8: worst-case step is scale/2 = max|col|/254
        assert float(err.max() / jnp.abs(w).max()) < 1 / 100
        assert qm.q.dtype == jnp.int8
        assert qm.scale.shape == (64,)

    def test_zero_column_is_stable(self):
        w = jnp.zeros((8, 4), jnp.float32)
        qm = QuantizedMatrix.quantize(w)
        assert not jnp.isnan(qm.dequant()).any()
        np.testing.assert_array_equal(qm.dequant(), w)

    def test_mat_is_identity_for_plain_arrays(self):
        w = jnp.ones((2, 2))
        assert mat(w) is w

    def test_flows_through_jit(self):
        qm = QuantizedMatrix.quantize(
            jax.random.normal(jax.random.PRNGKey(2), (16, 8))
        )
        out = jax.jit(lambda m, x: x @ mat(m))(qm, jnp.ones((4, 16), jnp.bfloat16))
        assert out.shape == (4, 8)


class TestQuantizedModel:
    def test_quantize_blocks_structure(self):
        qp = quantize_blocks(_params())
        for blk in qp["blocks"]:
            for key in ("qkv", "attn_out", "mlp_up", "mlp_down"):
                assert isinstance(blk[key], QuantizedMatrix)
            assert not isinstance(blk["ln1"], QuantizedMatrix)
        assert not isinstance(qp["embed"], QuantizedMatrix)

    def test_bytes_saved(self):
        qp = quantize_blocks(_params())
        stored, as_bf16 = quantized_bytes(qp)
        # block weights dominate this config; stored must be well under bf16
        assert stored < 0.75 * as_bf16

    def test_forward_matches_dense_closely(self):
        params = _params()
        tokens = burnin.sample_tokens(jax.random.PRNGKey(3), CFG, batch=2, seq=32)
        ref = burnin.forward(params, tokens, cfg=CFG)
        out = burnin.forward(quantize_blocks(params), tokens, cfg=CFG)
        # int8 weight error is <1% per matmul; logits track closely
        assert float(jnp.abs(out - ref).mean()) < 0.05 * float(jnp.abs(ref).mean() + 1)

    def test_greedy_decode_equals_manually_dequantized_params(self):
        """decode(quantized) must EXACTLY equal decode(params whose weights
        were pre-dequantized): same numbers, different storage."""
        params = _params()
        qp = quantize_blocks(params)
        deq = dict(qp)
        deq["blocks"] = [
            {k: (mat(v) if isinstance(v, QuantizedMatrix) else v) for k, v in blk.items()}
            for blk in qp["blocks"]
        ]
        prompt = burnin.sample_tokens(jax.random.PRNGKey(4), CFG, batch=2, seq=8)
        out_q = decode.greedy_decode(qp, prompt, 16, cfg=CFG, batch_prefill=True)
        out_d = decode.greedy_decode(deq, prompt, 16, cfg=CFG, batch_prefill=True)
        np.testing.assert_array_equal(np.asarray(out_q), np.asarray(out_d))

    def test_quantized_decode_mostly_agrees_with_bf16(self):
        params = _params()
        prompt = burnin.sample_tokens(jax.random.PRNGKey(5), CFG, batch=2, seq=8)
        ref = decode.greedy_decode(params, prompt, 24, cfg=CFG)
        out = decode.greedy_decode(quantize_blocks(params), prompt, 24, cfg=CFG)
        agree = float((np.asarray(ref) == np.asarray(out)).mean())
        assert agree > 0.7  # random-init logits are near-uniform; trained
        # models agree far more — the contract here is "sane, not garbage"
