"""Weight-only int8 serving quantization (models/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from k8s_dra_driver_tpu.models import burnin, decode
from k8s_dra_driver_tpu.models.quant import (
    QuantizedMatrix,
    mat,
    quantize_blocks,
    quantized_bytes,
)

CFG = burnin.ModelConfig(
    vocab_size=256, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=64
)


def _params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


class TestQuantizedMatrix:
    def test_roundtrip_error_is_small(self):
        w = jax.random.normal(jax.random.PRNGKey(1), (128, 64), jnp.float32)
        qm = QuantizedMatrix.quantize(w)
        err = jnp.abs(qm.dequant().astype(jnp.float32) - w)
        # symmetric per-column int8: worst-case step is scale/2 = max|col|/254
        assert float(err.max() / jnp.abs(w).max()) < 1 / 100
        assert qm.q.dtype == jnp.int8
        assert qm.scale.shape == (64,)

    def test_zero_column_is_stable(self):
        w = jnp.zeros((8, 4), jnp.float32)
        qm = QuantizedMatrix.quantize(w)
        assert not jnp.isnan(qm.dequant()).any()
        np.testing.assert_array_equal(qm.dequant(), w)

    def test_mat_is_identity_for_plain_arrays(self):
        w = jnp.ones((2, 2))
        assert mat(w) is w

    def test_flows_through_jit(self):
        qm = QuantizedMatrix.quantize(
            jax.random.normal(jax.random.PRNGKey(2), (16, 8))
        )
        out = jax.jit(lambda m, x: x @ mat(m))(qm, jnp.ones((4, 16), jnp.bfloat16))
        assert out.shape == (4, 8)


class TestQuantizedModel:
    def test_quantize_blocks_structure(self):
        qp = quantize_blocks(_params())
        for blk in qp["blocks"]:
            for key in ("qkv", "attn_out", "mlp_up", "mlp_down"):
                assert isinstance(blk[key], QuantizedMatrix)
            assert not isinstance(blk["ln1"], QuantizedMatrix)
        assert not isinstance(qp["embed"], QuantizedMatrix)

    def test_bytes_saved(self):
        qp = quantize_blocks(_params())
        stored, as_bf16 = quantized_bytes(qp)
        # block weights dominate this config; stored must be well under bf16
        assert stored < 0.75 * as_bf16

    def test_forward_matches_dense_closely(self):
        params = _params()
        tokens = burnin.sample_tokens(jax.random.PRNGKey(3), CFG, batch=2, seq=32)
        ref = burnin.forward(params, tokens, cfg=CFG)
        out = burnin.forward(quantize_blocks(params), tokens, cfg=CFG)
        # int8 weight error is <1% per matmul; logits track closely
        assert float(jnp.abs(out - ref).mean()) < 0.05 * float(jnp.abs(ref).mean() + 1)

    def test_greedy_decode_equals_manually_dequantized_params(self):
        """decode(quantized) must EXACTLY equal decode(params whose weights
        were pre-dequantized): same numbers, different storage."""
        params = _params()
        qp = quantize_blocks(params)
        deq = dict(qp)
        deq["blocks"] = [
            {k: (mat(v) if isinstance(v, QuantizedMatrix) else v) for k, v in blk.items()}
            for blk in qp["blocks"]
        ]
        prompt = burnin.sample_tokens(jax.random.PRNGKey(4), CFG, batch=2, seq=8)
        out_q = decode.greedy_decode(qp, prompt, 16, cfg=CFG, batch_prefill=True)
        out_d = decode.greedy_decode(deq, prompt, 16, cfg=CFG, batch_prefill=True)
        np.testing.assert_array_equal(np.asarray(out_q), np.asarray(out_d))

    def test_quantized_decode_mostly_agrees_with_bf16(self):
        params = _params()
        prompt = burnin.sample_tokens(jax.random.PRNGKey(5), CFG, batch=2, seq=8)
        ref = decode.greedy_decode(params, prompt, 24, cfg=CFG)
        out = decode.greedy_decode(quantize_blocks(params), prompt, 24, cfg=CFG)
        agree = float((np.asarray(ref) == np.asarray(out)).mean())
        assert agree > 0.7  # random-init logits are near-uniform; trained
        # models agree far more — the contract here is "sane, not garbage"


class TestInt4:
    def test_pack_unpack_exact(self):
        """Values already on the int4 grid survive the pack/unpack round
        trip (each group carries a ±7 so the derived scale lands exactly
        on the grid's step)."""
        from k8s_dra_driver_tpu.models.quant import Quantized4Matrix

        rng = np.random.RandomState(0)
        step = 0.25
        q = rng.randint(-7, 8, size=(128, 32)).astype(np.float32)
        q[0::64] = 7.0  # pin every group's max -> scale == step exactly
        w = jnp.asarray(q * step)
        qm = Quantized4Matrix.quantize(w, group_size=64)
        np.testing.assert_allclose(
            np.asarray(qm.dequant(), np.float32), np.asarray(w), atol=1e-6
        )

    def test_groupwise_beats_columnwise_on_outliers(self):
        """The reason for group scales: one outlier row must not wreck the
        whole column's resolution."""
        from k8s_dra_driver_tpu.models.quant import Quantized4Matrix

        w = jax.random.normal(jax.random.PRNGKey(2), (256, 16), jnp.float32)
        w = w.at[0].mul(50.0)  # outlier in group 0 only
        qm = Quantized4Matrix.quantize(w, group_size=64)
        err = jnp.abs(qm.dequant().astype(jnp.float32) - w)[64:]  # other groups
        rel = float(err.max() / jnp.abs(w[64:]).max())
        assert rel < 0.12  # int4 step within a clean group, not outlier-scaled

    def test_block_weight_bytes_are_half_of_int8(self):
        """Compare the BLOCK weights only (embeddings stay unquantized and
        dominate this tiny config's total)."""
        params = _params()
        blocks = lambda p: {"blocks": p["blocks"]}  # noqa: E731
        b4, dense = quantized_bytes(blocks(quantize_blocks(params, bits=4)))
        b8, _ = quantized_bytes(blocks(quantize_blocks(params, bits=8)))
        assert b4 < 0.62 * b8  # ~4.5 bits vs ~8.25 bits per weight
        assert b4 < 0.40 * dense

    def test_greedy_decode_equals_manually_dequantized_params(self):
        """The same exactness contract as int8: storage changes, numbers
        don't."""
        from k8s_dra_driver_tpu.models.quant import Quantized4Matrix

        params = _params()
        qp = quantize_blocks(params, bits=4)
        deq = dict(qp)
        deq["blocks"] = [
            {k: (mat(v) if isinstance(v, Quantized4Matrix) else v)
             for k, v in blk.items()}
            for blk in qp["blocks"]
        ]
        prompt = burnin.sample_tokens(jax.random.PRNGKey(6), CFG, batch=2, seq=8)
        out_q = decode.greedy_decode(qp, prompt, 12, cfg=CFG, batch_prefill=True)
        out_d = decode.greedy_decode(deq, prompt, 12, cfg=CFG, batch_prefill=True)
        np.testing.assert_array_equal(np.asarray(out_q), np.asarray(out_d))

    def test_int4_as_speculative_draft(self):
        """int4's extra error only moves ACCEPTANCE, never output — the
        natural draft config (half the draft's HBM bytes again)."""
        from k8s_dra_driver_tpu.models import speculative

        params = _params()
        prompt = burnin.sample_tokens(jax.random.PRNGKey(7), CFG, batch=2, seq=6)
        want = decode.greedy_decode(params, prompt, 14, cfg=CFG, batch_prefill=True)
        got = speculative.speculative_decode(
            params, quantize_blocks(params, bits=4), prompt, 14, CFG, gamma=3
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_group_size_passthrough(self):
        """Dims that 64 does not divide work with a caller-chosen group."""
        from k8s_dra_driver_tpu.models.quant import Quantized4Matrix

        cfg = burnin.ModelConfig(
            vocab_size=64, d_model=48, n_heads=4, n_layers=1, d_ff=96, max_seq=32
        )
        params = burnin.init_params(jax.random.PRNGKey(8), cfg)
        import pytest

        with pytest.raises(ValueError, match="divisible"):
            quantize_blocks(params, bits=4)  # 48 % 64 != 0
        qp = quantize_blocks(params, bits=4, group_size=16)
        assert isinstance(qp["blocks"][0]["qkv"], Quantized4Matrix)

    def test_bad_bits_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="bits"):
            quantize_blocks(_params(), bits=2)

    def test_odd_input_dim_rejected(self):
        import pytest

        from k8s_dra_driver_tpu.models.quant import Quantized4Matrix

        with pytest.raises(ValueError, match="divisible"):
            Quantized4Matrix.quantize(jnp.zeros((66, 8)), group_size=64)


class TestKVBlockQuant:
    """Per-block symmetric KV quantization — the primitives behind the
    kv_dtype pool modes (zero-tail requant invariant, pack/unpack
    exactness, scale conventions)."""

    def _blocks(self, seed=7, shape=(2, 3, 2, 16, 8)):
        from k8s_dra_driver_tpu.models.quant import quantize_kv_blocks

        x = jax.random.normal(jax.random.PRNGKey(seed), shape, jnp.float32)
        return x, quantize_kv_blocks

    def test_kv_dtype_bits(self):
        from k8s_dra_driver_tpu.models.quant import kv_dtype_bits

        assert kv_dtype_bits("int8") == 8
        assert kv_dtype_bits("int4") == 4
        import pytest

        with pytest.raises(ValueError, match="kv_dtype"):
            kv_dtype_bits("int2")

    def test_int8_scale_convention(self):
        """scale = amax/127, values clipped to +-127, amax maps exactly."""
        from k8s_dra_driver_tpu.models.quant import quantize_kv_blocks

        x, _ = self._blocks()
        q, scale = quantize_kv_blocks(x, "int8")
        assert q.dtype == jnp.int8 and q.shape == x.shape
        assert scale.dtype == jnp.float32 and scale.shape == x.shape[:-2]
        amax = np.max(np.abs(np.asarray(x)), axis=(-2, -1))
        np.testing.assert_allclose(np.asarray(scale), amax / 127.0, rtol=1e-6)
        assert np.abs(np.asarray(q)).max() <= 127

    def test_int4_packs_half_lanes(self):
        from k8s_dra_driver_tpu.models.quant import quantize_kv_blocks

        x, _ = self._blocks()
        q, scale = quantize_kv_blocks(x, "int4")
        assert q.dtype == jnp.uint8
        assert q.shape == x.shape[:-1] + (x.shape[-1] // 2,)
        amax = np.max(np.abs(np.asarray(x)), axis=(-2, -1))
        np.testing.assert_allclose(np.asarray(scale), amax / 7.0, rtol=1e-6)

    def test_zero_block_dequants_to_exact_zero(self):
        """All-zero blocks use scale 1.0 — dequant is exact 0, so untouched
        pool blocks stay bitwise zero across requant cycles."""
        from k8s_dra_driver_tpu.models.quant import (
            dequant_kv_blocks,
            quantize_kv_blocks,
        )

        z = jnp.zeros((1, 2, 2, 8, 8), jnp.float32)
        for kd in ("int8", "int4"):
            q, scale = quantize_kv_blocks(z, kd)
            np.testing.assert_array_equal(np.asarray(scale), 1.0)
            np.testing.assert_array_equal(
                np.asarray(dequant_kv_blocks(q, scale)), 0.0
            )

    def test_requant_is_stable(self):
        """quant -> dequant -> quant is a fixed point: block bytes stay a
        pure function of the written history (the zero-tail invariant the
        engine's _quantized_block_write depends on)."""
        from k8s_dra_driver_tpu.models.quant import (
            dequant_kv_blocks,
            quantize_kv_blocks,
        )

        x, _ = self._blocks(seed=11)
        for kd in ("int8", "int4"):
            q1, s1 = quantize_kv_blocks(x, kd)
            q2, s2 = quantize_kv_blocks(dequant_kv_blocks(q1, s1), kd)
            np.testing.assert_array_equal(np.asarray(q1), np.asarray(q2))
            np.testing.assert_allclose(
                np.asarray(s1), np.asarray(s2), rtol=1e-6
            )

    def test_pack_unpack_roundtrip_exact(self):
        from k8s_dra_driver_tpu.models.quant import pack_int4, unpack_int4

        q = jnp.asarray(
            np.random.default_rng(3).integers(-8, 8, (2, 5, 16), np.int8)
        )
        packed = pack_int4(q)
        assert packed.dtype == jnp.uint8 and packed.shape == (2, 5, 8)
        np.testing.assert_array_equal(np.asarray(unpack_int4(packed)), np.asarray(q))

    def test_pack_odd_axis_rejected(self):
        from k8s_dra_driver_tpu.models.quant import pack_int4

        import pytest

        with pytest.raises(ValueError):
            pack_int4(jnp.zeros((2, 7), jnp.int8))

    def test_dequant_error_bounded_by_half_step(self):
        from k8s_dra_driver_tpu.models.quant import (
            dequant_kv_blocks,
            quantize_kv_blocks,
        )

        x, _ = self._blocks(seed=13)
        for kd, levels in (("int8", 127.0), ("int4", 7.0)):
            q, scale = quantize_kv_blocks(x, kd)
            err = np.abs(np.asarray(dequant_kv_blocks(q, scale)) - np.asarray(x))
            half_step = np.asarray(scale)[..., None, None] / 2 + 1e-6
            assert (err <= half_step).all()
