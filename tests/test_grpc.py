"""gRPC DRA service tests: the kubelet wire path over unix sockets."""

import pytest

from k8s_dra_driver_tpu import DRIVER_NAME
from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
from k8s_dra_driver_tpu.plugin.driver import ClaimRef
from k8s_dra_driver_tpu.plugin.grpc_service import (
    DRAClient,
    PluginServer,
    RegistrationClient,
)


@pytest.fixture
def served(tmp_path):
    cluster = make_cluster(hosts=1, work_dir=str(tmp_path / "work"))
    node = cluster.nodes["tpu-host-0"]
    # Reach into the harness driver (it owns the DeviceState).
    from k8s_dra_driver_tpu.plugin.driver import Driver, DriverConfig

    driver = Driver(
        cluster.server,
        DriverConfig(
            node_name="tpu-host-0",
            cdi_root=str(tmp_path / "cdi"),
            checkpoint_path=str(tmp_path / "checkpoint.json"),
            topology_env={"TPUINFO_FAKE_TOPOLOGY": "v5e-16", "TPUINFO_FAKE_HOST_ID": "0"},
            publish=False,  # harness node already published this pool
        ),
    )
    server = PluginServer(
        driver,
        plugin_dir=str(tmp_path / "plugins" / DRIVER_NAME),
        registry_dir=str(tmp_path / "plugins_registry"),
    )
    server.start()
    yield cluster, server
    server.stop()


class TestGRPC:
    def test_method_paths_match_upstream_kubelet_api(self):
        """A real kubelet dials the UPSTREAM proto package paths
        (reference vendor k8s.io/kubelet dra/v1beta1 api.pb.go and
        pluginregistration/v1 api.pb.go) — custom package names would make
        every call fail UNIMPLEMENTED on a real cluster while
        driver-side tests still pass (round-1 advisor finding, high)."""
        import inspect

        from k8s_dra_driver_tpu.plugin import grpc_service

        src = inspect.getsource(grpc_service)
        for path in (
            "/k8s.io.kubelet.pkg.apis.dra.v1beta1.DRAPlugin/NodePrepareResources",
            "/k8s.io.kubelet.pkg.apis.dra.v1beta1.DRAPlugin/NodeUnprepareResources",
            "/pluginregistration.Registration/GetInfo",
            "/pluginregistration.Registration/NotifyRegistrationStatus",
        ):
            assert path in src, f"gRPC method path {path} not served/dialed"
        # and the generated descriptors carry the upstream packages too
        from k8s_dra_driver_tpu.plugin.proto.gen import dra_pb2, registration_pb2

        assert (
            dra_pb2.DESCRIPTOR.package == "k8s.io.kubelet.pkg.apis.dra.v1beta1"
        )
        assert registration_pb2.DESCRIPTOR.package == "pluginregistration"

    def test_registration_handshake(self, served):
        _, server = served
        client = RegistrationClient(server.registry_socket)
        info = client.handshake()
        assert info.type == "DRAPlugin"
        assert info.name == DRIVER_NAME
        assert info.endpoint == server.plugin_socket
        assert list(info.supported_versions) == ["v1beta1"]
        assert server.registered.is_set()
        client.close()

    def test_prepare_unprepare_roundtrip(self, served):
        cluster, server = served
        claim = cluster.server.create(simple_claim("rpc-claim"))
        allocated = cluster.allocator.allocate(claim, node_name="tpu-host-0")
        ref = ClaimRef(
            uid=allocated.metadata.uid, name="rpc-claim", namespace="default"
        )

        client = DRAClient(server.plugin_socket)
        resp = client.node_prepare_resources([ref])
        result = resp.claims[ref.uid]
        assert result.error == ""
        assert len(result.devices) == 1
        assert result.devices[0].pool_name == "tpu-host-0"
        assert result.devices[0].device_name.startswith("tpu-")
        assert len(result.devices[0].cdi_device_ids) == 2

        un = client.node_unprepare_resources([ref])
        assert un.claims[ref.uid].error == ""
        client.close()

    def test_per_claim_error_fanout(self, served):
        cluster, server = served
        good = cluster.server.create(simple_claim("good"))
        allocated = cluster.allocator.allocate(good, node_name="tpu-host-0")
        refs = [
            ClaimRef(uid=allocated.metadata.uid, name="good", namespace="default"),
            ClaimRef(uid="nope", name="missing", namespace="default"),
        ]
        client = DRAClient(server.plugin_socket)
        resp = client.node_prepare_resources(refs)
        assert resp.claims[allocated.metadata.uid].error == ""
        assert "missing" in resp.claims["nope"].error
        client.close()


class TestConcurrentLoad:
    def test_parallel_prepare_unprepare_over_the_wire(self, served):
        """The -race analog for the driver's mutex paths: many clients
        hammer NodePrepare/NodeUnprepare concurrently over the real unix
        socket; every claim must prepare exactly once, the checkpoint must
        end clean, and no cross-claim state may leak."""
        import threading

        cluster, server = served
        # the fake host publishes 4 chips: 3 concurrent holders always fit
        n_workers, claims_per_worker = 3, 5
        errors: list[str] = []
        lock = threading.Lock()

        def worker(wid: int):
            client = DRAClient(server.plugin_socket)
            try:
                for i in range(claims_per_worker):
                    name = f"load-{wid}-{i}"
                    claim = cluster.server.create(simple_claim(name))
                    with lock:
                        # the allocator stands in for kube-scheduler, which
                        # serializes allocation; Prepare below runs unlocked
                        allocated = cluster.allocator.allocate(
                            claim, node_name="tpu-host-0"
                        )
                    ref = ClaimRef(
                        uid=allocated.metadata.uid, name=name, namespace="default"
                    )
                    resp = client.node_prepare_resources([ref])
                    result = resp.claims[ref.uid]
                    if result.error:
                        errors.append(f"{name}: {result.error}")
                        continue
                    # idempotent double-prepare from a second in-flight call
                    again = client.node_prepare_resources([ref])
                    if [d.device_name for d in again.claims[ref.uid].devices] != [
                        d.device_name for d in result.devices
                    ]:
                        errors.append(f"{name}: non-idempotent prepare")
                    un = client.node_unprepare_resources([ref])
                    if un.claims[ref.uid].error:
                        errors.append(f"{name}: unprepare {un.claims[ref.uid].error}")
                    with lock:
                        cluster.allocator.deallocate(
                            cluster.server.get("ResourceClaim", name, "default")
                        )
            finally:
                client.close()

        threads = [threading.Thread(target=worker, args=(w,)) for w in range(n_workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        # a deadlocked NodePrepare must fail the test, not pass it vacuously
        assert not any(t.is_alive() for t in threads), "worker thread hung"
        assert not errors, errors[:5]
        # no residue: nothing prepared, no leftover transient CDI specs
        state = server.driver.state
        assert state.prepared_claim_uids() == []
        assert state.cdi.list_claim_spec_uids() == []
