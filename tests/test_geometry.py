"""Geometry + device-info tests, including the overlap property test
SURVEY.md §7 calls out as "easy to get subtly wrong"."""

import itertools

from k8s_dra_driver_tpu.plugin.deviceinfo import AllocatableDevices, TpuSubsliceInfo
from k8s_dra_driver_tpu.plugin.geometry import chip_marker, enumerate_subslices, host_origin
from k8s_dra_driver_tpu.tpuinfo.binding import enumerate_topology


def fake(spec: str, host_id: int = 0):
    return enumerate_topology(
        env={"TPUINFO_FAKE_TOPOLOGY": spec, "TPUINFO_FAKE_HOST_ID": str(host_id)}
    )


class TestEnumerateSubslices:
    def test_v5e_multihost_block_shapes(self):
        # 2x2 host block: 1x2 (x2 placements), 2x1 (x2), 2x2 (x1)
        subs = enumerate_subslices(fake("v5e-16"))
        by_shape = {}
        for s in subs:
            by_shape.setdefault(s.shape, []).append(s)
        assert set(by_shape) == {(1, 2, 1), (2, 1, 1), (2, 2, 1)}
        assert len(by_shape[(1, 2, 1)]) == 2
        assert len(by_shape[(2, 1, 1)]) == 2
        assert len(by_shape[(2, 2, 1)]) == 1

    def test_v5e8_single_host_shapes(self):
        subs = enumerate_subslices(fake("v5e-8"))  # 2x4 block
        shapes = {s.shape for s in subs}
        assert (2, 4, 1) in shapes  # whole host
        assert (2, 2, 1) in shapes
        assert (1, 2, 1) in shapes
        whole = [s for s in subs if s.shape == (2, 4, 1)]
        assert len(whole) == 1 and whole[0].chip_count == 8

    def test_v4_3d_block_shapes(self):
        subs = enumerate_subslices(fake("v4-16"))  # 2x2x1 host block
        shapes = {s.shape for s in subs}
        assert shapes == {(1, 2, 1), (2, 1, 1), (2, 2, 1)}

    def test_placements_are_aligned_and_tile(self):
        # Same-shape placements partition the block exactly.
        t = fake("v5e-8")
        subs = enumerate_subslices(t)
        for shape in {s.shape for s in subs}:
            covered = list(
                itertools.chain.from_iterable(
                    s.chip_indices for s in subs if s.shape == shape
                )
            )
            assert sorted(covered) == list(range(8)), shape
            assert len(set(covered)) == len(covered), shape

    def test_non_power_of_two_block_placements_fit(self):
        # 6x1 host block: extent-4 shapes only fit at origin 0; no placement
        # may reference chips outside the block.
        t = fake("v5e-6x1")
        subs = enumerate_subslices(t)
        for s in subs:
            assert all(0 <= i < 6 for i in s.chip_indices), s
        assert [s.origin for s in subs if s.shape == (4, 1, 1)] == [(0, 0, 0)]
        AllocatableDevices.from_topology(t).get_devices()  # no IndexError

    def test_global_origins_offset_by_host(self, ):
        t = fake("v5e-16", host_id=3)
        assert host_origin(t) == (2, 2, 0)
        whole = [s for s in enumerate_subslices(t) if s.shape == (2, 2, 1)][0]
        assert whole.origin == (2, 2, 0)
        assert whole.name(t.ndims) == "tpu-slice-2x2-2-2"


class TestOverlapMarkers:
    def test_shared_chip_implies_shared_marker(self):
        """THE property: any two devices sharing a chip share a capacity
        marker, so counter-aware allocation can never double-book a chip."""
        t = fake("v5e-8")
        devices = AllocatableDevices.from_topology(t)
        caps = {name: set(d.get_device().basic.capacity) for name, d in devices.devices.items()}
        chips = {
            name: set(
                d.subslice.subslice.chip_indices if d.subslice else [d.chip.chip.index]
            )
            for name, d in devices.devices.items()
        }
        for a, b in itertools.combinations(devices.devices, 2):
            share_chip = bool(chips[a] & chips[b])
            share_marker = bool(
                {c for c in caps[a] if c.startswith("chip")}
                & {c for c in caps[b] if c.startswith("chip")}
            )
            assert share_chip == share_marker, (a, b)

    def test_marker_names_match_local_indices(self):
        t = fake("v5e-16")
        dev = AllocatableDevices.from_topology(t).devices["tpu-slice-2x2-0-0"]
        cap = dev.get_device().basic.capacity
        assert {chip_marker(i) for i in range(4)} <= set(cap)


class TestDeviceConversion:
    def test_chip_device_attributes(self):
        t = fake("v5e-16", host_id=1)
        devices = AllocatableDevices.from_topology(t)
        d = devices.devices["tpu-0"].get_device()
        a = d.basic.attributes
        assert a["type"].value == "tpu"
        assert a["productName"].value == "tpu-v5e"
        assert a["tpuTopology"].value == "4x4"
        assert (a["coordX"].value, a["coordY"].value) == (2, 0)  # host 1 block
        assert d.basic.capacity["hbm"] == "16Gi"
        assert a["driverVersion"].version is not None

    def test_subslice_device(self):
        t = fake("v5e-16")
        devices = AllocatableDevices.from_topology(t)
        sub = devices.devices["tpu-slice-2x2-0-0"]
        d = sub.get_device()
        assert d.basic.attributes["type"].value == "subslice"
        assert d.basic.attributes["chipCount"].value == 4
        assert d.basic.capacity["hbm"] == "64Gi"
        assert len(sub.uuids()) == 4

    def test_total_device_count(self):
        # 4 chips + (2x 1x2 + 2x 2x1 + 1x 2x2) = 9 devices per v5e host block
        assert len(AllocatableDevices.from_topology(fake("v5e-16"))) == 9

    def test_gapped_device_node_numbering(self):
        # Real hosts may expose /dev/accel1..accel4 (gap at 0).  Overlap
        # markers must use positional indices so chip and subslice devices
        # still agree.
        import dataclasses

        t = fake("v5e-4")
        gapped = dataclasses.replace(
            t,
            chips=tuple(
                dataclasses.replace(
                    c, index=c.index + 1, device_path=f"/dev/accel{c.index + 1}"
                )
                for c in t.chips
            ),
        )
        devices = AllocatableDevices.from_topology(gapped)
        assert set(devices.devices) >= {"tpu-1", "tpu-2", "tpu-3", "tpu-4"}
        chip_caps = {
            name: {c for c in d.get_device().basic.capacity if c.startswith("chip")}
            for name, d in devices.devices.items()
        }
        # The whole-block subslice covers markers chip0..chip3 — exactly the
        # union of the per-chip markers.
        whole = [n for n in devices.devices if n.startswith("tpu-slice-2x2")][0]
        per_chip = set().union(*(chip_caps[f"tpu-{i}"] for i in range(1, 5)))
        assert chip_caps[whole] == per_chip == {f"chip{i}" for i in range(4)}
        # And uuids resolve without KeyError.
        assert len(devices.devices[whole].uuids()) == 4

    def test_subslice_uuid_is_membership_derived(self):
        t = fake("v5e-16")
        sub = [s for s in enumerate_subslices(t) if s.shape == (2, 2, 1)][0]
        info = TpuSubsliceInfo(sub, t)
        assert info.uuid.count("+") == 3
