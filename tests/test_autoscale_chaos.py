"""Autoscaler chaos suite: flash crowds and replica kills mid-scale.

The closed-loop twin of tests/test_fleet_chaos.py — a seeded trace with
a 4x flash crowd drives an autoscaled fleet of simulated engines while
utils/faults.py breaks the control loop's actuators (``spawn_fail``,
``spawn_latency_ms``) and its data plane (``replica_crash`` during the
post-crowd scale-down phase).  The PR's acceptance property:

    flash crowd -> scale-up; crowd ends -> scale-down; one replica
    killed mid-scale-down -> ZERO lost or duplicated streams, every
    completion BIT-EQUAL to an unfaulted reference run of the same
    trace (matched by prompt — prompts are unique per arrival), block
    accounting balanced on every replica once the fleet idles, and one
    journal correlation per scaling action.

Every fault draws from a seeded injector armed through the same
``DRA_FAULTS`` grammar operators use, so a failure replays from its
spec.  Runs in `make chaos-autoscale` (<15s, CPU — no jax imports on
the hot path; the engines are models/workload.py simulations).
"""

from collections import Counter

import pytest

from k8s_dra_driver_tpu.models import fleet
from k8s_dra_driver_tpu.models import workload as W
from k8s_dra_driver_tpu.models.autoscaler import (
    AutoscalerPolicy,
    FleetAutoscaler,
)
from k8s_dra_driver_tpu.utils.faults import FaultInjector, SpawnFault
from k8s_dra_driver_tpu.utils.journal import JOURNAL

SPEC = W.WorkloadSpec(
    seed=42,
    duration_s=120.0,
    base_rate_rps=12.0,
    diurnal_amplitude=0.3,
    diurnal_period_s=120.0,
    flash_crowds=(W.FlashCrowd(start_s=30.0, duration_s=20.0, multiplier=4.0),),
)

N_BLOCKS = 512


def _engine_factory(clock):
    def factory():
        return W.SimEngine(
            clock=clock, n_slots=8, n_blocks=N_BLOCKS, decode_tps=30.0
        )
    return factory


def _policy(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 5)
    kw.setdefault("up_ticks", 3)
    kw.setdefault("down_ticks", 30)
    kw.setdefault("cooldown_s", 5.0)
    return AutoscalerPolicy(**kw)


def _spy_autoscale_journal(monkeypatch):
    """The journal is a bounded ring; a 2000-request run evicts the early
    scale events.  Tee the autoscaler's records as they happen instead of
    reading the ring back."""
    events = []
    orig = JOURNAL.record

    def spy(component, event, correlation="", **attrs):
        if component == "autoscale":
            events.append({"event": event, "correlation": correlation})
        orig(component, event, correlation=correlation, **attrs)

    monkeypatch.setattr(JOURNAL, "record", spy)
    return events


def _run(injector=None, policy=None, collect=None):
    clock = W.SimClock()
    sink = W.SimSink()
    factory = _engine_factory(clock)

    def sinked_factory():
        eng = factory()
        eng.sink = sink
        return eng

    router = fleet.FleetRouter(
        [sinked_factory()], clock=clock, fault_injector=injector
    )
    asc = FleetAutoscaler(
        router, engine_factory=sinked_factory,
        policy=policy or _policy(), clock=clock,
    )
    rep = W.replay(
        W.generate(SPEC), router, clock=clock, sink=sink, autoscaler=asc,
        dt=0.1, queue_limit=2048, on_completion=collect,
    )
    return rep, router, asc


@pytest.fixture(scope="module")
def reference():
    """Unfaulted, statically overprovisioned run of the same trace: the
    bit-equality baseline.  Completes everything (zero shed/lost), so
    every chaos completion has a reference to match against."""
    clock = W.SimClock()
    sink = W.SimSink()
    engines = [
        W.SimEngine(clock=clock, n_slots=16, n_blocks=2048,
                    decode_tps=60.0, sink=sink)
        for _ in range(4)
    ]
    router = fleet.FleetRouter(engines, clock=clock)
    by_prompt = {}

    def collect(c):
        if c.status == "ok":
            prompt = tuple(c.tokens[: len(c.tokens) - len(c.generated)])
            by_prompt[prompt] = tuple(c.generated)

    rep = W.replay(W.generate(SPEC), router, clock=clock, sink=sink,
                   dt=0.1, queue_limit=100_000, on_completion=collect)
    assert rep.lost == 0 and rep.shed == 0
    assert rep.completed == rep.offered
    return by_prompt


def _check_bit_equal(seen, reference):
    """Every ok completion matches the reference stream for its prompt,
    and no stream completed twice."""
    assert seen, "chaos run completed nothing"
    dupes = [p for p, (n, _) in seen.items() if n > 1]
    assert not dupes, f"duplicated streams for prompts {dupes[:3]}"
    for prompt, (count, generated) in seen.items():
        assert prompt in reference, f"untraced completion {prompt}"
        assert generated == reference[prompt], (
            f"stream for {prompt} diverged from the unfaulted reference"
        )


class _OkCollector:
    def __init__(self):
        self.counts = Counter()
        self.streams = {}

    def __call__(self, c):
        if c.status != "ok":
            return
        prompt = tuple(c.tokens[: len(c.tokens) - len(c.generated)])
        self.counts[prompt] += 1
        self.streams[prompt] = tuple(c.generated)

    def seen(self):
        return {
            p: (self.counts[p], self.streams[p]) for p in self.counts
        }


class TestFlashCrowdLoop:
    def test_scales_up_through_crowd_and_back_down(self, monkeypatch):
        journal = _spy_autoscale_journal(monkeypatch)
        collect = _OkCollector()
        rep, router, asc = _run(collect=collect)
        assert rep.lost == 0
        assert rep.completed + rep.shed == rep.offered
        assert rep.offered > 1000
        # The crowd forced real growth...
        assert rep.max_replicas >= 3
        up = sum(1 for e in journal if e["event"] == "scale_up.begin")
        down = sum(1 for e in journal if e["event"] == "scale_down.begin")
        assert up >= 2 and down >= 1  # ...and the loop closed both ways
        # No stream completed twice, even across migrations.
        assert all(n == 1 for n in collect.counts.values())

    def test_block_accounting_balances_at_idle(self):
        rep, router, asc = _run()
        assert rep.lost == 0
        for r in router.replicas:
            assert not r.engine._active, f"{r.name} still holds streams"
            assert r.engine._free_blocks == N_BLOCKS, (
                f"{r.name} leaked blocks: {r.engine._free_blocks}"
            )

    def test_one_journal_correlation_per_scaling_action(self, monkeypatch):
        journal = _spy_autoscale_journal(monkeypatch)
        rep, router, asc = _run()
        begins = Counter(
            e["correlation"] for e in journal
            if e["event"] in ("scale_up.begin", "scale_down.begin")
        )
        assert sum(begins.values()) == asc.actions
        assert all(n == 1 for n in begins.values())
        # Every action's correlation also carries its terminal event.
        for corr in begins:
            events = [e["event"] for e in journal if e["correlation"] == corr]
            assert (
                "scale_up.resumed" in events
                or "scale_down.resumed" in events
            ), (corr, events)


class TestFaultedLoop:
    def test_replica_crash_mid_scale_down_stays_bit_equal(self, reference):
        # Tick 700 = t=70s: the crowd ended at 50s and the down-streak /
        # cooldown machinery is walking the fleet back down — the kill
        # lands between scale-down actions, while spawns are also slowed.
        inj = FaultInjector.from_env(
            "replica_crash_rate=1.0,steps=700,limit=1,"
            "spawn_latency_ms=500,seed=7"
        )
        collect = _OkCollector()
        rep, router, asc = _run(injector=inj, collect=collect)
        assert inj.stats().get("replica_crash") == 1, "the kill never fired"
        assert rep.lost == 0
        assert rep.completed + rep.shed == rep.offered
        _check_bit_equal(collect.seen(), reference)
        for r in router.replicas:
            assert not r.engine._active
            assert r.engine._free_blocks == N_BLOCKS

    def test_spawn_fail_storm_starves_growth_but_loses_nothing(self, reference):
        # Every spawn fails: the fleet is pinned at one replica through
        # the whole crowd.  Requests shed (bounded queue) but NOTHING is
        # lost or duplicated, and what completes is still bit-equal.
        inj = FaultInjector.from_env("spawn_fail=1.0,seed=11")
        collect = _OkCollector()
        rep, router, asc = _run(injector=inj, collect=collect)
        assert asc.spawn_failures >= 1
        assert len(router.replicas) == 1
        assert rep.lost == 0
        assert rep.completed + rep.shed == rep.offered
        _check_bit_equal(collect.seen(), reference)

    def test_spawn_hooks_parse_and_scope_from_env(self):
        inj = FaultInjector.from_env(
            "spawn_fail=1.0,spawn_latency_ms=250,steps=0+1,limit=2,seed=3"
        )
        (p,) = inj._profiles
        assert p.spawn_fail_rate == 1.0
        assert p.spawn_latency_s == pytest.approx(0.25)
        with pytest.raises(SpawnFault):
            inj.maybe_fail_spawn(0)
        inj.maybe_fail_spawn(5)  # out of steps scope: silent
        assert inj.take_spawn_latency(1) == pytest.approx(0.25)
        assert inj.take_spawn_latency(9) == 0.0  # out of scope
        # The shared budget is spent: nothing further fires.
        inj.maybe_fail_spawn(0)
        assert inj.stats().get("spawn_fail") == 1
        assert inj.stats().get("spawn_latency") == 1
