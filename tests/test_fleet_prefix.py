"""Fleet prefix-cache tier (models/fleet_prefix.py): index semantics,
cross-replica pulls, geometry fallbacks, and the bit-equality contract.

* FleetPrefixIndex: TTL via injected clock, LRU capacity eviction that
  skips pinned entries, pinned-while-pulling refcounts (invalidation of
  a pinned entry defers to unpin), owner invalidation, ledger balance.
* Bit-equality: a prefix exported from a warm owner, round-tripped
  through the KVSlice wire encoding and injected into a cold peer,
  decodes BYTE-IDENTICAL to cold prefill — at bfloat16, int8 and int4.
* Geometry fallbacks: dtype or quantized-block-size mismatches inject
  nothing (cold prefill), float payloads re-block across block sizes.
* The full tier flow on a FleetRouter: depth-aware routing sends a
  request home (local hit); a full home forces a neighbor admission
  that pulls the prefix over the LocalPrefixSource wire round-trip
  (remote hit), with pins back to zero and metrics observable through
  the parse_prom_text round-trip.

The two-process owner-death chaos test lives in
tests/test_transport_chaos.py (`make chaos-transport`).
"""

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, fleet, fleet_prefix, paged
from k8s_dra_driver_tpu.models.serve import KVSlice, ServeEngine
from k8s_dra_driver_tpu.models.workload import SimClock
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, parse_prom_text

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)
BS = 4


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 64)
    kw.setdefault("block_size", BS)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("prefix_cache_blocks", 24)
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


def _run(eng, prompt, max_tokens=6, seed=3):
    (c,) = eng.pump([{"prompt": list(prompt), "max_tokens": max_tokens,
                      "seed": seed}])
    assert c.status == "ok"
    return c.generated


# -- the index ---------------------------------------------------------------


class TestPrefixIndex:
    def _index(self, **kw):
        clock = SimClock()
        kw.setdefault("ttl_s", 10.0)
        kw.setdefault("clock", clock)
        return fleet_prefix.FleetPrefixIndex(**kw), clock

    def test_publish_deepest_survey(self):
        idx, _ = self._index()
        toks = list(range(12))
        idx.publish(tuple(toks[:4]), "A", n_tokens=4, block_size=4, kv_dtype="f")
        idx.publish(tuple(toks[:8]), "B", n_tokens=8, block_size=4, kv_dtype="f")
        chain = idx.chain_for_tokens(toks)
        assert [d for d, _ in chain] == [4, 8]  # >= 1 token left to prefill
        ent = idx.deepest(chain)
        assert ent.owner == "B" and ent.n_tokens == 8
        survey = idx.survey(chain)
        assert survey == {"A": (4, 1), "B": (8, 2)}
        # compatible= filters: rejecting B falls back to A's rung
        ent = idx.deepest(chain, compatible=lambda e: e.owner != "B")
        assert ent.owner == "A" and ent.n_tokens == 4

    def test_ttl_expiry_on_read_and_sweep(self):
        idx, clock = self._index(ttl_s=5.0)
        idx.publish((1, 2), "A", n_tokens=2, block_size=2, kv_dtype="f")
        idx.publish((3, 4), "A", n_tokens=2, block_size=2, kv_dtype="f")
        clock.advance(6.0)
        chain = [(2, (1, 2))]
        assert idx.deepest(chain) is None  # dropped on read
        assert len(idx) == 1
        assert idx.sweep() == 1
        assert len(idx) == 0
        m = parse_prom_text(REGISTRY.render())
        assert m["tpu_fleet_prefix_evictions_total"][(("reason", "ttl"),)] == 2.0

    def test_refresh_extends_ttl_and_moves_owner(self):
        idx, clock = self._index(ttl_s=5.0)
        idx.publish((1,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        clock.advance(4.0)
        idx.publish((1,), "B", n_tokens=1, block_size=1, kv_dtype="f")
        clock.advance(4.0)  # 8s after first publish, 4s after refresh
        ent = idx.deepest([(1, (1,))])
        assert ent is not None and ent.owner == "B"

    def test_capacity_eviction_lru_skips_pinned(self):
        idx, _ = self._index(max_entries=2)
        e1 = idx.publish((1,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        assert idx.pin(e1.key)
        idx.publish((2,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        idx.publish((3,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        # oldest entry is pinned: the NEXT oldest was evicted instead
        assert len(idx) == 2
        assert idx.deepest([(1, (1,))]) is not None
        assert idx.deepest([(1, (2,))]) is None
        idx.unpin(e1.key)

    def test_invalidate_owner_defers_pinned_to_unpin(self):
        idx, _ = self._index()
        e1 = idx.publish((1,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        idx.publish((2,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        idx.publish((3,), "B", n_tokens=1, block_size=1, kv_dtype="f")
        assert idx.pin(e1.key)
        assert idx.invalidate_owner("A") == 1  # unpinned entry drops now
        assert len(idx) == 2
        # dead entries are invisible to lookups and unpinnable-only
        assert idx.deepest([(1, (1,))]) is None
        assert not idx.pin(e1.key)
        idx.unpin(e1.key)
        assert len(idx) == 1  # deferred drop landed
        assert idx.deepest([(1, (3,))]).owner == "B"
        m = parse_prom_text(REGISTRY.render())
        assert (
            m["tpu_fleet_prefix_evictions_total"][(("reason", "invalidated"),)]
            == 2.0
        )

    def test_withdraw_respects_owner(self):
        idx, _ = self._index()
        idx.publish((1,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        assert not idx.withdraw((1,), owner="B")  # stale evict from a loser
        assert idx.withdraw((1,), owner="A")
        assert len(idx) == 0

    def test_ledger_balance(self):
        idx, _ = self._index()
        e1 = idx.publish((1,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        idx.publish((2,), "A", n_tokens=1, block_size=1, kv_dtype="f")
        idx.publish((3,), "B", n_tokens=1, block_size=1, kv_dtype="f")
        idx.pin(e1.key)
        led = idx.ledger()
        assert led.blocks == {"A": 2, "B": 1}
        assert led.entries == 3 and led.pinned == 1
        idx.unpin(e1.key)
        assert idx.ledger().pinned == 0

    def test_chain_mixed_granularities(self):
        idx, _ = self._index()
        idx.publish((0,) * 4, "A", n_tokens=4, block_size=4, kv_dtype="f")
        idx.publish((0,) * 16, "B", n_tokens=16, block_size=16, kv_dtype="d")
        chain = idx.chain_for_tokens(list(range(17)))
        assert [d for d, _ in chain] == [4, 8, 12, 16]

    def test_hit_metric_roundtrip(self):
        idx, _ = self._index()
        idx.note_hit("local")
        idx.note_hit("local")
        idx.note_hit("remote")
        m = parse_prom_text(REGISTRY.render())
        hits = m["tpu_fleet_prefix_hits_total"]
        assert hits[(("source", "local"),)] == 2.0
        assert hits[(("source", "remote"),)] == 1.0


# -- bit-equality across the wire --------------------------------------------


class TestRemotePullBitEquality:
    @pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8", "int4"])
    def test_export_wire_inject_bit_equal(self, params, kv_dtype):
        owner = _paged(params, kv_dtype=kv_dtype)
        peer = _paged(params, kv_dtype=kv_dtype)
        prompt = list(range(1, 15))  # 14 tokens -> 3 storable blocks of 4
        ref = _run(owner, prompt)  # cold prefill; warms owner's store
        kv = owner.export_prefix_kv(prompt)
        assert kv is not None and kv.valid_len == 12
        rid, kv2 = KVSlice.from_wire(kv.to_wire(7))  # the exact wire path
        assert rid == 7
        injected = peer.inject_prefix_kv(prompt, kv2)
        assert injected == 12
        assert peer.local_prefix_depth(prompt) == 12
        before = peer.prefix_hits
        assert _run(peer, prompt) == ref  # decode from pulled KV == cold
        assert peer.prefix_hits > before  # it really took the hit path

    def test_inject_accounts_blocks_and_survives_eviction(self, params):
        owner = _paged(params)
        peer = _paged(params)
        prompt = list(range(1, 15))
        ref = _run(owner, prompt)
        free0 = peer.free_blocks
        kv = owner.export_prefix_kv(prompt)
        assert peer.inject_prefix_kv(prompt, kv) == 12
        assert peer.free_blocks == free0 - 3  # 3 blocks of 4 now cached
        # an idempotent re-inject allocates nothing new
        assert peer.inject_prefix_kv(prompt, kv) == 0
        assert peer.free_blocks == free0 - 3
        assert _run(peer, prompt) == ref


class TestGeometryFallbacks:
    def test_quantized_dtype_mismatch_injects_nothing(self, params):
        owner = _paged(params, kv_dtype="int8")
        peer = _paged(params, kv_dtype="int4")
        prompt = list(range(1, 15))
        _run(owner, prompt)
        kv = owner.export_prefix_kv(prompt)
        assert peer.inject_prefix_kv(prompt, kv) == 0
        assert peer.local_prefix_depth(prompt) == 0

    def test_quantized_block_size_mismatch_injects_nothing(self, params):
        owner = _paged(params, kv_dtype="int8", block_size=4, n_blocks=64)
        peer = _paged(params, kv_dtype="int8", block_size=8, n_blocks=32)
        prompt = list(range(1, 15))
        _run(owner, prompt)
        kv = owner.export_prefix_kv(prompt)
        assert kv.quantized and kv.block_size == 4
        assert peer.inject_prefix_kv(prompt, kv) == 0

    def test_float_payload_reblocks_across_block_sizes(self, params):
        owner = _paged(params, block_size=4, n_blocks=64)
        peer = _paged(params, block_size=8, n_blocks=32, prefix_cache_blocks=8)
        prompt = list(range(1, 15))
        ref = _run(owner, prompt)
        kv = owner.export_prefix_kv(prompt)  # 12 tokens at bs=4
        # the receiver installs whole bs=8 blocks: 12 -> 8 tokens
        assert peer.inject_prefix_kv(prompt, kv) == 8
        assert peer.local_prefix_depth(prompt) == 8
        assert _run(peer, prompt) == ref

    def test_dense_export_feeds_paged_receiver(self, params):
        owner = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=32,
                            prefix_bucket=16, prefix_cache_entries=4)
        peer = _paged(params, prompt_bucket=32)
        prompt = list(range(1, 21))  # > prefix_bucket so dense stores
        ref = _run(owner, prompt)
        assert owner.local_prefix_depth(prompt) == 16
        kv = owner.export_prefix_kv(prompt)
        assert kv is not None and kv.valid_len == 16 and not kv.quantized
        assert peer.inject_prefix_kv(prompt, kv) == 16  # 4 whole bs=4 blocks
        assert _run(peer, prompt) == ref

    def test_dense_inject_requires_full_bucket(self, params):
        owner = _paged(params)
        peer = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=32,
                           prefix_bucket=16, prefix_cache_entries=4)
        prompt = list(range(1, 15))
        _run(owner, prompt)
        kv = owner.export_prefix_kv(prompt)  # 12 tokens < bucket 16
        assert peer.inject_prefix_kv(prompt, kv) == 0

    def test_dense_to_dense_roundtrip(self, params):
        owner = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=32,
                            prefix_bucket=16, prefix_cache_entries=4)
        peer = ServeEngine(params=params, cfg=CFG, n_slots=2, prompt_bucket=32,
                           prefix_bucket=16, prefix_cache_entries=4)
        prompt = list(range(1, 21))
        ref = _run(owner, prompt)
        rid, kv = KVSlice.from_wire(owner.export_prefix_kv(prompt).to_wire(3))
        assert peer.inject_prefix_kv(prompt, kv) == 16
        assert _run(peer, prompt) == ref


# -- the full tier on a router -----------------------------------------------


class TestFleetPrefixTier:
    def _fleet(self, params, **eng_kw):
        clock = SimClock()
        A = _paged(params, **eng_kw)
        B = _paged(params, **eng_kw)
        router = fleet.FleetRouter([("A", A), ("B", B)], clock=clock)
        tier = fleet_prefix.FleetPrefixTier(
            fleet_prefix.FleetPrefixIndex(clock=clock), clock=clock)
        router.attach_prefix_tier(tier)
        return router, tier, A, B, clock

    def _drain_engines(self, *engines, steps=400):
        out = []
        for _ in range(steps):
            for eng in engines:
                eng.step()
                out.extend(eng.completions())
            if all(e.free_slots() == e.n_slots for e in engines):
                return out
        raise AssertionError("engines did not drain")

    def test_publish_on_store_and_depth_routed_local_hit(self, params):
        router, tier, A, B, _ = self._fleet(params)
        prompt = list(range(1, 15))
        ref = _run(A, prompt)  # warm A through its own pump: hooks publish
        assert len(tier.index) == 3  # one rung per stored block
        assert tier.index.ledger().blocks == {"A": 3}
        # depth-aware scoring routes the shared prefix home to A
        router.submit(prompt, 6, seed=3)
        (c,) = self._drain_engines(A, B)
        assert c.generated == ref
        assert tier.counts["local"] == 1 and tier.counts["remote"] == 0
        m = parse_prom_text(REGISTRY.render())
        assert m["tpu_fleet_prefix_hits_total"][(("source", "local"),)] == 1.0

    def test_remote_pull_when_home_is_full(self, params):
        router, tier, A, B, _ = self._fleet(params)
        prompt = list(range(1, 15))
        ref = _run(A, prompt)
        for i in range(A.n_slots):  # fill A so the router must pick B
            A.submit([40 + i, 41 + i, 42 + i], max_tokens=4, seed=3)
        router.submit(prompt, 6, seed=3)
        done = self._drain_engines(A, B)
        mine = [c for c in done if c.generated == ref]
        assert len(mine) == 1
        assert tier.counts["remote"] == 1
        assert B.local_prefix_depth(prompt) == 12  # pulled blocks landed
        assert tier.index.ledger().pinned == 0  # pin released after pull
        m = parse_prom_text(REGISTRY.render())
        assert m["tpu_fleet_prefix_hits_total"][(("source", "remote"),)] == 1.0
        assert m["tpu_fleet_prefix_pull_seconds_count"][()] >= 1.0

    def test_drain_invalidates_owner_entries(self, params):
        router, tier, A, B, _ = self._fleet(params)
        prompt = list(range(1, 15))
        _run(A, prompt)
        assert tier.index.ledger().blocks == {"A": 3}
        router.drain("A")
        router.tick()
        assert tier.index.ledger().blocks.get("A") is None
        # subsequent admissions of the same prefix are cold, not wedged
        router.submit(prompt, 6, seed=3)
        (c,) = self._drain_engines(A, B)
        assert c.status == "ok"
        assert tier.counts["remote"] == 0

    def test_cross_dtype_fleet_falls_back_cold(self, params):
        clock = SimClock()
        A = _paged(params, kv_dtype="int8")
        B = _paged(params, kv_dtype="int4")
        router = fleet.FleetRouter([("A", A), ("B", B)], clock=clock)
        tier = fleet_prefix.FleetPrefixTier(
            fleet_prefix.FleetPrefixIndex(clock=clock), clock=clock)
        router.attach_prefix_tier(tier)
        prompt = list(range(1, 15))
        ref_b = _run(B, prompt)  # B's own cold decode at int4
        _run(A, prompt)
        for i in range(A.n_slots):
            A.submit([40 + i, 41 + i, 42 + i], max_tokens=4, seed=3)
        router.submit(prompt, 6, seed=3)
        done = self._drain_engines(A, B)
        assert any(c.generated == ref_b for c in done)
        assert tier.counts["remote"] == 0  # geometry-gated: no cross-dtype pull


# -- gossip ingest, epoch fences, and the pull-admission gate -----------------


def _ev(key="k1", n_tokens=8, **kw):
    """One wire publish event as PrefixGossip ships it (digest-keyed)."""
    ev = {"key": key, "n_tokens": n_tokens, "block_size": BS,
          "kv_dtype": "bfloat16", "n_layers": 1, "kv_heads": 2,
          "head_dim": 16, "adapter": 0, "blocks": 2}
    ev.update(kw)
    return ev


class TestEpochFencing:
    """Epoch-fenced ownership: entries stamped with a superseded owner
    epoch are typed misses, never pulls at the wrong process."""

    def _index(self):
        clock = SimClock()
        return fleet_prefix.FleetPrefixIndex(clock=clock), clock

    def test_stale_epoch_publish_is_fenced(self):
        idx, _ = self._index()
        assert idx.ingest_publish("W", 2, _ev())
        fenced0 = idx.fenced_total
        assert not idx.ingest_publish("W", 1, _ev(key="k2"))
        assert idx.fenced_total == fenced0 + 1
        assert len(idx) == 1  # the stale publish never landed
        m = parse_prom_text(REGISTRY.render())
        assert m["tpu_fleet_prefix_epoch_fences_total"][()] >= 1.0
        assert m["tpu_fleet_prefix_pub_total"][(("outcome", "fenced"),)] >= 1.0

    def test_set_owner_epoch_fences_older_entries(self):
        idx, _ = self._index()
        idx.ingest_publish("W", 1, _ev(key="a"))
        idx.ingest_publish("W", 1, _ev(key="b"))
        idx.ingest_publish("X", 1, _ev(key="c"))
        assert idx.set_owner_epoch("W", 2) == 2
        assert idx.owner_epoch["W"] == 2
        assert set(idx._entries) == {"c"}  # X's entry survives the fence

    def test_newer_epoch_publish_fences_implicitly(self):
        idx, _ = self._index()
        idx.ingest_publish("W", 1, _ev(key="a"))
        assert idx.ingest_publish("W", 2, _ev(key="b"))
        assert idx.owner_epoch["W"] == 2
        assert set(idx._entries) == {"b"}  # the bump fenced epoch-1 "a"

    def test_epoch_ok_drops_superseded_entry_at_pull_time(self):
        idx, _ = self._index()
        idx.ingest_publish("W", 1, _ev(key="a"))
        ent = idx._entries["a"]
        # a fence raced past this entry (e.g. it sat pinned): the pull-time
        # check is the last line of defense
        idx.owner_epoch["W"] = 2
        assert not idx.epoch_ok(ent)
        assert len(idx) == 0

    def test_ingest_withdraw_owner_and_epoch_guarded(self):
        idx, _ = self._index()
        idx.ingest_publish("W", 2, _ev(key="a"))
        assert not idx.ingest_withdraw("X", 2, {"key": "a"})  # wrong owner
        assert not idx.ingest_withdraw("W", 1, {"key": "a"})  # stale epoch
        assert idx.ingest_withdraw("W", 2, {"key": "a"})
        assert len(idx) == 0
        m = parse_prom_text(REGISTRY.render())
        assert (
            m["tpu_fleet_prefix_pub_total"][(("outcome", "withdrawn"),)] >= 1.0
        )

    def test_anti_entropy_digest_drops_unnamed_entries(self):
        idx, _ = self._index()
        idx.ingest_publish("W", 1, _ev(key="a"))
        idx.ingest_publish("W", 1, _ev(key="b"))
        idx.ingest_publish("X", 1, _ev(key="c"))
        res = idx.ingest_digest("W", 1, [_ev(key="b"), _ev(key="d")])
        assert res == {"ingested": 2, "dropped": 1}  # "a" diverged: dropped
        assert set(idx._entries) == {"b", "c", "d"}
        m = parse_prom_text(REGISTRY.render())
        assert (
            m["tpu_fleet_prefix_evictions_total"][(("reason", "anti_entropy"),)]
            >= 1.0
        )


class TestGossipWireIngest:
    """Tier-side PREFIXPUB/PREFIXWDL ingest: decoded frames apply whole,
    corrupt frames drop whole (typed, counted), never partially."""

    def _tier(self):
        clock = SimClock()
        return fleet_prefix.FleetPrefixTier(
            fleet_prefix.FleetPrefixIndex(clock=clock), clock=clock)

    def test_pub_and_wdl_frames_apply(self):
        tier = self._tier()
        body = fleet_prefix.encode_prefix_gossip(
            {"events": [_ev(key="a"), _ev(key="b", n_tokens=12)]},
            epoch=1, seq=1)
        assert tier._ingest_pub("W", body) == 2
        assert tier.index.owner_epoch["W"] == 1
        wdl = fleet_prefix.encode_prefix_gossip(
            {"events": [{"key": "a"}]}, epoch=1, seq=2)
        assert tier._ingest_wdl("W", wdl) == 1
        assert set(tier.index._entries) == {"b"}
        m = parse_prom_text(REGISTRY.render())
        assert m["tpu_fleet_prefix_pub_total"][(("outcome", "ingested"),)] >= 2.0

    def test_full_digest_frame_runs_anti_entropy(self):
        tier = self._tier()
        tier._ingest_pub("W", fleet_prefix.encode_prefix_gossip(
            {"events": [_ev(key="a"), _ev(key="b")]}, epoch=1, seq=1))
        body = fleet_prefix.encode_prefix_gossip(
            {"events": [_ev(key="b")], "full": True}, epoch=1, seq=2)
        assert tier._ingest_pub("W", body) == 1
        assert set(tier.index._entries) == {"b"}

    def test_corrupt_frame_dropped_whole_and_counted(self):
        tier = self._tier()
        good = fleet_prefix.encode_prefix_gossip(
            {"events": [_ev()]}, epoch=1, seq=1)
        corrupt = good[:-1] + bytes([good[-1] ^ 0x01])
        assert tier._ingest_pub("W", corrupt) == 0
        assert tier.gossip_decode_drops == 1
        assert len(tier.index) == 0  # nothing partially applied
        m = parse_prom_text(REGISTRY.render())
        assert (
            m["tpu_fleet_prefix_pub_total"][(("outcome", "decode_drop"),)] >= 1.0
        )


class _Gate:
    """reserve_pull/release_pull stub with a scripted verdict."""

    def __init__(self, verdict):
        self.verdict = verdict
        self.reserved = {}
        self.released = []

    def reserve_pull(self, nonce, blocks):
        if self.verdict is True:
            self.reserved[nonce] = blocks
        return self.verdict

    def release_pull(self, nonce):
        self.released.append(nonce)
        self.reserved.pop(nonce, None)


class TestPullAdmissionGate:
    """Ledger-gated pull admission: a remote pull is KV demand like any
    stream — it reserves receiver blocks for the transfer window or falls
    back to a reason-coded cold prefill, and the reservation contends
    with stream admission over ONE headroom number."""

    def _warm_pair(self, params):
        clock = SimClock()
        A = _paged(params)
        B = _paged(params)
        tier = fleet_prefix.FleetPrefixTier(
            fleet_prefix.FleetPrefixIndex(clock=clock), clock=clock)
        tier.bind_engine("A", A)
        tier.bind_engine("B", B)
        prompt = list(range(1, 15))
        _run(A, prompt)  # warm A: hooks publish 3 rungs
        return tier, A, B, prompt

    def test_refused_pull_falls_back_cold_reason_coded(self, params):
        tier, _A, B, prompt = self._warm_pair(params)
        gate = _Gate(False)
        tier.pull_gate = gate
        refused0 = fleet_prefix._M_PULL_ADMISSION.value(outcome="refused")
        assert tier.prepare("B", B, prompt, max_tokens=6) == "cold"
        assert tier.fallbacks["pull_admission"] == 1
        assert B.local_prefix_depth(prompt) == 0  # no transfer happened
        assert tier.index.ledger().pinned == 0
        assert gate.released == []  # nothing reserved, nothing to release
        m = parse_prom_text(REGISTRY.render())
        assert (
            m["tpu_fleet_prefix_pull_admission_total"][
                (("outcome", "refused"),)
            ] == refused0 + 1.0
        )

    def test_admitted_pull_reserves_for_the_window_then_releases(self, params):
        tier, _A, B, prompt = self._warm_pair(params)
        gate = _Gate(True)
        tier.pull_gate = gate
        assert tier.prepare("B", B, prompt, max_tokens=6) == "remote"
        assert B.local_prefix_depth(prompt) == 12
        assert gate.reserved == {}  # released when the window closed
        assert len(gate.released) == 1
        assert tier.index.ledger().pinned == 0
        m = parse_prom_text(REGISTRY.render())
        assert (
            m["tpu_fleet_prefix_pull_admission_total"][
                (("outcome", "admitted"),)
            ] >= 1.0
        )

    def test_unaccountable_headroom_bypasses_like_stream_admission(
            self, params):
        tier, _A, B, prompt = self._warm_pair(params)
        gate = _Gate(None)
        tier.pull_gate = gate
        assert tier.prepare("B", B, prompt, max_tokens=6) == "remote"
        assert gate.released == []  # bypass holds no reservation
        m = parse_prom_text(REGISTRY.render())
        assert (
            m["tpu_fleet_prefix_pull_admission_total"][
                (("outcome", "bypass"),)
            ] >= 1.0
        )

    def test_pull_reservation_flips_stream_admission(self, params):
        """THE acceptance assertion: at the same decode capacity, an
        admitted pull reservation shrinks the one headroom number stream
        admission budgets against — a stream that fits the bare pool is
        REFUSED while the pull window is open and admitted again after
        release — and refusals never fire the deadlock detector."""
        from k8s_dra_driver_tpu.models.disagg import DisaggRouter

        dec = _paged(params)
        router = DisaggRouter(prefill=[_paged(params)], decode=[dec],
                              admission_control=True)
        cap = dec.reservable_blocks
        assert router._decode_headroom_blocks() == cap
        entry = {"request_id": 7001, "prompt_len": 4,
                 "max_tokens": cap * BS - 4, "tokens": [1, 2, 3, 4]}
        assert router._admit_handoff({"entry": dict(entry)}) is True
        router.release_pull(-7001)  # rewind the probe reservation
        # an admitted pull shrinks the SAME headroom stream admission uses
        assert router.reserve_pull(55, 8) is True
        assert router._decode_headroom_blocks() == cap - 8
        fired0 = router.deadlock_fired
        assert router._admit_handoff({"entry": dict(entry)}) is False
        # over-demand pulls are refused without touching the ledger...
        for nonce in range(100, 120):
            assert router.reserve_pull(nonce, cap) is False
        assert router._decode_headroom_blocks() == cap - 8
        # ...and a refused pull is a cold-prefill fallback, not a parked
        # stream: the ARMED->COUNTING->FIRED detector never trips
        for _ in range(router.deadlock_ticks + 5):
            router._deadlock_tick()
        assert router.deadlock_fired == fired0
        router.release_pull(55)
        assert router._decode_headroom_blocks() == cap  # balanced ledger
        assert router._admit_handoff({"entry": dict(entry)}) is True

    def test_bypass_when_capacity_unaccountable(self, params):
        from k8s_dra_driver_tpu.models.disagg import DisaggRouter
        from k8s_dra_driver_tpu.models.serve import ServeEngine as _SE

        dense = _SE(params=params, cfg=CFG, n_slots=2, prompt_bucket=32)
        router = DisaggRouter(prefill=[_paged(params)], decode=[dense],
                              admission_control=True)
        assert router.reserve_pull(1, 4) is None  # dense pool: stand aside
        assert router._ledger == {}
