"""Test bootstrap.

Sharding tests run on a virtual 8-device CPU mesh: the XLA flag must be set
before the first jax import.  On hosts where a TPU plugin still wins the
default-backend race, tests explicitly ask for ``jax.devices("cpu")``.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TPUINFO_FAKE_TOPOLOGY", "v5e-16")

import pytest  # noqa: E402


@pytest.fixture
def api_server():
    from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer

    return InMemoryAPIServer()


def cpu_devices(n: int):
    """Return n CPU devices regardless of which backend won the default race."""
    import jax

    devs = jax.devices("cpu")
    assert len(devs) >= n, f"need {n} cpu devices, have {len(devs)}"
    return devs[:n]
