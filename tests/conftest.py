"""Test bootstrap.

Sharding tests run on a virtual 8-device CPU mesh: the XLA flag must be set
before the first jax import.  ``JAX_PLATFORMS=cpu`` is FORCED (not
defaulted): the harness env pre-sets ``JAX_PLATFORMS=axon``, and when that
accelerator tunnel is down jax backend init blocks forever — a setdefault
here let the whole suite hang instead of running CPU-only (observed round
2).  No test needs a real device; the bench owns the live-chip path."""

import os

from k8s_dra_driver_tpu.e2e.dryrun import force_cpu

# force_cpu (not just env edits): the harness sitecustomize imports jax at
# interpreter start, freezing JAX_PLATFORMS=axon into jax's config — the
# live config must be rewritten too or backends() still dials the tunnel.
force_cpu(n_devices=8)
os.environ.setdefault("TPUINFO_FAKE_TOPOLOGY", "v5e-16")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_observability():
    """Fresh global observability state per test: metric asserts can be
    absolute instead of before/after deltas against whatever earlier tests
    left in the process-wide REGISTRY, and journal asserts can't match a
    previous test's events.  Values reset, objects kept — modules bind
    metrics at import time (see Registry.reset)."""
    import sys

    from k8s_dra_driver_tpu.utils.journal import JOURNAL
    from k8s_dra_driver_tpu.utils.metrics import REGISTRY
    from k8s_dra_driver_tpu.utils.tracing import TRACES

    REGISTRY.reset()
    JOURNAL.clear()
    TRACES.clear()
    # The fleet merger is models-side; clear it only when some test has
    # already pulled it in — importing models/ from here would tax every
    # utils-only test with the package import.
    obs = sys.modules.get("k8s_dra_driver_tpu.models.obs_plane")
    if obs is not None:
        obs.FLEET.clear()
    yield


@pytest.fixture
def api_server():
    from k8s_dra_driver_tpu.kube.fakeserver import InMemoryAPIServer

    return InMemoryAPIServer()


def cpu_devices(n: int):
    """Return n CPU devices regardless of which backend won the default race."""
    import jax

    devs = jax.devices("cpu")
    assert len(devs) >= n, f"need {n} cpu devices, have {len(devs)}"
    return devs[:n]
