"""PagedServeEngine sharded over a mesh (DP serving, the production shape):
slot axis + pool blocks partition over the mesh axis, block tables hold
shard-local ids, and the hot loop is collective-free (jax.shard_map).

Contracts: sharded token streams are BIT-IDENTICAL to the unsharded
engine's for every composition the engine supports — plain greedy,
sampled, speculative, per-request LoRA, block-level prefix cache, chunked
admission, and recompute-preemption.  Capacity is per-shard (a request's
blocks must fit ONE shard's pool); accounting stays exact through churn.

Runs on the 8-device virtual CPU mesh (conftest's force_cpu)."""

import jax
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, lora, paged

CFG = burnin.ModelConfig(
    vocab_size=89, d_model=64, n_heads=4, n_layers=2, d_ff=128, max_seq=128
)
BS = 16
LORA = lora.LoraConfig(rank=4, alpha=8.0)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def bank(params):
    from tests.test_lora_serve import _trained_adapter

    return lora.stack_adapters(CFG, LORA, [_trained_adapter(1), _trained_adapter(2)])


def _mesh(n):
    from jax.sharding import Mesh

    return Mesh(np.array(jax.devices("cpu")[:n]), ("data",))


def _prompts(n, rng=7):
    r = np.random.RandomState(rng)
    return [
        r.randint(0, CFG.vocab_size, size=r.randint(3, 12)).tolist()
        for _ in range(n)
    ]


def _streams(engine, reqs, max_steps=10_000):
    """FIFO queue in front of the engine (same harness as the unsharded
    parity tests): ids assign in submit order, so dicts compare by id."""
    pending = list(reqs)
    out = {}
    for _ in range(max_steps):
        while pending:
            prompt, max_tokens, kw = pending[0]
            try:
                engine.submit(prompt, max_tokens, **kw)
                pending.pop(0)
            except RuntimeError:
                break
        stepped = engine.step()
        for c in engine.completions():
            out[c.request_id] = c.generated
        if (
            not pending
            and stepped == 0
            and engine.free_slots() == engine.n_slots
            and not getattr(engine, "_preempted", None)  # dense has none
        ):
            return out
    raise RuntimeError("queue did not drain")


def _drained_clean(eng):
    """After a drain the pools are fully free again, minus blocks the
    prefix stores legitimately still reference."""
    total_stored = sum(len(s) for s in eng._prefix_stores)
    assert eng.free_blocks == (eng.n_blocks - eng._axis_size) - total_stored


class TestShardedParity:
    def test_greedy_streams_identical(self, params):
        reqs = [(p, 12, {}) for p in _prompts(6)]
        ref = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=4, n_blocks=64, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        shd = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=4, n_blocks=64, block_size=BS,
            prompt_bucket=16, attn_impl="xla", mesh=_mesh(4),
        )
        want = _streams(ref, reqs)
        assert _streams(shd, reqs) == want
        _drained_clean(shd)

    def test_sampled_streams_identical(self, params):
        reqs = [
            (p, 8, dict(temperature=0.8, seed=100 + i))
            for i, p in enumerate(_prompts(4, rng=11))
        ]
        ref = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        shd = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=2, n_blocks=40, block_size=BS,
            prompt_bucket=16, attn_impl="xla", mesh=_mesh(2),
        )
        assert _streams(shd, reqs) == _streams(ref, reqs)

    def test_full_composition_streams_identical(self, params, bank):
        """The production serving shape: sharded + speculative + per-request
        LoRA + block prefix cache + chunked admission, all at once."""
        sys_prefix = list(range(1, 1 + 2 * BS))  # two shareable full blocks
        reqs = [
            (sys_prefix + p, 10, dict(adapter=i % 3))
            for i, p in enumerate(_prompts(6, rng=3))
        ]
        kw = dict(
            params=params, cfg=CFG, n_slots=4, n_blocks=96, block_size=BS,
            prompt_bucket=64, attn_impl="xla", spec_gamma=2,
            prefix_cache_blocks=4, prefill_chunk_blocks=1,
            adapter_bank=bank,
        )
        ref = paged.PagedServeEngine(**kw)
        shd = paged.PagedServeEngine(**kw, mesh=_mesh(2))
        want = _streams(ref, reqs)
        assert _streams(shd, reqs) == want
        # adapters actually diverged the streams (the bank is not identity)
        base = paged.PagedServeEngine(
            **{**kw, "adapter_bank": None, "spec_gamma": 0}
        )
        plain = _streams(base, [(p, m, {}) for p, m, _ in reqs])
        assert any(plain[i] != want[i] for i in want)

    def test_preemption_streams_identical(self, params):
        """Recompute-preemption under an undersized PER-SHARD pool (each
        shard's resident pair outgrows its 8-block pool mid-flight, the
        unsharded TestPreemption scenario doubled): parked requests resume
        bit-exactly and the streams match a roomy unsharded run."""
        reqs = [
            ([1, 2, 3, 4, 5, 6], 20, {}),
            ([7, 8, 9, 10, 11, 12], 20, {}),
            ([13, 14, 15, 16, 17, 18], 20, {}),
            ([19, 20, 21, 22, 23, 24], 20, {}),
        ]
        kw = dict(
            params=params, cfg=CFG, n_slots=4, block_size=4,
            prompt_bucket=32, attn_impl="xla",
        )
        ref = paged.PagedServeEngine(**kw, n_blocks=80)  # roomy, no pressure
        shd = paged.PagedServeEngine(
            **kw, n_blocks=16, preempt_on_stall=True, mesh=_mesh(2),
        )
        want = _streams(ref, reqs)
        assert _streams(shd, reqs) == want
        assert shd.preempted_count >= 1  # pressure actually preempted


class TestMultisliceServing:
    """slot_axis as a TUPLE over a multislice mesh (build_multislice_mesh:
    leading 'slice' axis = DCN): DP serving shards slots slice-major, the
    row-local hot loop never crosses the slice axis, and streams stay
    bit-equal a single-slice engine's — the serving side of the
    multislice-test1 slice-group contract."""

    def _ms_mesh(self):
        from k8s_dra_driver_tpu.parallel.mesh import (
            MeshShape,
            build_multislice_mesh,
        )

        return build_multislice_mesh(
            jax.devices("cpu")[:8], 2, MeshShape(data=2, model=2)
        )

    def test_dense_engine_bit_equal_across_slices(self, params):
        from k8s_dra_driver_tpu.models.serve import ServeEngine

        reqs = [(p, 10, {}) for p in _prompts(5, rng=13)]
        ref = ServeEngine(params=params, cfg=CFG, n_slots=4, prompt_bucket=16)
        shd = ServeEngine(
            params=params, cfg=CFG, n_slots=4, prompt_bucket=16,
            mesh=self._ms_mesh(), slot_axis=("slice", "data"),
        )
        assert _streams(shd, reqs) == _streams(ref, reqs)

    def test_paged_engine_bit_equal_across_slices(self, params):
        reqs = [(p, 10, {}) for p in _prompts(5, rng=17)]
        kw = dict(
            params=params, cfg=CFG, n_slots=4, n_blocks=64, block_size=BS,
            prompt_bucket=16, attn_impl="xla",
        )
        ref = paged.PagedServeEngine(**kw)
        shd = paged.PagedServeEngine(
            **kw, mesh=self._ms_mesh(), slot_axis=("slice", "data"),
        )
        want = _streams(ref, reqs)
        assert _streams(shd, reqs) == want
        # slots and pool really partitioned 4 ways (2 slices x data 2)
        assert shd._axis_size == 4

    def test_unknown_tuple_axis_rejected(self, params):
        with pytest.raises(ValueError, match="slot_axis"):
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=4, n_blocks=32, block_size=4,
                prompt_bucket=16, mesh=self._ms_mesh(),
                slot_axis=("slice", "nope"),
            )


class TestShardedAccounting:
    def test_capacity_is_per_shard(self, params):
        """A prompt whose blocks exceed ONE shard's pool is refused even
        when the sum of free blocks across shards would cover it."""
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=4, n_blocks=16, block_size=4,
            prompt_bucket=32, attn_impl="xla", mesh=_mesh(4),
        )
        # per shard: 4 blocks, 1 reserved null -> 3 usable; a 12-token
        # prompt needs ceil(13/4) = 4 blocks
        with pytest.raises(RuntimeError, match="no free blocks"):
            eng.submit(list(range(1, 13)), 4)
        assert eng.free_blocks == 12  # nothing leaked by the refusal

    def test_admission_spreads_across_shards(self, params):
        """Two admissions land on different shards when the first shard's
        slots are taken — the slot walk picks the first slot whose shard
        has blocks."""
        eng = paged.PagedServeEngine(
            params=params, cfg=CFG, n_slots=4, n_blocks=32, block_size=4,
            prompt_bucket=16, attn_impl="xla", mesh=_mesh(2),
        )
        eng.submit([1, 2, 3], 4)
        eng.submit([4, 5, 6], 4)
        eng.submit([7, 8, 9], 4)
        groups = {eng._group(s) for s, st in enumerate(eng._slots) if st}
        assert groups == {0, 1}

    def test_constructor_validation(self, params):
        with pytest.raises(ValueError, match="not a mesh axis"):
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=4, n_blocks=32, block_size=4,
                prompt_bucket=16, mesh=_mesh(2), slot_axis="nope",
            )
        with pytest.raises(ValueError, match="n_slots"):
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=3, n_blocks=32, block_size=4,
                prompt_bucket=16, mesh=_mesh(2),
            )
        with pytest.raises(ValueError, match="n_blocks"):
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=4, n_blocks=33, block_size=4,
                prompt_bucket=16, mesh=_mesh(2),
            )
        with pytest.raises(ValueError, match="null block"):
            paged.PagedServeEngine(
                params=params, cfg=CFG, n_slots=8, n_blocks=8, block_size=4,
                prompt_bucket=16, mesh=_mesh(8),
            )
