"""Disaggregated prefill/decode suite (models/disagg.py): the tentpole's
correctness contracts, fault-free.

* The bit-equality handoff matrix: every pool combination {dense, paged}
  prefill x {dense, paged} decode, under every stream-shaping feature
  {greedy, sampled, LoRA, prefix-cache, spec}, produces token streams
  identical to a unified reference engine — disaggregation moves
  scheduling and KV bytes, never tokens.
* The KV payload keystone: a prompt's captured KV bytes are bit-identical
  across engine kinds (canonical [L, valid_len, Hkv, hd] layout), which is
  what makes cross-kind injection exact rather than approximate.
* Block-leak accounting: paged pools return to their initial free-block
  level after success, forced-drop and forced-refusal paths alike.
* The channel as a claimed resource: bounded in-flight budget, deadline
  staleness, checksum integrity; ChannelClaim binds from the topology
  daemon's published info doc (TPU_HANDOFF_CHANNEL -> ResourceSlice ->
  claim), with a static fallback.
* /debug/disagg and the tpu_disagg_* metric surface.

Fault-injected storm variants live in tests/test_disagg_chaos.py
(`make chaos-disagg`).
"""

import json

import jax
import numpy as np
import pytest

from k8s_dra_driver_tpu.models import burnin, lora, paged
from k8s_dra_driver_tpu.models.disagg import (
    ChannelClaim,
    ChannelSet,
    DisaggRouter,
    HandoffChannel,
    debug_disagg_doc,
)
from k8s_dra_driver_tpu.models.serve import (
    KVSlice,
    ServeEngine,
    WireFormatError,
)
from k8s_dra_driver_tpu.plugin.deviceinfo import (
    DEVICE_TYPE_CHANNEL,
    AllocatableDevice,
    InterconnectChannelInfo,
)
from k8s_dra_driver_tpu.plugin.topology_daemon import TopologyDaemonServer
from k8s_dra_driver_tpu.utils.faults import FaultInjector, FaultProfile
from k8s_dra_driver_tpu.utils.metrics import REGISTRY

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)
LORA = lora.LoraConfig(rank=4, alpha=8.0)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def bank():
    def trained(seed):
        ad = lora.init_adapters(jax.random.PRNGKey(seed), CFG, LORA)
        for li, blk in enumerate(ad["blocks"]):
            for name, ab in blk.items():
                tag = li * 1000 + sum(ord(c) for c in name)
                key = jax.random.fold_in(jax.random.PRNGKey(seed), tag)
                ab["b"] = 0.3 * jax.random.normal(
                    key, ab["b"].shape, jax.numpy.float32
                )
        return ad

    return lora.stack_adapters(CFG, LORA, [trained(1), trained(2)])


def _prompts(n, rng=7, lo=3, hi=12):
    r = np.random.RandomState(rng)
    return [
        r.randint(0, CFG.vocab_size, size=r.randint(lo, hi)).tolist()
        for _ in range(n)
    ]


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)

def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 41)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


KINDS = {"dense": _dense, "paged": _paged}
COMBOS = [(a, b) for a in KINDS for b in KINDS]

_SYS = list(range(40, 48))  # shared 8-token system prompt (prefix feature)

# feature -> (requests builder, per-kind engine kwargs).  Prefix-cache
# kwargs differ by kind (prefix_bucket vs prefix_cache_blocks); the LoRA
# bank is injected by the test (fixture-built).
FEATURES = {
    "greedy": dict(
        reqs=lambda: [{"prompt": p, "max_tokens": 5} for p in _prompts(3)],
        dense={}, paged={},
    ),
    "sampled": dict(
        reqs=lambda: [
            {"prompt": p, "max_tokens": 5, "temperature": 0.8, "seed": 50 + i}
            for i, p in enumerate(_prompts(3, rng=11))
        ],
        dense={}, paged={},
    ),
    "lora": dict(
        reqs=lambda: [
            {"prompt": p, "max_tokens": 5, "adapter": i % 3}
            for i, p in enumerate(_prompts(3, rng=13))
        ],
        dense=dict(adapter_bank="BANK"), paged=dict(adapter_bank="BANK"),
    ),
    "prefix": dict(
        reqs=lambda: [
            {"prompt": _SYS + p, "max_tokens": 5}
            for p in _prompts(3, rng=17, lo=2, hi=8)
        ],
        dense=dict(prefix_bucket=8), paged=dict(prefix_cache_blocks=2),
    ),
    "spec": dict(
        reqs=lambda: [{"prompt": p, "max_tokens": 5} for p in _prompts(3, rng=19)],
        dense=dict(spec_gamma=2), paged=dict(spec_gamma=2),
    ),
}


def _engine(kind, params, feature, bank):
    kw = dict(FEATURES[feature][kind])
    if kw.get("adapter_bank") == "BANK":
        kw["adapter_bank"] = bank
    return KINDS[kind](params, **kw)


def _by_prompt(completions):
    """prompt-tuple -> generated-tuple: router-minted ids differ from the
    single-engine reference, prompts don't."""
    out = {}
    for c in completions:
        assert c.status == "ok", (c.request_id, c.status, c.error)
        out[tuple(c.tokens[: len(c.tokens) - len(c.generated)])] = tuple(
            c.generated
        )
    return out


_REF_CACHE: dict = {}


def _reference(params, feature, bank):
    """Unified-engine streams for a feature (memoized: the repo pins
    dense == paged and prefix/spec stream-invariance elsewhere, so one
    dense reference anchors every pool combination)."""
    if feature not in _REF_CACHE:
        eng = _engine("dense", params, feature, bank)
        _REF_CACHE[feature] = _by_prompt(
            eng.pump([dict(r) for r in FEATURES[feature]["reqs"]()])
        )
    return _REF_CACHE[feature]


class TestHandoffMatrix:
    """The acceptance matrix: 4 pool combinations x 5 features, every
    stream bit-equal to the unified reference, every transfer delivered
    (fault-free channel => zero fallbacks), paged pools leak-free."""

    @pytest.mark.parametrize("feature", list(FEATURES))
    @pytest.mark.parametrize(
        "pre_kind,dec_kind", COMBOS, ids=[f"{a}_to_{b}" for a, b in COMBOS]
    )
    def test_streams_bit_equal_and_zero_fallbacks(
        self, params, bank, pre_kind, dec_kind, feature
    ):
        reqs = FEATURES[feature]["reqs"]()
        pre = _engine(pre_kind, params, feature, bank)
        dec = _engine(dec_kind, params, feature, bank)
        free0 = {
            id(e): e.free_blocks
            for e in (pre, dec) if hasattr(e, "free_blocks")
        }
        router = DisaggRouter(prefill=[pre], decode=[dec])
        done = router.pump([dict(r) for r in reqs])
        assert _by_prompt(done) == _reference(params, feature, bank)
        # one Completion per request — never a lost or duplicated stream
        assert len(done) == len(reqs)
        assert router.handoffs == len(reqs)
        assert router.fallbacks == 0
        assert router.channel.counts == {"ok": len(reqs)}
        for e in (pre, dec):
            if not hasattr(e, "free_blocks"):
                continue
            if feature == "prefix":
                # the prefix store retains shared blocks BY DESIGN —
                # bounded by its configured capacity, not a leak
                assert e.free_blocks >= free0[id(e)] - e.prefix_cache_blocks
            else:
                assert e.free_blocks == free0[id(e)]


class TestKVPayload:
    """The keystone under the matrix: canonical KV capture is bit-identical
    across engine kinds, so cross-kind injection is exact."""

    def test_capture_bytes_bit_identical_across_kinds(self, params):
        (p,) = _prompts(1, rng=23, lo=9, hi=10)
        slices = []
        for make in (_dense, _paged):
            eng = make(params)
            eng.submit(p, max_tokens=5, handoff=True)
            eng.run_until_drained()
            (entry,) = eng.take_handoffs()
            slices.append(entry["kv"])
        a, b = slices
        assert isinstance(a, KVSlice) and isinstance(b, KVSlice)
        assert a.valid_len == b.valid_len == len(p)  # first-token handoff
        assert a.k.shape == b.k.shape == (
            CFG.n_layers, len(p), CFG.kv_heads, CFG.head_dim
        )
        assert np.array_equal(a.k, b.k) and np.array_equal(a.v, b.v)
        assert a.checksum() == b.checksum()

    def test_handoff_mode_is_optional_on_both_kinds(self, params):
        import inspect

        for make in (_dense, _paged):
            eng = make(params)
            assert inspect.signature(eng.submit).parameters[
                "handoff"
            ].default is False
            assert inspect.signature(eng.snapshot_active).parameters[
                "include_kv"
            ].default is False
            assert callable(eng.take_handoffs)


def _kv(fill=1.0):
    k = np.full((1, 2, 1, 2), fill, np.float32)
    return KVSlice(
        k=k, v=k + 1, valid_len=2, n_layers=1, kv_heads=1, head_dim=2,
        dtype="float32",
    )


class TestHandoffChannel:
    """The transfer path as a claimed resource: bounded in-flight bytes,
    per-transfer deadlines, end-to-end checksums — latency accounted,
    never slept."""

    def test_in_flight_budget_backpressures_then_releases(self):
        ch = HandoffChannel(max_in_flight_bytes=100)
        kv = _kv()
        t1 = ch.begin(1, 60, kv.checksum())
        assert t1 is not None and ch.in_flight_bytes == 60
        assert ch.begin(2, 60, kv.checksum()) is None  # budget spent
        assert ch.complete(t1, kv) == "ok"
        assert ch.in_flight_bytes == 0
        assert ch.begin(2, 60, kv.checksum()) is not None  # budget back

    def test_payload_past_whole_budget_never_fits(self):
        ch = HandoffChannel(max_in_flight_bytes=8)
        assert not ch.fits(32)
        ch.refuse(7, 32, "exceeds channel budget")
        assert ch.counts == {"no_capacity": 1}

    def test_deadline_marks_slow_transfer_stale_without_sleeping(self):
        import time

        # 1 Gbps over 1 MiB => ~8.4ms modeled latency vs a 1ms deadline
        ch = HandoffChannel(
            bandwidth_gbps=1.0, transfer_deadline_s=0.001,
            max_in_flight_bytes=1 << 30,
        )
        kv = _kv()
        t = ch.begin(3, 1 << 20, kv.checksum())
        t0 = time.perf_counter()
        assert ch.complete(t, kv) == "deadline"
        assert time.perf_counter() - t0 < 0.05  # accounted, not slept
        assert t.latency_s > ch.transfer_deadline_s
        assert ch.in_flight_bytes == 0  # stale transfers release budget too

    def test_checksum_mismatch_is_corrupt(self):
        ch = HandoffChannel()
        kv = _kv()
        t = ch.begin(4, kv.nbytes, kv.checksum() ^ 0xDEAD)
        assert ch.complete(t, kv) == "corrupt"


def _quant_kv(kv_dtype="int8", seed=5):
    """Synthetic QUANTIZED slice with the padded-extent geometry the
    engine captures: 2 blocks of 4, 6 valid positions (2-token tail)."""
    L, hkv, hd, bs, nb = 1, 2, 16, 4, 2
    padded = nb * bs
    r = np.random.RandomState(seed)
    if kv_dtype == "int8":
        k = r.randint(-127, 128, (L, padded, hkv, hd)).astype(np.int8)
        v = r.randint(-127, 128, (L, padded, hkv, hd)).astype(np.int8)
    else:  # packed int4: two positions per byte along the trailing dim
        k = r.randint(0, 256, (L, padded, hkv, hd // 2)).astype(np.uint8)
        v = r.randint(0, 256, (L, padded, hkv, hd // 2)).astype(np.uint8)
    return KVSlice(
        k=k, v=v, valid_len=6, n_layers=L, kv_heads=hkv, head_dim=hd,
        dtype=kv_dtype,
        k_scale=r.rand(L, nb, hkv).astype(np.float32),
        v_scale=r.rand(L, nb, hkv).astype(np.float32),
        block_size=bs,
    )


def _assert_wire_roundtrip(kv: KVSlice, rid: int) -> bytes:
    wire = kv.to_wire(rid)
    got_rid, got = KVSlice.from_wire(wire)
    assert got_rid == rid
    assert np.array_equal(np.asarray(got.k), np.asarray(kv.k))
    assert np.array_equal(np.asarray(got.v), np.asarray(kv.v))
    assert (got.valid_len, got.n_layers, got.kv_heads, got.head_dim) == (
        kv.valid_len, kv.n_layers, kv.kv_heads, kv.head_dim
    )
    assert got.dtype == kv.dtype
    assert got.block_size == kv.block_size
    if kv.quantized:
        assert np.array_equal(np.asarray(got.k_scale), np.asarray(kv.k_scale))
        assert np.array_equal(np.asarray(got.v_scale), np.asarray(kv.v_scale))
        assert got.k.dtype == kv.k.dtype  # int8 / packed-uint8 storage
    else:
        assert got.k_scale is None and got.v_scale is None
    assert got.checksum() == kv.checksum()
    return wire


class TestWireFormat:
    """Property tests for the KVSlice wire codec (models/transport.py
    ships these bytes between processes): decode(encode(kv)) is identity,
    and EVERY truncation point and EVERY single-byte flip is a typed
    ``WireFormatError`` — never a partially-installed payload, never an
    untyped struct/index error."""

    def test_roundtrip_identity_real_captures_both_kinds(self, params):
        (p,) = _prompts(1, rng=23, lo=9, hi=10)
        for i, make in enumerate((_dense, _paged)):
            eng = make(params)
            eng.submit(p, max_tokens=5, handoff=True)
            eng.run_until_drained()
            (entry,) = eng.take_handoffs()
            _assert_wire_roundtrip(entry["kv"], rid=1000 + i)

    def test_truncation_at_every_byte_is_typed_never_partial(self):
        wire = _assert_wire_roundtrip(_kv(), rid=7)
        for cut in range(len(wire)):
            with pytest.raises(WireFormatError):
                KVSlice.from_wire(wire[:cut])

    def test_single_byte_flips_at_every_offset_are_typed(self):
        kv = _kv()
        wire = bytearray(kv.to_wire(9))
        for off in range(len(wire)):
            for flip in (0x01, 0x80):
                mutated = bytes(
                    wire[:off] + bytes([wire[off] ^ flip]) + wire[off + 1:]
                )
                try:
                    got_rid, got = KVSlice.from_wire(mutated)
                except WireFormatError:
                    continue
                pytest.fail(
                    f"flip 0x{flip:02x} at offset {off} decoded "
                    f"silently (rid={got_rid})"
                )

    def test_error_carries_request_id_once_header_is_readable(self):
        kv = _kv()
        wire = bytearray(kv.to_wire(42))
        wire[-5] ^= 0x10  # corrupt the last payload byte, header intact
        with pytest.raises(WireFormatError) as exc:
            KVSlice.from_wire(bytes(wire))
        assert exc.value.request_id == 42
        # truncated before the header completes: rid unknowable, -1
        with pytest.raises(WireFormatError) as exc:
            KVSlice.from_wire(bytes(wire[:6]))
        assert exc.value.request_id == -1

    @pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
    def test_roundtrip_identity_quantized_synthetic(self, kv_dtype):
        """Quantized frames carry four payload segments (k, v, k_scale,
        v_scale) plus block geometry — identity must cover all of them."""
        _assert_wire_roundtrip(_quant_kv(kv_dtype), rid=77)

    @pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
    def test_roundtrip_identity_quantized_real_capture(self, params, kv_dtype):
        (p,) = _prompts(1, rng=23, lo=9, hi=10)
        eng = _paged(params, kv_dtype=kv_dtype)
        eng.submit(p, max_tokens=5, handoff=True)
        eng.run_until_drained()
        (entry,) = eng.take_handoffs()
        kv = entry["kv"]
        assert kv.quantized and kv.dtype == kv_dtype and kv.block_size == 4
        _assert_wire_roundtrip(kv, rid=2000)

    def test_quantized_truncation_at_every_byte_is_typed(self):
        wire = _assert_wire_roundtrip(_quant_kv("int4"), rid=8)
        for cut in range(len(wire)):
            with pytest.raises(WireFormatError):
                KVSlice.from_wire(wire[:cut])

    def test_quantized_flips_at_every_offset_are_typed(self):
        """Every byte of an int4 frame — header, sizes, packed nibbles,
        and BOTH scale segments — is under some checksum."""
        wire = bytearray(_quant_kv("int4").to_wire(9))
        for off in range(len(wire)):
            for flip in (0x01, 0x80):
                mutated = bytes(
                    wire[:off] + bytes([wire[off] ^ flip]) + wire[off + 1:]
                )
                try:
                    got_rid, got = KVSlice.from_wire(mutated)
                except WireFormatError:
                    continue
                pytest.fail(
                    f"flip 0x{flip:02x} at offset {off} decoded "
                    f"silently (rid={got_rid})"
                )

    def test_scale_corruption_attributed_to_request(self):
        kv = _quant_kv("int8")
        wire = bytearray(kv.to_wire(55))
        # the scale segments are the LAST bytes of the frame
        wire[-3] ^= 0x40
        with pytest.raises(WireFormatError) as exc:
            KVSlice.from_wire(bytes(wire))
        assert exc.value.request_id == 55


def _gossip_doc():
    return {
        "events": [
            {"key": "a" * 32, "n_tokens": 12, "block_size": 4,
             "kv_dtype": "float32", "n_layers": 2, "kv_heads": 2,
             "head_dim": 16, "adapter": 0, "blocks": 3},
            {"key": "b" * 32, "n_tokens": 4, "block_size": 4,
             "kv_dtype": "int8", "adapter": 1, "blocks": 1},
        ],
        "full": True,
    }


class TestPrefixGossipWireFormat:
    """Property tests for the PREFIXPUB/PREFIXWDL gossip codec
    (models/fleet_prefix.py; the frames PoolWorker ships to the fleet
    index) — same contract as the KVSlice codec above: decode(encode(doc))
    is identity, and EVERY truncation point and EVERY single-byte flip is
    a typed ``PrefixGossipError`` — a corrupt batch drops whole, never a
    partially-applied index update."""

    def test_roundtrip_identity(self):
        from k8s_dra_driver_tpu.models.fleet_prefix import (
            decode_prefix_gossip, encode_prefix_gossip)

        doc = _gossip_doc()
        body = encode_prefix_gossip(doc, epoch=7, seq=19)
        got, epoch, seq = decode_prefix_gossip(body)
        assert got == doc and epoch == 7 and seq == 19

    def test_truncation_at_every_byte_is_typed_never_partial(self):
        from k8s_dra_driver_tpu.models.fleet_prefix import (
            PrefixGossipError, decode_prefix_gossip, encode_prefix_gossip)

        body = encode_prefix_gossip(_gossip_doc(), epoch=7, seq=19)
        for cut in range(len(body)):
            with pytest.raises(PrefixGossipError):
                decode_prefix_gossip(body[:cut])

    def test_single_bit_flips_at_every_offset_are_typed(self):
        from k8s_dra_driver_tpu.models.fleet_prefix import (
            PrefixGossipError, decode_prefix_gossip, encode_prefix_gossip)

        body = bytearray(encode_prefix_gossip(_gossip_doc(), epoch=7, seq=19))
        for off in range(len(body)):
            for flip in (0x01, 0x80):
                mutated = bytes(
                    body[:off] + bytes([body[off] ^ flip]) + body[off + 1:]
                )
                try:
                    got, epoch, seq = decode_prefix_gossip(mutated)
                except PrefixGossipError:
                    continue
                pytest.fail(
                    f"flip 0x{flip:02x} at offset {off} decoded "
                    f"silently (epoch={epoch}, seq={seq})"
                )

    def test_error_carries_epoch_and_seq_once_header_is_readable(self):
        from k8s_dra_driver_tpu.models.fleet_prefix import (
            _GOSSIP_HEADER_BYTES, PrefixGossipError, decode_prefix_gossip,
            encode_prefix_gossip)

        body = bytearray(encode_prefix_gossip(_gossip_doc(), epoch=9, seq=42))
        body[-1] ^= 0x10  # corrupt the last payload byte, header intact
        with pytest.raises(PrefixGossipError) as exc:
            decode_prefix_gossip(bytes(body))
        assert exc.value.epoch == 9 and exc.value.seq == 42
        # truncated before the fixed header completes: attribution
        # unknowable, -1 (the WireFormatError.request_id contract)
        for cut in range(_GOSSIP_HEADER_BYTES):
            with pytest.raises(PrefixGossipError) as exc:
                decode_prefix_gossip(bytes(body[:cut]))
            assert exc.value.epoch == -1 and exc.value.seq == -1


class TestChannelClaim:
    """DRA binding: the channel's capacity parameters come from the
    interconnect device the topology daemon publishes."""

    def test_claim_binds_from_daemon_info(self, tmp_path):
        info = InterconnectChannelInfo(
            channel_name="ici-3", bandwidth_gbps=42.0,
            max_in_flight_bytes=1 << 20, transfer_deadline_ms=75,
        )
        srv = TopologyDaemonServer(
            str(tmp_path / "claim.sock"), claim_uid="uid-1",
            channel=info.to_info(),
        )
        doc = srv.handle_request({"op": "info"})
        claim = ChannelClaim.from_daemon_info(doc)
        assert claim is not None and claim.source == "daemon"
        assert claim.name == "ici-3"
        assert claim.bandwidth_gbps == 42.0
        assert claim.max_in_flight_bytes == 1 << 20
        assert claim.transfer_deadline_s == pytest.approx(0.075)
        ch = HandoffChannel(claim)
        assert ch.max_in_flight_bytes == 1 << 20
        assert ch.transfer_deadline_s == pytest.approx(0.075)
        assert ch.bandwidth_gbps == 42.0

    def test_daemon_parses_channel_from_env(self, tmp_path):
        env = {
            "TPU_HANDOFF_CHANNEL": json.dumps(
                InterconnectChannelInfo(channel_name="ici-9").to_info()
            ),
        }
        srv = TopologyDaemonServer.from_env(
            str(tmp_path / "c.sock"), "uid-2", environ=env
        )
        claim = ChannelClaim.from_daemon_info(srv.handle_request({"op": "info"}))
        assert claim.name == "ici-9" and claim.source == "daemon"

    def test_no_published_channel_falls_back_to_static(self, tmp_path):
        srv = TopologyDaemonServer(str(tmp_path / "c.sock"), claim_uid="u")
        assert ChannelClaim.from_daemon_info(
            srv.handle_request({"op": "info"})
        ) is None
        assert HandoffChannel().claim.source == "static"

    def test_channel_device_in_resourceslice_inventory(self):
        info = InterconnectChannelInfo(channel_name="ici-0")
        dev = AllocatableDevice(channel=info)
        assert dev.kind == DEVICE_TYPE_CHANNEL
        rendered = info.get_device()
        attrs = rendered.basic.attributes
        assert attrs["type"].string == DEVICE_TYPE_CHANNEL
        assert attrs["channelName"].string == "ici-0"
        assert "inFlightBytes" in rendered.basic.capacity


class TestMultiChannelBinding:
    """Multi-link parsing: the daemon publishes a channel LIST and
    ``all_from_daemon_info`` binds the whole scoreable set."""

    def _doc(self, *chans):
        return {"channels": [c.to_json() for c in chans]}

    def test_n_links_parse_with_daemon_source(self):
        claims = ChannelClaim.all_from_daemon_info(self._doc(
            ChannelClaim(name="ici-0", bandwidth_gbps=100.0),
            ChannelClaim(name="ici-1", bandwidth_gbps=50.0),
            ChannelClaim(name="dcn-0", bandwidth_gbps=10.0),
        ))
        assert [c.name for c in claims] == ["ici-0", "ici-1", "dcn-0"]
        assert all(c.source == "daemon" for c in claims)
        assert claims[1].bandwidth_gbps == 50.0

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError, match="duplicate channel names"):
            ChannelClaim.all_from_daemon_info(self._doc(
                ChannelClaim(name="ici-0"), ChannelClaim(name="ici-0"),
            ))

    def test_zero_bandwidth_links_excluded_from_scoring(self):
        claims = ChannelClaim.all_from_daemon_info(self._doc(
            ChannelClaim(name="ici-0", bandwidth_gbps=100.0),
            ChannelClaim(name="dead", bandwidth_gbps=0.0),
        ))
        assert [c.name for c in claims] == ["ici-0"]

    def test_old_single_channel_doc_still_binds(self):
        doc = {"channel": ChannelClaim(name="ici-7", bandwidth_gbps=9.0).to_json()}
        claims = ChannelClaim.all_from_daemon_info(doc)
        assert [c.name for c in claims] == ["ici-7"]
        one = ChannelClaim.from_daemon_info(doc)
        assert one is not None and one.name == "ici-7"

    def test_from_daemon_info_picks_highest_bandwidth(self):
        doc = self._doc(
            ChannelClaim(name="slow", bandwidth_gbps=10.0),
            ChannelClaim(name="fast", bandwidth_gbps=200.0),
        )
        assert ChannelClaim.from_daemon_info(doc).name == "fast"

    def test_daemon_publishes_channel_list_from_env(self, tmp_path):
        links = [
            InterconnectChannelInfo(
                channel_name=f"ici-{i}", bandwidth_gbps=100.0 - i
            ).to_info()
            for i in range(3)
        ]
        srv = TopologyDaemonServer.from_env(
            str(tmp_path / "c.sock"), "uid-3",
            environ={"TPU_HANDOFF_CHANNELS": json.dumps(links)},
        )
        doc = srv.handle_request({"op": "info"})
        assert len(doc["channels"]) == 3
        claims = ChannelClaim.all_from_daemon_info(doc)
        assert [c.name for c in claims] == ["ici-0", "ici-1", "ici-2"]
        # legacy single-channel key still served for old binders
        assert ChannelClaim.from_daemon_info(doc).name == "ici-0"


class TestChannelSet:
    """Set-level selection, health and failover — no pools involved."""

    def _set(self, *, inj=None):
        return ChannelSet(
            [
                ChannelClaim(name="ici-0", bandwidth_gbps=100.0,
                             max_in_flight_bytes=1 << 20),
                ChannelClaim(name="ici-1", bandwidth_gbps=50.0,
                             max_in_flight_bytes=1 << 20),
            ],
            fault_injector=inj,
        )

    def test_empty_and_duplicate_sets_rejected(self):
        with pytest.raises(ValueError, match="at least one"):
            ChannelSet([])
        with pytest.raises(ValueError, match="duplicate channel names"):
            ChannelSet([ChannelClaim(name="x"), ChannelClaim(name="x")])

    def test_pick_prefers_headroom_per_bandwidth(self):
        cs = self._set()
        # Empty set: the faster link wins (same bytes, more bandwidth).
        assert cs._pick(1000).claim.name == "ici-0"
        # Load ici-0 heavily: per-capacity score now favors ici-1.
        cs.members[0].in_flight_bytes = 900_000
        assert cs._pick(1000).claim.name == "ici-1"

    def test_begin_complete_routes_through_picked_member(self):
        cs = self._set()
        kv = _kv()
        t = cs.begin(1, kv.nbytes, kv.checksum())
        assert t is not None and t.channel == "ici-0"
        assert cs.complete(t, kv) == "ok"
        assert cs.members[0].counts.get("ok") == 1
        assert cs.failovers == 0

    def test_mid_transfer_link_death_fails_over_to_sibling(self):
        inj = FaultInjector.from_env(
            "channel_down=1.0,channels=ici-0,limit=1,seed=5"
        )
        cs = self._set(inj=inj)
        kv = _kv()
        t = cs.begin(2, kv.nbytes, kv.checksum())
        assert t.channel == "ici-0"
        assert cs.complete(t, kv) == "ok"          # hopped, not failed
        assert cs.failovers == 1
        assert t.channel == "ici-1"                # winning hop folded back
        assert cs.members[1].counts.get("ok") == 1
        assert "ici-0" in cs._forced_down
        assert not cs.down                          # sibling keeps the set up

    def test_down_only_when_every_link_unusable(self):
        inj = FaultInjector.from_env("channel_down=1.0,limit=4,seed=5")
        cs = self._set(inj=inj)
        assert cs._maybe_kill(cs.members[0])
        assert not cs.down                          # one survivor: still up
        assert cs._maybe_kill(cs.members[1])
        assert cs.down

    def test_stats_has_per_channel_table(self):
        cs = self._set()
        doc = cs.stats()
        assert {c["claim"]["name"] for c in doc["channels"]} == {
            "ici-0", "ici-1"
        }
        assert all(
            set(c) >= {"up", "breaker", "forced_down"}
            for c in doc["channels"]
        )
        assert doc["failovers"] == 0

    def test_router_binds_claim_list_as_channel_set(self, params):
        router = DisaggRouter(
            prefill=[_dense(params)], decode=[_dense(params)],
            channel=[
                ChannelClaim(name="a", bandwidth_gbps=10.0),
                ChannelClaim(name="b", bandwidth_gbps=10.0),
            ],
        )
        assert isinstance(router.channel, ChannelSet)
        done = router.pump(
            [{"prompt": [5, 6, 7], "max_tokens": 4}]
        )
        assert len(done) == 1 and done[0].status == "ok"
        assert router.stats()["channel"]["peer"] == "local"


class TestFallbackLadder:
    """Channel faults cost compute, never correctness: forced drops and
    outright refusals both re-prefill to bit-equal streams with balanced
    block accounting."""

    def test_forced_drops_fall_back_bit_equal_no_block_leak(self, params, bank):
        reqs = FEATURES["greedy"]["reqs"]()
        inj = FaultInjector(seed=5)
        inj.arm(FaultProfile(name="drop", handoff_drop_rate=1.0, limit=2))
        pre, dec = _paged(params), _paged(params)
        free0 = (pre.free_blocks, dec.free_blocks)
        router = DisaggRouter(
            prefill=[pre], decode=[dec], fault_injector=inj
        )
        done = router.pump([dict(r) for r in reqs])
        assert _by_prompt(done) == _reference(params, "greedy", bank)
        assert len(done) == len(reqs)
        assert router.fallbacks == 2
        assert router.channel.counts["dropped"] == 2
        assert router.channel.counts["ok"] == len(reqs) - 2
        assert (pre.free_blocks, dec.free_blocks) == free0
        assert REGISTRY.counter("tpu_disagg_fallback_total").value(
            reason="dropped"
        ) == 2

    def test_oversized_payload_refused_and_reprefilled(self, params, bank):
        reqs = FEATURES["greedy"]["reqs"]()
        pre, dec = _paged(params), _paged(params)
        free0 = (pre.free_blocks, dec.free_blocks)
        router = DisaggRouter(
            prefill=[pre], decode=[dec],
            channel=HandoffChannel(max_in_flight_bytes=8),
        )
        done = router.pump([dict(r) for r in reqs])
        assert _by_prompt(done) == _reference(params, "greedy", bank)
        assert router.fallbacks == len(reqs)
        assert router.channel.counts == {"no_capacity": len(reqs)}
        assert (pre.free_blocks, dec.free_blocks) == free0
        assert REGISTRY.counter("tpu_disagg_fallback_total").value(
            reason="too_large"
        ) == len(reqs)


class TestObservability:
    """/debug/disagg and the documented tpu_disagg_* metric surface."""

    def test_metrics_surface_after_a_clean_pump(self, params):
        reqs = FEATURES["greedy"]["reqs"]()
        router = DisaggRouter(
            prefill=[_dense(params)], decode=[_dense(params)]
        )
        router.pump([dict(r) for r in reqs])
        n = len(reqs)
        assert REGISTRY.counter("tpu_disagg_transfers_total").value(
            outcome="ok"
        ) == n
        assert REGISTRY.histogram("tpu_disagg_transfer_bytes").count() == n
        ttft = REGISTRY.histogram("tpu_disagg_ttft_breakdown_seconds")
        assert ttft.count(stage="prefill") == n
        assert ttft.count(stage="transfer") == n
        assert ttft.count(stage="decode") == n
        assert REGISTRY.gauge("tpu_disagg_inflight_bytes").value() == 0
        text = REGISTRY.render()
        for name in (
            "tpu_disagg_transfers_total",
            "tpu_disagg_transfer_bytes",
            "tpu_disagg_fallback_total",
            "tpu_disagg_ttft_breakdown_seconds",
            "tpu_disagg_inflight_bytes",
        ):
            assert f"# HELP {name} " in text, name

    def test_debug_disagg_doc_and_endpoint(self, params):
        import urllib.request

        from k8s_dra_driver_tpu.utils.diagnostics import DiagnosticsServer

        router = DisaggRouter(
            prefill=[_dense(params)], decode=[_dense(params)]
        )
        router.pump([{"prompt": [5, 6, 7], "max_tokens": 3}])
        doc = debug_disagg_doc()
        mine = {d["router_seq"]: d for d in doc["disagg"]}[router.seq]
        assert mine["handoffs"] == 1 and mine["fallbacks"] == 0
        assert mine["channel"]["outcomes"] == {"ok": 1}
        assert mine["prefill"]["replicas"][0]["state"] == "healthy"
        srv = DiagnosticsServer(port=0)
        srv.start()
        try:
            served = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/disagg").read())
        finally:
            srv.stop()
        assert router.seq in {d["router_seq"] for d in served["disagg"]}

    def test_trace_carries_handoff_events_across_pools(self, params):
        pre, dec = _dense(params), _dense(params)
        router = DisaggRouter(prefill=[pre], decode=[dec])
        (c,) = router.pump([{"prompt": [9, 10, 11], "max_tokens": 4}])
        tr = dec.telemetry._traces[c.request_id]
        names = [e["event"] for e in tr.events]
        assert "handoff_begin" in names
        assert "handoff_transfer" in names
        # one contiguous timeline: TTFT anchored at the PREFILL pool's
        # first token, e2e spans both pools
        assert tr.ttft_s() is not None and tr.e2e_s() is not None
        assert tr.e2e_s() >= tr.ttft_s()


class TestQuantizedHandoff:
    """kv_dtype axis over the handoff matrix: bf16 pools stay bit-equal to
    the dense reference on every path; int8/int4 are same-seed
    deterministic across the router (router streams == unified same-dtype
    engine); cross-dtype mismatches fall back to re-prefill, never decode
    against misinterpreted bytes.  Plus the acceptance criterion that the
    int8 capacity win is VISIBLE to the KV-demand ledger: >= 1.9x
    reservable blocks at equal HBM, and admission decisions flip on it."""

    def _reqs(self, rng=29):
        return [{"prompt": p, "max_tokens": 5} for p in _prompts(3, rng=rng)]

    def test_bf16_pools_bit_equal_to_dense_reference(self, params):
        reqs = self._reqs()
        ref = _by_prompt(
            _dense(params, cache_dtype="bfloat16").pump(
                [dict(r) for r in reqs]
            )
        )
        pre = _paged(params, cache_dtype="bfloat16")
        dec = _paged(params, cache_dtype="bfloat16")
        router = DisaggRouter(prefill=[pre], decode=[dec])
        done = router.pump([dict(r) for r in reqs])
        assert _by_prompt(done) == ref
        assert router.fallbacks == 0
        assert router.handoffs == len(reqs)

    @pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
    def test_same_dtype_router_matches_unified_engine(self, params, kv_dtype):
        """Quantized handoff injects raw block bytes + scales: the routed
        streams must be IDENTICAL to a unified engine of the same
        kv_dtype (deterministic), with zero re-prefill fallbacks."""
        reqs = self._reqs(rng=31)
        ref = _by_prompt(
            _paged(params, kv_dtype=kv_dtype).pump([dict(r) for r in reqs])
        )
        pre = _paged(params, kv_dtype=kv_dtype)
        dec = _paged(params, kv_dtype=kv_dtype)
        router = DisaggRouter(prefill=[pre], decode=[dec])
        incompat0 = REGISTRY.counter("tpu_disagg_fallback_total").value(
            reason="incompatible"
        )
        done = router.pump([dict(r) for r in reqs])
        assert _by_prompt(done) == ref
        assert router.fallbacks == 0
        assert router.handoffs == len(reqs)
        assert REGISTRY.counter("tpu_disagg_fallback_total").value(
            reason="incompatible"
        ) == incompat0

    @pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
    def test_quantized_divergence_from_float_is_bounded(self, params, kv_dtype):
        """Same seed, same prompts: quantized streams may drift from the
        float reference (lossy KV), but prompts echo intact and streams
        stay well-formed full-length generations."""
        reqs = FEATURES["greedy"]["reqs"]()  # the reference's prompt set
        ref = _reference(params, "greedy", None)
        got = _by_prompt(
            _paged(params, kv_dtype=kv_dtype).pump([dict(r) for r in reqs])
        )
        assert set(got) == {
            tuple(r["prompt"]) for r in reqs
        }  # prompts intact => keys align
        for prompt, gen in got.items():
            assert len(gen) == 5
            assert all(0 <= t < CFG.vocab_size for t in gen)
        # bounded divergence: at this tiny model most greedy tokens agree
        agree = sum(
            t1 == t2
            for p in got
            for t1, t2 in zip(got[p], ref[tuple(p)])
        )
        total = sum(len(g) for g in got.values())
        assert agree / total >= 0.5, (agree, total, got, ref)

    def test_cross_dtype_handoff_falls_back_to_reprefill(self, params):
        """int8 prefill -> float decode: geometry gate refuses the inject
        (the float pool cannot hold int8 bytes), the stream re-prefills
        and finishes EXACTLY like the float unified reference."""
        from k8s_dra_driver_tpu.models import serve as serve_mod

        reqs = FEATURES["greedy"]["reqs"]()  # the reference's prompt set
        pre = _paged(params, kv_dtype="int8")
        dec = _paged(params)
        router = DisaggRouter(prefill=[pre], decode=[dec])
        incompat0 = serve_mod._M_DISAGG_FALLBACK.value(reason="incompatible")
        done = router.pump([dict(r) for r in reqs])
        assert _by_prompt(done) == _reference(params, "greedy", None)
        assert serve_mod._M_DISAGG_FALLBACK.value(
            reason="incompatible"
        ) == incompat0 + len(reqs)

    def test_int8_capacity_reaches_the_admission_ledger(self, params):
        """THE acceptance assertion: at the same pool_hbm_bytes budget an
        int8 decode pool reports >= 1.9x reservable_blocks, the router's
        headroom sees those blocks, and a full-stream demand sized between
        the two pools is REFUSED by the bf16 router but ADMITTED by the
        int8 router — capacity flows budget -> blocks -> ledger ->
        admission decision."""
        hbm = 64 * paged.kv_block_bytes(CFG, 16, "bfloat16")
        engines = {}
        routers = {}
        for kd, cache in (("bf16", "bfloat16"), ("int8", "bfloat16")):
            dec = _paged(
                params,
                cache_dtype=cache,
                kv_dtype=None if kd == "bf16" else "int8",
                block_size=16,
                n_blocks=None,
                pool_hbm_bytes=hbm,
            )
            engines[kd] = dec
            routers[kd] = DisaggRouter(
                prefill=[_paged(params, block_size=16)], decode=[dec],
                admission_control=True,
            )
        assert engines["bf16"].pool_hbm_bytes == engines["int8"].pool_hbm_bytes
        lo = engines["bf16"].reservable_blocks
        hi = engines["int8"].reservable_blocks
        assert hi / lo >= 1.9, (hi, lo)
        # the ledger's headroom IS reservable_blocks while nothing is
        # committed
        assert routers["bf16"]._decode_headroom_blocks() == lo
        assert routers["int8"]._decode_headroom_blocks() == hi
        # a demand strictly between the two pools flips the decision
        mid_blocks = (lo + hi) // 2
        entry = {
            "request_id": 9001,
            "prompt_len": 4,
            "max_tokens": mid_blocks * 16 - 4,
            "tokens": [1, 2, 3, 4],
        }
        assert routers["bf16"]._admit_handoff({"entry": dict(entry)}) is False
        assert routers["int8"]._admit_handoff({"entry": dict(entry)}) is True
        # the admitted reservation is committed against the headroom
        assert routers["int8"]._decode_headroom_blocks() == hi - mid_blocks
        # and released again when the bf16 router refused
        assert routers["bf16"]._decode_headroom_blocks() == lo
