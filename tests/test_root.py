"""Driver-root discovery tests."""

import pytest

from k8s_dra_driver_tpu.plugin.root import DriverRoot, DriverRootError


class TestDriverRoot:
    def test_find_libtpu_under_chroot(self, tmp_path):
        (tmp_path / "usr/lib").mkdir(parents=True)
        (tmp_path / "usr/lib/libtpu.so").write_bytes(b"")
        root = DriverRoot(root=str(tmp_path))
        assert root.find_libtpu() == str(tmp_path / "usr/lib/libtpu.so")

    def test_probe_order_prefers_lib(self, tmp_path):
        for rel in ("lib", "usr/lib"):
            (tmp_path / rel).mkdir(parents=True)
            (tmp_path / rel / "libtpu.so").write_bytes(b"")
        assert DriverRoot(root=str(tmp_path)).find_libtpu() == str(
            tmp_path / "lib/libtpu.so"
        )

    def test_missing_libtpu_reports_probed_paths(self, tmp_path):
        with pytest.raises(DriverRootError, match="probed"):
            DriverRoot(root=str(tmp_path)).find_libtpu()

    def test_host_path_translation(self):
        root = DriverRoot(root="/driver-root", host_root="/")
        assert root.to_host_path("/driver-root/lib/libtpu.so") == "/lib/libtpu.so"
        assert root.to_host_path("/var/run/cdi/x.json") == "/var/run/cdi/x.json"
        nested = DriverRoot(root="/driver-root", host_root="/opt/tpu")
        assert nested.to_host_path("/driver-root/lib/libtpu.so") == "/opt/tpu/lib/libtpu.so"

    def test_device_nodes(self, tmp_path):
        (tmp_path / "dev").mkdir()
        for name in ("accel0", "accel1", "accelX", "accel"):
            (tmp_path / "dev" / name).write_bytes(b"")
        assert DriverRoot(root=str(tmp_path)).device_nodes() == [
            str(tmp_path / "dev/accel0"),
            str(tmp_path / "dev/accel1"),
        ]
