"""Fleet chaos suite: replica failure domains under injected faults.

The fleet twin of tests/test_serve_chaos.py — utils/faults.py's
REPLICA-scoped kinds (replica_crash, replica_wedge, stats_stale, scoped
per replica/tick) break one replica of a 3-replica FleetRouter
mid-decode, and these tests pin the PR's acceptance property:

    one replica killed mid-decode -> every in-flight stream completes
    BIT-EQUAL on the survivors, zero lost or duplicated completions,
    per-replica block accounting balanced (including the dead replica),
    and the whole evacuation observable under ONE journal correlation
    id spanning suspect -> snapshot -> restore -> resumed.

Plus the slower failure shapes: a wedged replica caught by the stalled-
burst detector, a frozen stats feed caught by the staleness detector,
and a quarantine storm escaping to healthy replicas.  Every fault draws
from a seeded injector: a failure replays from its seed.  Runs in
`make chaos-fleet` (<15s, CPU).
"""

import jax
import pytest

from k8s_dra_driver_tpu.models import burnin, paged
from k8s_dra_driver_tpu.models.fleet import (
    DRAINED,
    HEALTHY,
    FleetPolicy,
    FleetRouter,
)
from k8s_dra_driver_tpu.models.serve import ServeEngine
from k8s_dra_driver_tpu.utils.faults import FaultInjector, ReplicaCrash
from k8s_dra_driver_tpu.utils.journal import JOURNAL
from k8s_dra_driver_tpu.utils.metrics import REGISTRY, parse_prom_text

CFG = burnin.ModelConfig(
    vocab_size=64, d_model=32, n_heads=2, n_layers=1, d_ff=64, max_seq=64
)


@pytest.fixture(scope="module")
def params():
    return burnin.init_params(jax.random.PRNGKey(0), CFG)


def _dense(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("prompt_bucket", 16)
    return ServeEngine(params=params, cfg=CFG, **kw)


def _paged(params, **kw):
    kw.setdefault("n_slots", 3)
    kw.setdefault("n_blocks", 33)
    kw.setdefault("block_size", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("attn_impl", "xla")
    return paged.PagedServeEngine(params=params, cfg=CFG, **kw)


def _inj(spec: str) -> FaultInjector:
    return FaultInjector.from_env(spec)


# Explicit per-request seeds: replica-minted ids differ between a fleet
# run and the single-engine reference, so sampling keys must come from
# the request, never the id.
REQS = [
    {"prompt": [7, 8, 9], "max_tokens": 6, "seed": 5},
    {"prompt": [3, 4], "max_tokens": 6, "temperature": 0.7, "seed": 9},
    {"prompt": [11, 12, 13, 14], "max_tokens": 6, "seed": 21},
    {"prompt": [1, 2], "max_tokens": 6, "seed": 33},
    {"prompt": [21, 22, 23], "max_tokens": 6, "seed": 44},
]


def _by_prompt(completions, status="ok"):
    return {
        tuple(c.tokens[: len(c.tokens) - len(c.generated)]): tuple(c.generated)
        for c in completions
        if c.status == status
    }


@pytest.fixture(scope="module")
def reference(params):
    """Fault-free streams for REQS — the bit-equality baseline every
    evacuated stream must reproduce on its new replica."""
    return _by_prompt(_dense(params).pump([dict(r) for r in REQS]))


class TestReplicaFaultHooks:
    def test_from_env_parses_replica_kinds(self):
        inj = _inj(
            "replica_crash_rate=1.0,replica_wedge_rate=0.5,"
            "stats_stale_rate=0.25,replicas=0+2,steps=3,seed=7"
        )
        (p,) = inj._profiles
        assert p.replica_crash_rate == 1.0
        assert p.replica_wedge_rate == 0.5
        assert p.stats_stale_rate == 0.25
        assert p.replicas == (0, 2)
        assert p.steps == (3,)

    def test_replica_and_tick_scoping(self):
        inj = _inj("replica_crash_rate=1.0,replicas=1,steps=2")
        inj.maybe_crash_replica(0, 2)  # out of scope: silent
        inj.maybe_crash_replica(1, 3)
        with pytest.raises(ReplicaCrash) as exc:
            inj.maybe_crash_replica(1, 2)
        assert exc.value.replica == 1

    def test_wedge_and_stale_hooks_record_stats(self):
        inj = _inj("replica_wedge_rate=1.0,stats_stale_rate=1.0,replicas=0")
        assert inj.take_replica_wedge(0, 1)
        assert not inj.take_replica_wedge(1, 1)
        assert inj.take_stats_stale(0, 1)
        assert inj.stats().get("replica_wedge") == 1
        assert inj.stats().get("stats_stale") == 1

    def test_injection_budget_caps_replica_kinds(self):
        inj = FaultInjector(seed=0)
        from k8s_dra_driver_tpu.utils.faults import FaultProfile

        inj.arm(FaultProfile(name="once", replica_wedge_rate=1.0, limit=1))
        assert inj.take_replica_wedge(0, 1)
        assert not inj.take_replica_wedge(0, 2)


class TestCrashEvacuation:
    """The acceptance run: kill one of three replicas mid-decode."""

    @pytest.fixture()
    def crashed(self, params, reference):
        """3 mixed-kind replicas, replica 1 (paged) dies on router tick 2
        — after admission, mid-decode."""
        router = FleetRouter(
            [_dense(params), _paged(params), _dense(params)],
            fault_injector=_inj("replica_crash_rate=1.0,replicas=1,steps=2"),
        )
        pool0 = router.replicas[1].engine.free_blocks
        out = router.pump([dict(r) for r in REQS])
        return router, out, pool0

    def test_zero_lost_or_duplicated_streams(self, crashed, reference):
        router, out, _ = crashed
        assert len(out) == len(REQS)
        assert [c.status for c in out].count("ok") == len(REQS)
        rids = [c.request_id for c in out]
        assert len(rids) == len(set(rids)), "duplicated completion ids"
        # every stream bit-equal to the fault-free single-engine baseline
        assert _by_prompt(out) == reference

    def test_dead_replica_accounting_balances(self, crashed):
        router, _, pool0 = crashed
        dead = router.replicas[1]
        assert dead.state == DRAINED
        assert dead.engine.free_slots() == dead.engine.n_slots
        assert dead.engine.free_blocks == pool0  # every block refunded
        assert not dead.engine._preempted and not dead.engine._admitting
        # survivors drained their (evacuated) work and stayed healthy
        for rep in (router.replicas[0], router.replicas[2]):
            assert rep.state == HEALTHY
            assert rep.engine.free_slots() == rep.engine.n_slots
        assert not router._parked and not router._owner

    def test_breaker_tripped_open_immediately(self, crashed):
        router, _, _ = crashed
        assert router.replicas[1].breaker.state == "open"
        assert router.replicas[1].last_verdict == "replica_crash"

    def test_one_journal_correlation_spans_evacuation(self, params):
        JOURNAL.clear()
        router = FleetRouter(
            [_dense(params), _paged(params), _dense(params)],
            fault_injector=_inj("replica_crash_rate=1.0,replicas=1,steps=2"),
        )
        router.pump([dict(r) for r in REQS])
        events = JOURNAL.tail(limit=400, component="fleet")
        evac = [e for e in events if e["correlation"].startswith("evac-")]
        corrs = {e["correlation"] for e in evac}
        assert len(corrs) == 1, f"expected ONE evacuation correlation: {corrs}"
        kinds = [e["event"] for e in evac]
        # the full lifecycle under that single id
        for expected in (
            "replica.suspect", "replica.evacuating", "evac.snapshot",
            "evac.restore", "replica.drained", "evac.resumed",
        ):
            assert expected in kinds, f"missing {expected} in {kinds}"
        # ordering: suspect before snapshot before restore before resumed
        order = [kinds.index(k) for k in (
            "replica.suspect", "evac.snapshot", "evac.restore", "evac.resumed"
        )]
        assert order == sorted(order)

    def test_fleet_metrics_account_the_evacuation(self, crashed):
        router, _, _ = crashed
        doc = parse_prom_text(REGISTRY.render())
        states = doc["tpu_fleet_replicas"]
        assert states[(("state", "healthy"),)] == 2
        assert states[(("state", "drained"),)] == 1
        assert states[(("state", "suspect"),)] == 0
        assert doc["tpu_fleet_evacuations_total"][
            (("reason", "replica_crash"),)
        ] == 1
        assert doc["tpu_fleet_queue_depth"][()] == 0

    def test_crash_replays_from_seed(self, params):
        # Determinism of the chaos itself: same spec, same tick, same victim.
        for _ in range(2):
            inj = _inj("replica_crash_rate=1.0,replicas=1,steps=2,seed=13")
            with pytest.raises(ReplicaCrash) as exc:
                inj.maybe_crash_replica(1, 2)
            assert exc.value.replica == 1
            assert inj.stats().get("replica_crash") == 1


class TestWedgeEvacuation:
    def test_wedged_replica_detected_and_evacuated(self, params, reference):
        # Replica 0 hangs every tick (device never returns): the stalled-
        # burst detector must mark it suspect after stall_suspect_ticks,
        # open the breaker, and move its streams to the survivors.
        router = FleetRouter(
            [_dense(params), _dense(params)],
            fault_injector=_inj("replica_wedge_rate=1.0,replicas=0"),
        )
        out = router.pump([dict(r) for r in REQS])
        assert _by_prompt(out) == reference
        assert len(out) == len(REQS)
        assert router.replicas[0].state == DRAINED
        assert router.replicas[0].last_verdict == "wedged"
        doc = parse_prom_text(REGISTRY.render())
        assert doc["tpu_fleet_evacuations_total"][(("reason", "wedged"),)] == 1

    def test_wedge_policy_threshold_is_respected(self, params):
        # A higher stall threshold tolerates more hung ticks before the
        # verdict flips — the detector is policy, not hardcode.
        router = FleetRouter(
            [_dense(params), _dense(params)],
            policy=FleetPolicy(stall_suspect_ticks=10_000),
            fault_injector=_inj("replica_wedge_rate=1.0,replicas=0,limit=3"),
        )
        out = router.pump([dict(r) for r in REQS])
        # the wedge budget (limit=3) expires before the verdict threshold,
        # so the replica recovers and finishes its own streams
        assert len(out) == len(REQS)
        assert router.replicas[0].state == HEALTHY


class TestStaleStatsEvacuation:
    def test_frozen_stats_feed_gates_replica(self, params, reference):
        # Replica 1's stats() reads come from the router's stale cache:
        # uptime stops advancing, the staleness detector marks it suspect
        # (the router cannot CONFIRM health — rosy old numbers must not
        # keep attracting traffic), and its streams evacuate.
        # longer streams than REQS: the staleness detector (3 ticks) plus
        # the breaker (3 verdicts) need ~6 ticks of live decode to converge
        reqs = [{**r, "max_tokens": 12} for r in REQS]
        baseline = _by_prompt(_dense(params).pump([dict(r) for r in reqs]))
        router = FleetRouter(
            [_dense(params), _dense(params)],
            fault_injector=_inj("stats_stale_rate=1.0,replicas=1"),
        )
        out = router.pump([dict(r) for r in reqs])
        assert _by_prompt(out) == baseline
        assert router.replicas[1].state == DRAINED
        assert router.replicas[1].last_verdict == "stats_stale"
        doc = parse_prom_text(REGISTRY.render())
        assert doc["tpu_fleet_evacuations_total"][
            (("reason", "stats_stale"),)
        ] == 1


class TestQuarantineStormEscape:
    def test_storm_evacuates_survivors(self, params, reference):
        # Replica 0's ENGINE quarantines two poisoned slots (engine-scoped
        # nan_logits) — under quarantine_suspect=2 the router reads the
        # storm from EngineStats and evacuates the replica's HEALTHY
        # streams before the engine hits its own poison limit.
        router = FleetRouter(
            [
                _dense(
                    params, quarantine_limit=3,
                    fault_injector=_inj("nan_logits_rate=1.0,slots=0+1,steps=2"),
                ),
                _dense(params),
            ],
        )
        out = router.pump([dict(r) for r in REQS])
        assert len(out) == len(REQS)
        quarantined = [c for c in out if c.status == "quarantined"]
        assert len(quarantined) == 2
        assert all("non-finite" in c.error for c in quarantined)
        # every stream that was NOT poisoned finishes bit-equal
        ok = _by_prompt(out)
        assert ok == {p: g for p, g in reference.items() if p in ok}
        assert len(ok) == len(REQS) - 2
        assert router.replicas[0].state == DRAINED
        assert router.replicas[0].last_verdict == "quarantine_storm"
