"""Group-committed checkpoint crash-recovery semantics (the PR-2 acceptance
criterion): one durable write per NodePrepare/NodeUnprepareResources batch
must preserve the invariant that kubelet never sees success for state the
checkpoint does not cover.

Covered crash windows:
* process dies MID-BATCH (after prepares, before commit) — restart must
  show zero phantom prepared entries, orphan CDI specs must be cleanable,
  and a full re-prepare of every claim in the batch must succeed;
* commit WRITE fails — the batch unwinds (memory + disk artifacts), every
  claim reports an error so kubelet retries, and the retry converges;
* unprepare commit fails — entries are restored so the retry re-runs the
  idempotent teardown.
"""

import pytest

from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
from k8s_dra_driver_tpu.plugin.driver import ClaimRef, Driver, DriverConfig
from k8s_dra_driver_tpu.utils.metrics import REGISTRY


@pytest.fixture
def rig(tmp_path):
    cluster = make_cluster(hosts=1, topology="v5e-8", work_dir=str(tmp_path))
    cfg = DriverConfig(
        node_name="tpu-host-0",
        cdi_root=str(tmp_path / "gc-cdi"),
        checkpoint_path=str(tmp_path / "gc-checkpoint.json"),
        topology_env={"TPUINFO_FAKE_TOPOLOGY": "v5e-8", "TPUINFO_FAKE_HOST_ID": "0"},
        publish=False,
    )
    return cluster, cfg, Driver(cluster.server, cfg)


def allocate_refs(cluster, n, prefix="gc"):
    refs = []
    for i in range(n):
        claim = cluster.server.create(simple_claim(f"{prefix}-{i}"))
        allocated = cluster.allocator.allocate(
            claim, node_name="tpu-host-0",
            node_labels=cluster.node_labels("tpu-host-0"),
        )
        refs.append(
            ClaimRef(uid=allocated.metadata.uid, name=claim.metadata.name,
                     namespace="default")
        )
    return refs


class TestCrashMidBatch:
    def test_restart_recovers_cleanly_and_reprepares(self, rig):
        cluster, cfg, driver = rig
        refs = allocate_refs(cluster, 4)
        # Batch begun, claims prepared, commit NEVER runs: the process
        # "dies" between the last prepare and the durable write.
        driver.state.begin_checkpoint_batch()
        for ref in refs:
            claim = cluster.server.get("ResourceClaim", ref.name, "default")
            driver.state.prepare(claim)
        assert len(driver.state.prepared) == 4
        # CDI claim specs already hit disk (crash window artifact).
        assert len(driver.state.cdi.list_claim_spec_uids()) == 4

        restarted = Driver(cluster.server, cfg)  # restores from checkpoint
        # No phantom prepared entries: the checkpoint never saw the batch.
        assert restarted.state.prepared == {}
        # The crash residue is exactly what cleanup_orphans exists for.
        cleaned = restarted.cleanup_orphans()
        assert sorted(cleaned["cdi_specs"]) == sorted(r.uid for r in refs)
        assert restarted.state.cdi.list_claim_spec_uids() == []
        # Kubelet retries the whole batch: every claim re-prepares cleanly.
        out = restarted.node_prepare_resources(refs)
        assert all(not r.error for r in out.values())
        assert sorted(restarted.state.prepared) == sorted(r.uid for r in refs)
        # And THIS time the state is durable.
        rebooted = Driver(cluster.server, cfg)
        assert sorted(rebooted.state.prepared) == sorted(r.uid for r in refs)

    def test_committed_batch_survives_restart(self, rig):
        cluster, cfg, driver = rig
        refs = allocate_refs(cluster, 3)
        writes = REGISTRY.counter("dra_checkpoint_writes_total")
        w0 = writes.value()
        out = driver.node_prepare_resources(refs)
        assert all(not r.error for r in out.values())
        assert writes.value() == w0 + 1  # ONE durable write for the batch
        restarted = Driver(cluster.server, cfg)
        assert sorted(restarted.state.prepared) == sorted(r.uid for r in refs)


class TestCommitFailure:
    def test_prepare_commit_failure_unwinds_and_errors_all(self, rig, monkeypatch):
        cluster, cfg, driver = rig
        refs = allocate_refs(cluster, 3)

        def boom(prepared_claims):
            raise OSError("disk full")

        monkeypatch.setattr(driver.state._checkpoint, "write", boom)
        out = driver.node_prepare_resources(refs)
        # Success without durability is forbidden: every claim errors.
        assert all("checkpoint commit failed" in r.error for r in out.values())
        # The batch unwound completely: no in-memory entries, no disk
        # artifacts, no phantom state for a restart to resurrect.
        assert driver.state.prepared == {}
        assert driver.state.cdi.list_claim_spec_uids() == []
        assert Driver(cluster.server, cfg).state.prepared == {}

        monkeypatch.undo()  # disk recovers; the kubelet retry converges
        out = driver.node_prepare_resources(refs)
        assert all(not r.error for r in out.values())
        assert sorted(driver.state.prepared) == sorted(r.uid for r in refs)

    def test_unprepare_commit_failure_restores_entries(self, rig, monkeypatch):
        cluster, cfg, driver = rig
        refs = allocate_refs(cluster, 3)
        out = driver.node_prepare_resources(refs)
        assert all(not r.error for r in out.values())

        def boom(prepared_claims):
            raise OSError("disk full")

        monkeypatch.setattr(driver.state._checkpoint, "write", boom)
        out = driver.node_unprepare_resources(refs)
        assert all("checkpoint commit failed" in r.error for r in out.values())
        # Entries restored: no lost prepared state, the on-disk checkpoint
        # (still the pre-batch one) agrees with memory.
        assert sorted(driver.state.prepared) == sorted(r.uid for r in refs)

        monkeypatch.undo()
        out = driver.node_unprepare_resources(refs)  # idempotent teardown
        assert all(not r.error for r in out.values())
        assert driver.state.prepared == {}
        assert Driver(cluster.server, cfg).state.prepared == {}


class TestDirectPathUnchanged:
    def test_prepare_outside_batch_writes_immediately(self, rig):
        """The harness/tests path (DeviceState.prepare with no batch) keeps
        per-call durability — group commit is opt-in per gRPC call."""
        cluster, cfg, driver = rig
        refs = allocate_refs(cluster, 1)
        writes = REGISTRY.counter("dra_checkpoint_writes_total")
        w0 = writes.value()
        claim = cluster.server.get("ResourceClaim", refs[0].name, "default")
        driver.state.prepare(claim)
        assert writes.value() == w0 + 1
        assert Driver(cluster.server, cfg).state.prepared == {refs[0].uid: driver.state.prepared[refs[0].uid]}
