"""Host-sharded input pipeline (models/data.py)."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from k8s_dra_driver_tpu.models import burnin
from k8s_dra_driver_tpu.models.data import TokenBatches
from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
from tests.conftest import cpu_devices


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(cpu_devices(8), MeshShape(data=4, seq=1, model=2))


def dataset(n=64, s=16):
    return np.arange(n * s, dtype=np.int32).reshape(n, s) % 97


class TestTokenBatches:
    def test_batches_are_sharded_and_cover_the_epoch(self, mesh):
        data = dataset()
        tb = TokenBatches(data, batch_size=8, mesh=mesh)
        assert tb.steps_per_epoch == 8
        seen = []
        for batch in tb.epoch(0):
            assert batch.shape == (8, 16)
            assert batch.sharding.spec == P("data", None)
            seen.append(np.asarray(batch))
        got = np.concatenate(seen)
        # every dataset row appears exactly once per epoch
        assert got.shape == data.shape
        np.testing.assert_array_equal(
            np.sort(got, axis=0), np.sort(data, axis=0)
        )

    def test_epochs_are_deterministic_and_distinct(self, mesh):
        data = dataset()
        a = [np.asarray(b) for b in TokenBatches(data, 8, mesh, seed=5).epoch(1)]
        b = [np.asarray(b) for b in TokenBatches(data, 8, mesh, seed=5).epoch(1)]
        c = [np.asarray(b) for b in TokenBatches(data, 8, mesh, seed=5).epoch(2)]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)  # replayable (resume)
        assert any(not np.array_equal(x, y) for x, y in zip(a, c))  # reshuffled

    def test_remainder_rows_dropped(self, mesh):
        tb = TokenBatches(dataset(n=20), batch_size=8, mesh=mesh)
        assert tb.steps_per_epoch == 2  # 20 // 8, 4 rows dropped (static shapes)

    def test_validation(self, mesh):
        with pytest.raises(ValueError, match="not divisible"):
            TokenBatches(dataset(), batch_size=6, mesh=mesh)  # data axis = 4
        with pytest.raises(ValueError, match="< one batch"):
            TokenBatches(dataset(n=4), batch_size=8, mesh=mesh)
        with pytest.raises(ValueError, match="positive"):
            TokenBatches(dataset(), batch_size=0, mesh=mesh)

    def test_feeds_the_sharded_train_step(self, mesh):
        cfg = burnin.TINY
        fns = burnin.build_train_step(cfg, mesh=mesh)
        data = np.asarray(
            burnin.sample_tokens(jax.random.PRNGKey(1), cfg, batch=32, seq=32)
        )
        tb = TokenBatches(data, batch_size=8, mesh=mesh)
        with mesh:
            params, opt_state = fns.init(jax.random.PRNGKey(0))
            for batch in tb.epoch(0):
                params, opt_state, loss = fns.step(params, opt_state, batch)
                break
        assert np.isfinite(float(loss))


def test_unknown_data_axis_is_a_value_error(mesh=None):
    from k8s_dra_driver_tpu.parallel.mesh import MeshShape, build_mesh
    from tests.conftest import cpu_devices

    m = build_mesh(cpu_devices(8), MeshShape(data=4, seq=1, model=2))
    with pytest.raises(ValueError, match="not in mesh axes"):
        TokenBatches(dataset(), batch_size=8, mesh=m, data_axis="dp")
