"""Scheduler extender: filter/prioritize/bind over the real HTTP wire.

The service is the SURVEY §3.5 escape hatch — upstream-scheduler semantics
(CEL + markers) delegated to the structured allocator via the
kube-scheduler extender webhook protocol.  Tests drive it end-to-end with
urllib against a multi-host fake cluster.
"""

import json
import urllib.request

import pytest

from k8s_dra_driver_tpu.e2e.harness import make_cluster, simple_claim
from k8s_dra_driver_tpu.kube.objects import ObjectMeta, Pod, ResourceClaim
from k8s_dra_driver_tpu.scheduler.extender import SchedulerExtender


def _post(port: int, verb: str, body: dict):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{verb}",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


def _pod(server, name: str, claim_refs: list[dict]) -> dict:
    """Create the Pod object and return its extender-wire dict."""
    server.create(
        Pod(
            metadata=ObjectMeta(name=name, namespace="default", uid=f"uid-{name}"),
            spec={"resourceClaims": claim_refs},
        )
    )
    return {
        "metadata": {"name": name, "namespace": "default", "uid": f"uid-{name}"},
        "spec": {"resourceClaims": claim_refs},
    }


@pytest.fixture
def cluster(tmp_path):
    return make_cluster(hosts=2, topology="v5e-16", work_dir=str(tmp_path))


@pytest.fixture
def extender(cluster):
    ext = SchedulerExtender(cluster.server)
    ext.start()
    yield ext
    ext.stop()


NODES = ["tpu-host-0", "tpu-host-1"]


class TestFilter:
    def test_all_nodes_feasible(self, cluster, extender):
        cluster.server.create(simple_claim("c1"))
        pod = _pod(cluster.server, "p1", [{"name": "tpu", "resourceClaimName": "c1"}])
        out = _post(extender.port, "filter", {"pod": pod, "nodenames": NODES})
        assert out["nodenames"] == NODES
        assert out["failedNodes"] == {}
        assert out["error"] == ""

    def test_exhausted_node_fails_with_reason(self, cluster, extender):
        # consume ALL of host-0's chips (4 chips per fake host)
        blocker = cluster.server.create(simple_claim("blocker", count=4))
        cluster.allocator.allocate(
            blocker, node_name="tpu-host-0",
            node_labels=cluster.node_labels("tpu-host-0"),
        )
        cluster.server.create(simple_claim("c1", count=4))
        pod = _pod(cluster.server, "p1", [{"name": "tpu", "resourceClaimName": "c1"}])
        out = _post(extender.port, "filter", {"pod": pod, "nodenames": NODES})
        assert out["nodenames"] == ["tpu-host-1"]
        assert "cannot satisfy" in out["failedNodes"]["tpu-host-0"]

    def test_allocated_shared_claim_pins_node(self, cluster, extender):
        """gpu-test3 pattern: pod 2 of a shared claim only fits where the
        claim already landed."""
        shared = cluster.server.create(simple_claim("shared"))
        cluster.allocator.allocate(
            shared, node_name="tpu-host-1",
            node_labels=cluster.node_labels("tpu-host-1"),
        )
        pod = _pod(cluster.server, "p2", [{"name": "tpu", "resourceClaimName": "shared"}])
        out = _post(extender.port, "filter", {"pod": pod, "nodenames": NODES})
        assert out["nodenames"] == ["tpu-host-1"]
        assert "already allocated" in out["failedNodes"]["tpu-host-0"]

    def test_full_node_objects_carry_labels(self, cluster, extender):
        cluster.server.create(simple_claim("c1"))
        pod = _pod(cluster.server, "p1", [{"name": "tpu", "resourceClaimName": "c1"}])
        nodes = {
            "items": [
                {"metadata": {"name": n, "labels": {"kubernetes.io/hostname": n}}}
                for n in NODES
            ]
        }
        out = _post(extender.port, "filter", {"pod": pod, "nodes": nodes})
        assert out["nodenames"] == NODES

    def test_podless_claimless_pod_passes_everywhere(self, cluster, extender):
        pod = _pod(cluster.server, "p1", [])
        out = _post(extender.port, "filter", {"pod": pod, "nodenames": NODES})
        assert out["nodenames"] == NODES

    def test_template_claim_naming(self, cluster, extender):
        """A template ref resolves to <pod>-<ref-name> (THE naming rule)."""
        cluster.server.create(simple_claim("p1-tpu"))
        pod = _pod(
            cluster.server, "p1", [{"name": "tpu", "resourceClaimTemplateName": "t"}]
        )
        out = _post(extender.port, "filter", {"pod": pod, "nodenames": NODES})
        assert out["nodenames"] == NODES

    def test_full_nodes_request_gets_nodes_reply(self, cluster, extender):
        """A scheduler without nodeCacheCapable reads result.Nodes — the
        reply must echo a filtered NodeList, not just nodenames."""
        blocker = cluster.server.create(simple_claim("blocker", count=4))
        cluster.allocator.allocate(
            blocker, node_name="tpu-host-0",
            node_labels=cluster.node_labels("tpu-host-0"),
        )
        cluster.server.create(simple_claim("c1", count=4))
        pod = _pod(cluster.server, "p1", [{"name": "tpu", "resourceClaimName": "c1"}])
        nodes = {
            "items": [
                {"metadata": {"name": n, "labels": {"kubernetes.io/hostname": n}}}
                for n in NODES
            ]
        }
        out = _post(extender.port, "filter", {"pod": pod, "nodes": nodes})
        kept = [n["metadata"]["name"] for n in out["nodes"]["items"]]
        assert kept == ["tpu-host-1"]

    def test_jointly_infeasible_multi_claim_pod_fails_filter(self, cluster, extender):
        """Two claims that each fit alone but not together must fail the
        node at FILTER time, not livelock at bind (claims planned jointly:
        later searches exclude earlier plans' devices)."""
        cluster.server.create(simple_claim("a", count=3))
        cluster.server.create(simple_claim("b", count=3))
        pod = _pod(
            cluster.server,
            "p1",
            [
                {"name": "x", "resourceClaimName": "a"},
                {"name": "y", "resourceClaimName": "b"},
            ],
        )
        out = _post(extender.port, "filter", {"pod": pod, "nodenames": NODES})
        assert out["nodenames"] == []  # 3+3 > 4 chips on every host
        assert set(out["failedNodes"]) == set(NODES)


class TestPrioritize:
    def test_most_allocated_wins(self, cluster, extender):
        """The fuller node scores higher: small claims densify broken
        geometry instead of fragmenting a pristine host."""
        warm = cluster.server.create(simple_claim("warm", count=3))
        cluster.allocator.allocate(
            warm, node_name="tpu-host-0",
            node_labels=cluster.node_labels("tpu-host-0"),
        )
        cluster.server.create(simple_claim("c1"))
        pod = _pod(cluster.server, "p1", [{"name": "tpu", "resourceClaimName": "c1"}])
        out = _post(extender.port, "prioritize", {"pod": pod, "nodenames": NODES})
        scores = {e["host"]: e["score"] for e in out}
        assert scores["tpu-host-0"] > scores["tpu-host-1"]

    def test_missing_claim_still_returns_a_list(self, cluster, extender):
        """HostPriorityList is the wire type even on errors: a pod whose
        template claim isn't instantiated yet scores 0 everywhere instead
        of breaking the scheduler-side unmarshal with an error object."""
        pod = _pod(cluster.server, "p1", [{"name": "t", "resourceClaimName": "nope"}])
        out = _post(extender.port, "prioritize", {"pod": pod, "nodenames": NODES})
        assert isinstance(out, list)
        assert [e["score"] for e in out] == [0, 0]

    def test_infeasible_scores_zero(self, cluster, extender):
        blocker = cluster.server.create(simple_claim("blocker", count=4))
        cluster.allocator.allocate(
            blocker, node_name="tpu-host-0",
            node_labels=cluster.node_labels("tpu-host-0"),
        )
        cluster.server.create(simple_claim("c1", count=2))
        pod = _pod(cluster.server, "p1", [{"name": "tpu", "resourceClaimName": "c1"}])
        out = _post(extender.port, "prioritize", {"pod": pod, "nodenames": NODES})
        scores = {e["host"]: e["score"] for e in out}
        assert scores["tpu-host-0"] == 0
        assert scores["tpu-host-1"] > 0


class TestBind:
    def test_bind_allocates_reserves_and_pins(self, cluster, extender):
        cluster.server.create(simple_claim("c1"))
        _pod(cluster.server, "p1", [{"name": "tpu", "resourceClaimName": "c1"}])
        out = _post(
            extender.port,
            "bind",
            {"podName": "p1", "podNamespace": "default", "podUID": "uid-p1",
             "node": "tpu-host-0"},
        )
        assert out["error"] == ""
        claim = cluster.server.get(ResourceClaim.KIND, "c1", "default")
        assert claim.status.allocation is not None
        assert [r.uid for r in claim.status.reserved_for] == ["uid-p1"]
        pod = cluster.server.get(Pod.KIND, "p1", "default")
        assert pod.metadata.labels["_scheduled_node"] == "tpu-host-0"
        assert pod.spec["nodeName"] == "tpu-host-0"
        # bound pod tears down through the standard lifecycle
        cluster.delete_pod("p1")
        claim = cluster.server.get(ResourceClaim.KIND, "c1", "default")
        assert claim.status.allocation is None

    def test_bind_failure_compensates(self, cluster, extender):
        """Two claims, second unsatisfiable: the first must be rolled back
        (unreserved AND deallocated) — no partial scheduling state."""
        cluster.server.create(simple_claim("ok-claim"))
        cluster.server.create(simple_claim("too-big", count=8))
        _pod(
            cluster.server,
            "p1",
            [
                {"name": "a", "resourceClaimName": "ok-claim"},
                {"name": "b", "resourceClaimName": "too-big"},
            ],
        )
        out = _post(
            extender.port,
            "bind",
            {"podName": "p1", "podNamespace": "default", "podUID": "uid-p1",
             "node": "tpu-host-0"},
        )
        assert "cannot satisfy" in out["error"]
        claim = cluster.server.get(ResourceClaim.KIND, "ok-claim", "default")
        assert claim.status.allocation is None
        assert not claim.status.reserved_for

    def test_bind_refuses_node_away_from_shared_allocation(self, cluster, extender):
        """Race: both pods of a shared claim pass filter while it is
        unallocated; pod 1 binds on host-0 (allocating there).  Pod 2's
        bind to host-1 must REFUSE — allocate's idempotent early-return
        would otherwise strand pod 2 away from the claim's devices."""
        cluster.server.create(simple_claim("shared"))
        _pod(cluster.server, "p1", [{"name": "t", "resourceClaimName": "shared"}])
        _pod(cluster.server, "p2", [{"name": "t", "resourceClaimName": "shared"}])
        out = _post(
            extender.port, "bind",
            {"podName": "p1", "podNamespace": "default", "podUID": "uid-p1",
             "node": "tpu-host-0"},
        )
        assert out["error"] == ""
        out = _post(
            extender.port, "bind",
            {"podName": "p2", "podNamespace": "default", "podUID": "uid-p2",
             "node": "tpu-host-1"},
        )
        assert "already allocated" in out["error"]
        claim = cluster.server.get(ResourceClaim.KIND, "shared", "default")
        assert [r.uid for r in claim.status.reserved_for] == ["uid-p1"]  # no p2 residue

    def test_bind_unknown_pod_errors(self, cluster, extender):
        out = _post(
            extender.port,
            "bind",
            {"podName": "ghost", "podNamespace": "default", "podUID": "u",
             "node": "tpu-host-0"},
        )
        assert "ghost" in out["error"]

    def test_bind_shared_claim_second_pod(self, cluster, extender):
        """Second consumer of an allocated claim: reserve only, claim
        survives the first pod's teardown until the last consumer goes."""
        cluster.server.create(simple_claim("shared"))
        _pod(cluster.server, "p1", [{"name": "t", "resourceClaimName": "shared"}])
        _pod(cluster.server, "p2", [{"name": "t", "resourceClaimName": "shared"}])
        for pod_name in ("p1", "p2"):
            out = _post(
                extender.port,
                "bind",
                {"podName": pod_name, "podNamespace": "default",
                 "podUID": f"uid-{pod_name}", "node": "tpu-host-0"},
            )
            assert out["error"] == ""
        claim = cluster.server.get(ResourceClaim.KIND, "shared", "default")
        assert len(claim.status.reserved_for) == 2
        cluster.delete_pod("p1")
        claim = cluster.server.get(ResourceClaim.KIND, "shared", "default")
        assert claim.status.allocation is not None  # p2 still consuming
        cluster.delete_pod("p2")
        claim = cluster.server.get(ResourceClaim.KIND, "shared", "default")
        assert claim.status.allocation is None


class TestWire:
    def test_bad_json_is_400(self, extender):
        req = urllib.request.Request(
            f"http://127.0.0.1:{extender.port}/filter",
            data=b"{not json",
            method="POST",
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 400

    def test_unknown_verb_is_404(self, extender):
        req = urllib.request.Request(
            f"http://127.0.0.1:{extender.port}/preempt", data=b"{}", method="POST"
        )
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 404

    def test_missing_claim_reports_error_body(self, cluster, extender):
        pod = _pod(cluster.server, "p1", [{"name": "t", "resourceClaimName": "nope"}])
        out = _post(extender.port, "filter", {"pod": pod, "nodenames": NODES})
        assert "error" in out and out["error"] != ""

    def test_tls_serves_https(self, cluster, tmp_path):
        """extenderTLSSecret path: with a cert/key pair the webhook serves
        HTTPS (scheduler policy enableHTTPS: true) — the advisor's mitigation
        for /bind mutating cluster state over plaintext."""
        import ssl
        import subprocess

        cert, key = tmp_path / "tls.crt", tmp_path / "tls.key"
        subprocess.run(
            [
                "openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
                "-keyout", str(key), "-out", str(cert), "-days", "1",
                "-subj", "/CN=127.0.0.1",
            ],
            check=True, capture_output=True, timeout=60,
        )
        ext = SchedulerExtender(
            cluster.server, tls_cert=str(cert), tls_key=str(key)
        )
        assert ext.scheme == "https"
        ext.start()
        try:
            ctx = ssl.create_default_context(cafile=str(cert))
            ctx.check_hostname = False
            req = urllib.request.Request(
                f"https://127.0.0.1:{ext.port}/filter",
                data=json.dumps(
                    {"pod": {"metadata": {"name": "p", "namespace": "default"},
                             "spec": {}},
                     "nodenames": NODES}
                ).encode(),
                method="POST",
            )
            out = json.loads(
                urllib.request.urlopen(req, timeout=10, context=ctx).read()
            )
            assert out["nodenames"] == NODES

            # A bare TCP client that connects and sends nothing must NOT
            # wedge the accept loop (handshake is deferred to the handler
            # thread): a real TLS request issued while the silent client is
            # still connected has to succeed.
            import socket as socketlib

            silent = socketlib.create_connection(("127.0.0.1", ext.port))
            try:
                out2 = json.loads(
                    urllib.request.urlopen(req, timeout=10, context=ctx).read()
                )
                assert out2["nodenames"] == NODES
            finally:
                silent.close()
        finally:
            ext.stop()

    def test_half_specified_tls_fails_closed(self, cluster, tmp_path):
        """Cert without key (or vice versa) must raise — never silently
        serve the mutating /bind verb over plain HTTP."""
        cert = tmp_path / "tls.crt"
        cert.write_text("not-even-read")
        with pytest.raises(ValueError, match="BOTH"):
            SchedulerExtender(cluster.server, tls_cert=str(cert))
        with pytest.raises(ValueError, match="BOTH"):
            SchedulerExtender(cluster.server, tls_key=str(cert))
