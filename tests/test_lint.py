"""First-party linter (tools/lint.py) — the golangci-lint slot.

Unit-tests each check on synthetic sources, then self-enforces: the repo
itself must lint clean (reference runs 9 linters on every PR,
.github/workflows/golang.yaml:27-49)."""

import sys
from pathlib import Path

REPO = Path(__file__).parent.parent
sys.path.insert(0, str(REPO / "tools"))

import lint  # noqa: E402


def findings_for(tmp_path, source):
    f = tmp_path / "case.py"
    f.write_text(source)
    return [x.check for x in lint.check_file(f)]


class TestChecks:
    def test_unused_import_flagged(self, tmp_path):
        assert findings_for(tmp_path, "import os\nimport sys\nprint(sys.path)\n") == [
            "unused-import"
        ]

    def test_used_import_clean(self, tmp_path):
        assert findings_for(tmp_path, "import os\nprint(os.sep)\n") == []

    def test_string_annotation_counts_as_use(self, tmp_path):
        src = "import numpy as np\n\ndef f(x: 'np.ndarray'):\n    return x\n"
        assert findings_for(tmp_path, src) == []

    def test_mutable_default(self, tmp_path):
        assert findings_for(tmp_path, "def f(x=[]):\n    return x\n") == [
            "mutable-default"
        ]
        assert findings_for(tmp_path, "def f(x=dict()):\n    return x\n") == [
            "mutable-default"
        ]

    def test_bare_except(self, tmp_path):
        src = "try:\n    pass\nexcept:\n    pass\n"
        assert findings_for(tmp_path, src) == ["bare-except"]
        src_ok = "try:\n    pass\nexcept Exception:\n    pass\n"
        assert findings_for(tmp_path, src_ok) == []

    def test_fstring_without_placeholder(self, tmp_path):
        assert findings_for(tmp_path, "x = f'plain'\n") == ["fstring-no-field"]
        assert findings_for(tmp_path, "y = 1\nx = f'{y}'\n") == []
        # implicit concatenation where ANY part has a field is fine
        assert findings_for(tmp_path, "y = 1\nx = f'a ' f'{y}'\n") == []

    def test_none_compare(self, tmp_path):
        assert findings_for(tmp_path, "x = 1\nprint(x == None)\n") == ["none-compare"]
        assert findings_for(tmp_path, "x = 1\nprint(x is None)\n") == []

    def test_duplicate_def_in_class(self, tmp_path):
        src = "class A:\n    def m(self): pass\n    def m(self): pass\n"
        assert findings_for(tmp_path, src) == ["duplicate-def"]

    def test_branch_scoped_redefinition_in_function_ok(self, tmp_path):
        src = (
            "def outer(flag):\n"
            "    if flag:\n"
            "        def inner(): return 1\n"
            "        return inner\n"
            "    def inner(): return 2\n"
            "    return inner\n"
        )
        assert findings_for(tmp_path, src) == []

    def test_property_setter_not_flagged(self, tmp_path):
        src = (
            "class A:\n"
            "    @property\n"
            "    def x(self): return 1\n"
            "    @x.setter\n"
            "    def x(self, v): pass\n"
        )
        assert findings_for(tmp_path, src) == []

    def test_ignore_pragma(self, tmp_path):
        src = "import os  # lint: ignore[unused-import]\n"
        assert findings_for(tmp_path, src) == []

    def test_skip_file_pragma(self, tmp_path):
        src = "# lint: skip-file\nimport os\n"
        assert findings_for(tmp_path, src) == []

    def test_syntax_error_reported_not_raised(self, tmp_path):
        assert findings_for(tmp_path, "def broken(:\n") == ["syntax"]


class TestMetricHygiene:
    def test_counter_without_total_flagged(self, tmp_path):
        src = "r.counter('dra_allocations', 'help text')\n"
        assert findings_for(tmp_path, src) == ["metric-hygiene"]

    def test_counter_with_total_clean(self, tmp_path):
        src = "r.counter('dra_allocations_total', 'help text')\n"
        assert findings_for(tmp_path, src) == []

    def test_gauge_claiming_total_flagged(self, tmp_path):
        src = "r.gauge('dra_devices_total', 'help text')\n"
        assert findings_for(tmp_path, src) == ["metric-hygiene"]

    def test_histogram_needs_unit_suffix(self, tmp_path):
        src = "r.histogram('dra_prepare_latency', 'help text')\n"
        assert findings_for(tmp_path, src) == ["metric-hygiene"]
        for ok in ("_seconds", "_bytes", "_tokens"):
            src = f"r.histogram('dra_prepare{ok}', 'help text')\n"
            assert findings_for(tmp_path, src) == []

    def test_non_snake_case_flagged(self, tmp_path):
        src = "r.counter('DraErrors_total', 'help text')\n"
        assert findings_for(tmp_path, src) == ["metric-hygiene"]

    def test_explicit_empty_help_flagged(self, tmp_path):
        src = "r.counter('dra_errors_total', '')\n"
        assert findings_for(tmp_path, src) == ["metric-hygiene"]

    def test_omitted_help_is_lookup_idiom(self, tmp_path):
        # No help argument = look up the existing metric; never flagged.
        src = "r.counter('dra_errors_total')\n"
        assert findings_for(tmp_path, src) == []

    def test_help_keyword_checked(self, tmp_path):
        src = "r.gauge('dra_devices', help='')\n"
        assert findings_for(tmp_path, src) == ["metric-hygiene"]

    def test_non_metric_calls_ignored(self, tmp_path):
        # .counter() on arbitrary objects with non-string args is not ours.
        src = "x = 1\nfoo.counter(x)\n"
        assert findings_for(tmp_path, src) == []

    def test_ignore_pragma_applies(self, tmp_path):
        src = "r.counter('weird', 'h')  # lint: ignore[metric-hygiene]\n"
        assert findings_for(tmp_path, src) == []


class TestSleepRetry:
    RETRY_LOOP = (
        "import time\n"
        "while True:\n"
        "    try:\n"
        "        connect()\n"
        "        break\n"
        "    except OSError:\n"
        "        time.sleep(1.0)\n"
    )

    def test_sleep_in_retry_loop_flagged(self, tmp_path):
        assert findings_for(tmp_path, self.RETRY_LOOP) == ["sleep-retry"]

    def test_for_loop_variant_flagged(self, tmp_path):
        src = (
            "import time\n"
            "def dial(n):\n"
            "    for _ in range(n):\n"
            "        try:\n"
            "            return connect()\n"
            "        except OSError:\n"
            "            time.sleep(0.5)\n"
        )
        assert findings_for(tmp_path, src) == ["sleep-retry"]

    def test_sleep_without_exception_handling_clean(self, tmp_path):
        # A poll/pace loop that handles no errors is not a retry loop.
        src = (
            "import time\n"
            "while busy():\n"
            "    time.sleep(0.1)\n"
        )
        assert findings_for(tmp_path, src) == []

    def test_sleep_outside_loop_clean(self, tmp_path):
        src = (
            "import time\n"
            "try:\n"
            "    connect()\n"
            "except OSError:\n"
            "    time.sleep(1.0)\n"
        )
        assert findings_for(tmp_path, src) == []

    def test_retry_module_exempt(self, tmp_path):
        d = tmp_path / "utils"
        d.mkdir()
        f = d / "retry.py"
        f.write_text(self.RETRY_LOOP)
        assert [x.check for x in lint.check_file(f)] == []

    def test_nested_loops_report_once(self, tmp_path):
        src = (
            "import time\n"
            "while True:\n"
            "    for _ in range(3):\n"
            "        try:\n"
            "            connect()\n"
            "        except OSError:\n"
            "            time.sleep(1.0)\n"
        )
        assert findings_for(tmp_path, src) == ["sleep-retry"]

    def test_ignore_pragma_applies(self, tmp_path):
        src = self.RETRY_LOOP.replace(
            "time.sleep(1.0)", "time.sleep(1.0)  # lint: ignore[sleep-retry]"
        )
        assert findings_for(tmp_path, src) == []


class TestReadbackInLoop:
    PER_SLOT_LOOP = (
        "def drain(eng):\n"
        "    for slot in range(eng.n_slots):\n"
        "        tok = eng._readback(eng._last)[slot]\n"
        "        handle(tok)\n"
    )

    def test_readback_in_loop_flagged(self, tmp_path):
        assert findings_for(tmp_path, self.PER_SLOT_LOOP) == ["readback-in-loop"]

    def test_device_get_in_while_flagged(self, tmp_path):
        src = (
            "import jax\n"
            "def watch(x):\n"
            "    while running():\n"
            "        val = jax.device_get(x)\n"
            "        emit(val)\n"
        )
        assert findings_for(tmp_path, src) == ["readback-in-loop"]

    def test_readback_outside_loop_clean(self, tmp_path):
        src = (
            "def snapshot(eng):\n"
            "    trace = eng._readback(eng._last)\n"
            "    return [trace[s] for s in range(eng.n_slots)]\n"
        )
        assert findings_for(tmp_path, src) == []

    def test_engine_modules_exempt(self, tmp_path):
        d = tmp_path / "models"
        d.mkdir()
        for name in ("serve.py", "paged.py"):
            f = d / name
            f.write_text(self.PER_SLOT_LOOP)
            assert [x.check for x in lint.check_file(f)] == []

    def test_ignore_pragma_applies(self, tmp_path):
        src = self.PER_SLOT_LOOP.replace(
            "[slot]", "[slot]  # lint: ignore[readback-in-loop]"
        )
        assert findings_for(tmp_path, src) == []

    def test_nested_loops_report_once(self, tmp_path):
        src = (
            "def drain(eng):\n"
            "    while pending(eng):\n"
            "        for slot in range(eng.n_slots):\n"
            "            handle(eng._readback(eng._last)[slot])\n"
        )
        assert findings_for(tmp_path, src) == ["readback-in-loop"]


class TestMetricDocs:
    """The cross-file metric-docs check: serving metrics declared in
    models/ must carry help text somewhere and appear in ARCHITECTURE.md."""

    def _models_file(self, tmp_path, source):
        d = tmp_path / "models"
        d.mkdir()
        f = d / "case.py"
        f.write_text(source)
        return f

    def test_undocumented_serving_metric_flagged(self, tmp_path):
        f = self._models_file(
            tmp_path,
            'M = REGISTRY.counter("tpu_serve_bogus_total", "what it counts")\n',
        )
        findings = lint.check_metric_docs([f], arch_text="")
        assert [x.check for x in findings] == ["metric-docs"]
        assert "not documented" in findings[0].message

    def test_helpless_serving_metric_flagged(self, tmp_path):
        f = self._models_file(
            tmp_path,
            'M = REGISTRY.counter("tpu_serve_bogus_total")\n',
        )
        findings = lint.check_metric_docs(
            [f], arch_text="`tpu_serve_bogus_total` documented here"
        )
        assert [x.check for x in findings] == ["metric-docs"]
        assert "help text" in findings[0].message

    def test_documented_metric_with_help_clean(self, tmp_path):
        f = self._models_file(
            tmp_path,
            'M = REGISTRY.histogram("tpu_serve_bogus_seconds", "latency")\n'
            'M2 = REGISTRY.histogram("tpu_serve_bogus_seconds")  # lookup\n',
        )
        assert lint.check_metric_docs(
            [f], arch_text="| `tpu_serve_bogus_seconds` | histogram | latency |"
        ) == []

    def test_non_models_and_non_serving_names_exempt(self, tmp_path):
        # outside models/: not part of the serving contract
        outside = tmp_path / "other.py"
        outside.write_text('M = REGISTRY.counter("tpu_serve_bogus_total")\n')
        # inside models/ but not tpu_serve_*: control-plane namespace
        inside = self._models_file(
            tmp_path, 'M = REGISTRY.counter("dra_other_total")\n'
        )
        assert lint.check_metric_docs([outside, inside], arch_text="") == []

    def test_repo_serving_metrics_are_documented(self):
        models = sorted((REPO / "k8s_dra_driver_tpu" / "models").glob("*.py"))
        arch = (REPO / "ARCHITECTURE.md").read_text()
        assert lint.check_metric_docs(models, arch) == []


class TestMain:
    def test_missing_target_fails_loudly(self, capsys):
        rc = lint.main(["lint", "no/such/dir"])
        assert rc == 2
        assert "not a directory" in capsys.readouterr().err


class TestRepoIsClean:
    def test_repo_lints_clean(self):
        targets = [
            REPO / "k8s_dra_driver_tpu",
            REPO / "tests",
            REPO / "bench.py",
            REPO / "__graft_entry__.py",
            REPO / "tools",  # the whole dir, matching the Makefile gate
        ]
        rc = lint.main(["lint", *map(str, targets)])
        assert rc == 0, "repo has lint findings (see stdout)"


class TestHelmCheck:
    def test_chart_is_consistent(self):
        import helm_check

        assert helm_check.check_chart(helm_check.DEFAULT_CHART) == []

    def test_detects_undefined_value(self, tmp_path):
        import helm_check

        (tmp_path / "templates").mkdir()
        (tmp_path / "values.yaml").write_text("image:\n  tag: v1\n")
        (tmp_path / "templates" / "d.yaml").write_text(
            "image: {{ .Values.image.repo }}:{{ .Values.image.tag }}\n"
        )
        findings = helm_check.check_chart(tmp_path)
        assert any("image.repo is not defined" in f for f in findings)

    def test_detects_dead_value_and_missing_define(self, tmp_path):
        import helm_check

        (tmp_path / "templates").mkdir()
        (tmp_path / "values.yaml").write_text("used: 1\nunused: 2\n")
        (tmp_path / "templates" / "d.yaml").write_text(
             'x: {{ .Values.used }}\ny: {{ include "chart.name" . }}\n'
        )
        findings = helm_check.check_chart(tmp_path)
        assert any("unused is never referenced" in f for f in findings)
        assert any('include "chart.name" has no define' in f for f in findings)

    def test_allow_pragma(self, tmp_path):
        import helm_check

        (tmp_path / "templates").mkdir()
        (tmp_path / "values.yaml").write_text("a: 1\n")
        (tmp_path / "templates" / "v.yaml").write_text(
            "{{/* helm-check: allow */}}\n"
            "{{- if .Values.forbidden }}{{- fail \"no\" }}{{- end }}\n"
            "x: {{ .Values.a }}\n"
        )
        assert helm_check.check_chart(tmp_path) == []
